"""Adaptive Radix Tree over fixed 6-byte keys (the 64-bit layer's key index).

Re-expression of the reference's ``art/`` package (art/Art.java:10-13, per
Leis et al. "The adaptive radix tree: ARTful indexing for main-memory
databases"): keys are the high 48 bits of a 64-bit value as 6 big-endian
bytes (longlong/LongUtils.java high48 split), so unsigned numeric order ==
lexicographic byte order. Four node widths with upgrade/downgrade
(art/Node4.java, Node16.java, Node48.java, Node256.java), path compression
(the ``prefix`` field), and ordered forward/backward traversal (the
reference's ``AbstractShuttle``/``ForwardShuttle``/``BackwardShuttle``
cursors become Python generators).

Leaves store an opaque payload (here: a 16-bit Container), playing the role
of the reference's packed container index into ``art/Containers.java``.
Structure is plain Python objects — this is the host-side index; device
work happens on the packed container store (parallel/store.py).
"""

from __future__ import annotations

from typing import Any, Iterator, Optional, Tuple

KEY_BYTES = 6


class _Leaf:
    __slots__ = ("key", "value")

    def __init__(self, key: bytes, value: Any):
        self.key = key
        self.value = value


class _Node:
    """Inner node; concrete width decided by ``n_children``."""

    __slots__ = ("prefix", "keys", "children", "child_index")

    GROW_AT = {4: 16, 16: 48, 48: 256}

    def __init__(self, prefix: bytes):
        self.prefix = prefix
        # Node4/16 representation: sorted parallel arrays
        self.keys: Optional[bytearray] = bytearray()
        self.children: list = []
        # Node48/256 representation: 256-entry dispatch table
        self.child_index: Optional[list] = None

    # -- representation management ----------------------------------------
    @property
    def n_children(self) -> int:
        if self.child_index is not None:
            return sum(1 for c in self.child_index if c is not None)
        return len(self.children)

    def node_width(self) -> int:
        """4/16/48/256 — the concrete reference node type this corresponds to
        (used by introspection/tests; the physical representation here is
        array-form up to 48 children, table-form beyond)."""
        n = self.n_children
        if self.child_index is not None:
            return 256 if n > 48 else 48
        return 4 if n <= 4 else (16 if n <= 16 else 48)

    def find(self, byte: int):
        if self.child_index is not None:
            return self.child_index[byte]
        # binary search over the sorted key array (Node16's SSE compare
        # becomes a bisect here)
        keys = self.keys
        lo, hi = 0, len(keys)
        while lo < hi:
            mid = (lo + hi) // 2
            if keys[mid] < byte:
                lo = mid + 1
            else:
                hi = mid
        if lo < len(keys) and keys[lo] == byte:
            return self.children[lo]
        return None

    def put(self, byte: int, child) -> None:
        if self.child_index is not None:
            self.child_index[byte] = child
            return
        keys = self.keys
        # ascending-order fast path (bulk_load, sorted ingest): append
        if (not keys or byte > keys[-1]) and len(keys) < 48:
            keys.append(byte)
            self.children.append(child)
            return
        lo, hi = 0, len(keys)
        while lo < hi:
            mid = (lo + hi) // 2
            if keys[mid] < byte:
                lo = mid + 1
            else:
                hi = mid
        if lo < len(keys) and keys[lo] == byte:
            self.children[lo] = child
            return
        if len(keys) >= 48:  # upgrade to the 256-table form (Node48 -> Node256
            # boundary; 16 -> 48 also lands here as the table form covers both)
            table = [None] * 256
            for k, c in zip(keys, self.children):
                table[k] = c
            table[byte] = child
            self.child_index = table
            self.keys = None
            self.children = []
            return
        keys.insert(lo, byte)
        self.children.insert(lo, child)

    def delete(self, byte: int) -> None:
        if self.child_index is not None:
            self.child_index[byte] = None
            if self.n_children <= 36:  # downgrade back to array form
                pairs = [
                    (k, c) for k, c in enumerate(self.child_index) if c is not None
                ]
                self.keys = bytearray(k for k, _ in pairs)
                self.children = [c for _, c in pairs]
                self.child_index = None
            return
        keys = self.keys
        for i, k in enumerate(keys):
            if k == byte:
                del keys[i]
                del self.children[i]
                return

    # -- ordered access -----------------------------------------------------
    def items(self):
        """(byte, child) in ascending byte order."""
        if self.child_index is not None:
            for b, c in enumerate(self.child_index):
                if c is not None:
                    yield b, c
        else:
            yield from zip(self.keys, self.children)

    def items_reverse(self):
        if self.child_index is not None:
            for b in range(255, -1, -1):
                c = self.child_index[b]
                if c is not None:
                    yield b, c
        else:
            yield from zip(reversed(self.keys), reversed(self.children))

    def only_child(self):
        for item in self.items():
            return item
        return None


def _common_prefix(a: bytes, b: bytes) -> int:
    n = min(len(a), len(b))
    for i in range(n):
        if a[i] != b[i]:
            return i
    return n


class Art:
    """The trie facade (art/Art.java:35 ``insert`` / :47 ``findByKey``)."""

    __slots__ = ("_root", "_size")

    def __init__(self):
        self._root = None
        self._size = 0

    def __len__(self) -> int:
        return self._size

    def is_empty(self) -> bool:
        return self._root is None

    # -- core ---------------------------------------------------------------
    def insert(self, key: bytes, value: Any) -> None:
        assert len(key) == KEY_BYTES
        if self._root is None:
            self._root = _Leaf(key, value)
            self._size = 1
            return
        self._root = self._insert(self._root, key, 0, value)

    def _insert(self, node, key: bytes, depth: int, value: Any):
        if isinstance(node, _Leaf):
            if node.key == key:
                node.value = value  # replaceContainer path
                return node
            # split into a new inner node holding both leaves
            cp = _common_prefix(node.key[depth:], key[depth:])
            new = _Node(key[depth : depth + cp])
            new.put(node.key[depth + cp], node)
            new.put(key[depth + cp], _Leaf(key, value))
            self._size += 1
            return new
        pfx = node.prefix
        cp = _common_prefix(pfx, key[depth:])
        if cp < len(pfx):
            # split the compressed path (prefix mismatch)
            new = _Node(key[depth : depth + cp])
            node.prefix = pfx[cp + 1 :]
            new_branch_old = pfx[cp]
            new.put(new_branch_old, node)
            new.put(key[depth + cp], _Leaf(key, value))
            self._size += 1
            return new
        depth += cp
        child = node.find(key[depth])
        if child is None:
            node.put(key[depth], _Leaf(key, value))
            self._size += 1
        else:
            node.put(key[depth], self._insert(child, key, depth + 1, value))
        return node

    def bulk_load(self, pairs) -> None:
        """Build the whole trie from SORTED DISTINCT (key, value) pairs in
        one bottom-up pass — O(n) node construction with no per-key descent
        (the reference only has per-key ``insert``; bulk ingest through it
        costs a full root-to-leaf walk per key, which is what
        Roaring64Bitmap.add_many's scattered-key profile showed dominating).
        Only valid on an empty trie; node widths come out identical to
        incremental insertion because ``put`` upgrades at the same
        thresholds."""
        if self._root is not None:
            raise ValueError("bulk_load requires an empty trie")
        items = list(pairs)
        if not items:
            return
        assert all(len(k) == KEY_BYTES for k, _ in items), "keys must be 6 bytes"
        assert all(
            items[i][0] < items[i + 1][0] for i in range(len(items) - 1)
        ), "keys must be sorted distinct"
        self._root = self._bulk_build(items, 0)
        self._size = len(items)

    def _bulk_build(self, items, depth: int):
        if len(items) == 1:
            k, v = items[0]
            return _Leaf(k, v)
        # sorted input: the common prefix of (first, last) is common to all
        first, last = items[0][0], items[-1][0]
        cp = _common_prefix(first[depth:], last[depth:])
        node = _Node(first[depth : depth + cp])
        d = depth + cp
        i, n = 0, len(items)
        while i < n:
            b = items[i][0][d]
            j = i + 1
            while j < n and items[j][0][d] == b:
                j += 1
            # ascending bytes: put() appends at the tail, no mid-array shifts
            node.put(b, self._bulk_build(items[i:j], d + 1))
            i = j
        return node

    def find(self, key: bytes):
        """Payload for key, or None (art/Art.java:47 findByKey)."""
        node = self._root
        depth = 0
        while node is not None:
            if isinstance(node, _Leaf):
                return node.value if node.key == key else None
            pfx = node.prefix
            if key[depth : depth + len(pfx)] != pfx:
                return None
            depth += len(pfx)
            node = node.find(key[depth])
            depth += 1
        return None

    def remove(self, key: bytes) -> bool:
        if self._root is None:
            return False
        removed, self._root = self._remove(self._root, key, 0)
        if removed:
            self._size -= 1
        return removed

    def _remove(self, node, key: bytes, depth: int):
        if isinstance(node, _Leaf):
            return (True, None) if node.key == key else (False, node)
        pfx = node.prefix
        if key[depth : depth + len(pfx)] != pfx:
            return False, node
        depth += len(pfx)
        byte = key[depth]
        child = node.find(byte)
        if child is None:
            return False, node
        removed, new_child = self._remove(child, key, depth + 1)
        if not removed:
            return False, node
        if new_child is None:
            node.delete(byte)
        else:
            node.put(byte, new_child)
        n = node.n_children
        if n == 0:
            return True, None
        if n == 1:
            # path-compress single-child inner nodes away
            b, only = node.only_child()
            if isinstance(only, _Leaf):
                return True, only
            only.prefix = node.prefix + bytes([b]) + only.prefix
            return True, only
        return True, node

    # -- ordered traversal (Forward/BackwardShuttle) -------------------------
    def items(self) -> Iterator[Tuple[bytes, Any]]:
        yield from self._walk(self._root, reverse=False)

    def items_reverse(self) -> Iterator[Tuple[bytes, Any]]:
        """Streaming descending traversal in O(depth) memory — the
        BackwardShuttle (art/BackwardShuttle.java:1); callers must NOT need
        the trie materialized (it exists precisely to hold huge key sets)."""
        yield from self._walk(self._root, reverse=True)

    def _walk(self, node, reverse: bool):
        """Explicit-stack shuttle (art/AbstractShuttle.java:1): one child
        iterator per trie level, so traversal holds O(depth) frames — never
        the O(n) node list — in either direction."""
        if node is None:
            return
        stack = [iter(((0, node),))]
        while stack:
            nxt = next(stack[-1], None)
            if nxt is None:
                stack.pop()
                continue
            child = nxt[1]
            if isinstance(child, _Leaf):
                yield child.key, child.value
            else:
                stack.append(
                    child.items_reverse() if reverse else child.items()
                )

    def node_width_histogram(self) -> dict:
        """Count of inner nodes per reference node class (4/16/48/256) —
        introspection for the adaptive-width design (art/Node4.java etc.;
        here widths <= 48 share the sorted-array physical form and wider
        nodes the 256-table form, with upgrade at 48 and downgrade at 36)."""
        hist = {4: 0, 16: 0, 48: 0, 256: 0, "leaves": 0}
        stack = [self._root] if self._root is not None else []
        while stack:
            node = stack.pop()
            if isinstance(node, _Leaf):
                hist["leaves"] += 1
                continue
            hist[node.node_width()] += 1
            for _, child in node.items():
                stack.append(child)
        return hist

    def first(self) -> Optional[Tuple[bytes, Any]]:
        for kv in self.items():
            return kv
        return None

    def last(self) -> Optional[Tuple[bytes, Any]]:
        for kv in self.items_reverse():
            return kv
        return None

    def items_from(self, key: bytes) -> Iterator[Tuple[bytes, Any]]:
        """Ordered (k, v) with k >= key — the shuttle's seek support
        (LeafNodeIterator with from-key)."""
        yield from self._walk_from(self._root, key, 0)

    def items_to(self, key: bytes) -> Iterator[Tuple[bytes, Any]]:
        """Reverse-ordered (k, v) with k <= key (the BackwardShuttle seek)."""
        yield from self._walk_to(self._root, key, 0)

    def _walk_to(self, node, key: bytes, depth: int):
        if node is None:
            return
        if isinstance(node, _Leaf):
            if node.key <= key:
                yield node.key, node.value
            return
        pfx = node.prefix
        sub = key[depth : depth + len(pfx)]
        if pfx < sub:  # whole subtree is before the seek point
            yield from self._walk(node, reverse=True)
            return
        if pfx > sub:  # whole subtree is after it
            return
        depth += len(pfx)
        target = key[depth] if depth < len(key) else 255
        for b, child in node.items_reverse():
            if b > target:
                continue
            if b == target:
                yield from self._walk_to(child, key, depth + 1)
            else:
                yield from self._walk(child, reverse=True)

    def _walk_from(self, node, key: bytes, depth: int):
        if node is None:
            return
        if isinstance(node, _Leaf):
            if node.key >= key:
                yield node.key, node.value
            return
        pfx = node.prefix
        sub = key[depth : depth + len(pfx)]
        if pfx > sub:  # whole subtree is after the seek point
            yield from self._walk(node, reverse=False)
            return
        if pfx < sub:  # whole subtree is before it
            return
        depth += len(pfx)
        target = key[depth] if depth < len(key) else 0
        for b, child in node.items():
            if b < target:
                continue
            if b == target:
                yield from self._walk_from(child, key, depth + 1)
            else:
                yield from self._walk(child, reverse=False)
