"""L1' containers: three chunk formats over a 16-bit sub-universe.

Logical model follows the reference (Container.java:19 and its three
concrete types): a sorted unique ``uint16`` array (sparse), a 1024x``uint64``
bitset (dense), and run-length (start, length) pairs — chosen dynamically by
cardinality / serialized-size thresholds (ArrayContainer.java:27
``DEFAULT_MAX_SIZE=4096``; RunContainer.java:78 serialized size
``2 + 4*nruns``; BitmapContainer fixed 8 KiB).

Physical model differs deliberately from the Java triple-dispatch matrix
(9 type-combinations per op, Container.java:63-98): here every pairwise op is
computed vectorized in numpy on the natural representation (sorted-array set
ops for sparse x sparse, word ops otherwise) and the *result* container type
is chosen by the same cardinality rule the reference converges to
(<=4096 -> array, else bitmap; runs arise from ``run_optimize``, range
constructors and deserialization). Value semantics and serialized-form
validity are identical; the batched device path (parallel/store.py) is where
the performance lives.

Containers are value-semantic: mutating ops return the (possibly new,
possibly different-type) container, Java-style (``c = c.add(x)``).
"""

from __future__ import annotations

from typing import Iterator, Optional, Tuple

import numpy as np

from ..utils import bits

# raw C point-probe kernels, bound once: scalar contains is the per-call
# latency floor (simplebenchmark contains row; Util.java:697's
# unsignedBinarySearch role), and every avoided Python frame or numpy
# scalar-index on that path is ~70-150 ns
_EXT_CONTAINS = None  # contains_u16(sorted_content, x) -> bool
_EXT_WORDBIT = None  # word_bit(words_u64, x) -> bool
_EXT_RUNCONTAINS = None  # run_contains(starts, lengths, x) -> bool
_EXT_ADVANCE = None  # advance_until(sorted, pos, min) -> first idx with a[i] >= min
_EXT_PROBES_TRIED = False


def _bind_scalar_probes():
    # callers share one stanza shape (keep it when adding probes):
    #   e = _EXT_X
    #   if e is None and not _EXT_PROBES_TRIED:
    #       _bind_scalar_probes()
    #       e = _EXT_X
    # — a helper function here would cost the hot path the very frame the
    # probes exist to avoid
    global _EXT_CONTAINS, _EXT_WORDBIT, _EXT_RUNCONTAINS, _EXT_ADVANCE
    global _EXT_PROBES_TRIED
    if not _EXT_PROBES_TRIED:
        _EXT_PROBES_TRIED = True
        from .. import native

        if native.available():
            e = native._load_ext()
            if e is not None:
                _EXT_CONTAINS = getattr(e, "contains_u16", None)
                _EXT_WORDBIT = getattr(e, "word_bit", None)
                _EXT_RUNCONTAINS = getattr(e, "run_contains", None)
                _EXT_ADVANCE = getattr(e, "advance_until", None)
    return _EXT_CONTAINS

ARRAY_MAX_SIZE = 4096  # ArrayContainer.java:27 DEFAULT_MAX_SIZE
MAX_CAPACITY = 1 << 16  # BitmapContainer.java:25

ARRAY_TYPE = "array"
BITMAP_TYPE = "bitmap"
RUN_TYPE = "run"


def _as_u16(values) -> np.ndarray:
    return np.asarray(values, dtype=np.uint16)


def _wrap_u16(content: np.ndarray) -> "ArrayContainer":
    """ArrayContainer around an ALREADY-uint16 sorted array (kernel output
    or a mask/fancy index of existing content) — bypasses __init__'s
    dtype conversion, which is pure overhead on the pairwise-algebra hot
    path (~10k container ops per merge on adversarial key sets)."""
    out = ArrayContainer.__new__(ArrayContainer)
    out.content = content
    return out


class Container:
    """Abstract chunk over a 16-bit sub-universe (Container.java:19)."""

    TYPE: str = "?"

    # --- representation ---------------------------------------------------
    @property
    def cardinality(self) -> int:
        raise NotImplementedError

    def to_array(self) -> np.ndarray:
        """Sorted uint16 values."""
        raise NotImplementedError

    def to_words(self) -> np.ndarray:
        """1024-word uint64 bitset copy."""
        raise NotImplementedError

    def num_runs(self) -> int:
        raise NotImplementedError

    def clone(self) -> "Container":
        raise NotImplementedError

    # --- size / conversion (Container.java:882 runOptimize) ---------------
    def serialized_size(self) -> int:
        """Bytes of the container payload in RoaringFormatSpec."""
        raise NotImplementedError

    def run_optimize(self) -> "Container":
        """Convert to the smallest serialized representation
        (Container.runOptimize, Container.java:882)."""
        card = self.cardinality
        nruns = self.num_runs()
        run_size = RunContainer.serialized_size_for(nruns)
        current = 8192 if card > ARRAY_MAX_SIZE else 2 + 2 * card
        if run_size < current:
            return RunContainer.from_values(self.to_array())
        return self.to_efficient_non_run()

    def to_efficient_non_run(self) -> "Container":
        card = self.cardinality
        if card > ARRAY_MAX_SIZE:
            if isinstance(self, BitmapContainer):
                return self
            return BitmapContainer(bits.words_from_values(self.to_array()), card)
        if isinstance(self, ArrayContainer):
            return self
        return ArrayContainer(self.to_array())

    # --- point ops --------------------------------------------------------
    def contains(self, x: int) -> bool:
        raise NotImplementedError

    def contains_many(self, values: np.ndarray) -> np.ndarray:
        """Vectorized membership mask for uint16 values (per-type overrides
        avoid materializing the container)."""
        return np.isin(values, self.to_array())

    def add(self, x: int) -> "Container":
        raise NotImplementedError

    def remove(self, x: int) -> "Container":
        raise NotImplementedError

    # --- range ops (half-open [start, end) over 0..65536) -----------------
    def add_range(self, start: int, end: int) -> "Container":
        if start >= end:
            return self
        words = self.to_words()
        bits.set_bitmap_range(words, start, end)
        return best_container_of_words(words)

    def remove_range(self, start: int, end: int) -> "Container":
        if start >= end:
            return self
        words = self.to_words()
        bits.clear_bitmap_range(words, start, end)
        return best_container_of_words(words)

    def flip_range(self, start: int, end: int) -> "Container":
        """not(range) (Container.inot/not)."""
        if start >= end:
            return self
        words = self.to_words()
        bits.flip_bitmap_range(words, start, end)
        return best_container_of_words(words)

    def contains_range(self, start: int, end: int) -> bool:
        if start >= end:
            return True
        return self.rank(end - 1) - (self.rank(start - 1) if start > 0 else 0) == end - start

    def intersects_range(self, start: int, end: int) -> bool:
        if start >= end:
            return False
        nv = self.next_value(start)
        return nv >= 0 and nv < end

    # --- pairwise algebra -------------------------------------------------
    def and_(self, other: "Container") -> "Container":
        raise NotImplementedError

    def or_(self, other: "Container") -> "Container":
        raise NotImplementedError

    def xor_(self, other: "Container") -> "Container":
        raise NotImplementedError

    def andnot(self, other: "Container") -> "Container":
        raise NotImplementedError

    def intersects(self, other: "Container") -> bool:
        return self.and_cardinality(other) > 0

    def and_cardinality(self, other: "Container") -> int:
        return self.and_(other).cardinality

    def contains_container(self, other: "Container") -> bool:
        """Subset test: other ⊆ self (Container.contains, RoaringBitmap.java:2781)."""
        if other.cardinality > self.cardinality:
            return False
        return other.andnot(self).cardinality == 0

    # --- order statistics -------------------------------------------------
    def rank(self, x: int) -> int:
        """Number of values <= x (Container.rank, Container.java:849)."""
        raise NotImplementedError

    def rank_many(self, lows: np.ndarray) -> np.ndarray:
        """Vectorized rank over a uint16 probe array (no reference
        equivalent — Container.java only has the scalar :849); concrete
        types override with one numpy pass per batch."""
        return np.array([self.rank(int(x)) for x in lows], dtype=np.int64)

    def select_many(self, js: np.ndarray) -> np.ndarray:
        """Vectorized select over in-container 0-based ranks (the bulk twin
        of Container.select, Container.java:891); concrete types override
        with one numpy pass."""
        return np.array([self.select(int(j)) for j in js], dtype=np.uint16)

    def select(self, j: int) -> int:
        """j-th smallest value, 0-based (Container.select, Container.java:891)."""
        raise NotImplementedError

    def first(self) -> int:
        return self.select(0)

    def last(self) -> int:
        return self.select(self.cardinality - 1)

    def next_value(self, from_value: int) -> int:
        """Smallest value >= from_value, or -1 (Container.nextValue)."""
        raise NotImplementedError

    def previous_value(self, from_value: int) -> int:
        """Largest value <= from_value, or -1."""
        raise NotImplementedError

    def next_absent_value(self, from_value: int) -> int:
        """Smallest absent value >= from_value (65536 when the whole tail is
        present). Vectorized: a contiguous present run starting at from_value
        satisfies arr[i+k] == from_value+k; the first mismatch is the gap."""
        arr = self.to_array().astype(np.int64)
        i = int(np.searchsorted(arr, from_value))
        if i == arr.size or arr[i] != from_value:
            return from_value
        tail = arr[i:]
        mismatch = np.nonzero(tail != from_value + np.arange(tail.size))[0]
        return from_value + (int(mismatch[0]) if mismatch.size else tail.size)

    def previous_absent_value(self, from_value: int) -> int:
        """Largest absent value <= from_value, or -1 when [0, from_value] is
        entirely present."""
        arr = self.to_array().astype(np.int64)
        i = int(np.searchsorted(arr, from_value, side="right"))
        if i == 0 or arr[i - 1] != from_value:
            return from_value
        head = arr[:i][::-1]  # values ending the run at from_value, descending
        mismatch = np.nonzero(head != from_value - np.arange(head.size))[0]
        return from_value - (int(mismatch[0]) if mismatch.size else head.size)

    # --- iteration --------------------------------------------------------
    def __iter__(self) -> Iterator[int]:
        return iter(self.to_array().tolist())

    def __len__(self) -> int:
        return self.cardinality

    def __eq__(self, other) -> bool:
        if not isinstance(other, Container):
            return NotImplemented
        return (
            self.cardinality == other.cardinality
            and np.array_equal(self.to_array(), other.to_array())
        )

    def __hash__(self):  # containers are not hashable (mutable value semantics)
        raise TypeError("containers are unhashable")

    def __repr__(self):
        c = self.cardinality
        head = ",".join(str(v) for v in self.to_array()[:8].tolist())
        return f"<{type(self).__name__} card={c} [{head}{'...' if c > 8 else ''}]>"


# ---------------------------------------------------------------------------


class ArrayContainer(Container):
    """Sorted unique uint16 values; holds <= 4096 (ArrayContainer.java:27)."""

    TYPE = ARRAY_TYPE
    __slots__ = ("content",)

    def __init__(self, content=None):
        self.content = _as_u16(content if content is not None else [])

    @property
    def cardinality(self) -> int:
        return int(self.content.size)

    def to_array(self) -> np.ndarray:
        return self.content

    def to_words(self) -> np.ndarray:
        return bits.words_from_values(self.content)

    def num_runs(self) -> int:
        if self.content.size == 0:
            return 0
        # rb-ok: dtype-discipline -- uint16 payload (<= 0xFFFF) is exact in
        # int32; signed diff is the point (uint16 wraparound would lose the
        # negative gaps this counts)
        return int((np.diff(self.content.astype(np.int32)) != 1).sum()) + 1

    def clone(self) -> "ArrayContainer":
        return _wrap_u16(self.content.copy())

    def serialized_size(self) -> int:
        return 2 * self.cardinality  # payload: cardinality uint16s

    def contains(self, x: int) -> bool:
        c = self.content
        e = _EXT_CONTAINS
        if e is None and not _EXT_PROBES_TRIED:
            _bind_scalar_probes()
            e = _EXT_CONTAINS
        if e is not None:
            return e(c, x)
        i = bits.lower_bound(c, x)
        return bool(i < c.size and c[i] == x)

    def contains_many(self, values: np.ndarray) -> np.ndarray:
        if self.content.size == 0:
            return np.zeros(values.size, dtype=bool)
        v = values.astype(np.uint16)
        idx = np.searchsorted(self.content, v)
        idx_c = np.minimum(idx, self.content.size - 1)
        return (idx < self.content.size) & (self.content[idx_c] == v)

    def add(self, x: int) -> Container:
        c = self.content
        e = _EXT_ADVANCE
        if e is None and not _EXT_PROBES_TRIED:
            _bind_scalar_probes()
            e = _EXT_ADVANCE
        i = e(c, -1, x) if e is not None else bits.lower_bound(c, x)
        if i < c.size and c[i] == x:
            return self
        if c.size >= ARRAY_MAX_SIZE:
            return self._promote().add(x)  # ArrayContainer.java:158 promotion
        # manual two-slice insert: np.insert pays ~5 us of generic shape
        # machinery per call on this point-mutation hot path
        out = np.empty(c.size + 1, dtype=np.uint16)
        out[:i] = c[:i]
        out[i] = x
        out[i + 1 :] = c[i:]
        self.content = out
        return self

    def remove(self, x: int) -> Container:
        c = self.content
        e = _EXT_ADVANCE
        if e is None and not _EXT_PROBES_TRIED:
            _bind_scalar_probes()
            e = _EXT_ADVANCE
        i = e(c, -1, x) if e is not None else bits.lower_bound(c, x)
        if i < c.size and c[i] == x:
            out = np.empty(c.size - 1, dtype=np.uint16)
            out[:i] = c[:i]
            out[i:] = c[i + 1 :]
            self.content = out
        return self

    def _promote(self) -> "BitmapContainer":
        return BitmapContainer(bits.words_from_values(self.content), self.cardinality)

    # pairwise
    def and_(self, other: Container) -> Container:
        if isinstance(other, ArrayContainer):
            return _wrap_u16(bits.intersect_sorted(self.content, other.content))
        if isinstance(other, BitmapContainer):
            mask = other.contains_many(self.content)
            return _wrap_u16(self.content[mask])
        return other.and_(self)  # run

    def or_(self, other: Container) -> Container:
        if isinstance(other, ArrayContainer):
            merged = bits.merge_sorted_unique(self.content, other.content)
            if merged.size > ARRAY_MAX_SIZE:
                return BitmapContainer(bits.words_from_values(merged), int(merged.size))
            return _wrap_u16(merged)
        return other.or_(self)

    def xor_(self, other: Container) -> Container:
        if isinstance(other, ArrayContainer):
            out = bits.xor_sorted(self.content, other.content)
            if out.size > ARRAY_MAX_SIZE:
                return BitmapContainer(bits.words_from_values(out), int(out.size))
            return _wrap_u16(out)
        return other.xor_(self)

    def andnot(self, other: Container) -> Container:
        if isinstance(other, ArrayContainer):
            return _wrap_u16(bits.difference_sorted(self.content, other.content))
        if isinstance(other, BitmapContainer):
            mask = other.contains_many(self.content)
            return _wrap_u16(self.content[~mask])
        return _wrap_u16(
            self.content[~_run_contains_many(other, self.content)]
        )

    def and_cardinality(self, other: Container) -> int:
        if isinstance(other, BitmapContainer):
            return int(other.contains_many(self.content).sum())
        return self.and_(other).cardinality

    def rank(self, x: int) -> int:
        # values <= x == first index with content[i] >= x+1
        if x >= 0xFFFF:
            return self.content.size
        e = _EXT_ADVANCE
        if e is None and not _EXT_PROBES_TRIED:
            _bind_scalar_probes()
            e = _EXT_ADVANCE
        if e is not None:
            return e(self.content, -1, int(x) + 1)
        return bits.lower_bound(self.content, int(x) + 1)

    def rank_many(self, lows: np.ndarray) -> np.ndarray:
        return np.searchsorted(self.content, lows, side="right").astype(np.int64)

    def select(self, j: int) -> int:
        return int(self.content[j])

    def select_many(self, js: np.ndarray) -> np.ndarray:
        return self.content[np.asarray(js, dtype=np.int64)]

    def next_value(self, from_value: int) -> int:
        i = bits.lower_bound(self.content, from_value)
        return int(self.content[i]) if i < self.content.size else -1

    def previous_value(self, from_value: int) -> int:
        i = int(np.searchsorted(self.content, np.uint16(from_value), side="right"))
        return int(self.content[i - 1]) if i > 0 else -1


# ---------------------------------------------------------------------------


class BitmapContainer(Container):
    """1024x uint64 bitset + tracked cardinality (BitmapContainer.java:25)."""

    TYPE = BITMAP_TYPE
    __slots__ = ("words", "_card")

    def __init__(self, words: Optional[np.ndarray] = None, cardinality: Optional[int] = None):
        self.words = words if words is not None else bits.new_words()
        self._card = (
            cardinality if cardinality is not None else bits.cardinality_of_words(self.words)
        )

    @property
    def cardinality(self) -> int:
        return self._card

    def to_array(self) -> np.ndarray:
        return bits.values_from_words(self.words)

    def to_words(self) -> np.ndarray:
        return self.words.copy()

    def num_runs(self) -> int:
        return bits.num_runs_in_words(self.words)

    def clone(self) -> "BitmapContainer":
        return BitmapContainer(self.words.copy(), self._card)

    def serialized_size(self) -> int:
        return 8192

    def contains(self, x: int) -> bool:
        e = _EXT_WORDBIT
        if e is None and not _EXT_PROBES_TRIED:
            _bind_scalar_probes()
            e = _EXT_WORDBIT
        if e is not None:
            return e(self.words, x)
        return bits.get_bit(self.words, x)

    def contains_many(self, values: np.ndarray) -> np.ndarray:
        """Vectorized membership mask for uint16 values."""
        v = values.astype(np.uint32)
        return (
            (self.words[v >> 6] >> (v & np.uint32(63)).astype(np.uint64)) & np.uint64(1)
        ).astype(bool)

    def add(self, x: int) -> Container:
        if not bits.get_bit(self.words, x):
            bits.set_bit(self.words, x)
            self._card += 1
        return self

    def remove(self, x: int) -> Container:
        if bits.get_bit(self.words, x):
            bits.clear_bit(self.words, x)
            self._card -= 1
            if self._card <= ARRAY_MAX_SIZE:  # demotion (BitmapContainer -> Array)
                return ArrayContainer(self.to_array())
        return self

    def _binary(self, other: Container, fn) -> Container:
        ow = other.words if isinstance(other, BitmapContainer) else other.to_words()
        return best_container_of_words(fn(self.words, ow))

    def and_(self, other: Container) -> Container:
        if isinstance(other, ArrayContainer):
            return other.and_(self)
        return self._binary(other, np.bitwise_and)

    def or_(self, other: Container) -> Container:
        if isinstance(other, ArrayContainer):
            words = self.words.copy()
            bits.or_values_into_words(words, other.content)
            return BitmapContainer(words)
        return self._binary(other, np.bitwise_or)

    def xor_(self, other: Container) -> Container:
        return self._binary(other, np.bitwise_xor)

    def andnot(self, other: Container) -> Container:
        ow = other.words if isinstance(other, BitmapContainer) else other.to_words()
        return best_container_of_words(self.words & ~ow)

    def and_cardinality(self, other: Container) -> int:
        if isinstance(other, ArrayContainer):
            return other.and_cardinality(self)
        ow = other.words if isinstance(other, BitmapContainer) else other.to_words()
        return bits.cardinality_of_words(self.words & ow)

    def rank(self, x: int) -> int:
        return bits.cardinality_in_range(self.words, 0, x + 1)

    def rank_many(self, lows: np.ndarray) -> np.ndarray:
        # exclusive per-word popcount prefix + masked popcount of the
        # probe's own word, all vectorized
        pc = bits.popcount64(self.words).astype(np.int64)
        cum = np.concatenate(([0], np.cumsum(pc)[:-1]))
        lows = np.asarray(lows, dtype=np.uint32)
        wi = (lows >> 6).astype(np.int64)
        b = (lows & 63).astype(np.uint64)
        masks = np.uint64(0xFFFFFFFFFFFFFFFF) >> (np.uint64(63) - b)
        partial = bits.popcount64(self.words[wi] & masks).astype(np.int64)
        return cum[wi] + partial

    def select(self, j: int) -> int:
        return bits.select_in_words(self.words, j)

    def select_many(self, js: np.ndarray) -> np.ndarray:
        # one vectorized unpack of the whole word form answers any batch
        return self.to_array()[np.asarray(js, dtype=np.int64)]

    def next_value(self, from_value: int) -> int:
        w = from_value >> 6
        masked = self.words[w] >> np.uint64(from_value & 63)
        if masked != 0:
            return from_value + int(masked & (~masked + np.uint64(1))).bit_length() - 1
        nz = np.nonzero(self.words[w + 1 :])[0]
        if nz.size == 0:
            return -1
        ww = w + 1 + int(nz[0])
        word = int(self.words[ww])
        return (ww << 6) + (word & -word).bit_length() - 1

    def previous_value(self, from_value: int) -> int:
        w = from_value >> 6
        masked = self.words[w] << np.uint64(63 - (from_value & 63))
        if masked != 0:
            return from_value - (64 - int(masked).bit_length())
        nz = np.nonzero(self.words[:w])[0]
        if nz.size == 0:
            return -1
        ww = int(nz[-1])
        return (ww << 6) + int(self.words[ww]).bit_length() - 1

    _ALL64 = (1 << 64) - 1

    def next_absent_value(self, from_value: int) -> int:
        """Word-level (BitmapContainer.nextAbsentValue): first zero bit >=
        from_value, without the base class's full 65536-bit unpack."""
        w = from_value >> 6
        cur = (~int(self.words[w]) & self._ALL64) >> (from_value & 63)
        if cur:
            return from_value + (cur & -cur).bit_length() - 1
        inv = ~self.words[w + 1 :]
        nz = np.nonzero(inv)[0]
        if nz.size == 0:
            return 1 << 16
        ww = w + 1 + int(nz[0])
        word = int(inv[nz[0]])
        return (ww << 6) + (word & -word).bit_length() - 1

    def previous_absent_value(self, from_value: int) -> int:
        """Last zero bit <= from_value, or -1 when [0, from_value] is full."""
        w = from_value >> 6
        cur = (~int(self.words[w]) & self._ALL64) & ((1 << ((from_value & 63) + 1)) - 1)
        if cur:
            return (w << 6) + cur.bit_length() - 1
        inv = ~self.words[:w]
        nz = np.nonzero(inv)[0]
        if nz.size == 0:
            return -1
        ww = int(nz[-1])
        return (ww << 6) + int(inv[ww]).bit_length() - 1


# ---------------------------------------------------------------------------


def _intervals_of(c: Container):
    """Disjoint sorted half-open [start, end) int64 intervals of a container.

    Cheap for run (direct) and array (runs_from_values); bitmap goes through
    its value array — callers avoid that path for dense operands."""
    if isinstance(c, RunContainer):
        s = c.starts.astype(np.int64)
        return s, s + c.lengths.astype(np.int64) + 1
    rs, rl = bits.runs_from_values(c.to_array())
    s = rs.astype(np.int64)
    return s, s + rl.astype(np.int64) + 1


def _interval_op(as_, ae, bs, be, op):
    """Boolean algebra on two disjoint-interval sets, fully vectorized.

    The membership function of each side is piecewise-constant with
    breakpoints at its interval bounds; between consecutive breakpoints of
    the union both are constant, so evaluating ``op`` per segment and
    merging adjacent kept segments yields the exact result intervals.
    Replaces the reference's per-type two-pointer merges
    (RunContainer.java:590-900 and/or/xor/andNot) with one O((m+n)log(m+n))
    kernel shared by all four ops."""
    pts = np.unique(np.concatenate([as_, ae, bs, be]))
    if pts.size == 0:
        empty = np.empty(0, dtype=np.int64)
        return empty, empty
    seg = pts[:-1]
    in_a = np.searchsorted(as_, seg, side="right") > np.searchsorted(ae, seg, side="right")
    in_b = np.searchsorted(bs, seg, side="right") > np.searchsorted(be, seg, side="right")
    keep = op(in_a, in_b)
    # rb-ok: dtype-discipline -- diffs of a boolean mask are in {-1, 0, 1}
    change = np.diff(keep.astype(np.int8), prepend=np.int8(0), append=np.int8(0))
    return pts[change == 1], pts[np.nonzero(change == -1)[0]]


def _container_of_intervals(out_s: np.ndarray, out_e: np.ndarray) -> Container:
    """Best container for disjoint half-open intervals, by the reference's
    size rule (RunContainer.toEfficientContainer, RunContainer.java:691):
    run iff 2+4·nruns is smallest (ties keep the run, matching
    RunContainer.run_optimize), else array (≤4096) else bitmap."""
    card = int((out_e - out_s).sum())
    if card == 0:
        return ArrayContainer()
    nruns = int(out_s.size)
    run_size = RunContainer.serialized_size_for(nruns)
    other = 8192 if card > ARRAY_MAX_SIZE else 2 + 2 * card
    if run_size <= other:
        return RunContainer(
            out_s.astype(np.uint16), (out_e - out_s - 1).astype(np.uint16)
        )
    if card <= ARRAY_MAX_SIZE:
        return ArrayContainer(
            bits.values_from_runs(out_s.astype(np.uint16), (out_e - out_s - 1).astype(np.uint16))
        )
    return BitmapContainer(bits.words_from_intervals(out_s, out_e), card)


def _run_contains_many(run: "RunContainer", values: np.ndarray) -> np.ndarray:
    """Vectorized membership of uint16 values in a RunContainer."""
    if run.starts.size == 0:
        return np.zeros(values.size, dtype=bool)
    v = values.astype(np.int64)
    idx = np.searchsorted(run.starts.astype(np.int64), v, side="right") - 1
    valid = idx >= 0
    idx = np.clip(idx, 0, run.starts.size - 1)
    s = run.starts.astype(np.int64)[idx]
    e = s + run.lengths.astype(np.int64)[idx]
    return valid & (v >= s) & (v <= e)


class RunContainer(Container):
    """Run-length encoded: (start, length) pairs, run = [start, start+length]
    (RunContainer.java interleaved char pairs; serialized 2 + 4*nruns bytes,
    RunContainer.java:78)."""

    TYPE = RUN_TYPE
    __slots__ = ("starts", "lengths", "_card")

    def __init__(self, starts=None, lengths=None):
        self.starts = _as_u16(starts if starts is not None else [])
        self.lengths = _as_u16(lengths if lengths is not None else [])
        # run payloads are copy-on-write (every mutating op returns a new
        # container), so cardinality is computed at most once
        self._card = -1

    @staticmethod
    def from_values(values: np.ndarray) -> "RunContainer":
        s, l = bits.runs_from_values(values)
        return RunContainer(s, l)

    @staticmethod
    def serialized_size_for(nruns: int) -> int:
        return 2 + 4 * nruns  # RunContainer.java:78

    @property
    def cardinality(self) -> int:
        if self._card < 0:
            self._card = int(self.lengths.astype(np.int64).sum()) + int(self.starts.size)
        return self._card

    def to_array(self) -> np.ndarray:
        return bits.values_from_runs(self.starts, self.lengths)

    def to_words(self) -> np.ndarray:
        s = self.starts.astype(np.int64)
        return bits.words_from_intervals(s, s + self.lengths.astype(np.int64) + 1)

    def num_runs(self) -> int:
        return int(self.starts.size)

    def clone(self) -> "RunContainer":
        out = RunContainer.__new__(RunContainer)
        out.starts = self.starts.copy()
        out.lengths = self.lengths.copy()
        out._card = self._card
        return out

    def serialized_size(self) -> int:
        return self.serialized_size_for(self.num_runs())

    def contains(self, x: int) -> bool:
        # scalar fast path: one C probe over (starts, lengths) — or one
        # searchsorted when no ext — instead of the vectorized
        # _run_contains_many machinery (~8x less overhead per point probe)
        e = _EXT_RUNCONTAINS
        if e is None and not _EXT_PROBES_TRIED:
            _bind_scalar_probes()
            e = _EXT_RUNCONTAINS
        starts = self.starts
        # mapped twins hold strided zero-copy views the ext rejects; a
        # flags check is ~100 ns vs a raised-and-caught TypeError per probe
        if e is not None and starts.flags.c_contiguous:
            return e(starts, self.lengths, x)
        i = int(np.searchsorted(starts, x, side="right")) - 1
        if i < 0:
            return False
        return x - int(starts[i]) <= int(self.lengths[i])

    def contains_many(self, values: np.ndarray) -> np.ndarray:
        return _run_contains_many(self, values)

    def add(self, x: int) -> Container:
        if self.contains(x):
            return self
        return _mutate_via_words(self, lambda w: bits.set_bit(w, x))

    def remove(self, x: int) -> Container:
        if not self.contains(x):
            return self
        return _mutate_via_words(self, lambda w: bits.clear_bit(w, x))

    def run_optimize(self) -> Container:
        # RunContainer.toEfficientContainer (RunContainer.java:691)
        card = self.cardinality
        run_size = self.serialized_size()
        other = 8192 if card > ARRAY_MAX_SIZE else 2 + 2 * card
        if run_size <= other:
            return self
        return self.to_efficient_non_run()

    def _interval_binary(self, other: Container, op) -> Container:
        """Run-space algebra with run/array operands (RunContainer.java:590-900
        re-expressed as one vectorized interval kernel, no word expansion)."""
        as_, ae = _intervals_of(self)
        bs, be = _intervals_of(other)
        return _container_of_intervals(*_interval_op(as_, ae, bs, be, op))

    def and_(self, other: Container) -> Container:
        if isinstance(other, ArrayContainer):
            return _wrap_u16(other.content[_run_contains_many(self, other.content)])
        if isinstance(other, RunContainer):
            return self._interval_binary(other, np.logical_and)
        # run x bitmap: words are the natural shape for the dense side
        return best_container_of_words(self.to_words() & other.words)

    def or_(self, other: Container) -> Container:
        if isinstance(other, (RunContainer, ArrayContainer)):
            if self.is_full():
                return self.clone()
            return self._interval_binary(other, np.logical_or)
        return best_container_of_words(self.to_words() | other.words)

    def xor_(self, other: Container) -> Container:
        if isinstance(other, (RunContainer, ArrayContainer)):
            return self._interval_binary(other, np.logical_xor)
        return best_container_of_words(self.to_words() ^ other.words)

    def andnot(self, other: Container) -> Container:
        if isinstance(other, (RunContainer, ArrayContainer)):
            return self._interval_binary(other, lambda a, b: a & ~b)
        return best_container_of_words(self.to_words() & ~other.words)

    def and_cardinality(self, other: Container) -> int:
        if isinstance(other, ArrayContainer):
            return int(_run_contains_many(self, other.content).sum())
        if isinstance(other, RunContainer):
            as_, ae = _intervals_of(self)
            bs, be = _intervals_of(other)
            s, e = _interval_op(as_, ae, bs, be, np.logical_and)
            return int((e - s).sum())
        return bits.cardinality_of_words(self.to_words() & other.words)

    def rank(self, x: int) -> int:
        s = self.starts.astype(np.int64)
        e = s + self.lengths.astype(np.int64)
        full = s <= x
        contrib = np.where(full, np.minimum(e, x) - s + 1, 0)
        return int(contrib.sum())

    def rank_many(self, lows: np.ndarray) -> np.ndarray:
        s = self.starts.astype(np.int64)
        lens = self.lengths.astype(np.int64) + 1
        cum = np.concatenate(([0], np.cumsum(lens)))  # exclusive prefix
        lows = np.asarray(lows, dtype=np.int64)
        i = np.searchsorted(s, lows, side="right") - 1  # last run with start <= x
        safe = np.maximum(i, 0)
        # full runs before run i, plus the in-run contribution clipped to
        # its length (0 when the probe precedes every run)
        inside = np.where(i >= 0, np.clip(lows - s[safe] + 1, 0, lens[safe]), 0)
        return np.where(i >= 0, cum[safe], 0) + inside

    def select_many(self, js: np.ndarray) -> np.ndarray:
        lens = self.lengths.astype(np.int64) + 1
        cum = np.concatenate(([0], np.cumsum(lens)))  # exclusive prefix
        js = np.asarray(js, dtype=np.int64)
        i = np.searchsorted(cum, js, side="right") - 1  # run holding rank j
        return (self.starts.astype(np.int64)[i] + (js - cum[i])).astype(np.uint16)

    def select(self, j: int) -> int:
        lens = self.lengths.astype(np.int64) + 1
        cum = np.cumsum(lens)
        r = int(np.searchsorted(cum, j + 1))
        if r >= self.starts.size:
            raise IndexError(f"select({j})")
        prior = int(cum[r - 1]) if r else 0
        return int(self.starts[r]) + (j - prior)

    def next_value(self, from_value: int) -> int:
        if self.starts.size == 0:
            return -1
        s = self.starts.astype(np.int64)
        e = s + self.lengths.astype(np.int64)
        i = int(np.searchsorted(e, from_value))
        if i >= s.size:
            return -1
        return int(max(from_value, s[i]))

    def previous_value(self, from_value: int) -> int:
        if self.starts.size == 0:
            return -1
        s = self.starts.astype(np.int64)
        e = s + self.lengths.astype(np.int64)
        i = int(np.searchsorted(s, from_value, side="right")) - 1
        if i < 0:
            return -1
        return int(min(from_value, e[i]))

    def next_absent_value(self, from_value: int) -> int:
        """Run-space (RunContainer.nextAbsentValue): if from_value falls in
        a run, the answer is one past that run's end — normalized runs never
        touch, so that position is absent (or 65536 past the universe)."""
        s = self.starts.astype(np.int64)
        i = int(np.searchsorted(s, from_value, side="right")) - 1
        if i >= 0 and from_value <= int(s[i]) + int(self.lengths[i]):
            return int(s[i]) + int(self.lengths[i]) + 1
        return from_value

    def previous_absent_value(self, from_value: int) -> int:
        """Run-space twin: one before the covering run's start (absent by
        the no-touching invariant), or -1 when that run starts at 0."""
        s = self.starts.astype(np.int64)
        i = int(np.searchsorted(s, from_value, side="right")) - 1
        if i >= 0 and from_value <= int(s[i]) + int(self.lengths[i]):
            return int(s[i]) - 1
        return from_value

    def is_full(self) -> bool:
        return self.num_runs() == 1 and self.starts[0] == 0 and self.lengths[0] == 0xFFFF


def _mutate_via_words(c: Container, fn) -> Container:
    words = c.to_words()
    fn(words)
    new = best_container_of_words(words)
    if isinstance(c, RunContainer):
        return new.run_optimize()
    return new


# ---------------------------------------------------------------------------


def best_container_of_words(words: np.ndarray) -> Container:
    """Array if card <= 4096, else Bitmap (the reference's conversion rule)."""
    card = bits.cardinality_of_words(words)
    if card <= ARRAY_MAX_SIZE:
        return ArrayContainer(bits.values_from_words(words))
    return BitmapContainer(words, card)


def container_from_values(values: np.ndarray) -> Container:
    """Best non-run container from sorted unique uint16 values."""
    v = _as_u16(values)
    if v.size > ARRAY_MAX_SIZE:
        return BitmapContainer(bits.words_from_values(v), int(v.size))
    return ArrayContainer(v)


def container_range_of_ones(start: int, end: int) -> Container:
    """Container holding [start, end) — Container.rangeOfOnes
    (Container.java:29-37): array below the 2-value threshold, else run."""
    n = end - start
    if n <= 2:
        return ArrayContainer(np.arange(start, end, dtype=np.uint16))
    c = RunContainer(
        np.array([start], dtype=np.uint16), np.array([n - 1], dtype=np.uint16)
    )
    c._card = n
    return c
