"""L5' bit-sliced index (BSI) — the reference's ``bsi/`` module rebuilt
TPU-first.

Logical model matches BitmapSliceIndex (bsi/.../BitmapSliceIndex.java:22,
vertical layout :45-60): ``ebm`` = existence bitmap over column ids,
``slices[i]`` = bitmap of columns whose value has bit i set. Queries are the
O'Neil compare (RoaringBitmapSliceIndex.java:432-469: one pass high->low
maintaining GT/LT/EQ bitmaps), with the min/max short-circuit (:515-578),
``sum`` (:581-592), element-wise ``add`` with ripple carry (:66-95) and
disjoint ``merge`` (:379).

TPU inversion: a 32-slice compare is ~96 whole-bitmap AND/OR/ANDNOT ops
(SURVEY §3.5) — here the entire chain runs as ONE ``lax.scan`` over a dense
``[S, K, 2048]`` device tensor (slices x key-chunks x words), with the
GT/LT/EQ state carried as ``[K, 2048]`` blocks, and ``sum`` as a
popcount-weighted batched reduce. Construction is vectorized: building from
a (columns, values) array materializes each slice from one boolean mask,
not per-column point inserts.

Serialization: the reference's ByteBuffer layout (RoaringBitmapSliceIndex
.serialize(ByteBuffer) :240-255): int32 minValue, int32 maxValue, byte
runOptimized, ebm, int32 sliceCount, slices — little-endian.
"""

from __future__ import annotations

import enum
import functools
import struct
from typing import Iterable, List, Optional, Tuple

import numpy as np

from .roaring import RoaringBitmap
from ..serialization import InvalidRoaringFormat, read_into


class Operation(enum.Enum):
    """Compare ops (BitmapSliceIndex.java:23-38)."""

    EQ = "EQ"
    NEQ = "NEQ"
    LE = "LE"
    LT = "LT"
    GE = "GE"
    GT = "GT"
    RANGE = "RANGE"


class config:
    mode: str = "auto"  # 'auto' | 'cpu' | 'device'
    # slices x key-chunks below which the CPU path wins; device dispatch has
    # a fixed per-call cost (worst on tunneled dev chips) that only pays off
    # on large indexes (the 100M-row north-star is ~49k cells)
    min_device_cells = 4096
    # jax.sharding.Mesh: when set, device compare/sum dispatches run sharded
    # over the (containers, words) mesh (parallel/sharding.py) — the key-chunk
    # axis is padded up to the containers-axis size with empty chunks
    mesh = None


def min_max_verdict(op, start_or_value, end, mn, mx):
    """compareUsingMinMax (RoaringBitmapSliceIndex.java:515-578) as a pure
    symbol — 'all' | 'empty' | 'fixed' | None — shared by the 32- and
    64-bit indexes so the materializing and count-only callers each pay
    only for what they return (no eager ebm clone on the no-shortcut
    path). 'fixed' = the raw fixed set for out-of-range NEQ (Java keeps
    found_set un-intersected there); avoids the slice walk seeing a
    bit-truncated predicate (strictly more correct than the reference,
    which truncates)."""
    v = start_or_value
    if op == Operation.LT:
        if v > mx:
            return "all"
        if v <= mn:
            return "empty"
    elif op == Operation.LE:
        if v >= mx:
            return "all"
        if v < mn:
            return "empty"
    elif op == Operation.GT:
        if v < mn:
            return "all"
        if v >= mx:
            return "empty"
    elif op == Operation.GE:
        if v <= mn:
            return "all"
        if v > mx:
            return "empty"
    elif op == Operation.EQ:
        if mn == mx and mn == v:
            return "all"
        if v < mn or v > mx:
            return "empty"
    elif op == Operation.NEQ:
        if mn == mx:
            return "empty" if mn == v else "all"
        if v < mn or v > mx:
            return "fixed"
    elif op == Operation.RANGE:
        if v <= mn and end >= mx:
            return "all"
        if v > mx or end < mn:
            return "empty"
    return None


def values_for_columns(cols: np.ndarray, slices, dtype=np.int64) -> np.ndarray:
    """Reassemble the stored value of each column from the slice bitmaps:
    one vectorized membership mask per slice, bits OR'd back together.
    Shared by every transpose/to_pair_list variant (32/64-bit, buffer)."""
    values = np.zeros(cols.size, dtype=dtype)
    for i, s in enumerate(slices):
        members = np.isin(cols, s.to_array(), assume_unique=True)
        values |= np.left_shift(members.astype(dtype), dtype(i))
    return values


def _bulk_get_values(index, cols: np.ndarray):
    """Shared bulk-read core for both BSI widths (32-bit get_values and
    bsi64's twin): one ``contains_many`` membership pass per slice into an
    int64 accumulator. Above 63 slices, bit 63+ would wrap the accumulator
    (and numpy shifts >= 64 are undefined), so that domain — which
    set_value accepts as arbitrary Python ints — falls back to exact
    per-column object-dtype reads."""
    exists = index.ebm.contains_many(cols)
    if index.bit_count() > 63:
        values = np.array(
            [index.get_value(int(c))[0] if e else 0 for c, e in zip(cols, exists)],
            dtype=object,
        )
        return values, exists
    values = np.zeros(cols.shape, dtype=np.int64)
    if not exists.any():
        return values, exists
    for i, s in enumerate(index.slices):
        values |= s.contains_many(cols).astype(np.int64) << i
    values[~exists] = 0
    return values, exists


def transpose_value_counts(cols: np.ndarray, slices, dtype=np.int64):
    """(distinct values, multiplicities) over the given columns — the shared
    body of every transposeWithCount twin (BitSliceIndexBase.java:578,
    Roaring64BitmapSliceIndex.java:603)."""
    return np.unique(values_for_columns(cols, slices, dtype=dtype), return_counts=True)


class RoaringBitmapSliceIndex:
    """32-bit-value BSI over 32-bit column ids (RoaringBitmapSliceIndex.java)."""

    def __init__(self, min_value: int = 0, max_value: int = 0):
        self.min_value = int(min_value)
        self.max_value = int(max_value)
        self.ebm = RoaringBitmap()
        self.slices: List[RoaringBitmap] = [
            RoaringBitmap() for _ in range(max(0, int(max_value)).bit_length())
        ]
        self.run_optimized = False
        # mutation counter kept for subclasses/diagnostics; the device pack
        # is keyed by member-bitmap fingerprints in the shared PACK_CACHE
        # (parallel/store.py) since ISSUE 4, not by this counter
        self._version = 0

    # ------------------------------------------------------------------
    # construction
    # ------------------------------------------------------------------
    def bit_count(self) -> int:
        return len(self.slices)

    def _grow(self, bit_depth: int) -> None:
        while len(self.slices) < bit_depth:
            self.slices.append(RoaringBitmap())

    def _ensure_capacity(self, lo: int, hi: int) -> None:
        # ensureCapacityInternal (RoaringBitmapSliceIndex.java:315-326)
        if self.ebm.is_empty():
            self.min_value, self.max_value = lo, hi
            self._grow(max(1, hi.bit_length()))
        else:
            if lo < self.min_value:
                self.min_value = lo
            if hi > self.max_value:
                self.max_value = hi
                self._grow(max(1, hi.bit_length()))

    def set_value(self, column_id: int, value: int) -> None:
        """setValue (RoaringBitmapSliceIndex.java:299) — single-column
        compatibility shim, O(bit_depth) bitmap point-updates per call.

        Bulk ingest should use :meth:`set_values`, which builds every slice
        from one vectorized mask over the whole value array (~1000x faster
        per column at scale, and the path every benchmark and the 100M-row
        north star use). The remove() per unset bit below is only needed
        when overwriting an existing column; fresh columns skip it."""
        value = int(value)
        if value < 0:
            raise ValueError("BSI values must be non-negative")
        self._ensure_capacity(value, value)
        overwriting = self.ebm.contains(column_id)
        for i in range(self.bit_count()):
            if (value >> i) & 1:
                self.slices[i].add(column_id)
            elif overwriting:
                self.slices[i].remove(column_id)
        self.ebm.add(column_id)
        self._version += 1

    def set_values(self, pairs) -> None:
        """Vectorized bulk construction (setValues,
        RoaringBitmapSliceIndex.java:349): each slice is built from one
        boolean mask over the value array.

        Input is either a 2-tuple ``(columns, values)`` of parallel arrays,
        or any other iterable of ``(column, value)`` pairs. Duplicate columns
        follow last-pair-wins, matching sequential ``set_value`` calls."""
        if isinstance(pairs, tuple) and len(pairs) == 2:
            cols, vals = pairs
        else:
            seq = list(pairs)
            if not seq:
                return
            cols = [p[0] for p in seq]
            vals = [p[1] for p in seq]
        cols = np.asarray(cols, dtype=np.uint32)
        vals = np.asarray(vals, dtype=np.int64)
        if cols.size == 0:
            return
        # last-pair-wins for duplicate columns within the batch
        _, last_idx = np.unique(cols[::-1], return_index=True)
        keep = np.sort(cols.size - 1 - last_idx)
        if keep.size != cols.size:
            cols, vals = cols[keep], vals[keep]
        if vals.min() < 0:
            raise ValueError("BSI values must be non-negative")
        self._ensure_capacity(int(vals.min()), int(vals.max()))
        # columns already present must have their old bits cleared first
        if not self.ebm.is_empty():
            existing = RoaringBitmap(cols)
            overlap = RoaringBitmap.and_(self.ebm, existing)
            if not overlap.is_empty():
                for s in self.slices:
                    s.iandnot(overlap)
        for i in range(self.bit_count()):
            mask = (vals >> i) & 1 == 1
            if mask.any():
                self.slices[i].add_many(cols[mask])
        self.ebm.add_many(cols)
        self._version += 1

    def get_value(self, column_id: int) -> Tuple[int, bool]:
        """(value, exists) (getValue, RoaringBitmapSliceIndex.java:181) —
        single-column compatibility shim, one point-``contains`` per slice.

        Reading many columns should use :meth:`get_values`, which answers
        the whole batch with one vectorized membership pass per slice
        instead of O(bit_count) point probes per column."""
        if not self.ebm.contains(column_id):
            return 0, False
        value = 0
        for i, s in enumerate(self.slices):
            if s.contains(column_id):
                value |= 1 << i
        return value, True

    def get_values(self, columns) -> Tuple[np.ndarray, np.ndarray]:
        """Vectorized bulk read: ``(values, exists)`` int64/bool arrays
        parallel to ``columns`` (an exact object-dtype array when the index
        holds more than 63 slices, where int64 would wrap).

        The bulk twin of :meth:`get_value` (the reference answers batch
        reads one getValue at a time, RoaringBitmapSliceIndex.java:181):
        each slice contributes its bit to every queried column via one
        ``contains_many`` membership pass, so the cost is O(bit_count)
        vectorized passes instead of O(bit_count * len(columns)) point
        probes. Columns absent from the index read as value 0 with
        ``exists`` False."""
        return _bulk_get_values(self, np.asarray(columns, dtype=np.uint32).ravel())

    def value_exist(self, column_id: int) -> bool:
        return self.ebm.contains(column_id)

    def get_existence_bitmap(self) -> RoaringBitmap:
        return self.ebm

    def get_cardinality(self) -> int:
        return self.ebm.get_cardinality()

    def clone(self) -> "RoaringBitmapSliceIndex":
        out = RoaringBitmapSliceIndex(self.min_value, self.max_value)
        out.ebm = self.ebm.clone()
        out.slices = [s.clone() for s in self.slices]
        out.run_optimized = self.run_optimized
        return out

    def run_optimize(self) -> None:
        self.ebm.run_optimize()
        for s in self.slices:
            s.run_optimize()
        self.run_optimized = True
        self._version += 1

    def has_run_compression(self) -> bool:
        """True when any member bitmap holds a run container
        (hasRunCompression, MutableBitSliceIndex.java:117)."""
        return self.ebm.has_run_compression() or any(
            s.has_run_compression() for s in self.slices
        )

    # ------------------------------------------------------------------
    # combination
    # ------------------------------------------------------------------
    def merge(self, other: "RoaringBitmapSliceIndex") -> None:
        """Disjoint-column merge (RoaringBitmapSliceIndex.java:379)."""
        if other is None or other.ebm.is_empty():
            return
        if RoaringBitmap.intersects(self.ebm, other.ebm):
            raise ValueError("merge requires disjoint column sets")
        depth = max(self.bit_count(), other.bit_count())
        self._grow(depth)
        for i in range(other.bit_count()):
            self.slices[i].ior(other.slices[i])
        self.ebm.ior(other.ebm)
        if not self.ebm.is_empty():
            self.min_value = min(self.min_value, other.min_value)
            self.max_value = max(self.max_value, other.max_value)
        self._version += 1

    def add(self, other: "RoaringBitmapSliceIndex") -> None:
        """Element-wise sum with ripple carry (add/addDigit,
        RoaringBitmapSliceIndex.java:66-95)."""
        if other is None or other.ebm.is_empty():
            return
        self.ebm.ior(other.ebm)
        if other.bit_count() > self.bit_count():
            self._grow(other.bit_count())
        for i in range(other.bit_count()):
            self._add_digit(other.slices[i], i)
        self.min_value = self._min_value()
        self.max_value = self._max_value()
        self._version += 1

    def _add_digit(self, found_set: RoaringBitmap, i: int) -> None:
        carry = RoaringBitmap.and_(self.slices[i], found_set)
        self.slices[i].ixor(found_set)
        if not carry.is_empty():
            if i + 1 >= self.bit_count():
                self._grow(self.bit_count() + 1)
            self._add_digit(carry, i + 1)

    def _min_value(self) -> int:
        if self.ebm.is_empty():
            return 0
        ids = self.ebm
        for i in range(self.bit_count() - 1, -1, -1):
            tmp = RoaringBitmap.andnot(ids, self.slices[i])
            if not tmp.is_empty():
                ids = tmp
        return self.get_value(ids.first())[0]

    def _max_value(self) -> int:
        if self.ebm.is_empty():
            return 0
        ids = self.ebm
        for i in range(self.bit_count() - 1, -1, -1):
            tmp = RoaringBitmap.and_(ids, self.slices[i])
            if not tmp.is_empty():
                ids = tmp
        return self.get_value(ids.first())[0]

    # ------------------------------------------------------------------
    # queries
    # ------------------------------------------------------------------
    def compare(
        self,
        operation: Operation,
        start_or_value: int,
        end: int = 0,
        found_set: Optional[RoaringBitmap] = None,
        mode: Optional[str] = None,
    ) -> RoaringBitmap:
        """compare (RoaringBitmapSliceIndex.java:482-513): min/max
        short-circuit then O'Neil."""
        res = self._compare_using_min_max(operation, start_or_value, end, found_set)
        if res is not None:
            return res
        if operation == Operation.RANGE:
            # clamp the upper bound to the representable bit depth: the slice
            # walk only sees bit_count() bits, and every stored value fits in
            # them, so LE(end) == LE(clamped) — without this, an oversized
            # `end` would be silently truncated to its low bits
            end = min(int(end), (1 << self.bit_count()) - 1)
            if self._use_device(mode):
                # both slice walks + AND fused into one device dispatch
                return self._o_neil_device(
                    Operation.RANGE, start_or_value, found_set, end=end
                )
            left = self._o_neil(Operation.GE, start_or_value, found_set, mode)
            right = self._o_neil(Operation.LE, end, found_set, mode)
            return RoaringBitmap.and_(left, right)
        return self._o_neil(operation, start_or_value, found_set, mode)

    def _min_max_verdict(self, op, start_or_value, end):
        return min_max_verdict(op, start_or_value, end, self.min_value, self.max_value)

    def _compare_using_min_max(self, op, start_or_value, end, found_set):
        verdict = self._min_max_verdict(op, start_or_value, end)
        if verdict is None:
            return None
        if verdict == "empty":
            return RoaringBitmap()
        if verdict == "fixed":
            return self.ebm.clone() if found_set is None else found_set.clone()
        return (
            self.ebm.clone()
            if found_set is None
            else RoaringBitmap.and_(self.ebm, found_set)
        )

    def _use_device(self, mode: Optional[str]) -> bool:
        mode = mode or config.mode
        if mode == "cpu":
            return False
        if mode == "device":
            return True
        try:
            import jax

            backend = jax.default_backend()
        except (ImportError, RuntimeError):  # no jax / no usable backend
            return False
        cells = self.bit_count() * self.ebm.get_container_count()
        return backend != "cpu" and cells >= config.min_device_cells

    def _o_neil(self, op, predicate, found_set, mode=None) -> RoaringBitmap:
        if self._use_device(mode):
            return self._o_neil_device(op, predicate, found_set)
        return self._o_neil_cpu(op, predicate, found_set)

    def _o_neil_cpu(self, op, predicate, found_set) -> RoaringBitmap:
        """oNeilCompare (RoaringBitmapSliceIndex.java:432-469)."""
        fixed = self.ebm if found_set is None else found_set
        gt, lt, eq = RoaringBitmap(), RoaringBitmap(), self.ebm
        for i in range(self.bit_count() - 1, -1, -1):
            if (predicate >> i) & 1:
                lt = RoaringBitmap.or_(lt, RoaringBitmap.andnot(eq, self.slices[i]))
                eq = RoaringBitmap.and_(eq, self.slices[i])
            else:
                gt = RoaringBitmap.or_(gt, RoaringBitmap.and_(eq, self.slices[i]))
                eq = RoaringBitmap.andnot(eq, self.slices[i])
        eq = RoaringBitmap.and_(fixed, eq)
        return self._finish(op, gt, lt, eq, fixed)

    @staticmethod
    def _finish(op, gt, lt, eq, fixed) -> RoaringBitmap:
        if op == Operation.EQ:
            return eq
        if op == Operation.NEQ:
            return RoaringBitmap.andnot(fixed, eq)
        if op == Operation.GT:
            return RoaringBitmap.and_(gt, fixed)
        if op == Operation.LT:
            return RoaringBitmap.and_(lt, fixed)
        if op == Operation.LE:
            return RoaringBitmap.and_(RoaringBitmap.or_(lt, eq), fixed)
        if op == Operation.GE:
            return RoaringBitmap.and_(RoaringBitmap.or_(gt, eq), fixed)
        raise ValueError(f"unsupported operation {op}")

    # ---- device path --------------------------------------------------
    def _pack_dense(self):
        """[S, K, 2048] slice tensor + [K, 2048] ebm over the ebm's keys,
        resident in the process-wide pack cache (parallel/store.PACK_CACHE,
        ISSUE 4) under the member bitmaps' fingerprints — repeat queries
        skip the host-side marshal entirely, BSI tensors share ONE byte
        budget and LRU with the aggregation/query packs, and any mutation
        (including one that bypasses this object and touches a slice bitmap
        directly) re-keys the entry so the stale pack ages out."""
        from ..parallel import store

        key = (
            "bsi",
            self.ebm.fingerprint(),
            tuple(s.fingerprint() for s in self.slices),
        )

        def build():
            import jax.numpy as jnp

            from ..ops import device as dev
            from ..parallel.store import container_words_u32

            keys = list(self.ebm.high_low_container.keys)
            kidx = {k: i for i, k in enumerate(keys)}
            K = len(keys)
            S = self.bit_count()
            ebm_w = np.zeros((K, dev.DEVICE_WORDS), dtype=np.uint32)
            for k, c in zip(keys, self.ebm.high_low_container.containers):
                ebm_w[kidx[k]] = container_words_u32(c)
            slices_w = np.zeros((S, K, dev.DEVICE_WORDS), dtype=np.uint32)
            for i, s in enumerate(self.slices):
                hlc = s.high_low_container
                for k, c in zip(hlc.keys, hlc.containers):
                    j = kidx.get(k)
                    if j is not None:
                        slices_w[i, j] = container_words_u32(c)
            value = (keys, jnp.asarray(ebm_w), jnp.asarray(slices_w))
            return value, int(ebm_w.nbytes) + int(slices_w.nbytes)

        return store.PACK_CACHE.get_or_build(
            key, build, refs=store.static_fp_refs([self.ebm] + list(self.slices))
        )

    @staticmethod
    def _found_words(keys, shape, found_set: RoaringBitmap):
        """found_set marshalled onto the packed key layout: [K, 2048]."""
        import jax.numpy as jnp

        from ..parallel import store

        fixed_np = np.zeros(shape, dtype=np.uint32)
        kidx = {k: i for i, k in enumerate(keys)}
        hlc = found_set.high_low_container
        for k, c in zip(hlc.keys, hlc.containers):
            j = kidx.get(k)
            if j is not None:
                fixed_np[j] = store.container_words_u32(c)
        return jnp.asarray(fixed_np)

    def _sum_device(self, found_set: RoaringBitmap) -> int:
        """Σ 2^i · |bA[i] ∩ found| in ONE device dispatch: the packed
        [S, K, 2048] tensor is masked by the found words and per-(slice,
        chunk) popcounts come back; the 2^i weighting runs host-side in
        exact python ints (S can exceed 62 bits in theory)."""
        keys, ebm_w, slices_w = self._pack_dense()
        found_w = self._found_words(keys, ebm_w.shape, found_set)
        if config.mesh is not None:
            from ..parallel import sharding

            s3, f2 = _pad_chunk_axis(config.mesh, slices_w, found_w)
            per_chunk = np.asarray(sharding.distributed_bsi_sum(config.mesh)(s3, f2))
        else:
            per_chunk = np.asarray(_slice_masked_popcounts(slices_w, found_w))
        per_slice = per_chunk.astype(object).sum(axis=1)  # exact python ints
        return sum(int(c) << i for i, c in enumerate(per_slice.tolist()))

    def compare_cardinality(
        self,
        operation: Operation,
        start_or_value: int,
        end: int = 0,
        found_set: Optional[RoaringBitmap] = None,
        mode: Optional[str] = None,
    ) -> int:
        """Count-only compare: the device path fetches ONLY the per-chunk
        popcounts — no result words, no container rebuild. This generalizes
        the reference RangeBitmap's *Cardinality query family
        (RangeBitmap.java:111-414) to the BSI, where the reference has no
        count-only variant."""
        verdict = self._min_max_verdict(operation, start_or_value, end)
        if verdict == "empty":
            return 0
        if verdict == "fixed":
            return (self.ebm if found_set is None else found_set).get_cardinality()
        if verdict == "all":
            if found_set is None:
                return self.ebm.get_cardinality()
            return RoaringBitmap.and_cardinality(self.ebm, found_set)
        if self._use_device(mode):
            if operation == Operation.RANGE:
                end = min(int(end), (1 << self.bit_count()) - 1)
            keys, _out, cards, fixed_bm = self._o_neil_device_walk(
                operation, start_or_value, found_set, end
            )
            total = int(np.asarray(cards).astype(np.int64).sum())
            if operation == Operation.NEQ and found_set is not None:
                total += self._neq_outside_ebm(fixed_bm, keys)
            return total
        return self.compare(
            operation, start_or_value, end, found_set, mode="cpu"
        ).get_cardinality()

    def compare_cardinality_many(
        self,
        operation: Operation,
        values,
        ends=None,
        found_set: Optional[RoaringBitmap] = None,
        mode: Optional[str] = None,
    ) -> np.ndarray:
        """Count-only compare for a whole batch of predicates in ONE device
        dispatch: ``values`` is a [Q] array of thresholds (plus ``ends`` for
        RANGE), the result a [Q] int64 count array.

        The reference API answers one predicate per call
        (RoaringBitmapSliceIndex.java:482); on TPU that wastes the dominant
        cost — streaming the [S, K, 2048] slice tensor from HBM — Q times.
        Here the fused O'Neil walk is vmapped over the query axis, so all Q
        walks ride a single pass over the resident pack (a multi-tenant /
        per-query-threshold filter answers its whole batch at once)."""

        return _counts_many(
            self,
            operation,
            values,
            ends,
            found_set,
            mode,
            batched_ok=self._use_device(mode),
            pack_fixed=lambda: self._pack_with_fixed(found_set),
            neq_remainder=lambda keys: self._neq_outside_ebm(found_set, keys),
            mesh=config.mesh,
        )

    def _pack_with_fixed(self, found_set: Optional[RoaringBitmap]):
        """(keys, ebm_w, slices_w, fixed_w) — the resident pack plus the
        found-set words marshalled onto its key layout (fixed = ebm when no
        found set); shared by the single- and batched-predicate paths."""
        keys, ebm_w, slices_w = self._pack_dense()
        fixed_w = (
            ebm_w
            if found_set is None
            else self._found_words(keys, ebm_w.shape, found_set)
        )
        return keys, ebm_w, slices_w, fixed_w

    @staticmethod
    def _neq_outside_ebm(found_set: RoaringBitmap, keys) -> int:
        """Count of found-set columns in chunks outside the packed ebm keys
        (disjoint from every packed chunk, so NEQ qualifies them wholesale)
        — a clone-free cardinality walk, no container materialization."""
        kset = set(keys)
        hlc = found_set.high_low_container
        return sum(
            c.cardinality for k, c in zip(hlc.keys, hlc.containers) if k not in kset
        )

    def _o_neil_device_walk(self, op, predicate, found_set, end: int = 0):
        """Run the fused device O'Neil walk; returns (keys, out_device,
        cards_device, fixed_bm) with NOTHING fetched to host — callers
        decide whether to pull the result words (compare) or only the
        popcounts (compare_cardinality)."""
        import jax.numpy as jnp

        keys, ebm_w, slices_w, fixed_w = self._pack_with_fixed(found_set)
        fixed_bm = self.ebm if found_set is None else found_set
        S = self.bit_count()
        bits_vec = np.array(
            [(predicate >> i) & 1 for i in range(S - 1, -1, -1)], dtype=bool
        )
        if op == Operation.RANGE:
            bits_hi = np.array(
                [(end >> i) & 1 for i in range(S - 1, -1, -1)], dtype=bool
            )
            bits_vec = np.stack([bits_vec, bits_hi])

        if config.mesh is not None:
            from ..parallel import sharding

            k_orig = ebm_w.shape[0]
            s3, e2, f2 = _pad_chunk_axis(config.mesh, slices_w, ebm_w, fixed_w)
            out, cards = sharding.distributed_bsi_compare(config.mesh, op.value)(
                s3, jnp.asarray(bits_vec), e2, f2
            )
            out, cards = out[:k_orig], cards[:k_orig]
        else:
            from ..ops import pallas_kernels as pk

            out, cards = pk.best_oneil_compare(
                jnp.asarray(slices_w),
                jnp.asarray(bits_vec),
                jnp.asarray(ebm_w),
                jnp.asarray(fixed_w),
                op.value,
            )
        return keys, out, cards, fixed_bm

    def _o_neil_device(self, op, predicate, found_set, end: int = 0) -> RoaringBitmap:
        """The whole O'Neil chain — scan, op epilogue and popcount — as ONE
        jitted device call (the SURVEY §3.5 batched-kernel target; a single
        dispatch also matters because device round-trips dominate small
        queries). For RANGE, both slice walks (GE lo, LE hi) and the final
        AND run inside the same dispatch."""
        from ..parallel import store

        keys, out, cards, fixed_bm = self._o_neil_device_walk(
            op, predicate, found_set, end
        )
        result = store.unpack_to_bitmap(
            np.asarray(keys, dtype=np.int64),
            np.asarray(out),
            np.asarray(cards).astype(np.int64),
        )
        if op == Operation.NEQ and found_set is not None:
            # found_set columns in key-chunks outside the ebm were not packed;
            # none of them can be EQ, so they all qualify (Java semantics:
            # NEQ = foundSet \ EQ without intersecting foundSet with ebm)
            missing = RoaringBitmap.andnot(fixed_bm, _keys_subset(fixed_bm, set(keys)))
            result = RoaringBitmap.or_(result, missing)
        return result

    def sum(
        self, found_set: Optional[RoaringBitmap] = None, mode: Optional[str] = None
    ) -> Tuple[int, int]:
        """(sum, count) over found columns (RoaringBitmapSliceIndex.java:581-592).
        On the device path the whole popcount-weighted reduce is one
        dispatch over the resident [S, K, 2048] pack (SURVEY §7.7)."""
        if found_set is None or found_set.is_empty():
            return 0, 0
        count = found_set.get_cardinality()
        if self._use_device(mode):
            return self._sum_device(found_set), count
        total = sum(
            (1 << i) * RoaringBitmap.and_cardinality(s, found_set)
            for i, s in enumerate(self.slices)
        )
        return total, count

    def transpose(self, found_set: Optional[RoaringBitmap] = None) -> RoaringBitmap:
        """Bitmap of distinct values over the found columns (the buffer
        base's transpose helper). Vectorized: one membership mask per slice
        over the column array, values reassembled bit-by-bit."""
        cols = (
            self.ebm if found_set is None else RoaringBitmap.and_(self.ebm, found_set)
        ).to_array()
        if cols.size == 0:
            return RoaringBitmap()
        return RoaringBitmap(np.unique(values_for_columns(cols, self.slices)))

    def top_k(self, found_set: Optional[RoaringBitmap], k: int) -> RoaringBitmap:
        """Columns holding the k largest values — MSB-first slice descent
        (buffer BitSliceIndexBase.topK, bsi/.../BitSliceIndexBase.java:303).
        Ties at the cut line are broken by smallest column id."""
        if found_set is None:
            found_set = self.ebm
        if found_set.is_empty() or k <= 0:
            return RoaringBitmap()
        if k >= found_set.get_cardinality():
            return found_set.clone()
        result = RoaringBitmap()
        candidates = found_set.clone()
        for i in range(self.bit_count() - 1, -1, -1):
            if candidates.is_empty() or k <= 0:
                break
            with_bit = RoaringBitmap.and_(candidates, self.slices[i])
            card = with_bit.get_cardinality()
            if card > k:
                candidates = with_bit
            else:
                result.ior(with_bit)
                candidates.iandnot(self.slices[i])
                k -= card
        if k > 0 and not candidates.is_empty():
            result.ior(candidates.limit(k))
        return result

    def to_pair_list(
        self, found_set: Optional[RoaringBitmap] = None
    ) -> List[Tuple[int, int]]:
        """(column, value) pairs (BitSliceIndexBase.toPairList)."""
        cols = (
            self.ebm if found_set is None else RoaringBitmap.and_(self.ebm, found_set)
        ).to_array()
        if cols.size == 0:
            return []
        values = values_for_columns(cols, self.slices)
        return list(zip(cols.tolist(), values.tolist()))

    # ------------------------------------------------------------------
    # serialization (ByteBuffer layout, little-endian)
    # ------------------------------------------------------------------
    def serialize(self) -> bytes:
        parts = [
            struct.pack("<iib", self.min_value, self.max_value, 1 if self.run_optimized else 0),
            self.ebm.serialize(),
            struct.pack("<i", self.bit_count()),
        ]
        parts.extend(s.serialize() for s in self.slices)
        return b"".join(parts)

    def serialized_size_in_bytes(self) -> int:
        from ..serialization import serialized_size_in_bytes

        return (
            4 + 4 + 1 + 4
            + serialized_size_in_bytes(self.ebm)
            + sum(serialized_size_in_bytes(s) for s in self.slices)
        )

    def serialize_into(self, fileobj) -> int:
        """Stream overload (the reference's DataOutput path,
        MutableBitSliceIndex.java:331 serialize(DataOutput)); BSIs written
        back-to-back deserialize back with :meth:`deserialize_from`.
        Returns the byte count written."""
        data = self.serialize()
        fileobj.write(data)
        return len(data)

    @classmethod
    def deserialize_from(cls, fileobj):
        """Stream twin of :meth:`serialize_into`
        (MutableBitSliceIndex.java:379 deserialize(DataInput)): consumes
        exactly one BSI from the stream, leaving the position at the next
        byte, so back-to-back indexes read sequentially. Subclasses
        (MutableBitSliceIndex) return their own type."""
        from ..serialization import read_exact

        header = read_exact(fileobj, 9)
        ebm = RoaringBitmap.deserialize_from(fileobj)
        (depth,) = struct.unpack("<i", read_exact(fileobj, 4))
        if depth < 0 or depth > 64:
            raise InvalidRoaringFormat(f"implausible BSI depth {depth}")
        min_v, max_v, ro = struct.unpack("<iib", header)
        out = cls()
        out.min_value, out.max_value = min_v, max_v
        out.run_optimized = bool(ro)
        out.ebm = ebm
        out.slices = [RoaringBitmap.deserialize_from(fileobj) for _ in range(depth)]
        return out

    def __reduce__(self):
        """Pickle via the BSI wire format; subclasses reconstruct their
        own type (MutableBitSliceIndex overrides deserialize)."""
        return type(self).deserialize, (self.serialize(),)

    @staticmethod
    def deserialize(data) -> "RoaringBitmapSliceIndex":
        buf = memoryview(data if isinstance(data, (bytes, bytearray, memoryview)) else bytes(data))
        if len(buf) < 9:
            raise InvalidRoaringFormat("truncated BSI header")
        min_v, max_v, ro = struct.unpack_from("<iib", buf, 0)
        pos = 9
        out = RoaringBitmapSliceIndex()
        out.min_value, out.max_value = min_v, max_v
        out.run_optimized = bool(ro)
        out.ebm = RoaringBitmap()
        pos += read_into(out.ebm, buf[pos:])
        if pos + 4 > len(buf):
            raise InvalidRoaringFormat("truncated BSI slice count")
        (depth,) = struct.unpack_from("<i", buf, pos)
        pos += 4
        if depth < 0 or depth > 64:
            raise InvalidRoaringFormat(f"implausible BSI depth {depth}")
        out.slices = []
        for _ in range(depth):
            s = RoaringBitmap()
            pos += read_into(s, buf[pos:])
            out.slices.append(s)
        return out

    def __eq__(self, other):
        if not isinstance(other, RoaringBitmapSliceIndex):
            return NotImplemented
        return (
            self.ebm == other.ebm
            and len(self.slices) == len(other.slices)
            and all(a == b for a, b in zip(self.slices, other.slices))
        )

    def __repr__(self):
        return (
            f"RoaringBitmapSliceIndex(cols={self.get_cardinality()}, "
            f"slices={self.bit_count()}, min={self.min_value}, max={self.max_value})"
        )


def _keys_subset(bm: RoaringBitmap, keys: set) -> RoaringBitmap:
    """Sub-bitmap of bm restricted to the given high-16 keys."""
    out = RoaringBitmap()
    hlc = bm.high_low_container
    for k, c in zip(hlc.keys, hlc.containers):
        if k in keys:
            out.high_low_container.append(k, c.clone())
    return out


def _scan_body(carry, xs):
    import jax.numpy as jnp

    gt, lt, eq = carry
    slice_w, bit = xs
    lt_new = jnp.where(bit, lt | (eq & ~slice_w), lt)
    gt_new = jnp.where(bit, gt, gt | (eq & slice_w))
    eq_new = jnp.where(bit, eq & slice_w, eq & ~slice_w)
    return (gt_new, lt_new, eq_new), None


def _pad_chunk_axis(mesh, *arrays):
    """Pad the key-chunk axis (second-to-last of [S,K,W], first of [K,W])
    up to a multiple of the mesh's containers axis with empty chunks —
    empty ebm/fixed words make padded chunks contribute nothing."""
    import jax.numpy as jnp

    n_c = int(mesh.devices.shape[0])
    out = []
    for a in arrays:
        k_axis = a.ndim - 2
        pad = (-a.shape[k_axis]) % n_c
        if pad:
            widths = [(0, 0)] * a.ndim
            widths[k_axis] = (0, pad)
            a = jnp.pad(jnp.asarray(a), widths)
        else:
            a = jnp.asarray(a)
        out.append(a)
    return out if len(out) > 1 else out[0]


def o_neil_math(slices_w, bits_rev, ebm_w, fixed_w, op_name: str):
    """The pure O'Neil slice walk + epilogue: [S, K, 2048] slices ->
    ([K, 2048] result words, [K] cardinalities). Elementwise over the
    key-chunk and word axes (the scan carries only along S), so it is
    directly shard_map-able across a device mesh with no communication
    except a words-axis psum of the cards (parallel/sharding.py)."""
    import jax.numpy as jnp
    from jax import lax

    zeros = jnp.zeros_like(ebm_w)
    rev = slices_w[::-1]

    def walk(bits):
        (gt, lt, eq), _ = lax.scan(_scan_body, (zeros, zeros, ebm_w), (rev, bits))
        return gt, lt, eq

    if op_name == "RANGE":  # bits_rev is [2, S]: (lo GE, hi LE)
        gt_lo, _, eq_lo = walk(bits_rev[0])
        _, lt_hi, eq_hi = walk(bits_rev[1])
        out = ((gt_lo | eq_lo) & (lt_hi | eq_hi)) & fixed_w
    else:
        gt, lt, eq = walk(bits_rev)
        eq = eq & fixed_w
        if op_name == "EQ":
            out = eq
        elif op_name == "NEQ":
            out = fixed_w & ~eq
        elif op_name == "GT":
            out = gt & fixed_w
        elif op_name == "LT":
            out = lt & fixed_w
        elif op_name == "LE":
            out = (lt | eq) & fixed_w
        else:  # GE
            out = (gt | eq) & fixed_w
    cards = jnp.sum(lax.population_count(out).astype(jnp.int32), axis=-1)
    return out, cards


_o_neil_fused_jit = None


def _o_neil_compare_fused(slices_w, bits_rev, ebm_w, fixed_w, op_name: str):
    """One device dispatch for the whole compare: lax.scan over the slice
    axis carrying (GT, LT, EQ) [K, 2048] blocks, the per-op epilogue, and the
    popcount — fused so repeat queries cost a single round-trip. The jitted
    callable is cached at module level (predicate bits are a runtime
    argument; only the op name is a static trace constant)."""
    global _o_neil_fused_jit
    if _o_neil_fused_jit is None:
        import functools

        import jax

        _o_neil_fused_jit = functools.partial(
            jax.jit, static_argnames=("op_name",)
        )(o_neil_math)
    return _o_neil_fused_jit(slices_w, bits_rev, ebm_w, fixed_w, op_name)


_o_neil_many_jits: dict = {}


def _o_neil_counts_batched(slices_w, bits_mat, ebm_w, fixed_w, op_name: str):
    """Multi-query O'Neil: the fused walk vmapped over the query axis of
    ``bits_mat`` ([Q, S], or [Q, 2, S] for RANGE) with the resident
    [S, K, 2048] pack broadcast. Returns per-(query, chunk) popcounts
    [Q, K] int32 — one device dispatch answers all Q predicates, so the
    single HBM read of the slice tensor is amortized Q ways (the batching
    the per-call reference API cannot express,
    RoaringBitmapSliceIndex.java:482)."""
    fn = _o_neil_many_jits.get(op_name)
    if fn is None:
        import jax

        def one(slices_w, bits, ebm_w, fixed_w):
            _, cards = o_neil_math(slices_w, bits, ebm_w, fixed_w, op_name)
            return cards

        fn = jax.jit(jax.vmap(one, in_axes=(None, 0, None, None)))
        _o_neil_many_jits[op_name] = fn
    from ..ops.pallas_kernels import _DISPATCH_TOTAL

    _DISPATCH_TOTAL.inc(1, ("oneil_batched", "xla_vmap"))
    return fn(slices_w, bits_mat, ebm_w, fixed_w)


def _mesh_batched_counts(mesh, slices_w, bits, ebm_w, fixed_w, op_name):
    """Mesh twin of _o_neil_counts_batched, shared by both BSI designs:
    pad the chunk axis up to the containers-axis size with empty chunks
    (zero ebm/fixed words contribute nothing for every op incl. NEQ), run
    the sharded vmapped walk, drop the padding columns."""
    from ..ops.pallas_kernels import _DISPATCH_TOTAL
    from ..parallel import sharding

    _DISPATCH_TOTAL.inc(1, ("oneil_batched", "mesh"))
    k_orig = ebm_w.shape[0]
    s3, e2, f2 = _pad_chunk_axis(mesh, slices_w, ebm_w, fixed_w)
    cards = sharding.distributed_bsi_counts_many(mesh, op_name)(s3, bits, e2, f2)
    return cards[:, :k_orig]


def _counts_many(
    owner,
    operation,
    values,
    ends,
    found_set,
    mode,
    *,
    batched_ok: bool,
    pack_fixed,
    neq_remainder,
    mesh=None,
) -> np.ndarray:
    """Shared engine behind compare_cardinality_many on both BSI designs
    (32-bit and the 64-bit high-48-chunk twin): per-predicate min/max
    verdicts resolve host-side, the remainder rides one vmapped device walk.

    ``owner`` provides bit_count/ebm/min_value/max_value/compare_cardinality;
    ``pack_fixed()`` returns the twin's (keys, ebm_w, slices_w, fixed_w);
    ``neq_remainder(keys)`` the per-query count of found-set columns in
    chunks outside the packed ebm (NEQ qualifies them wholesale).

    Thresholds stay exact Python ints end-to-end — an int64 cast would wrap
    (or refuse) predicates >= 2^63, which the index itself stores exactly
    (code-review r4)."""
    vals = [int(v) for v in np.asarray(values, dtype=object).ravel()]
    q_n = len(vals)
    out = np.zeros(q_n, dtype=np.int64)
    if q_n == 0:
        return out
    cap = (1 << owner.bit_count()) - 1
    ends_list = None
    if operation == Operation.RANGE:
        if ends is None:
            raise ValueError("RANGE requires ends")
        ends_list = [min(int(e), cap) for e in np.asarray(ends, dtype=object).ravel()]
        if len(ends_list) != q_n:
            raise ValueError("ends must align with values")
    ebm_t = type(owner.ebm)
    pend = []
    for qi in range(q_n):
        end_q = ends_list[qi] if ends_list is not None else 0
        verdict = min_max_verdict(
            operation, vals[qi], end_q, owner.min_value, owner.max_value
        )
        if verdict is None:
            pend.append(qi)
        elif verdict == "empty":
            out[qi] = 0
        elif verdict == "fixed":
            out[qi] = (owner.ebm if found_set is None else found_set).get_cardinality()
        else:  # "all"
            out[qi] = (
                owner.ebm.get_cardinality()
                if found_set is None
                else ebm_t.and_cardinality(owner.ebm, found_set)
            )
    if not pend:
        return out
    if not batched_ok:
        for qi in pend:
            end_q = ends_list[qi] if ends_list is not None else 0
            out[qi] = owner.compare_cardinality(
                operation, vals[qi], end_q, found_set, mode
            )
        return out
    import jax.numpy as jnp

    keys, ebm_w, slices_w, fixed_w = pack_fixed()
    s_count = owner.bit_count()

    def bits_of(v):
        return [(v >> i) & 1 for i in range(s_count - 1, -1, -1)]

    if operation == Operation.RANGE:
        bits = np.array(
            [[bits_of(vals[qi]), bits_of(ends_list[qi])] for qi in pend], dtype=bool
        )
    else:
        bits = np.array([bits_of(vals[qi]) for qi in pend], dtype=bool)
    run = (
        functools.partial(_mesh_batched_counts, mesh)
        if mesh is not None
        else _o_neil_counts_batched
    )
    cards = np.asarray(
        run(slices_w, jnp.asarray(bits), ebm_w, fixed_w, operation.value)
    )
    totals = cards.astype(np.int64).sum(axis=1)
    if operation == Operation.NEQ and found_set is not None:
        totals += neq_remainder(keys)
    out[np.array(pend)] = totals
    return out


_slice_popcounts_jit = None


def _slice_masked_popcounts(slices_w, found_w):
    """[S, K, 2048] & [K, 2048] -> per-slice popcounts [S] (device)."""
    global _slice_popcounts_jit
    if _slice_popcounts_jit is None:
        import jax
        import jax.numpy as jnp
        from jax import lax

        @jax.jit
        def run(slices_w, found_w):
            masked = slices_w & found_w[None]
            # per-(slice, key-chunk) counts: each <= 65536, safely int32;
            # the cross-chunk sum happens host-side in python ints
            return jnp.sum(lax.population_count(masked).astype(jnp.int32), axis=2)

        _slice_popcounts_jit = run
    return _slice_popcounts_jit(slices_w, found_w)
