from . import container, roaring_array, roaring

__all__ = ["container", "roaring_array", "roaring"]
