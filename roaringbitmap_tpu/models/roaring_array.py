"""L2' key -> container index: sorted parallel arrays.

Mirrors RoaringArray.java:22 — parallel sorted ``keys`` (high-16-bit chunk
keys) and ``containers``. Host-side pure Python/bisect; tiny (at most 65536
entries) and never on the device hot path.

Mutation tracking (ISSUE 2 + ISSUE 4): every mutator bumps ``_version``
(the substrate of ``RoaringBitmap.fingerprint()``, which keys the query
result cache) and *attributes* the mutation to its chunk key in
``_key_versions`` — which is what lets the resident pack cache
(parallel/store.py) answer "which containers changed since version v?" and
re-pack only those rows instead of the whole working set. Paths that
rebind state wholesale without per-key attribution (the deserialize refill
in serialization.py) call :meth:`mark_all_dirty`, after which
:meth:`dirty_keys_since` answers ``None`` (= unknown, do a full repack)
for any baseline predating the wholesale change.
"""

from __future__ import annotations

import itertools
from bisect import bisect_left
from typing import Dict, List, Optional, Set, Tuple

from .container import Container

# process-unique generation ids: a (gen, version) pair is a stable identity
# token for "this container array at this mutation count" that can never be
# confused with a different array reusing the same memory address — the
# substrate of RoaringBitmap.fingerprint() (query/cache.py invalidation)
_GEN = itertools.count(1)


class RoaringArray:
    __slots__ = ("keys", "containers", "_gen", "_version", "_key_versions",
                 "_unattributed_version", "_fp", "_fp_ident")

    def __init__(self):
        self.keys: List[int] = []
        self.containers: List[Container] = []
        self._gen = next(_GEN)
        self._version = 0
        # chunk key -> version of its most recent attributed mutation
        self._key_versions: Dict[int, int] = {}
        # version of the most recent wholesale (key-less) mutation; dirty
        # queries with an older baseline cannot be answered incrementally
        self._unattributed_version = 0
        # cached fingerprint tuple + cache-identity tuple (ISSUE 11
        # satellite): every mutator invalidates _fp (the version moved);
        # _fp_ident depends only on the generation, which is fixed at
        # construction, so it never invalidates. The 10k-operand warm
        # lookup path walks fingerprints on EVERY call — caching turns
        # that walk from 2 tuple allocations per bitmap per call into two
        # attribute loads (and stops the allocation burst that made the
        # walk the delta wall's dominant stage, BENCH_NOTES r12).
        self._fp: "Optional[Tuple[int, int]]" = None
        self._fp_ident: "Optional[Tuple[str, int]]" = None

    @property
    def size(self) -> int:
        return len(self.keys)

    def get_index(self, key: int) -> int:
        """Index of key, or -(insertion_point)-1 if absent (RoaringArray.java:749)."""
        i = bisect_left(self.keys, key)
        if i < len(self.keys) and self.keys[i] == key:
            return i
        return -i - 1

    def get_container(self, key: int):
        i = self.get_index(key)
        return self.containers[i] if i >= 0 else None

    def get_container_at_index(self, i: int) -> Container:
        return self.containers[i]

    def get_key_at_index(self, i: int) -> int:
        return self.keys[i]

    def touch_key(self, key: int) -> None:
        """Record an attributed mutation of ``key``'s container — the hook
        for frame-flat hot paths that mutate ``containers[i]`` in place
        without going through a mutator method."""
        self._version += 1
        self._key_versions[key] = self._version
        self._fp = None

    def mark_all_dirty(self) -> None:
        """Record a wholesale mutation that cannot be attributed to
        specific keys (deserialize refill); incremental dirty queries with
        an older baseline will answer None (full repack)."""
        self._version += 1
        self._unattributed_version = self._version
        self._fp = None

    def wholesale_since(self, version: int) -> bool:
        """Did a wholesale (key-less) mutation land after ``version``?
        The O(1) pre-check that lets the pack cache's delta validator skip
        the per-key dirty scan entirely when ``mark_all_dirty`` already
        forced a full repack (ISSUE 8 satellite)."""
        return self._unattributed_version > int(version)

    def dirty_keys_since(self, version: int) -> Optional[Set[int]]:
        """Chunk keys whose containers were mutated after ``version``
        (touched, inserted, replaced, or removed), or ``None`` when the
        answer is unknowable — a wholesale mutation happened after
        ``version``, so the caller must treat everything as dirty."""
        if version >= self._version:
            return set()
        if self._unattributed_version > version:
            return None
        return {k for k, v in self._key_versions.items() if v > version}

    def set_container_at_index(self, i: int, c: Container) -> None:
        self.containers[i] = c
        self.touch_key(self.keys[i])

    def insert_new_key_value_at(self, i: int, key: int, c: Container) -> None:
        self.keys.insert(i, key)
        self.containers.insert(i, c)
        self.touch_key(key)

    def remove_at_index(self, i: int) -> None:
        key = self.keys[i]
        del self.keys[i]
        del self.containers[i]
        self.touch_key(key)

    def remove_index_range(self, begin: int, end: int) -> None:
        removed = self.keys[begin:end]
        del self.keys[begin:end]
        del self.containers[begin:end]
        for key in removed:
            self.touch_key(key)

    def append(self, key: int, c: Container) -> None:
        """Append-only builder path (RoaringArray.java:111); key must exceed all
        existing keys."""
        if self.keys and key <= self.keys[-1]:
            raise ValueError(f"append key {key} <= last key {self.keys[-1]}")
        self.keys.append(key)
        self.containers.append(c)
        self.touch_key(key)

    def advance_until(self, key: int, pos: int) -> int:
        """First index > pos with keys[index] >= key (RoaringArray.java:64)."""
        return bisect_left(self.keys, key, lo=pos + 1)

    def clone(self) -> "RoaringArray":
        """Deep copy under a FRESH ``(gen, version=0)`` identity — and that
        is correct, not an oversight: generations are process-unique
        (``_GEN``), so the clone's fingerprints ``(child_gen, ·)`` can never
        equal the parent's ``(parent_gen, ·)``. Mutating the clone therefore
        cannot invalidate the parent's cached packs or query results, and
        the clone can never be served an entry packed from the parent —
        the regression tests in tests/test_pack_cache.py pin both
        directions. Routing the copy through the versioned mutators would
        only burn O(keys) dict stores to arrive at the same guarantee."""
        out = RoaringArray()
        out.keys = list(self.keys)
        out.containers = [c.clone() for c in self.containers]
        return out

    def items(self) -> List[Tuple[int, Container]]:
        return list(zip(self.keys, self.containers))

    def __eq__(self, other):
        if not isinstance(other, RoaringArray):
            return NotImplemented
        return self.keys == other.keys and all(
            a == b for a, b in zip(self.containers, other.containers)
        )
