"""L2' key -> container index: sorted parallel arrays.

Mirrors RoaringArray.java:22 — parallel sorted ``keys`` (high-16-bit chunk
keys) and ``containers``. Host-side pure Python/bisect; tiny (at most 65536
entries) and never on the device hot path.
"""

from __future__ import annotations

import itertools
from bisect import bisect_left
from typing import List, Tuple

from .container import Container

# process-unique generation ids: a (gen, version) pair is a stable identity
# token for "this container array at this mutation count" that can never be
# confused with a different array reusing the same memory address — the
# substrate of RoaringBitmap.fingerprint() (query/cache.py invalidation)
_GEN = itertools.count(1)


class RoaringArray:
    __slots__ = ("keys", "containers", "_gen", "_version")

    def __init__(self):
        self.keys: List[int] = []
        self.containers: List[Container] = []
        self._gen = next(_GEN)
        self._version = 0

    @property
    def size(self) -> int:
        return len(self.keys)

    def get_index(self, key: int) -> int:
        """Index of key, or -(insertion_point)-1 if absent (RoaringArray.java:749)."""
        i = bisect_left(self.keys, key)
        if i < len(self.keys) and self.keys[i] == key:
            return i
        return -i - 1

    def get_container(self, key: int):
        i = self.get_index(key)
        return self.containers[i] if i >= 0 else None

    def get_container_at_index(self, i: int) -> Container:
        return self.containers[i]

    def get_key_at_index(self, i: int) -> int:
        return self.keys[i]

    def set_container_at_index(self, i: int, c: Container) -> None:
        self.containers[i] = c
        self._version += 1

    def insert_new_key_value_at(self, i: int, key: int, c: Container) -> None:
        self.keys.insert(i, key)
        self.containers.insert(i, c)
        self._version += 1

    def remove_at_index(self, i: int) -> None:
        del self.keys[i]
        del self.containers[i]
        self._version += 1

    def remove_index_range(self, begin: int, end: int) -> None:
        del self.keys[begin:end]
        del self.containers[begin:end]
        self._version += 1

    def append(self, key: int, c: Container) -> None:
        """Append-only builder path (RoaringArray.java:111); key must exceed all
        existing keys."""
        if self.keys and key <= self.keys[-1]:
            raise ValueError(f"append key {key} <= last key {self.keys[-1]}")
        self.keys.append(key)
        self.containers.append(c)
        self._version += 1

    def advance_until(self, key: int, pos: int) -> int:
        """First index > pos with keys[index] >= key (RoaringArray.java:64)."""
        return bisect_left(self.keys, key, lo=pos + 1)

    def clone(self) -> "RoaringArray":
        out = RoaringArray()
        out.keys = list(self.keys)
        out.containers = [c.clone() for c in self.containers]
        return out

    def items(self) -> List[Tuple[int, Container]]:
        return list(zip(self.keys, self.containers))

    def __eq__(self, other):
        if not isinstance(other, RoaringArray):
            return NotImplemented
        return self.keys == other.keys and all(
            a == b for a, b in zip(self.containers, other.containers)
        )
