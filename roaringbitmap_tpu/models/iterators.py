"""Iterator layer: peekable point iterators and buffer-filling batch
iterators (reference: PeekableIntIterator.java, IntIteratorFlyweight.java,
ReverseIntIteratorFlyweight.java, PeekableIntRankIterator,
BatchIterator.java:12 ``nextBatch`` contract with ``advanceIfNeeded`` :72,
RoaringBatchIterator.java:19-28).

TPU inversion: Java's flyweights exist to avoid per-value allocation in hot
scalar loops; here the batch iterator is the primary surface (it yields
numpy arrays — the natural unit for feeding vectorized/device consumers)
and the point iterators are thin cursors over per-container arrays. All
iterators support ``advance_if_needed(minval)`` skip via container-key
bisect + in-container searchsorted rather than scalar stepping.
"""

from __future__ import annotations

from bisect import bisect_left
from typing import Optional

import numpy as np

from .roaring import RoaringBitmap


class PeekableIntIterator:
    """Forward iterator with peek + advance (PeekableIntIterator.java:90,
    flyweight IntIteratorFlyweight.java)."""

    __slots__ = ("_hlc", "_ci", "_arr", "_pos")

    def __init__(self, bm: RoaringBitmap):
        self._hlc = bm.high_low_container
        self._ci = 0
        self._arr: Optional[np.ndarray] = None
        self._pos = 0
        self._load()

    def _load(self) -> None:
        while self._ci < self._hlc.size:
            arr = self._hlc.containers[self._ci].to_array()
            if arr.size:
                self._arr = arr
                self._pos = 0
                return
            self._ci += 1
        self._arr = None

    def has_next(self) -> bool:
        return self._arr is not None

    def peek_next(self) -> int:
        """peekNext: next value without consuming it."""
        if self._arr is None:
            raise StopIteration
        return (self._hlc.keys[self._ci] << 16) | int(self._arr[self._pos])

    def next(self) -> int:
        v = self.peek_next()
        self._pos += 1
        if self._pos >= self._arr.size:
            self._ci += 1
            self._load()
        return v

    def advance_if_needed(self, minval: int) -> None:
        """Skip forward so the next value is >= minval (advanceIfNeeded):
        key bisect across containers + searchsorted within."""
        if self._arr is None:
            return
        key, low = minval >> 16, minval & 0xFFFF
        if self._hlc.keys[self._ci] < key:
            self._ci = bisect_left(self._hlc.keys, key, lo=self._ci)
            self._load()
            if self._arr is None:
                return
        if self._hlc.keys[self._ci] > key:
            return
        p = int(np.searchsorted(self._arr, np.uint16(low)))
        if self._pos < p:
            self._pos = p
            if self._pos >= self._arr.size:
                self._ci += 1
                self._load()

    def __iter__(self):
        return self

    def __next__(self) -> int:
        if self._arr is None:
            raise StopIteration
        return self.next()


class PeekableIntRankIterator(PeekableIntIterator):
    """Peekable iterator that also reports the rank of the next value
    (PeekableIntRankIterator; FastRank's iterator). Rank is derived in O(1)
    from the cursor position + a precomputed cumulative-cardinality table,
    not recomputed per call."""

    __slots__ = ("_cum",)

    def __init__(self, bm: RoaringBitmap):
        super().__init__(bm)
        cards = [c.cardinality for c in self._hlc.containers]
        self._cum = np.concatenate(([0], np.cumsum(cards))) if cards else np.zeros(1)

    def peek_next_rank(self) -> int:
        """1-based rank of the value peek_next() would return."""
        if self._arr is None:
            raise StopIteration
        return int(self._cum[self._ci]) + self._pos + 1


class ReverseIntIterator:
    """Descending iterator (ReverseIntIteratorFlyweight.java)."""

    __slots__ = ("_hlc", "_ci", "_arr", "_pos")

    def __init__(self, bm: RoaringBitmap):
        self._hlc = bm.high_low_container
        self._ci = self._hlc.size - 1
        self._arr: Optional[np.ndarray] = None
        self._load()

    def _load(self) -> None:
        while self._ci >= 0:
            arr = self._hlc.containers[self._ci].to_array()
            if arr.size:
                self._arr = arr
                self._pos = arr.size - 1
                return
            self._ci -= 1
        self._arr = None

    def has_next(self) -> bool:
        return self._arr is not None

    def next(self) -> int:
        if self._arr is None:
            raise StopIteration
        v = (self._hlc.keys[self._ci] << 16) | int(self._arr[self._pos])
        self._pos -= 1
        if self._pos < 0:
            self._ci -= 1
            self._load()
        return v

    def __iter__(self):
        return self

    __next__ = next


class RoaringBatchIterator:
    """Buffer-filling iterator (BatchIterator.java:12 nextBatch contract;
    RoaringBatchIterator.java walks containers reusing per-type cursors).

    ``next_batch(buffer)`` fills a caller-provided uint32 numpy array and
    returns the count filled; ``advance_if_needed`` skips whole containers
    by key bisect."""

    __slots__ = ("_hlc", "_ci", "_arr", "_pos")

    def __init__(self, bm: RoaringBitmap):
        self._hlc = bm.high_low_container
        self._ci = 0
        self._arr: Optional[np.ndarray] = None
        self._pos = 0

    def _ensure(self) -> bool:
        while self._arr is None or self._pos >= self._arr.size:
            if self._arr is not None:
                self._ci += 1
                self._arr = None
            if self._ci >= self._hlc.size:
                return False
            arr = self._hlc.containers[self._ci].to_array()
            if arr.size:
                self._arr = arr.astype(np.uint32) | np.uint32(
                    self._hlc.keys[self._ci] << 16
                )
                self._pos = 0
        return True

    def has_next(self) -> bool:
        return self._ensure()

    def next_batch(self, buffer: np.ndarray) -> int:
        """Fill `buffer` (uint32) with the next values; returns how many."""
        filled = 0
        cap = buffer.shape[0]
        while filled < cap and self._ensure():
            take = min(cap - filled, self._arr.size - self._pos)
            buffer[filled : filled + take] = self._arr[self._pos : self._pos + take]
            self._pos += take
            filled += take
        return filled

    def advance_if_needed(self, minval: int) -> None:
        """advanceIfNeeded (BatchIterator.java:72)."""
        key, low = minval >> 16, minval & 0xFFFF
        if self._arr is not None and self._hlc.keys[self._ci] == key:
            p = int(np.searchsorted(self._arr, np.uint32(minval)))
            self._pos = max(self._pos, p)
            return
        if self._arr is None or self._hlc.keys[self._ci] < key:
            self._ci = bisect_left(self._hlc.keys, key, lo=self._ci)
            self._arr = None
            if self._ensure() and self._hlc.keys[self._ci] == key:
                p = int(np.searchsorted(self._arr, np.uint32(minval)))
                self._pos = max(self._pos, p)

    def as_int_iterator(self) -> "BatchIntIterator":
        """Wrap as a point iterator (BatchIterator.asIntIterator :32)."""
        return BatchIntIterator(self)


class BatchIntIterator:
    """Point-iterator adapter over a batch iterator (BatchIntIterator.java)."""

    __slots__ = ("_it", "_buf", "_n", "_pos")

    def __init__(self, it: RoaringBatchIterator, batch_size: int = 256):
        self._it = it
        self._buf = np.empty(batch_size, dtype=np.uint32)
        self._n = 0
        self._pos = 0

    def has_next(self) -> bool:
        if self._pos < self._n:
            return True
        self._n = self._it.next_batch(self._buf)
        self._pos = 0
        return self._n > 0

    def next(self) -> int:
        if not self.has_next():
            raise StopIteration
        v = int(self._buf[self._pos])
        self._pos += 1
        return v

    def __iter__(self):
        return self

    __next__ = next
