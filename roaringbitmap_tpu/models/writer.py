"""L6' ingest/streaming writers.

API parity with the builder wizard (RoaringBitmapWriter.java:9-115):
``RoaringBitmapWriter.writer().optimise_for_arrays()...get()``. The
``ConstantMemoryContainerAppender`` strategy (ConstantMemoryContainerAppender
.java:10-40: accumulate into one fixed 8 KiB word buffer, emit the best
container on key advance) is the sorted-stream fast path; unsorted input is
buffered per key and flushed vectorized (the ``partialRadixSort`` analogue,
Util.java:1196, is numpy's sort on the full 32-bit values).

This writer is also the device->host streaming endpoint: aggregation results
come back from the TPU as (key, words, cardinality) triples and append
through the same path (RoaringArray.append, RoaringArray.java:111).
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional

import numpy as np

from ..utils import bits
from .container import (
    ARRAY_MAX_SIZE,
    ArrayContainer,
    BitmapContainer,
    Container,
    RunContainer,
    best_container_of_words,
    container_from_values,
)
from .fastrank import FastRankRoaringBitmap
from .roaring import RoaringBitmap


class RoaringBitmapWriter:
    """Builder DSL (RoaringBitmapWriter.java:36-115)."""

    def __init__(self):
        self._optimise_runs = False
        self._constant_memory = False
        self._partially_sorted = False
        self._run_compress = True
        self._fast_rank = False
        self._expected_container_size = 16
        self._initial_capacity = 16

    # wizard options --------------------------------------------------
    @staticmethod
    def writer() -> "RoaringBitmapWriter":
        return RoaringBitmapWriter()

    def optimise_for_arrays(self) -> "RoaringBitmapWriter":
        self._optimise_runs = False
        return self

    def optimise_for_runs(self) -> "RoaringBitmapWriter":
        self._optimise_runs = True
        return self

    def constant_memory(self) -> "RoaringBitmapWriter":
        self._constant_memory = True
        return self

    def expected_values_per_container(self, n: int) -> "RoaringBitmapWriter":
        # thresholds from RoaringBitmapWriter.java:68-77
        self._expected_container_size = int(n)
        if n < ARRAY_MAX_SIZE:
            self._optimise_runs = False
        elif n < 1 << 14:
            self._constant_memory = True
        else:
            self._optimise_runs = True
        return self

    def expected_density(self, density: float) -> "RoaringBitmapWriter":
        return self.expected_values_per_container(int(density * (1 << 16)))

    def expected_range(self, min_value: int, max_value: int) -> "RoaringBitmapWriter":
        self._initial_capacity = max(1, ((int(max_value) >> 16) - (int(min_value) >> 16) + 1))
        return self

    def initial_capacity(self, n: int) -> "RoaringBitmapWriter":
        self._initial_capacity = int(n)
        return self

    def partially_sort_values(self) -> "RoaringBitmapWriter":
        self._partially_sorted = True
        return self

    def run_compress(self, enabled: bool) -> "RoaringBitmapWriter":
        self._run_compress = bool(enabled)
        return self

    def fast_rank(self) -> "RoaringBitmapWriter":
        self._fast_rank = True
        return self

    def get(self) -> "BitmapWriter":
        return BitmapWriter(
            optimise_runs=self._optimise_runs and self._run_compress,
            constant_memory=self._constant_memory,
            fast_rank=self._fast_rank,
        )


class BitmapWriter:
    """Streaming appender. Sorted streams take the constant-memory fast path
    (one 8 KiB buffer); out-of-order values fall back to per-key buffers.

    ``into=`` points the writer at an EXISTING bitmap instead of a fresh
    one: every emit lands through the bitmap's attributed mutators
    (``set_container_at_index`` / ``insert_new_key_value_at`` /
    ``append`` — all of which ``touch_key``), so the pack cache's per-key
    dirty tracking prices each flushed chunk and a later
    ``store.packed_for`` repack takes the O(k) delta path. This is the
    serving tier's ingest surface (serve/ingest.py): the epoch flip
    drains the mutation log through one writer per touched bitmap."""

    def __init__(self, optimise_runs=False, constant_memory=False, fast_rank=False,
                 into: Optional[RoaringBitmap] = None):
        self._optimise_runs = optimise_runs
        self._constant_memory = constant_memory
        if into is not None:
            if fast_rank and not isinstance(into, FastRankRoaringBitmap):
                raise ValueError("fast_rank writer cannot stream into a "
                                 "plain RoaringBitmap")
            self._bitmap = into
        else:
            self._bitmap = FastRankRoaringBitmap() if fast_rank else RoaringBitmap()
        self._current_key: Optional[int] = None
        self._words = bits.new_words()
        self._words_dirty = False
        self._pending: Dict[int, List[np.ndarray]] = {}

    # ------------------------------------------------------------------
    def add(self, value: int) -> None:
        value = int(value)
        if not 0 <= value < 1 << 32:
            raise ValueError(f"value {value} outside unsigned 32-bit range")
        key, low = value >> 16, value & 0xFFFF
        if self._current_key is None:
            self._current_key = key
        if key == self._current_key:
            bits.set_bit(self._words, low)
            self._words_dirty = True
        elif key > self._current_key:
            self._flush_current()
            self._current_key = key
            bits.set_bit(self._words, low)
            self._words_dirty = True
        else:  # out of order: buffer
            self._pending.setdefault(key, []).append(
                np.array([low], dtype=np.uint16)
            )

    def add_many(self, values: Iterable[int]) -> None:
        if not isinstance(values, np.ndarray):
            values = np.fromiter(iter(values), dtype=np.int64)
        v = np.asarray(values, dtype=np.int64).ravel()
        if v.size == 0:
            return
        if v.min() < 0 or v.max() >= 1 << 32:
            raise ValueError("values outside unsigned 32-bit range")
        keys = (v >> 16).astype(np.int64)
        lows = (v & 0xFFFF).astype(np.uint16)
        if self._current_key is not None and np.all(keys == self._current_key):
            bits.or_values_into_words(self._words, lows)
            self._words_dirty = True
            return
        for key in np.unique(keys):
            self._pending.setdefault(int(key), []).append(lows[keys == key])

    def add_range(self, start: int, end: int) -> None:
        self.flush()
        self._bitmap.add_range(start, end)

    # ------------------------------------------------------------------
    def _emit(self, key: int, container: Container) -> None:
        if container.cardinality == 0:
            return
        if self._optimise_runs:
            container = container.run_optimize()
        hlc = self._bitmap.high_low_container
        i = hlc.get_index(key)
        if i >= 0:
            merged = hlc.get_container_at_index(i).or_(container)
            if self._optimise_runs:
                # re-select the MERGED result's format, not just the
                # emitted chunk's: or_ returns arrays/bitmaps by
                # construction, so without this the serving ingest path
                # (into= an existing corpus bitmap) drifts every
                # write-hot container away from the size rule no matter
                # how run-friendly the stream is (ISSUE 16) — only the
                # already-dirty merged row is touched, never a scan
                merged = merged.run_optimize()
            hlc.set_container_at_index(i, merged)
        elif hlc.size == 0 or key > hlc.keys[-1]:
            hlc.append(key, container)
        else:
            hlc.insert_new_key_value_at(-i - 1, key, container)

    def _flush_current(self) -> None:
        if self._current_key is not None and self._words_dirty:
            # hand the buffer off: best_container_of_words keeps a reference
            # when it builds a dense container, so zeroing it in place would
            # clobber the just-emitted chunk (and any bitmap already
            # returned by get()) — allocate fresh instead of aliasing
            self._emit(self._current_key, best_container_of_words(self._words))
            self._words = bits.new_words()
            self._words_dirty = False

    def flush(self) -> None:
        """Flush buffers into the underlying bitmap (BitmapWriter.flush)."""
        self._flush_current()
        self._current_key = None
        for key in sorted(self._pending):
            chunks = self._pending[key]
            merged = np.unique(np.concatenate(chunks)) if len(chunks) > 1 else np.unique(chunks[0])
            self._emit(key, container_from_values(merged))
        self._pending.clear()
        if isinstance(self._bitmap, FastRankRoaringBitmap):
            self._bitmap._invalidate()

    def get(self) -> RoaringBitmap:
        """Finish and return the bitmap (writer.get())."""
        self.flush()
        return self._bitmap

    get_underlying = get

    def reset(self) -> None:
        """Discard buffered state and start a fresh underlying bitmap
        (RoaringBitmapWriter.reset — reuse one writer across bitmaps)."""
        self._pending.clear()
        self._current_key = None
        self._words = bits.new_words()  # never zero in place: see _flush_current
        self._words_dirty = False
        self._bitmap = (
            FastRankRoaringBitmap()
            if isinstance(self._bitmap, FastRankRoaringBitmap)
            else RoaringBitmap()
        )


def writer() -> RoaringBitmapWriter:
    """Module-level convenience: roaringbitmap_tpu.models.writer.writer()."""
    return RoaringBitmapWriter.writer()
