"""Drop-in BitSet-style facade backed by a RoaringBitmap
(RoaringBitSet.java:9-12) plus BitSetUtil-style conversions
(BitSetUtil.java:29/174)."""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..utils import bits
from .container import container_from_values
from .roaring import RoaringBitmap


class RoaringBitSet:
    """java.util.BitSet-flavoured API over a RoaringBitmap."""

    __slots__ = ("bitmap",)

    def __init__(self, bitmap: Optional[RoaringBitmap] = None):
        self.bitmap = bitmap if bitmap is not None else RoaringBitmap()

    # BitSet API
    def set(self, index: int, value: bool = True) -> None:
        if value:
            self.bitmap.add(index)
        else:
            self.bitmap.remove(index)

    def set_range(self, start: int, end: int) -> None:
        self.bitmap.add_range(start, end)

    def clear(self, index: Optional[int] = None) -> None:
        if index is None:
            self.bitmap = RoaringBitmap()
        else:
            self.bitmap.remove(index)

    def clear_range(self, start: int, end: int) -> None:
        self.bitmap.remove_range(start, end)

    def get(self, index: int) -> bool:
        return self.bitmap.contains(index)

    def flip(self, index: int) -> None:
        self.bitmap.flip_range(index, index + 1)

    def flip_range(self, start: int, end: int) -> None:
        self.bitmap.flip_range(start, end)

    def cardinality(self) -> int:
        return self.bitmap.get_cardinality()

    def is_empty(self) -> bool:
        return self.bitmap.is_empty()

    def length(self) -> int:
        """Highest set bit + 1, or 0 (BitSet.length)."""
        return 0 if self.bitmap.is_empty() else self.bitmap.last() + 1

    def next_set_bit(self, from_index: int) -> int:
        return self.bitmap.next_value(from_index)

    def next_clear_bit(self, from_index: int) -> int:
        return self.bitmap.next_absent_value(from_index)

    def previous_set_bit(self, from_index: int) -> int:
        return self.bitmap.previous_value(from_index)

    def previous_clear_bit(self, from_index: int) -> int:
        return self.bitmap.previous_absent_value(from_index)

    def and_(self, other: "RoaringBitSet") -> None:
        self.bitmap.iand(other.bitmap)

    def or_(self, other: "RoaringBitSet") -> None:
        self.bitmap.ior(other.bitmap)

    def xor(self, other: "RoaringBitSet") -> None:
        self.bitmap.ixor(other.bitmap)

    def and_not(self, other: "RoaringBitSet") -> None:
        self.bitmap.iandnot(other.bitmap)

    def intersects(self, other: "RoaringBitSet") -> bool:
        return RoaringBitmap.intersects(self.bitmap, other.bitmap)

    def __eq__(self, other):
        if not isinstance(other, RoaringBitSet):
            return NotImplemented
        return self.bitmap == other.bitmap

    def __hash__(self):
        return hash(self.bitmap)

    def __reduce__(self):
        return _bitset_from_bytes, (self.bitmap.serialize(),)

    def __len__(self):
        return self.cardinality()

    def __repr__(self):
        return f"RoaringBitSet({self.bitmap!r})"


def bitmap_of_words(words: np.ndarray) -> RoaringBitmap:
    """long[]-backed BitSet words -> RoaringBitmap
    (BitSetUtil.bitmapOf(long[]), BitSetUtil.java:174). Block-wise: each
    1024-word block becomes one container (BLOCK_LENGTH, BitSetUtil.java:20)."""
    words = np.asarray(words, dtype=np.uint64).ravel()
    bm = RoaringBitmap()
    for key, start in enumerate(range(0, words.size, bits.WORDS_PER_CONTAINER)):
        block = words[start : start + bits.WORDS_PER_CONTAINER]
        if block.size < bits.WORDS_PER_CONTAINER:
            block = np.concatenate(
                [block, np.zeros(bits.WORDS_PER_CONTAINER - block.size, dtype=np.uint64)]
            )
        values = bits.values_from_words(block)
        if values.size:
            bm.high_low_container.append(key, container_from_values(values))
    return bm


def words_of_bitmap(bm: RoaringBitmap) -> np.ndarray:
    """RoaringBitmap -> long[] BitSet words (BitSetUtil.bitsetOf,
    BitSetUtil.java:29). Requires all values < 2^32; sized to the last bit."""
    if bm.is_empty():
        return np.empty(0, dtype=np.uint64)
    n_words = (bm.last() >> 6) + 1
    out = np.zeros(n_words, dtype=np.uint64)
    hlc = bm.high_low_container
    for k, c in zip(hlc.keys, hlc.containers):
        base = k * bits.WORDS_PER_CONTAINER
        out[base : base + bits.WORDS_PER_CONTAINER] = c.to_words()[
            : max(0, min(bits.WORDS_PER_CONTAINER, n_words - base))
        ]
    return out


def _bitset_from_bytes(blob: bytes) -> "RoaringBitSet":
    return RoaringBitSet(RoaringBitmap.deserialize(blob))
