"""64-bit layer, design 1 of 2: ``Roaring64NavigableMap``.

The reference ships two 64-bit designs — ``Roaring64NavigableMap``
(longlong/Roaring64NavigableMap.java:29: NavigableMap of high-32 bits ->
32-bit bitmap, cached cumulative cardinalities for rank/select :66-72,
signed/unsigned key ordering :97-100) — this module — and the ART-based
``Roaring64Bitmap`` (longlong/Roaring64Bitmap.java:29: high-48 trie ->
16-bit container), built in ``roaring64art.py`` over ``art.py``.

Here the NavigableMap becomes a sorted high-32 index over full 32-bit
RoaringBitmaps; every bucket reuses the whole 32-bit stack including the
packed device aggregation path, so 64-bit wide-ORs batch exactly like
32-bit ones (TPU-first, SURVEY §5 "long-context" analogue).

Serialization supports both reference modes
(Roaring64NavigableMap.java:35/:47/:51 SERIALIZATION_MODE switch):

* **portable** (default here; the cross-language spec, validated against the
  CRoaring-written golden files testdata/64map*.bin): uint64 LE bucket
  count, then per bucket uint32 LE high key + standard 32-bit
  serialization, buckets in unsigned key order.
* **legacy** (the reference's Java-default, serializeLegacy): uint8 bool
  signed_longs, int32 BE bucket count, per bucket int32 BE key + 32-bit
  serialization, buckets in comparator order.

Values are unsigned 64-bit [0, 2^64) by default; ``signed_longs=True``
orders them as two's-complement longs (negative half first).
"""

from __future__ import annotations

from bisect import bisect_left, bisect_right
from typing import Iterable, Iterator, List, Optional

import numpy as np

from .roaring import RoaringBitmap
from ..serialization import InvalidRoaringFormat
from ..utils import bits

_MAX64 = 1 << 64
_MAX32 = 1 << 32


def _check64(x: int) -> int:
    x = int(x)
    if not 0 <= x < _MAX64:
        raise ValueError(f"value {x} outside unsigned 64-bit range")
    return x


def chunk_ranges_64(start: int, end: int, shift: int):
    """Split a 64-bit half-open range into per-chunk (high, lo, hi) pieces,
    where chunks are 2^shift wide and (lo, hi) is half-open within a chunk.
    Shared by both 64-bit designs (shift=32 buckets / shift=16 containers)."""
    start, end = int(start), int(end)
    if not 0 <= start <= end <= _MAX64:
        raise ValueError(f"invalid range [{start}, {end})")
    if start == end:
        return
    mask = (1 << shift) - 1
    h_start, h_end = start >> shift, (end - 1) >> shift
    for h in range(h_start, h_end + 1):
        lo = start & mask if h == h_start else 0
        hi = ((end - 1) & mask) + 1 if h == h_end else (1 << shift)
        yield h, lo, hi


def group_by_high(values, shift: int):
    """Sort+coerce an iterable of unsigned 64-bit values and yield
    (high, sorted unique low parts) groups, where high = v >> shift.
    Shared batching for both 64-bit designs' add_many."""
    if not isinstance(values, np.ndarray):
        values = np.fromiter(iter(values), dtype=np.uint64)
    if np.issubdtype(values.dtype, np.signedinteger) and values.size and values.min() < 0:
        raise ValueError("values outside unsigned 64-bit range")
    v = np.asarray(values).astype(np.uint64).ravel()
    # pre-sorted bulk input (BSI slice masks, sorted ingest) skips the sort
    # and the per-bucket uniques
    presorted = bits.is_strictly_increasing(v)
    if not presorted:
        v = np.sort(v)
    if v.size == 0:
        return
    mask = np.uint64((1 << shift) - 1)
    highs = (v >> np.uint64(shift)).astype(np.uint64)
    lows = v & mask
    boundaries = np.nonzero(np.diff(highs))[0] + 1
    starts = np.concatenate(([0], boundaries))
    ends = np.concatenate((boundaries, [v.size]))
    for s, e in zip(starts.tolist(), ends.tolist()):
        chunk = lows[s:e]
        yield int(highs[s]), (chunk if presorted else np.unique(chunk))


def bucketed_membership(values, shift: int, probe) -> np.ndarray:
    """Shared vectorized-membership scaffold for both 64-bit designs'
    ``contains_many``: bucket the queries by ``high = v >> shift`` (one
    stable argsort), then ask ``probe(high, lows) -> bool array`` once per
    distinct bucket. Negative ints are taken as their two's-complement
    bit patterns (Java long semantics)."""
    vals = np.asarray(values).astype(np.uint64, copy=False).ravel()
    out = np.zeros(vals.shape, dtype=bool)
    if vals.size == 0:
        return out
    highs = vals >> np.uint64(shift)
    order = np.argsort(highs, kind="stable")
    sh = highs[order]
    bounds = np.flatnonzero(np.concatenate([[True], sh[1:] != sh[:-1]]))
    bounds = np.append(bounds, sh.size)
    mask = np.uint64((1 << shift) - 1)
    for s, e in zip(bounds[:-1], bounds[1:]):
        idx = order[s:e]
        got = probe(int(sh[s]), vals[idx] & mask)
        if got is not None:
            out[idx] = got
    return out


SERIALIZATION_MODE_LEGACY = 0  # Roaring64NavigableMap.java:35
SERIALIZATION_MODE_PORTABLE = 1  # Roaring64NavigableMap.java:47


class Roaring64NavigableMap:
    """64-bit Roaring bitmap as a sorted map of high-32 buckets
    (longlong/Roaring64NavigableMap.java:29)."""

    # Mutable global switch like the reference's (:51-52); this framework
    # defaults to the portable cross-language spec rather than the Java
    # legacy format.
    SERIALIZATION_MODE = SERIALIZATION_MODE_PORTABLE

    __slots__ = (
        "_buckets",
        "_keys",
        "_ckeys",
        "_ckeys_arr",
        "_keys_dirty",
        "_cum_cards",
        "_cum_dirty",
        "signed_longs",
        "supplier",
    )

    def __init__(
        self,
        values: Optional[Iterable[int]] = None,
        signed_longs: bool = False,
        supplier=None,
    ):
        self._buckets: dict = {}  # high32 -> RoaringBitmap
        self._keys: List[int] = []
        self._ckeys: Optional[List[int]] = None
        self._ckeys_arr: Optional[np.ndarray] = None
        self._keys_dirty = False
        self._cum_cards: Optional[np.ndarray] = None
        self._cum_dirty = True
        self.signed_longs = signed_longs  # Roaring64NavigableMap.java:100
        # pluggable per-bucket backend (BitmapDataProviderSupplier,
        # Roaring64NavigableMap.java:63): any callable returning a
        # RoaringBitmap-compatible instance, e.g. MutableRoaringBitmap
        self.supplier = supplier or RoaringBitmap
        if values is not None:
            self.add_many(values)

    # ------------------------------------------------------------------
    def _key_order(self, k: int) -> int:
        """Comparator: unsigned by default, two's-complement when signed."""
        if self.signed_longs and k >= (1 << 31):
            return k - _MAX32
        return k

    def _sorted_keys(self) -> List[int]:
        if self._keys_dirty:
            self._keys = sorted(self._buckets, key=self._key_order)
            self._ckeys = None
            self._ckeys_arr = None
            self._keys_dirty = False
        return self._keys

    def _comparator_keys(self) -> List[int]:
        """_sorted_keys mapped through the comparator, for bisecting; the
        identity (same list) in unsigned mode, cached in signed mode."""
        keys = self._sorted_keys()
        if not self.signed_longs:
            return keys
        if self._ckeys is None:
            self._ckeys = [self._key_order(k) for k in keys]
        return self._ckeys

    def _invalidate(self):
        self._cum_dirty = True

    def _cum(self) -> np.ndarray:
        """Cached cumulative cardinalities per bucket
        (Roaring64NavigableMap.java:66-72)."""
        if self._cum_dirty:
            keys = self._sorted_keys()
            cards = np.array(
                [self._buckets[k].get_cardinality() for k in keys], dtype=np.int64
            )
            self._cum_cards = np.cumsum(cards) if keys else np.empty(0, dtype=np.int64)
            self._cum_dirty = False
        return self._cum_cards

    def _bucket_for_add(self, high: int) -> RoaringBitmap:
        b = self._buckets.get(high)
        if b is None:
            b = self.supplier()
            self._buckets[high] = b
            self._keys_dirty = True
        return b

    # ------------------------------------------------------------------
    # construction / point ops
    # ------------------------------------------------------------------
    @staticmethod
    def bitmap_of(*values: int) -> "Roaring64NavigableMap":
        return Roaring64NavigableMap(values)

    def add(self, x: int) -> None:
        """addLong (Roaring64NavigableMap.java:50)."""
        x = _check64(x)
        self._bucket_for_add(x >> 32).add(x & 0xFFFFFFFF)
        self._invalidate()

    def add_many(self, values: Iterable[int]) -> None:
        for high, lows in group_by_high(values, 32):
            self._bucket_for_add(high).add_many(lows.astype(np.uint32))
        self._invalidate()

    def remove(self, x: int) -> None:
        x = _check64(x)
        high = x >> 32
        b = self._buckets.get(high)
        if b is None:
            return
        b.remove(x & 0xFFFFFFFF)
        if b.is_empty():
            del self._buckets[high]
            self._keys_dirty = True
        self._invalidate()

    def contains(self, x: int) -> bool:
        x = _check64(x)
        b = self._buckets.get(x >> 32)
        return b is not None and b.contains(x & 0xFFFFFFFF)

    def contains_many(self, values) -> np.ndarray:
        """Vectorized membership: bool array parallel to ``values`` — the
        64-bit twin of ``RoaringBitmap.contains_many``, one vectorized
        bucket probe per distinct high-32 key (bucketed_membership)."""

        def probe(high, lows):
            b = self._buckets.get(high)
            return None if b is None else b.contains_many(lows.astype(np.uint32))

        return bucketed_membership(values, 32, probe)

    @staticmethod
    def _chunk_ranges(start: int, end: int):
        return chunk_ranges_64(start, end, 32)

    def _drop_if_empty(self, h: int) -> None:
        if h in self._buckets and self._buckets[h].is_empty():
            del self._buckets[h]
            self._keys_dirty = True

    def add_range(self, start: int, end: int) -> None:
        """Add [start, end) (Roaring64NavigableMap range add :1460)."""
        for h, lo, hi in self._chunk_ranges(start, end):
            self._bucket_for_add(h).add_range(lo, hi)
        self._invalidate()

    def remove_range(self, start: int, end: int) -> None:
        for h, lo, hi in self._chunk_ranges(start, end):
            b = self._buckets.get(h)
            if b is not None:
                b.remove_range(lo, hi)
                self._drop_if_empty(h)
        self._invalidate()

    def flip_range(self, start: int, end: int) -> None:
        """Flip [start, end) (Roaring64NavigableMap.flip :1530)."""
        for h, lo, hi in self._chunk_ranges(start, end):
            b = self._bucket_for_add(h)
            b.flip_range(lo, hi)
            self._drop_if_empty(h)
        self._invalidate()

    # ------------------------------------------------------------------
    # algebra (in-place, Java-style: Roaring64NavigableMap.java:773-935)
    # ------------------------------------------------------------------
    def ior(self, other: "Roaring64NavigableMap") -> "Roaring64NavigableMap":
        for h, ob in other._buckets.items():
            mine = self._buckets.get(h)
            if mine is None:
                self._buckets[h] = ob.clone()
                self._keys_dirty = True
            else:
                mine.ior(ob)
        self._invalidate()
        return self

    def iand(self, other: "Roaring64NavigableMap") -> "Roaring64NavigableMap":
        for h in list(self._buckets):
            ob = other._buckets.get(h)
            if ob is None:
                del self._buckets[h]
                self._keys_dirty = True
            else:
                mine = self._buckets[h]
                mine.iand(ob)
                if mine.is_empty():
                    del self._buckets[h]
                    self._keys_dirty = True
        self._invalidate()
        return self

    def ixor(self, other: "Roaring64NavigableMap") -> "Roaring64NavigableMap":
        # snapshot: other may alias self (x ^= x), and emptied buckets are
        # deleted from self._buckets during the walk
        for h, ob in list(other._buckets.items()):
            mine = self._buckets.get(h)
            if mine is None:
                self._buckets[h] = ob.clone()
                self._keys_dirty = True
            else:
                mine.ixor(ob)
                if mine.is_empty():
                    del self._buckets[h]
                    self._keys_dirty = True
        self._invalidate()
        return self

    def iandnot(self, other: "Roaring64NavigableMap") -> "Roaring64NavigableMap":
        for h in list(self._buckets):
            ob = other._buckets.get(h)
            if ob is not None:
                mine = self._buckets[h]
                mine.iandnot(ob)
                if mine.is_empty():
                    del self._buckets[h]
                    self._keys_dirty = True
        self._invalidate()
        return self

    # Java naming aliases
    or_inplace = ior
    and_inplace = iand
    xor_inplace = ixor
    andnot_inplace = iandnot

    def naive_lazy_or(self, other: "Roaring64NavigableMap") -> "Roaring64NavigableMap":
        """naivelazyor (Roaring64NavigableMap.java:730). The rank caches
        here are already invalidated lazily and rebuilt on demand, so the
        lazy protocol is structurally free: this IS ior."""
        return self.ior(other)

    naivelazyor = naive_lazy_or  # exact reference spelling

    def repair_after_lazy(self) -> None:
        """repairAfterLazy (Roaring64NavigableMap.java:1160) — a no-op:
        cumulative cardinalities rebuild on next rank/select."""

    @staticmethod
    def or_(a: "Roaring64NavigableMap", b: "Roaring64NavigableMap") -> "Roaring64NavigableMap":
        return a.clone().ior(b)

    @staticmethod
    def and_(a: "Roaring64NavigableMap", b: "Roaring64NavigableMap") -> "Roaring64NavigableMap":
        return a.clone().iand(b)

    @staticmethod
    def xor(a: "Roaring64NavigableMap", b: "Roaring64NavigableMap") -> "Roaring64NavigableMap":
        return a.clone().ixor(b)

    @staticmethod
    def andnot(a: "Roaring64NavigableMap", b: "Roaring64NavigableMap") -> "Roaring64NavigableMap":
        return a.clone().iandnot(b)

    __or__ = lambda self, o: Roaring64NavigableMap.or_(self, o)
    __and__ = lambda self, o: Roaring64NavigableMap.and_(self, o)
    __xor__ = lambda self, o: Roaring64NavigableMap.xor(self, o)
    __sub__ = lambda self, o: Roaring64NavigableMap.andnot(self, o)
    __ior__ = ior
    __iand__ = iand
    __ixor__ = ixor
    __isub__ = iandnot

    def intersects(self, other: "Roaring64NavigableMap") -> bool:
        for h, b in self._buckets.items():
            ob = other._buckets.get(h)
            if ob is not None and RoaringBitmap.intersects(b, ob):
                return True
        return False

    # ------------------------------------------------------------------
    # cardinality / order statistics
    # ------------------------------------------------------------------
    def get_cardinality(self) -> int:
        """getLongCardinality — served from the cached cumulative
        cardinalities (Roaring64NavigableMap.java:66-72), so repeat calls
        between writes are O(1)."""
        cum = self._cum()
        return int(cum[-1]) if len(cum) else 0

    def is_empty(self) -> bool:
        return not self._buckets

    def rank(self, x: int) -> int:
        """rankLong (Roaring64NavigableMap.java:351)."""
        from ..utils.order_stats import bucketed_rank

        x = _check64(x)
        high, low = x >> 32, x & 0xFFFFFFFF
        keys = self._sorted_keys()
        kt = self._comparator_keys()  # bisect in comparator order
        return bucketed_rank(
            kt, self._cum(), self._key_order(high),
            lambda i: self._buckets[keys[i]].rank(low),
        )

    def rank_many(self, values) -> np.ndarray:
        """Bulk rankLong: int64 counts aligned with ``values`` — one
        vectorized bucket resolution in comparator order plus one 32-bit
        ``rank_many`` per touched bucket (the bulk twin of rank; the
        reference answers order statistics one rankLong at a time,
        Roaring64NavigableMap.java:351). Negative ints are taken as their
        two's-complement bit patterns, like contains_many."""
        from ..utils.order_stats import bucketed_rank_many

        vals = np.asarray(values).astype(np.uint64, copy=False).ravel()
        if vals.size == 0 or not self._buckets:
            return np.zeros(vals.size, dtype=np.int64)
        keys = self._sorted_keys()
        if self._ckeys_arr is None:  # cached int64 comparator keys
            self._ckeys_arr = np.array(self._comparator_keys(), dtype=np.int64)
        kt = self._ckeys_arr
        highs = (vals >> np.uint64(32)).astype(np.int64)
        ch = (
            np.where(highs >= (1 << 31), highs - _MAX32, highs)
            if self.signed_longs
            else highs
        )
        lows = (vals & np.uint64(0xFFFFFFFF)).astype(np.int64)

        def in_bucket(i, pos):
            bucket = self._buckets[keys[i]]
            if pos.size < 8:
                # scattered probes (one or two per bucket): the scalar walk
                # beats the vectorized path's per-call numpy setup
                return np.array(
                    [bucket.rank_long(int(v)) for v in lows[pos]], dtype=np.int64
                )
            return bucket.rank_many(lows[pos])

        return bucketed_rank_many(kt, self._cum(), ch, in_bucket)

    def select_many(self, ranks) -> np.ndarray:
        """Bulk selectLong: uint64 values at the given comparator-order
        ranks, one vectorized bucket resolution plus one 32-bit
        ``select_many`` per touched bucket (bulk twin of select)."""
        from ..utils.order_stats import bucketed_select_many

        keys = self._sorted_keys()
        return bucketed_select_many(
            self._cum(),
            ranks,
            lambda i, js: (np.uint64(keys[i]) << np.uint64(32))
            | self._buckets[keys[i]].select_many(js).astype(np.uint64),
        )

    def select(self, j: int) -> int:
        """selectLong (Roaring64NavigableMap.java:473)."""
        from ..utils.order_stats import bucketed_select

        keys = self._sorted_keys()
        return bucketed_select(
            keys,
            self._cum(),
            j,
            lambda i, lj: (keys[i] << 32) | self._buckets[keys[i]].select(lj),
        )

    def first(self) -> int:
        if self.is_empty():
            raise ValueError("empty bitmap")
        k = self._sorted_keys()[0]
        return (k << 32) | self._buckets[k].first()

    def last(self) -> int:
        if self.is_empty():
            raise ValueError("empty bitmap")
        k = self._sorted_keys()[-1]
        return (k << 32) | self._buckets[k].last()

    def next_value(self, from_value: int) -> int:
        """Smallest value >= from_value, or -1."""
        from_value = _check64(from_value)
        high, low = from_value >> 32, from_value & 0xFFFFFFFF
        keys = self._sorted_keys()
        kt = self._comparator_keys()
        for i in range(bisect_left(kt, self._key_order(high)), len(keys)):
            k = keys[i]
            v = self._buckets[k].next_value(low if k == high else 0)
            if v >= 0:
                return (k << 32) | v
        return -1

    def previous_value(self, from_value: int) -> int:
        from_value = _check64(from_value)
        high, low = from_value >> 32, from_value & 0xFFFFFFFF
        keys = self._sorted_keys()
        kt = self._comparator_keys()
        for i in range(bisect_right(kt, self._key_order(high)) - 1, -1, -1):
            k = keys[i]
            v = self._buckets[k].previous_value(low if k == high else _MAX32 - 1)
            if v >= 0:
                return (k << 32) | v
        return -1

    # ------------------------------------------------------------------
    # structure
    # ------------------------------------------------------------------
    def run_optimize(self) -> bool:
        changed = False
        for b in self._buckets.values():
            changed |= b.run_optimize()
        return changed

    def clone(self) -> "Roaring64NavigableMap":
        out = Roaring64NavigableMap(signed_longs=self.signed_longs, supplier=self.supplier)
        out._buckets = {h: b.clone() for h, b in self._buckets.items()}
        out._keys_dirty = True
        return out

    def to_array(self) -> np.ndarray:
        """All values, unsigned-sorted, as uint64."""
        keys = self._sorted_keys()
        if not keys:
            return np.empty(0, dtype=np.uint64)
        parts = [
            self._buckets[k].to_array().astype(np.uint64) | np.uint64(k << 32)
            for k in keys
        ]
        return np.concatenate(parts)

    def __iter__(self) -> Iterator[int]:
        for k in self._sorted_keys():
            base = k << 32
            for v in self._buckets[k]:
                yield base | v

    def get_high_to_bitmap_count(self) -> int:
        """Bucket count (getHighToBitmap().size() analogue)."""
        return len(self._buckets)

    # -- reference long-tail surface (Roaring64NavigableMap.java) ---------
    def add_int(self, x: int) -> None:
        """addInt: the int zero-extended to a long (Util.toUnsignedLong)."""
        self.add(int(x) & 0xFFFFFFFF)

    def get_int_cardinality(self) -> int:
        """getIntCardinality (:330): cardinality, if it fits a signed int."""
        card = self.get_cardinality()
        if card > (1 << 31) - 1:
            raise OverflowError("cardinality exceeds 32-bit int")
        return card

    def get_long_iterator(self) -> Iterator[int]:
        return iter(self)

    def get_reverse_long_iterator(self) -> Iterator[int]:
        for k in reversed(self._sorted_keys()):
            base = k << 32
            for v in reversed(self._buckets[k]):
                yield base | v

    def for_each(self, consumer) -> None:
        for v in self:
            consumer(v)

    def limit(self, max_cardinality: int) -> "Roaring64NavigableMap":
        """First max_cardinality values as a new map (limit analogue)."""
        out = Roaring64NavigableMap(signed_longs=self.signed_longs, supplier=self.supplier)
        remaining = int(max_cardinality)
        for k in self._sorted_keys():
            if remaining <= 0:
                break
            b = self._buckets[k]
            card = b.get_cardinality()
            take = b.clone() if card <= remaining else b.limit(remaining)
            out._buckets[k] = take
            out._keys_dirty = True
            remaining -= take.get_cardinality()
        return out

    def clear(self) -> None:
        """Empty in place (Roaring64NavigableMap.clear)."""
        self._buckets = {}
        self._keys = []
        self._ckeys = None
        self._keys_dirty = False
        self._invalidate()

    empty = clear  # reference also exposes empty()

    def trim(self) -> None:
        """No-op: numpy storage is exact-sized."""

    def get_size_in_bytes(self) -> int:
        """In-memory estimate: bucket payloads + per-bucket key overhead."""
        return sum(8 + b.get_size_in_bytes() for b in self._buckets.values())

    get_long_size_in_bytes = get_size_in_bytes

    # ------------------------------------------------------------------
    # serialization (portable 64-bit spec)
    # ------------------------------------------------------------------
    def serialize(self, mode: Optional[int] = None) -> bytes:
        """Serialize in the active mode (legacy/portable switch,
        Roaring64NavigableMap.java:51 + serialize dispatch)."""
        if mode is None:
            mode = type(self).SERIALIZATION_MODE
        if mode == SERIALIZATION_MODE_LEGACY:
            return self.serialize_legacy()
        return self.serialize_portable()

    def serialize_portable(self) -> bytes:
        """Portable 64-bit spec (serializePortable): LE u64 count, per
        bucket LE u32 key + 32-bit spec bytes, unsigned key order."""
        import struct

        keys = sorted(self._buckets)  # portable order is always unsigned
        parts = [struct.pack("<Q", len(keys))]
        for k in keys:
            parts.append(struct.pack("<I", k))
            parts.append(self._buckets[k].serialize())
        return b"".join(parts)

    def serialize_legacy(self) -> bytes:
        """Legacy Java format (serializeLegacy): u8 bool signed_longs,
        BE i32 count, per bucket BE i32 key + 32-bit spec bytes, buckets in
        comparator order."""
        import struct

        keys = self._sorted_keys()
        parts = [struct.pack(">?i", self.signed_longs, len(keys))]
        for k in keys:
            parts.append(struct.pack(">i", k - _MAX32 if k >= (1 << 31) else k))
            parts.append(self._buckets[k].serialize())
        return b"".join(parts)

    def serialized_size_in_bytes(self, mode: Optional[int] = None) -> int:
        from ..serialization import serialized_size_in_bytes

        if mode is None:
            mode = type(self).SERIALIZATION_MODE
        header = 5 if mode == SERIALIZATION_MODE_LEGACY else 8
        return header + sum(
            4 + serialized_size_in_bytes(b) for b in self._buckets.values()
        )

    @staticmethod
    def deserialize(data, mode: Optional[int] = None) -> "Roaring64NavigableMap":
        if mode is None:
            mode = Roaring64NavigableMap.SERIALIZATION_MODE
        if mode == SERIALIZATION_MODE_LEGACY:
            return Roaring64NavigableMap.deserialize_legacy(data)
        return Roaring64NavigableMap.deserialize_portable(data)

    @staticmethod
    def _as_view(data) -> memoryview:
        return memoryview(
            bytes(data) if not isinstance(data, (bytes, bytearray, memoryview)) else data
        )

    @staticmethod
    def deserialize_portable(data) -> "Roaring64NavigableMap":
        import struct

        from ..serialization import read_into

        buf = Roaring64NavigableMap._as_view(data)
        if len(buf) < 8:
            raise InvalidRoaringFormat("truncated 64-bit header")
        (count,) = struct.unpack_from("<Q", buf, 0)
        if count > len(buf) // 4:  # each bucket needs >= 4 bytes of key alone
            raise InvalidRoaringFormat(f"implausible bucket count {count}")
        pos = 8
        out = Roaring64NavigableMap()
        prev_key = -1
        for _ in range(count):
            if pos + 4 > len(buf):
                raise InvalidRoaringFormat("truncated bucket key")
            (key,) = struct.unpack_from("<I", buf, pos)
            pos += 4
            if key <= prev_key:
                raise InvalidRoaringFormat("bucket keys not strictly increasing")
            prev_key = key
            bm = RoaringBitmap()
            pos += read_into(bm, buf[pos:])
            if not bm.is_empty():
                out._buckets[key] = bm
        out._keys_dirty = True
        return out

    def serialize_into(self, fileobj, mode: Optional[int] = None) -> int:
        """Stream overload (the Externalizable/DataOutput path,
        Roaring64NavigableMap.java writeExternal/serialize); returns bytes
        written. ``mode`` as in :meth:`serialize`."""
        data = self.serialize(mode)
        fileobj.write(data)
        return len(data)

    @staticmethod
    def deserialize_from(fileobj, mode: Optional[int] = None) -> "Roaring64NavigableMap":
        """Stream twin: consumes exactly one 64-bit map in the given (or
        active) mode, leaving the stream at the next byte — bucket payloads
        ride RoaringBitmap.deserialize_from's exact-consumption contract."""
        import struct

        from ..serialization import read_exact

        if mode is None:
            mode = Roaring64NavigableMap.SERIALIZATION_MODE
        legacy = mode == SERIALIZATION_MODE_LEGACY
        header = read_exact(fileobj, 5 if legacy else 8)
        if legacy:
            signed, count = struct.unpack(">?i", header)
            if count < 0:
                raise InvalidRoaringFormat(f"implausible bucket count {count}")
            out = Roaring64NavigableMap(signed_longs=signed)
        else:
            (count,) = struct.unpack("<Q", header)
            if count > (1 << 32):
                raise InvalidRoaringFormat(f"implausible bucket count {count}")
            out = Roaring64NavigableMap()
        prev_key = -1
        for _ in range(count):
            key_raw = read_exact(fileobj, 4)
            if legacy:
                (key,) = struct.unpack(">i", key_raw)
                key &= 0xFFFFFFFF  # stored two's-complement
                if key in out._buckets:
                    raise InvalidRoaringFormat("duplicate bucket key")
            else:
                (key,) = struct.unpack("<I", key_raw)
                if key <= prev_key:
                    raise InvalidRoaringFormat("bucket keys not strictly increasing")
                prev_key = key
            bm = RoaringBitmap.deserialize_from(fileobj)
            if not bm.is_empty():
                out._buckets[key] = bm
        out._keys_dirty = True
        return out

    @staticmethod
    def deserialize_legacy(data) -> "Roaring64NavigableMap":
        import struct

        from ..serialization import read_into

        buf = Roaring64NavigableMap._as_view(data)
        if len(buf) < 5:
            raise InvalidRoaringFormat("truncated legacy 64-bit header")
        signed, count = struct.unpack_from(">?i", buf, 0)
        if count < 0 or count > len(buf) // 4:
            raise InvalidRoaringFormat(f"implausible bucket count {count}")
        pos = 5
        out = Roaring64NavigableMap(signed_longs=signed)
        for _ in range(count):
            if pos + 4 > len(buf):
                raise InvalidRoaringFormat("truncated bucket key")
            (key,) = struct.unpack_from(">i", buf, pos)
            pos += 4
            key &= 0xFFFFFFFF  # stored two's-complement
            if key in out._buckets:
                raise InvalidRoaringFormat("duplicate bucket key")
            bm = RoaringBitmap()
            pos += read_into(bm, buf[pos:])
            if not bm.is_empty():
                out._buckets[key] = bm
        out._keys_dirty = True
        return out

    # ------------------------------------------------------------------
    def __eq__(self, other):
        if not isinstance(other, Roaring64NavigableMap):
            return NotImplemented
        if set(self._buckets) != set(other._buckets):
            return False
        return all(b == other._buckets[h] for h, b in self._buckets.items())

    def __hash__(self):
        return hash(self.to_array().tobytes())

    def __len__(self) -> int:
        return self.get_cardinality()

    def __contains__(self, x: int) -> bool:
        return self.contains(x)

    def __bool__(self) -> bool:
        return not self.is_empty()

    def __repr__(self) -> str:
        card = self.get_cardinality()
        head = ",".join(str(v) for v in self.to_array()[:8].tolist())
        return f"Roaring64NavigableMap(card={card}, values=[{head}{'...' if card > 8 else ''}])"

    # reference facade naming aliases (Roaring64NavigableMap.java addLong :50,
    # removeLong, getLongCardinality) for drop-in familiarity
    add_long = add
    remove_long = remove
    contains_long = contains
    get_long_cardinality = get_cardinality

    def __reduce__(self):
        """Pickle via the active SERIALIZATION_MODE wire format (the
        Externalizable analogue, Roaring64NavigableMap.java:35-52).
        signed_longs and the bucket supplier are config, not wire state,
        so they ride alongside the bytes."""
        mode = Roaring64NavigableMap.SERIALIZATION_MODE
        supplier = None if self.supplier is RoaringBitmap else self.supplier
        return _r64nm_unpickle, (self.serialize(mode), mode, self.signed_longs, supplier)


def _r64nm_unpickle(blob, mode, signed, supplier=None):
    out = Roaring64NavigableMap.deserialize(blob, mode)
    out.signed_longs = signed
    if supplier is not None:
        out.supplier = supplier
        # re-adopt the deserialized buckets into the supplier's type so the
        # BitmapDataProviderSupplier contract survives the round trip
        for k, b in out._buckets.items():
            nb = supplier()
            nb.high_low_container = b.high_low_container
            out._buckets[k] = nb
    return out
