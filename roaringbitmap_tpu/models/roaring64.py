"""64-bit layer: key-space extension over the 32-bit machinery.

The reference ships two 64-bit designs — ``Roaring64NavigableMap``
(longlong/Roaring64NavigableMap.java:29: NavigableMap of high-32 bits ->
32-bit bitmap, cached cumulative cardinalities for rank/select :66-72) and
the ART-based ``Roaring64Bitmap`` (longlong/Roaring64Bitmap.java:29: high-48
trie -> 16-bit container). This framework uses one class with the
NavigableMap decomposition: a sorted high-32 index over full 32-bit
RoaringBitmaps. Rationale (TPU-first, SURVEY §5 "long-context" analogue):
every bucket reuses the whole 32-bit stack including the packed device
aggregation path, so 64-bit wide-ORs batch exactly like 32-bit ones; an ART
trie is a pointer-chasing CPU structure with nothing to offer the device
path, and the sorted-dict index has identical asymptotics at the bucket
counts Python can hold.

Serialization implements the portable 64-bit RoaringFormatSpec
(Roaring64NavigableMap.java:47 SERIALIZATION_MODE_PORTABLE, validated
byte-for-byte against the CRoaring-written golden files
testdata/64map*.bin): uint64 LE bucket count, then per bucket uint32 LE high
key + standard 32-bit serialization, buckets in unsigned key order.

Values are unsigned 64-bit: [0, 2^64).
"""

from __future__ import annotations

from bisect import bisect_left, bisect_right
from typing import Iterable, Iterator, List, Optional

import numpy as np

from .roaring import RoaringBitmap
from ..serialization import InvalidRoaringFormat

_MAX64 = 1 << 64
_MAX32 = 1 << 32


def _check64(x: int) -> int:
    x = int(x)
    if not 0 <= x < _MAX64:
        raise ValueError(f"value {x} outside unsigned 64-bit range")
    return x


class Roaring64Bitmap:
    """Unsigned 64-bit Roaring bitmap (Roaring64NavigableMap /
    Roaring64Bitmap capability union)."""

    __slots__ = ("_buckets", "_keys", "_keys_dirty", "_cum_cards", "_cum_dirty")

    def __init__(self, values: Optional[Iterable[int]] = None):
        self._buckets: dict = {}  # high32 -> RoaringBitmap
        self._keys: List[int] = []
        self._keys_dirty = False
        self._cum_cards: Optional[np.ndarray] = None
        self._cum_dirty = True
        if values is not None:
            self.add_many(values)

    # ------------------------------------------------------------------
    def _sorted_keys(self) -> List[int]:
        if self._keys_dirty:
            self._keys = sorted(self._buckets)
            self._keys_dirty = False
        return self._keys

    def _invalidate(self):
        self._cum_dirty = True

    def _cum(self) -> np.ndarray:
        """Cached cumulative cardinalities per bucket
        (Roaring64NavigableMap.java:66-72)."""
        if self._cum_dirty:
            keys = self._sorted_keys()
            cards = np.array(
                [self._buckets[k].get_cardinality() for k in keys], dtype=np.int64
            )
            self._cum_cards = np.cumsum(cards) if keys else np.empty(0, dtype=np.int64)
            self._cum_dirty = False
        return self._cum_cards

    def _bucket_for_add(self, high: int) -> RoaringBitmap:
        b = self._buckets.get(high)
        if b is None:
            b = RoaringBitmap()
            self._buckets[high] = b
            self._keys_dirty = True
        return b

    # ------------------------------------------------------------------
    # construction / point ops
    # ------------------------------------------------------------------
    @staticmethod
    def bitmap_of(*values: int) -> "Roaring64Bitmap":
        return Roaring64Bitmap(values)

    def add(self, x: int) -> None:
        """addLong (Roaring64Bitmap.java:50)."""
        x = _check64(x)
        self._bucket_for_add(x >> 32).add(x & 0xFFFFFFFF)
        self._invalidate()

    def add_many(self, values: Iterable[int]) -> None:
        if not isinstance(values, np.ndarray):
            values = np.fromiter(iter(values), dtype=np.uint64)
        if np.issubdtype(values.dtype, np.signedinteger) and values.size and values.min() < 0:
            raise ValueError("values outside unsigned 64-bit range")
        v = np.asarray(values).astype(np.uint64).ravel()
        if v.size == 0:
            return
        highs = (v >> np.uint64(32)).astype(np.int64)
        lows = (v & np.uint64(0xFFFFFFFF)).astype(np.uint32)
        order = np.argsort(highs, kind="stable")
        highs, lows = highs[order], lows[order]
        boundaries = np.nonzero(np.diff(highs))[0] + 1
        starts = np.concatenate(([0], boundaries))
        ends = np.concatenate((boundaries, [v.size]))
        for s, e in zip(starts.tolist(), ends.tolist()):
            self._bucket_for_add(int(highs[s])).add_many(lows[s:e])
        self._invalidate()

    def remove(self, x: int) -> None:
        x = _check64(x)
        high = x >> 32
        b = self._buckets.get(high)
        if b is None:
            return
        b.remove(x & 0xFFFFFFFF)
        if b.is_empty():
            del self._buckets[high]
            self._keys_dirty = True
        self._invalidate()

    def contains(self, x: int) -> bool:
        x = _check64(x)
        b = self._buckets.get(x >> 32)
        return b is not None and b.contains(x & 0xFFFFFFFF)

    @staticmethod
    def _chunk_ranges(start: int, end: int):
        """Split a 64-bit half-open range into per-bucket (high, lo, hi)
        pieces with 32-bit half-open sub-ranges."""
        start, end = int(start), int(end)
        if not 0 <= start <= end <= _MAX64:
            raise ValueError(f"invalid range [{start}, {end})")
        if start == end:
            return
        h_start, h_end = start >> 32, (end - 1) >> 32
        for h in range(h_start, h_end + 1):
            lo = start & 0xFFFFFFFF if h == h_start else 0
            hi = ((end - 1) & 0xFFFFFFFF) + 1 if h == h_end else _MAX32
            yield h, lo, hi

    def _drop_if_empty(self, h: int) -> None:
        if h in self._buckets and self._buckets[h].is_empty():
            del self._buckets[h]
            self._keys_dirty = True

    def add_range(self, start: int, end: int) -> None:
        """Add [start, end) (Roaring64NavigableMap range add :1460)."""
        for h, lo, hi in self._chunk_ranges(start, end):
            self._bucket_for_add(h).add_range(lo, hi)
        self._invalidate()

    def remove_range(self, start: int, end: int) -> None:
        for h, lo, hi in self._chunk_ranges(start, end):
            b = self._buckets.get(h)
            if b is not None:
                b.remove_range(lo, hi)
                self._drop_if_empty(h)
        self._invalidate()

    def flip_range(self, start: int, end: int) -> None:
        """Flip [start, end) (Roaring64NavigableMap.flip :1530)."""
        for h, lo, hi in self._chunk_ranges(start, end):
            b = self._bucket_for_add(h)
            b.flip_range(lo, hi)
            self._drop_if_empty(h)
        self._invalidate()

    # ------------------------------------------------------------------
    # algebra (in-place, Java-style: Roaring64NavigableMap.java:773-935)
    # ------------------------------------------------------------------
    def ior(self, other: "Roaring64Bitmap") -> "Roaring64Bitmap":
        for h, ob in other._buckets.items():
            mine = self._buckets.get(h)
            if mine is None:
                self._buckets[h] = ob.clone()
                self._keys_dirty = True
            else:
                mine.ior(ob)
        self._invalidate()
        return self

    def iand(self, other: "Roaring64Bitmap") -> "Roaring64Bitmap":
        for h in list(self._buckets):
            ob = other._buckets.get(h)
            if ob is None:
                del self._buckets[h]
                self._keys_dirty = True
            else:
                mine = self._buckets[h]
                mine.iand(ob)
                if mine.is_empty():
                    del self._buckets[h]
                    self._keys_dirty = True
        self._invalidate()
        return self

    def ixor(self, other: "Roaring64Bitmap") -> "Roaring64Bitmap":
        for h, ob in other._buckets.items():
            mine = self._buckets.get(h)
            if mine is None:
                self._buckets[h] = ob.clone()
                self._keys_dirty = True
            else:
                mine.ixor(ob)
                if mine.is_empty():
                    del self._buckets[h]
                    self._keys_dirty = True
        self._invalidate()
        return self

    def iandnot(self, other: "Roaring64Bitmap") -> "Roaring64Bitmap":
        for h in list(self._buckets):
            ob = other._buckets.get(h)
            if ob is not None:
                mine = self._buckets[h]
                mine.iandnot(ob)
                if mine.is_empty():
                    del self._buckets[h]
                    self._keys_dirty = True
        self._invalidate()
        return self

    # Java naming aliases
    or_inplace = ior
    and_inplace = iand
    xor_inplace = ixor
    andnot_inplace = iandnot

    @staticmethod
    def or_(a: "Roaring64Bitmap", b: "Roaring64Bitmap") -> "Roaring64Bitmap":
        return a.clone().ior(b)

    @staticmethod
    def and_(a: "Roaring64Bitmap", b: "Roaring64Bitmap") -> "Roaring64Bitmap":
        return a.clone().iand(b)

    @staticmethod
    def xor(a: "Roaring64Bitmap", b: "Roaring64Bitmap") -> "Roaring64Bitmap":
        return a.clone().ixor(b)

    @staticmethod
    def andnot(a: "Roaring64Bitmap", b: "Roaring64Bitmap") -> "Roaring64Bitmap":
        return a.clone().iandnot(b)

    __or__ = lambda self, o: Roaring64Bitmap.or_(self, o)
    __and__ = lambda self, o: Roaring64Bitmap.and_(self, o)
    __xor__ = lambda self, o: Roaring64Bitmap.xor(self, o)
    __sub__ = lambda self, o: Roaring64Bitmap.andnot(self, o)
    __ior__ = ior
    __iand__ = iand
    __ixor__ = ixor
    __isub__ = iandnot

    def intersects(self, other: "Roaring64Bitmap") -> bool:
        for h, b in self._buckets.items():
            ob = other._buckets.get(h)
            if ob is not None and RoaringBitmap.intersects(b, ob):
                return True
        return False

    # ------------------------------------------------------------------
    # cardinality / order statistics
    # ------------------------------------------------------------------
    def get_cardinality(self) -> int:
        """getLongCardinality."""
        return sum(b.get_cardinality() for b in self._buckets.values())

    def is_empty(self) -> bool:
        return not self._buckets

    def rank(self, x: int) -> int:
        """rankLong (Roaring64NavigableMap.java:351)."""
        from ..utils.order_stats import bucketed_rank

        x = _check64(x)
        high, low = x >> 32, x & 0xFFFFFFFF
        keys = self._sorted_keys()
        return bucketed_rank(
            keys, self._cum(), high, lambda i: self._buckets[keys[i]].rank(low)
        )

    def select(self, j: int) -> int:
        """selectLong (Roaring64NavigableMap.java:473)."""
        from ..utils.order_stats import bucketed_select

        keys = self._sorted_keys()
        return bucketed_select(
            keys,
            self._cum(),
            j,
            lambda i, lj: (keys[i] << 32) | self._buckets[keys[i]].select(lj),
        )

    def first(self) -> int:
        if self.is_empty():
            raise ValueError("empty bitmap")
        k = self._sorted_keys()[0]
        return (k << 32) | self._buckets[k].first()

    def last(self) -> int:
        if self.is_empty():
            raise ValueError("empty bitmap")
        k = self._sorted_keys()[-1]
        return (k << 32) | self._buckets[k].last()

    def next_value(self, from_value: int) -> int:
        """Smallest value >= from_value, or -1."""
        from_value = _check64(from_value)
        high, low = from_value >> 32, from_value & 0xFFFFFFFF
        keys = self._sorted_keys()
        for i in range(bisect_left(keys, high), len(keys)):
            k = keys[i]
            v = self._buckets[k].next_value(low if k == high else 0)
            if v >= 0:
                return (k << 32) | v
        return -1

    def previous_value(self, from_value: int) -> int:
        from_value = _check64(from_value)
        high, low = from_value >> 32, from_value & 0xFFFFFFFF
        keys = self._sorted_keys()
        for i in range(bisect_right(keys, high) - 1, -1, -1):
            k = keys[i]
            v = self._buckets[k].previous_value(low if k == high else _MAX32 - 1)
            if v >= 0:
                return (k << 32) | v
        return -1

    # ------------------------------------------------------------------
    # structure
    # ------------------------------------------------------------------
    def run_optimize(self) -> bool:
        changed = False
        for b in self._buckets.values():
            changed |= b.run_optimize()
        return changed

    def clone(self) -> "Roaring64Bitmap":
        out = Roaring64Bitmap()
        out._buckets = {h: b.clone() for h, b in self._buckets.items()}
        out._keys_dirty = True
        return out

    def to_array(self) -> np.ndarray:
        """All values, unsigned-sorted, as uint64."""
        keys = self._sorted_keys()
        if not keys:
            return np.empty(0, dtype=np.uint64)
        parts = [
            self._buckets[k].to_array().astype(np.uint64) | np.uint64(k << 32)
            for k in keys
        ]
        return np.concatenate(parts)

    def __iter__(self) -> Iterator[int]:
        for k in self._sorted_keys():
            base = k << 32
            for v in self._buckets[k]:
                yield base | v

    def get_high_to_bitmap_count(self) -> int:
        """Bucket count (getHighToBitmap().size() analogue)."""
        return len(self._buckets)

    # ------------------------------------------------------------------
    # serialization (portable 64-bit spec)
    # ------------------------------------------------------------------
    def serialize(self) -> bytes:
        import struct

        keys = self._sorted_keys()
        parts = [struct.pack("<Q", len(keys))]
        for k in keys:
            parts.append(struct.pack("<I", k))
            parts.append(self._buckets[k].serialize())
        return b"".join(parts)

    def serialized_size_in_bytes(self) -> int:
        from ..serialization import serialized_size_in_bytes

        return 8 + sum(
            4 + serialized_size_in_bytes(b) for b in self._buckets.values()
        )

    @staticmethod
    def deserialize(data) -> "Roaring64Bitmap":
        import struct

        from ..serialization import read_into

        buf = memoryview(bytes(data) if not isinstance(data, (bytes, bytearray, memoryview)) else data)
        if len(buf) < 8:
            raise InvalidRoaringFormat("truncated 64-bit header")
        (count,) = struct.unpack_from("<Q", buf, 0)
        if count > len(buf) // 4:  # each bucket needs >= 4 bytes of key alone
            raise InvalidRoaringFormat(f"implausible bucket count {count}")
        pos = 8
        out = Roaring64Bitmap()
        prev_key = -1
        for _ in range(count):
            if pos + 4 > len(buf):
                raise InvalidRoaringFormat("truncated bucket key")
            (key,) = struct.unpack_from("<I", buf, pos)
            pos += 4
            if key <= prev_key:
                raise InvalidRoaringFormat("bucket keys not strictly increasing")
            prev_key = key
            bm = RoaringBitmap()
            pos += read_into(bm, buf[pos:])
            if not bm.is_empty():
                out._buckets[key] = bm
        out._keys_dirty = True
        return out

    # ------------------------------------------------------------------
    def __eq__(self, other):
        if not isinstance(other, Roaring64Bitmap):
            return NotImplemented
        if set(self._buckets) != set(other._buckets):
            return False
        return all(b == other._buckets[h] for h, b in self._buckets.items())

    def __hash__(self):
        return hash(self.to_array().tobytes())

    def __len__(self) -> int:
        return self.get_cardinality()

    def __contains__(self, x: int) -> bool:
        return self.contains(x)

    def __bool__(self) -> bool:
        return not self.is_empty()

    def __repr__(self) -> str:
        card = self.get_cardinality()
        head = ",".join(str(v) for v in self.to_array()[:8].tolist())
        return f"Roaring64Bitmap(card={card}, values=[{head}{'...' if card > 8 else ''}])"


# The reference exposes the same capability under this name with a pluggable
# backend (longlong/Roaring64NavigableMap.java:29); here it is one class.
Roaring64NavigableMap = Roaring64Bitmap
