"""Buffer-package BSI twins: ``MutableBitSliceIndex`` /
``ImmutableBitSliceIndex`` (bsi/buffer/MutableBitSliceIndex.java:20,
ImmutableBitSliceIndex.java:17, shared base BitSliceIndexBase.java:30).

In the reference the buffer twins re-run every algorithm over
ByteBuffer-backed Mappeable containers; in this framework the heap/buffer
split collapses (models/immutable.py explains why: numpy views already give
zero-copy over serialized bytes), so the Mutable twin IS the 32-bit BSI
with the buffer API's method names, and the Immutable twin wraps it behind
a mutation guard and deserializes lazily from a buffer.

The reference's fork-join variants (``parallelIn``
BitSliceIndexBase.java:611, ``parallelTransposeWithCount`` :578) map to the
batched device engine: on TPU the O'Neil chain is already one fused
dispatch over all key-chunks at once (models/bsi.py), which *is* the
parallel evaluation — the ``parallelism`` argument is accepted for API
compatibility and ignored beyond choosing the engine.
"""

from __future__ import annotations

import struct
from typing import List, Optional, Tuple

import numpy as np

from ..serialization import InvalidRoaringFormat
from .bsi import Operation, RoaringBitmapSliceIndex
from .roaring import RoaringBitmap


class _RangeQueryAPI:
    """The shared query surface of BitSliceIndexBase.java:351-620 — the
    reference defines rangeEQ..range, parallelIn, and
    parallelTransposeWithCount once on the base both twins extend; this
    mixin is that base. Requires ``compare``/``get_existence_bitmap``/
    ``slices`` on self."""

    # no state: keeps ImmutableBitSliceIndex's __slots__ effective (a
    # slotless base would silently hand it a __dict__ and let attribute
    # assignment bypass the immutability guard — code-review r4)
    __slots__ = ()

    # range* named queries (BitSliceIndexBase.java:351-420)
    def range_eq(self, found_set: Optional[RoaringBitmap], predicate: int) -> RoaringBitmap:
        return self.compare(Operation.EQ, predicate, 0, found_set)

    def range_neq(self, found_set: Optional[RoaringBitmap], predicate: int) -> RoaringBitmap:
        return self.compare(Operation.NEQ, predicate, 0, found_set)

    def range_lt(self, found_set: Optional[RoaringBitmap], predicate: int) -> RoaringBitmap:
        return self.compare(Operation.LT, predicate, 0, found_set)

    def range_le(self, found_set: Optional[RoaringBitmap], predicate: int) -> RoaringBitmap:
        return self.compare(Operation.LE, predicate, 0, found_set)

    def range_gt(self, found_set: Optional[RoaringBitmap], predicate: int) -> RoaringBitmap:
        return self.compare(Operation.GT, predicate, 0, found_set)

    def range_ge(self, found_set: Optional[RoaringBitmap], predicate: int) -> RoaringBitmap:
        return self.compare(Operation.GE, predicate, 0, found_set)

    def range(self, found_set: Optional[RoaringBitmap], start: int, end: int) -> RoaringBitmap:
        return self.compare(Operation.RANGE, start, end, found_set)

    def parallel_in(
        self,
        parallelism: int,
        operation: Operation,
        start_or_value: int,
        end: int = 0,
        found_set: Optional[RoaringBitmap] = None,
    ) -> RoaringBitmap:
        """parallelIn (BitSliceIndexBase.java:611). The batched engine
        evaluates all key-chunks in one dispatch; parallelism is accepted
        for API compatibility."""
        return self.compare(operation, start_or_value, end, found_set)

    def parallel_transpose_with_count(
        self, found_set: Optional[RoaringBitmap] = None, parallelism: int = 0
    ) -> "MutableBitSliceIndex":
        """parallelTransposeWithCount (BitSliceIndexBase.java:578):
        value -> multiplicity BSI."""
        ebm = self.get_existence_bitmap()
        cols = (
            ebm if found_set is None else RoaringBitmap.and_(ebm, found_set)
        ).to_array()
        out = MutableBitSliceIndex()
        if cols.size == 0:
            return out
        from .bsi import transpose_value_counts

        uniq, counts = transpose_value_counts(cols, self.slices)
        out.set_values((uniq.astype(np.uint32), counts.astype(np.int64)))
        return out


class MutableBitSliceIndex(_RangeQueryAPI, RoaringBitmapSliceIndex):
    """bsi/buffer/MutableBitSliceIndex.java:20 — the mutable buffer twin."""

    get_long_cardinality = RoaringBitmapSliceIndex.get_cardinality

    def get_mutable_slice(self, i: int) -> RoaringBitmap:
        """getMutableSlice (MutableBitSliceIndex.java:136)."""
        return self.slices[i]

    def add_digit(self, found_set: RoaringBitmap, i: int) -> None:
        """addDigit (MutableBitSliceIndex.java:121)."""
        self._grow(i + 1)
        self._add_digit(found_set, i)
        self._version += 1

    def to_immutable_bit_slice_index(self) -> "ImmutableBitSliceIndex":
        """toImmutableBitSliceIndex (MutableBitSliceIndex.java:411) — O(1),
        shares structure (castable like Mutable->ImmutableRoaringBitmap)."""
        return ImmutableBitSliceIndex(self)

    @staticmethod
    def deserialize(data) -> "MutableBitSliceIndex":
        base = RoaringBitmapSliceIndex.deserialize(data)
        out = MutableBitSliceIndex()
        out.__dict__.update(base.__dict__)
        return out


class _LazySlices:
    """Sequence of slice bitmaps decoded zero-copy on first access — the
    Mappeable analogue of ImmutableBitSliceIndex's per-slice ByteBuffer
    views (ImmutableBitSliceIndex.java:52)."""

    def __init__(self, buf: memoryview, extents: List[Tuple[int, int]]):
        self._buf = buf
        self._extents = extents
        self._cache: dict = {}

    def __len__(self) -> int:
        return len(self._extents)

    def __getitem__(self, i):
        from .immutable import ImmutableRoaringBitmap

        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(len(self)))]
        if i < 0:
            i += len(self)
        got = self._cache.get(i)
        if got is None:
            off, ln = self._extents[i]
            got = ImmutableRoaringBitmap(self._buf[off : off + ln])
            self._cache[i] = got
        return got

    def __iter__(self):
        return (self[i] for i in range(len(self)))


def _map_bsi(buf: memoryview) -> RoaringBitmapSliceIndex:
    """Open the BSI wire format (models/bsi.py serialize) as a lazy
    zero-copy index: the existence bitmap and each slice become
    ImmutableRoaringBitmap views; construction cost is one O(#containers)
    header scan per bitmap to find extents, with no payload copies."""
    from .immutable import ImmutableRoaringBitmap

    if len(buf) < 9:
        raise InvalidRoaringFormat("truncated BSI header")
    min_v, max_v, ro = struct.unpack_from("<iib", buf, 0)
    pos = 9
    ebm = ImmutableRoaringBitmap(buf[pos:])
    pos += ebm.serialized_size_in_bytes()
    if pos + 4 > len(buf):
        raise InvalidRoaringFormat("truncated BSI slice count")
    (depth,) = struct.unpack_from("<i", buf, pos)
    pos += 4
    if depth < 0 or depth > 64:
        raise InvalidRoaringFormat(f"implausible BSI depth {depth}")
    extents: List[Tuple[int, int]] = []
    for _ in range(depth):
        # header-only parse to learn this slice's extent; the view is
        # rebuilt lazily (and cached) on first real access
        probe = ImmutableRoaringBitmap(buf[pos:])
        ln = probe.serialized_size_in_bytes()
        extents.append((pos, ln))
        pos += ln
    out = RoaringBitmapSliceIndex()
    out.min_value, out.max_value = min_v, max_v
    out.run_optimized = bool(ro)
    out.ebm = ebm
    out.slices = _LazySlices(buf, extents)
    return out


class ImmutableBitSliceIndex(_RangeQueryAPI):
    """bsi/buffer/ImmutableBitSliceIndex.java:17 — read-only view, either
    over an existing index (O(1) cast) or mapped zero-copy from a
    serialized buffer (ImmutableBitSliceIndex(ByteBuffer), :52): slice
    payloads stay in the source buffer and are viewed lazily. Query
    surface (rangeEQ..range, parallelIn, parallelTransposeWithCount) is
    the shared _RangeQueryAPI, exactly as the reference defines it on the
    base class both twins extend."""

    __slots__ = ("_base",)

    def __init__(self, source):
        if isinstance(source, RoaringBitmapSliceIndex):
            self._base = source
        else:  # serialized buffer: lazy zero-copy map
            buf = memoryview(
                source
                if isinstance(source, (bytes, bytearray, memoryview))
                else bytes(source)
            )
            self._base = _map_bsi(buf)

    # read surface delegates
    def bit_count(self) -> int:
        return self._base.bit_count()

    def get_long_cardinality(self) -> int:
        return self._base.get_cardinality()

    get_cardinality = get_long_cardinality

    @property
    def slices(self):
        """Read-only slice views (consumed by the shared query mixin)."""
        return self._base.slices

    def has_run_compression(self) -> bool:
        return self._base.has_run_compression()

    def get_value(self, column_id: int) -> Tuple[int, bool]:
        return self._base.get_value(column_id)

    def value_exist(self, column_id: int) -> bool:
        return self._base.value_exist(column_id)

    def get_existence_bitmap(self) -> RoaringBitmap:
        return self._base.ebm

    @property
    def min_value(self) -> int:
        return self._base.min_value

    @property
    def max_value(self) -> int:
        return self._base.max_value

    def compare(self, operation, start_or_value, end=0, found_set=None, mode=None):
        return self._base.compare(operation, start_or_value, end, found_set, mode)

    def compare_cardinality(
        self, operation, start_or_value, end=0, found_set=None, mode=None
    ):
        return self._base.compare_cardinality(
            operation, start_or_value, end, found_set, mode
        )

    def compare_cardinality_many(
        self, operation, values, ends=None, found_set=None, mode=None
    ):
        return self._base.compare_cardinality_many(
            operation, values, ends, found_set, mode
        )

    def sum(self, found_set=None):
        return self._base.sum(found_set)

    def top_k(self, found_set, k):
        return self._base.top_k(found_set, k)

    def transpose(self, found_set=None):
        return self._base.transpose(found_set)

    def to_pair_list(self, found_set=None):
        return self._base.to_pair_list(found_set)

    def serialize(self) -> bytes:
        return self._base.serialize()

    def serialize_into(self, fileobj) -> int:
        return self._base.serialize_into(fileobj)

    @staticmethod
    def deserialize_from(fileobj) -> "ImmutableBitSliceIndex":
        """Consume one BSI from the stream and wrap it read-only (the O(1)
        cast; a stream cannot be lazily mapped the way a buffer can)."""
        return ImmutableBitSliceIndex(RoaringBitmapSliceIndex.deserialize_from(fileobj))

    def serialized_size_in_bytes(self) -> int:
        return self._base.serialized_size_in_bytes()

    def to_mutable_bit_slice_index(self) -> MutableBitSliceIndex:
        """Deep copy back to the mutable twin."""
        base = self._base.clone()
        out = MutableBitSliceIndex()
        out.__dict__.update(base.__dict__)
        return out

    # mutation guard
    def _refuse(self, *_a, **_k):
        raise TypeError("ImmutableBitSliceIndex does not support mutation")

    set_value = set_values = add = merge = run_optimize = add_digit = _refuse

    def __eq__(self, other):
        if isinstance(other, ImmutableBitSliceIndex):
            return self._base == other._base
        if isinstance(other, RoaringBitmapSliceIndex):
            return self._base == other
        return NotImplemented

    def __reduce__(self):
        return ImmutableBitSliceIndex, (self.serialize(),)

    def __repr__(self):
        return f"Immutable{self._base!r}"
