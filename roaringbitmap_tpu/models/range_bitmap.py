"""Succinct range index: the reference's ``RangeBitmap`` (RangeBitmap.java).

Logical model: an append-only, then sealed, base-2 bit-sliced index over
row ids (RangeBitmap.java:38-66 ``appender(maxValue)`` / ``map(buffer)``),
answering lt/lte/gt/gte/eq/neq/between with ``Cardinality`` and ``context``
(pre-filter) overloads (RangeBitmap.java:111-414). Row ids are dense
0..maxRid; every appended row has a value.

Three design obligations carried over from the reference (VERDICT r2 #5):

* **Bounded-memory append-then-seal** (RangeBitmap.Appender,
  RangeBitmap.java:1378-1520): the appender buffers at most one 2^16-row
  chunk of raw values; on each chunk boundary the chunk is flushed into
  per-slice *compressed containers* (the ``toEfficientContainer`` analogue),
  so peak transient memory is O(chunk) regardless of row count.
* **Lazy map** (RangeBitmap.java:66-96): ``map(buffer)`` parses only the
  16-byte header and the slice directory; each slice is materialized as a
  zero-copy ``ImmutableRoaringBitmap`` view over its payload bytes on first
  access, and ``serialize()`` of a mapped index re-emits the stored payload
  bytes without decoding.
* **Context-masked chunk skipping** (computeRange, RangeBitmap.java:551-620):
  queries with a ``context`` pre-filter walk only the 2^16-row chunks whose
  key appears in the context, running the O'Neil slice recurrence at
  container level per chunk and seeding EQ with the context container (the
  recurrence classifies each rid independently, so seeding == masking).
  ``chunks_evaluated`` counts touched chunks so skipping is observable.

TPU inversion: context-free queries on a built index run through the shared
fused-device/CPU BSI compare engine (models/bsi.py) — the reference's
streaming per-chunk evaluation becomes the K axis of the ``[S, K, 2048]``
device tensor. The container walk serves selective/context queries and
mapped indexes, where decoding everything for one chunk's answer would waste
more than it saves.

Serialized layout — **byte-compatible with the reference** (VERDICT r3 #6).
The default wire format is the reference's sealed form
(RangeBitmap.java:1483-1520 Appender.serialize / :66-96 map):

* header (10 bytes LE): u16 cookie 0xF00D, u8 base(=2), u8 sliceCount,
  u16 maxKey (chunk count), u32 maxRid;
* per-chunk slice masks: maxKey * ceil(sliceCount/8) bytes, each mask the
  little-endian truncation of the u64 whose bit ``i`` says chunk has a
  container for slice ``i``;
* container stream, ascending (chunk, slice): u8 type (0=bitmap, 1=run,
  2=array); bitmap: u16 cardinality (wraps at 2^16) + 8192 word bytes;
  run: u16 nruns + nruns x (u16 start, u16 length); array: u16 count +
  count x u16 values.

Reference slices store the **complement**: slice ``i`` holds rid iff bit
``i`` of the value is 0 (RangeBitmap.java:1510 ``~value & rangeMask``) —
the encoding that makes lte evaluation one andNot chain. This module keeps
value-bit slices internally (they are what the shared BSI device engine
consumes) and inverts per chunk container at the wire boundary
(``universe andnot c`` both ways — an involution, so round-trips are exact).

``map()`` also still reads this framework's round-3 native form (u16 cookie,
u8 base, u8 sliceCount, u64 maxValue, u32 maxRid, then per-slice u32 length
+ RoaringFormatSpec bytes), distinguished by strict stream validation;
``serialize(form="native")`` still writes it. Values are unsigned 64-bit;
the reference format caps sliceCount at 64 likewise.
"""

from __future__ import annotations

import struct
from typing import Iterable, List, Optional, Union

import numpy as np

from ..serialization import InvalidRoaringFormat
from .bsi import Operation, RoaringBitmapSliceIndex
from .container import Container, container_from_values, container_range_of_ones
from .roaring import RoaringBitmap
from .roaring_array import RoaringArray

COOKIE = 0xF00D  # RangeBitmap.java:25
CHUNK = 1 << 16
_MAX64 = 1 << 64
# reference container stream type codes (RangeBitmap.java:26-28)
J_BITMAP, J_RUN, J_ARRAY = 0, 1, 2


def _encode_java_container(c: Container) -> bytes:
    """One container in the reference stream form (RangeBitmap.java:1553-1580):
    u8 type, then bitmap: u16 cardinality (wraps at 2^16) + 8192 word bytes;
    run: u16 nruns + (start, length) u16 pairs; array: u16 count + u16 values."""
    from .container import ArrayContainer, BitmapContainer, RunContainer

    if isinstance(c, BitmapContainer):
        return (
            struct.pack("<BH", J_BITMAP, c.cardinality & 0xFFFF)
            + c.words.astype("<u8", copy=False).tobytes()
        )
    if isinstance(c, RunContainer):
        pairs = np.empty(2 * c.starts.size, dtype="<u2")
        pairs[0::2] = c.starts
        pairs[1::2] = c.lengths
        return struct.pack("<BH", J_RUN, c.starts.size) + pairs.tobytes()
    assert isinstance(c, ArrayContainer), type(c)
    return (
        struct.pack("<BH", J_ARRAY, c.content.size)
        + c.content.astype("<u2", copy=False).tobytes()
    )


def _java_wire_container(comp: Container, slice_idx: int) -> bytes:
    """Byte-exact form choice of the reference appender's flush.

    The appender grows slices 0-4 as BitmapContainers and slices >= 5 as
    RunContainers (containerForSlice, RangeBitmap.java:1608-1613), then
    serializes ``container.runOptimize()`` (:1552) — and the two classes
    optimize differently:

    * BitmapContainer.runOptimize (BitmapContainer.java:1227-1245) only
      ever converts bitmap -> run (when 2+4*nruns < 8192); it never
      produces an array, however small the cardinality;
    * RunContainer.runOptimize -> toEfficientContainer (RunContainer.java)
      keeps the run iff 2+4*nruns <= min(8192, 2+2*card) (ties keep run),
      else array iff card <= 4096 (toBitmapOrArrayContainer) else bitmap.

    Replicating the rule (not just "smallest form") is what makes the
    emitted stream byte-identical to a Java-sealed RangeBitmap."""
    from .container import ArrayContainer, BitmapContainer, RunContainer

    run = comp if isinstance(comp, RunContainer) else RunContainer.from_values(comp.to_array())
    card = comp.cardinality
    run_size = 2 + 4 * run.num_runs()
    if slice_idx >= 5:
        if run_size <= min(8192, 2 + 2 * card):
            choice: Container = run
        elif card <= 4096:
            choice = (
                comp
                if isinstance(comp, ArrayContainer)
                else ArrayContainer(comp.to_array())
            )
        else:
            choice = (
                comp if isinstance(comp, BitmapContainer) else BitmapContainer(comp.to_words())
            )
    else:
        if run_size < 8192:
            choice = run
        else:
            choice = (
                comp if isinstance(comp, BitmapContainer) else BitmapContainer(comp.to_words())
            )
    return _encode_java_container(choice)


def _decode_java_container(buf: memoryview, t: int, off: int) -> Container:
    """Decode one directory entry (type + payload offset past the type byte)."""
    from .container import ArrayContainer, BitmapContainer, RunContainer

    if t == J_BITMAP:
        words = np.frombuffer(buf, dtype="<u8", count=1024, offset=off + 2)
        return BitmapContainer(words.astype(np.uint64, copy=False))
    if t == J_RUN:
        (n_runs,) = struct.unpack_from("<H", buf, off)
        pairs = np.frombuffer(buf, dtype="<u2", count=2 * n_runs, offset=off + 2)
        starts, lengths = pairs[0::2], pairs[1::2]
        s64 = starts.astype(np.int64)
        ends = s64 + lengths.astype(np.int64)
        if n_runs and (np.any(s64[1:] <= ends[:-1]) or np.any(ends > 0xFFFF)):
            raise InvalidRoaringFormat("invalid run container in RangeBitmap stream")
        return RunContainer(starts, lengths)
    (card,) = struct.unpack_from("<H", buf, off)
    values = np.frombuffer(buf, dtype="<u2", count=card, offset=off + 2)
    if card and np.any(np.diff(values.astype(np.int64)) <= 0):
        raise InvalidRoaringFormat("unsorted array container in RangeBitmap stream")
    return ArrayContainer(values)


class _JavaMap:
    """Lazily mapped reference-format buffer: the parsed header plus a
    (slice, chunk) -> (type, offset) directory built by one validating walk
    over the container stream (no payload decode — the reference map()'s
    "minimal allocation" contract, RangeBitmap.java:60-96)."""

    __slots__ = ("buf", "slice_count", "n_chunks", "max_rid", "directory", "end")

    def __init__(self, buffer) -> None:
        buf = memoryview(buffer).cast("B")
        if len(buf) < 10:
            raise InvalidRoaringFormat("truncated RangeBitmap header")
        cookie, base, slice_count, n_chunks, max_rid = struct.unpack_from("<HBBHI", buf, 0)
        if cookie != COOKIE:
            raise InvalidRoaringFormat(f"invalid RangeBitmap cookie {cookie:#x}")
        if base != 2:
            raise InvalidRoaringFormat(f"unsupported base {base}")
        if slice_count < 1 or slice_count > 64:
            raise InvalidRoaringFormat(f"implausible slice count {slice_count}")
        # a sealed appender always has key == ceil(rid / 2^16) chunks
        # (RangeBitmap.java:1530 append() per 2^16 rids) — the check that
        # cheaply rejects this framework's native form, whose bytes 4..9
        # hold maxValue instead
        if n_chunks != (max_rid + CHUNK - 1) // CHUNK:
            raise InvalidRoaringFormat("chunk count inconsistent with maxRid")
        bpm = (slice_count + 7) >> 3
        masks_off = 10
        pos = masks_off + n_chunks * bpm
        if pos > len(buf):
            raise InvalidRoaringFormat("truncated slice masks")
        directory = {}
        for key in range(n_chunks):
            mask = int.from_bytes(buf[masks_off + key * bpm : masks_off + (key + 1) * bpm], "little")
            if mask >> slice_count:
                # a flagged slice past sliceCount would smuggle an orphan
                # container through the walk (queries never read it, but
                # accepting it would bless malformed input)
                raise InvalidRoaringFormat(
                    f"chunk {key} mask flags slices past sliceCount {slice_count}"
                )
            i = 0
            while mask:
                if mask & 1:
                    if pos + 3 > len(buf):
                        raise InvalidRoaringFormat("truncated container stream")
                    t = buf[pos]
                    if t == J_BITMAP:
                        size = 3 + 8192
                    elif t == J_RUN:
                        (n_runs,) = struct.unpack_from("<H", buf, pos + 1)
                        size = 3 + 4 * n_runs
                    elif t == J_ARRAY:
                        (card,) = struct.unpack_from("<H", buf, pos + 1)
                        size = 3 + 2 * card
                    else:
                        raise InvalidRoaringFormat(f"invalid container type {t}")
                    if pos + size > len(buf):
                        raise InvalidRoaringFormat("container payload out of bounds")
                    directory[(i, key)] = (t, pos + 1)
                    pos += size
                mask >>= 1
                i += 1
        # exact-extent contract (Appender.serialize writes exactly
        # serializedSizeInBytes bytes): trailing bytes mean this is not a
        # reference-format buffer — notably a native-form buffer with
        # maxValue == 0, whose first 10 bytes alone would parse as an empty
        # reference map and silently drop every row (code-review r4)
        if pos != len(buf):
            raise InvalidRoaringFormat(
                f"trailing bytes after container stream ({len(buf) - pos})"
            )
        self.buf = buf
        self.slice_count = slice_count
        self.n_chunks = n_chunks
        self.max_rid = max_rid
        self.directory = directory
        self.end = pos


class RangeBitmap:
    """Sealed range index; construct via ``RangeBitmap.appender`` or
    ``RangeBitmap.map``."""

    def __init__(
        self,
        slices: List[Optional[RoaringBitmap]],
        max_value: int,
        max_rid: int,
        payloads: Optional[List[bytes]] = None,
        java_map: Optional[_JavaMap] = None,
    ):
        self._slices = slices  # per-slice bitmap, or None when lazily mapped
        self._payloads = payloads  # native-mapped: RoaringFormatSpec bytes per slice
        self._jmap = java_map  # reference-format map: lazy container directory
        self._jcache: dict = {}  # (slice, key) -> value-bit Container
        self._max_value = int(max_value)
        self._max_rid = int(max_rid)  # number of rows
        self._bsi: Optional[RoaringBitmapSliceIndex] = None
        self.chunks_evaluated = 0  # observability: chunk-walk work counter

    # ------------------------------------------------------------------
    # construction
    # ------------------------------------------------------------------
    @staticmethod
    def appender(max_value: int) -> "RangeBitmapAppender":
        """Appender sized for values in [0, max_value] (RangeBitmap.java:38)."""
        return RangeBitmapAppender(max_value)

    @staticmethod
    def map(buffer: Union[bytes, bytearray, memoryview]) -> "RangeBitmap":
        """Open a sealed buffer (RangeBitmap.map, RangeBitmap.java:66).

        Accepts both the reference wire format (the default ``serialize``
        output — byte-compatible with a Java-sealed RangeBitmap) and this
        framework's round-3 native form. Either way the open is lazy:
        O(header + container directory), payload bytes stay views and
        containers decode zero-copy on first access. The two headers are
        disambiguated by strict validation — the reference header pins
        ``maxKey == ceil(maxRid / 2^16)`` plus exact stream bounds, which
        native-form bytes (maxValue u64 in those positions) cannot satisfy."""
        try:
            jm = _JavaMap(buffer)
            return RangeBitmap(
                [None] * jm.slice_count,
                (1 << jm.slice_count) - 1,  # rangeMask implied by sliceCount
                jm.max_rid,
                java_map=jm,
            )
        except InvalidRoaringFormat as java_err:
            try:
                return RangeBitmap._map_native(buffer)
            except InvalidRoaringFormat as native_err:
                raise InvalidRoaringFormat(
                    f"not a RangeBitmap in either format "
                    f"(reference: {java_err}; native: {native_err})"
                ) from None

    @staticmethod
    def _map_native(buffer: Union[bytes, bytearray, memoryview]) -> "RangeBitmap":
        """The round-3 native form: u64 maxValue header + whole-slice
        RoaringFormatSpec payloads."""
        buf = memoryview(buffer)
        if len(buf) < 16:
            raise InvalidRoaringFormat("truncated RangeBitmap header")
        cookie, base, slice_count = struct.unpack_from("<HBB", buf, 0)
        if cookie != COOKIE:
            raise InvalidRoaringFormat(f"invalid RangeBitmap cookie {cookie:#x}")
        if base != 2:
            raise InvalidRoaringFormat(f"unsupported base {base}")
        (max_value,) = struct.unpack_from("<Q", buf, 4)
        (max_rid,) = struct.unpack_from("<I", buf, 12)
        pos = 16
        payloads: List[bytes] = []
        for _ in range(slice_count):
            if pos + 4 > len(buf):
                raise InvalidRoaringFormat("truncated slice length")
            (ln,) = struct.unpack_from("<I", buf, pos)
            pos += 4
            if pos + ln > len(buf):
                raise InvalidRoaringFormat("truncated slice payload")
            payloads.append(buf[pos : pos + ln])
            pos += ln
        return RangeBitmap(
            [None] * slice_count, max_value, max_rid, payloads=payloads
        )

    # ------------------------------------------------------------------
    # slice access
    # ------------------------------------------------------------------
    @property
    def _slice_count(self) -> int:
        return len(self._slices)

    def _chunk_rows(self, key: int) -> int:
        return min(CHUNK, self._max_rid - key * CHUNK)

    def _slice(self, i: int) -> RoaringBitmap:
        """Slice bitmap, decoding a mapped payload zero-copy on first use."""
        s = self._slices[i]
        if s is None:
            if self._jmap is not None:
                # assemble the value-bit slice from the chunk directory
                # (decodes slice i's containers; the batch/BSI path needs
                # the whole slice, same as the reference's full evaluation)
                arr = RoaringArray()
                for key in range((self._max_rid + CHUNK - 1) // CHUNK):
                    c = self._slice_container(i, key)
                    if c is not None and c.cardinality:
                        arr.append(key, c)
                s = RoaringBitmap()
                s.high_low_container = arr
            else:
                from .immutable import ImmutableRoaringBitmap

                s = ImmutableRoaringBitmap(self._payloads[i])
            self._slices[i] = s
        return s

    def _slice_container(self, i: int, key: int) -> Optional[Container]:
        """Value-bit container of slice ``i`` in chunk ``key`` (None = no
        rows in the chunk have bit i set). Reference-format maps store the
        complement (RangeBitmap.java:1510), inverted here on first decode:
        an absent directory entry means *every* row has bit i set."""
        if self._jmap is None:
            return self._slice(i).high_low_container.get_container(key)
        ck = (i, key)
        if ck in self._jcache:
            return self._jcache[ck]
        chunk_rows = self._chunk_rows(key)
        if chunk_rows <= 0:
            return None
        entry = self._jmap.directory.get(ck)
        universe = container_range_of_ones(0, chunk_rows)
        if entry is None:  # complement empty: all rows have bit i set
            c = universe
        else:
            comp = _decode_java_container(self._jmap.buf, *entry)
            c = universe.andnot(comp)
            if c.cardinality == 0:
                c = None
        self._jcache[ck] = c
        return c

    def _bsi_index(self) -> RoaringBitmapSliceIndex:
        """The whole-index view used by context-free queries (the fused
        device/CPU engine). For a mapped index the slices are zero-copy
        ImmutableRoaringBitmap views — materialized lazily here, cached, and
        legal operands of the engine's algebra, so a pickled/mapped index
        keeps the batch path instead of degrading to the chunk walk."""
        if self._bsi is None:
            index = RoaringBitmapSliceIndex()
            index.min_value, index.max_value = 0, self._max_value
            index.ebm = RoaringBitmap.bitmap_of_range(0, self._max_rid)
            index.slices = [self._slice(i) for i in range(self._slice_count)]
            self._bsi = index
        return self._bsi

    # ------------------------------------------------------------------
    # serialization
    # ------------------------------------------------------------------
    def serialize(self, form: Optional[str] = None) -> bytes:
        """Sealed bytes. ``form=None`` re-emits the backing store's format
        without decoding (reference-format and native maps pass their bytes
        through; heap indexes default to the reference format).
        ``form="java"`` / ``form="native"`` force the respective layout."""
        if form not in (None, "java", "native"):
            raise ValueError(f"form must be 'java' or 'native', got {form!r}")
        if form is None:
            if self._jmap is not None:
                return bytes(self._jmap.buf[: self._jmap.end])
            if self._payloads is not None:
                return self._serialize_native()
            form = "java"
        if form == "java":
            if self._jmap is not None:
                return bytes(self._jmap.buf[: self._jmap.end])
            return self._serialize_java()
        return self._serialize_native()

    def _serialize_java(self) -> bytes:
        """Encode the reference wire format (Appender.serialize,
        RangeBitmap.java:1483-1520): complement containers per (chunk,
        slice), run-optimized like the reference's flush (:1552)."""
        n_chunks = (self._max_rid + CHUNK - 1) // CHUNK
        if n_chunks > 0xFFFF:
            # the reference header's maxKey is a u16 (RangeBitmap.java:1494);
            # fail actionably up front instead of a struct.error after
            # walking 65536 chunks (code-review r4)
            raise ValueError(
                f"{self._max_rid} rows = {n_chunks} chunks exceeds the "
                "reference wire format's u16 chunk count; use "
                "serialize(form='native')"
            )
        bpm = (self._slice_count + 7) >> 3
        masks = bytearray()
        stream = bytearray()
        for key in range(n_chunks):
            universe = container_range_of_ones(0, self._chunk_rows(key))
            mask = 0
            for i in range(self._slice_count):
                si = self._slice_container(i, key)
                comp = universe if si is None else universe.andnot(si)
                if comp.cardinality == 0:
                    continue
                mask |= 1 << i
                stream += _java_wire_container(comp, i)
            masks += mask.to_bytes(bpm, "little")
        return (
            struct.pack("<HBBHI", COOKIE, 2, self._slice_count, n_chunks, self._max_rid)
            + bytes(masks)
            + bytes(stream)
        )

    def _serialize_native(self) -> bytes:
        parts = [
            struct.pack("<HBB", COOKIE, 2, self._slice_count),
            struct.pack("<Q", self._max_value),
            struct.pack("<I", self._max_rid),
        ]
        for i in range(self._slice_count):
            if self._payloads is not None:
                payload = bytes(self._payloads[i])  # mapped: no decode
            else:
                payload = self._slice(i).serialize()
            parts.append(struct.pack("<I", len(payload)))
            parts.append(payload)
        return b"".join(parts)

    def serialized_size_in_bytes(self, form: Optional[str] = None) -> int:
        from ..serialization import serialized_size_in_bytes

        if form is None and self._jmap is not None:
            return self._jmap.end
        if form is None and self._payloads is None:
            form = "java"
        if form == "java":
            return len(self.serialize(form="java"))
        total = 16
        for i in range(self._slice_count):
            if self._payloads is not None:
                total += 4 + len(self._payloads[i])
            else:
                # _slice (not _slices[i]): materializes reference-mapped
                # slices, which are still None here (code-review r4)
                total += 4 + serialized_size_in_bytes(self._slice(i))
        return total

    def __reduce__(self):
        return RangeBitmap.map, (self.serialize(),)

    # ------------------------------------------------------------------
    # evaluation
    # ------------------------------------------------------------------
    def _compare(self, op: Operation, value: int, end: int, context) -> RoaringBitmap:
        value = int(value)
        if value < 0:
            raise ValueError("RangeBitmap values are unsigned")
        if context is not None:
            return self._chunk_walk(op, value, end, context)
        out = self._bsi_index().compare(op, value, end, None)
        if op is Operation.NEQ:
            # rows outside the appended universe cannot hold a value
            out = RoaringBitmap.and_(out, self._bsi_index().ebm)
        return out

    def _chunk_walk(
        self, op: Operation, value: int, end: int, context: Optional[RoaringBitmap]
    ) -> RoaringBitmap:
        """Per-chunk container-level O'Neil evaluation
        (computeRange, RangeBitmap.java:551-620).

        With a context, only chunk keys present in the context are touched
        (the reference's context-masked skipping); the recurrence is seeded
        with the context container, which masks every output for free."""
        out = RoaringBitmap()
        n_chunks = (self._max_rid + CHUNK - 1) // CHUNK
        if context is not None:
            hlc = context.high_low_container
            keys = [
                (hlc.get_key_at_index(i), hlc.get_container_at_index(i))
                for i in range(hlc.size)
            ]
        else:
            keys = [(k, None) for k in range(n_chunks)]
        for key, ctx_container in keys:
            if key >= n_chunks:
                break
            self.chunks_evaluated += 1
            res = self._eval_chunk(op, value, end, key, ctx_container)
            if res is not None and res.cardinality > 0:
                out.high_low_container.append(key, res)
        return out

    def _eval_chunk(
        self, op: Operation, value: int, end: int, key: int, ctx: Optional[Container]
    ) -> Optional[Container]:
        chunk_rows = min(CHUNK, self._max_rid - key * CHUNK)
        if chunk_rows <= 0:
            return None
        universe = container_range_of_ones(0, chunk_rows)
        seed = universe if ctx is None else ctx.and_(universe)
        if seed.cardinality == 0:
            return None
        if op is Operation.LT:
            lt, eq, _ = self._oneil_chunk(value, key, seed, want_gt=False)
            return lt
        if op is Operation.LE:
            lt, eq, _ = self._oneil_chunk(value, key, seed, want_gt=False)
            return lt.or_(eq)
        if op is Operation.GT:
            _, eq, gt = self._oneil_chunk(value, key, seed, want_lt=False)
            return gt
        if op is Operation.GE:
            _, eq, gt = self._oneil_chunk(value, key, seed, want_lt=False)
            return gt.or_(eq)
        if op is Operation.EQ:
            _, eq, _ = self._oneil_chunk(value, key, seed, want_lt=False, want_gt=False)
            return eq
        if op is Operation.NEQ:
            _, eq, _ = self._oneil_chunk(value, key, seed, want_lt=False, want_gt=False)
            return seed.andnot(eq)
        if op is Operation.RANGE:
            _, eq_lo, gt_lo = self._oneil_chunk(value, key, seed, want_lt=False)
            ge = gt_lo.or_(eq_lo)
            if ge.cardinality == 0:
                return None
            lt_hi, eq_hi, _ = self._oneil_chunk(end, key, ge, want_gt=False)
            return lt_hi.or_(eq_hi)
        raise ValueError(f"unsupported operation {op}")

    def _oneil_chunk(
        self,
        value: int,
        key: int,
        seed: Container,
        want_lt: bool = True,
        want_gt: bool = True,
    ):
        """O'Neil recurrence over the slice axis for one chunk
        (RoaringBitmapSliceIndex.java:432-469, restricted to ``seed``).

        A threshold above the indexed bit depth means every row's value is
        smaller: LT = seed, EQ/GT empty."""
        empty = container_from_values(np.empty(0, dtype=np.uint16))
        if value.bit_length() > self._slice_count:
            return (seed if want_lt else empty), empty, empty
        lt, gt = empty, empty
        eq = seed
        for i in range(self._slice_count - 1, -1, -1):
            if eq.cardinality == 0:
                break
            si = self._slice_container(i, key)
            bit = (value >> i) & 1
            if bit:
                if si is None:  # no rows have bit i set in this chunk
                    if want_lt:
                        lt = lt.or_(eq)
                    eq = empty
                else:
                    if want_lt:
                        lt = lt.or_(eq.andnot(si))
                    eq = eq.and_(si)
            else:
                if si is not None:
                    if want_gt:
                        gt = gt.or_(eq.and_(si))
                    eq = eq.andnot(si)
        return lt, eq, gt

    # ------------------------------------------------------------------
    # queries (RangeBitmap.java:111-414)
    # ------------------------------------------------------------------
    def lt(self, value: int, context: Optional[RoaringBitmap] = None) -> RoaringBitmap:
        return self._compare(Operation.LT, value, 0, context)

    def lte(self, value: int, context: Optional[RoaringBitmap] = None) -> RoaringBitmap:
        return self._compare(Operation.LE, value, 0, context)

    def gt(self, value: int, context: Optional[RoaringBitmap] = None) -> RoaringBitmap:
        return self._compare(Operation.GT, value, 0, context)

    def gte(self, value: int, context: Optional[RoaringBitmap] = None) -> RoaringBitmap:
        return self._compare(Operation.GE, value, 0, context)

    def eq(self, value: int, context: Optional[RoaringBitmap] = None) -> RoaringBitmap:
        return self._compare(Operation.EQ, value, 0, context)

    def neq(self, value: int, context: Optional[RoaringBitmap] = None) -> RoaringBitmap:
        return self._compare(Operation.NEQ, value, 0, context)

    def between(
        self, lo: int, hi: int, context: Optional[RoaringBitmap] = None
    ) -> RoaringBitmap:
        return self._compare(Operation.RANGE, lo, hi, context)

    # Cardinality overloads (RangeBitmap.java lteCardinality etc.) — the
    # count never materializes a result bitmap on the context-free path:
    # the BSI's compare_cardinality fetches only per-chunk popcounts
    def _compare_cardinality(self, op: Operation, value: int, end: int, context) -> int:
        value = int(value)
        if value < 0:  # validated before the context branch, like _compare
            raise ValueError("RangeBitmap values are unsigned")
        if context is not None:
            return self._chunk_walk(op, value, end, context).get_cardinality()
        return self._bsi_index().compare_cardinality(op, value, end, None)

    def lt_cardinality(self, value: int, context=None) -> int:
        return self._compare_cardinality(Operation.LT, value, 0, context)

    def lte_cardinality(self, value: int, context=None) -> int:
        return self._compare_cardinality(Operation.LE, value, 0, context)

    def gt_cardinality(self, value: int, context=None) -> int:
        return self._compare_cardinality(Operation.GT, value, 0, context)

    def gte_cardinality(self, value: int, context=None) -> int:
        return self._compare_cardinality(Operation.GE, value, 0, context)

    def eq_cardinality(self, value: int, context=None) -> int:
        return self._compare_cardinality(Operation.EQ, value, 0, context)

    def neq_cardinality(self, value: int, context=None) -> int:
        return self._compare_cardinality(Operation.NEQ, value, 0, context)

    def between_cardinality(self, lo: int, hi: int, context=None) -> int:
        return self._compare_cardinality(Operation.RANGE, lo, hi, context)

    # Batched cardinality family: a whole [Q] array of thresholds answered
    # in ONE device dispatch on the context-free path (the BSI's vmapped
    # O'Neil walk shares a single HBM pass over the packed slice tensor —
    # no reference equivalent; RangeBitmap.java answers one query per call)
    def _compare_cardinality_many(self, op, values, ends=None, context=None):
        vals = [int(v) for v in np.asarray(values, dtype=object).ravel()]
        if any(v < 0 for v in vals):
            raise ValueError("RangeBitmap values are unsigned")
        if op is Operation.RANGE:
            # same contract as the context-free engine (bsi._counts_many)
            if ends is None:
                raise ValueError("RANGE requires ends")
            end_list = [int(e) for e in np.asarray(ends, dtype=object).ravel()]
            if len(end_list) != len(vals):
                raise ValueError("ends must align with values")
        else:
            end_list = [0] * len(vals)
        if context is not None:
            return np.array(
                [
                    self._chunk_walk(op, v, e, context).get_cardinality()
                    for v, e in zip(vals, end_list)
                ],
                dtype=np.int64,
            )
        return self._bsi_index().compare_cardinality_many(
            op, vals, end_list if op is Operation.RANGE else None
        )

    def lt_cardinality_many(self, values, context=None):
        return self._compare_cardinality_many(Operation.LT, values, None, context)

    def lte_cardinality_many(self, values, context=None):
        return self._compare_cardinality_many(Operation.LE, values, None, context)

    def gt_cardinality_many(self, values, context=None):
        return self._compare_cardinality_many(Operation.GT, values, None, context)

    def gte_cardinality_many(self, values, context=None):
        return self._compare_cardinality_many(Operation.GE, values, None, context)

    def eq_cardinality_many(self, values, context=None):
        return self._compare_cardinality_many(Operation.EQ, values, None, context)

    def neq_cardinality_many(self, values, context=None):
        return self._compare_cardinality_many(Operation.NEQ, values, None, context)

    def between_cardinality_many(self, los, his, context=None):
        return self._compare_cardinality_many(Operation.RANGE, los, his, context)

    # ------------------------------------------------------------------
    @property
    def row_count(self) -> int:
        return self._max_rid

    def __repr__(self):
        return (
            f"RangeBitmap(rows={self._max_rid}, slices={self._slice_count}, "
            f"max_value={self._max_value})"
        )


class RangeBitmapAppender:
    """Append-only builder (RangeBitmap.Appender, RangeBitmap.java:1378-1520).

    Bounded memory: raw values are buffered in a single fixed 2^16-slot
    chunk; crossing the boundary flushes the chunk into one compressed
    container per slice (mask -> sorted uint16 positions -> best container,
    run-optimized), mirroring the reference's per-2^16-rid slice flush.
    Peak transient memory is O(chunk) regardless of total rows."""

    def __init__(self, max_value: int):
        max_value = int(max_value)
        if not 0 <= max_value < _MAX64:
            raise ValueError("max_value outside unsigned 64-bit range")
        self._max_value = max_value
        self._slice_count = max(1, max_value.bit_length())
        self._buf = np.empty(CHUNK, dtype=np.uint64)
        self._fill = 0
        self._slice_arrays = [RoaringArray() for _ in range(self._slice_count)]
        self._rows = 0

    def add(self, value: int) -> None:
        """Append the value for the next row id (Appender.add)."""
        value = int(value)
        if not 0 <= value <= self._max_value:
            raise ValueError(
                f"value {value} outside appender range [0, {self._max_value}]"
            )
        self._buf[self._fill] = value
        self._fill += 1
        if self._fill == CHUNK:
            self._flush()

    def add_many(self, values: Iterable[int]) -> None:
        arr = np.asarray(
            values
            if isinstance(values, np.ndarray)
            else np.fromiter(iter(values), dtype=np.uint64)
        )
        if np.issubdtype(arr.dtype, np.signedinteger) and arr.size and arr.min() < 0:
            raise ValueError("RangeBitmap values are unsigned")
        arr = arr.astype(np.uint64).ravel()
        if arr.size and int(arr.max()) > self._max_value:
            raise ValueError("value outside appender range")
        pos = 0
        while pos < arr.size:
            take = min(CHUNK - self._fill, arr.size - pos)
            self._buf[self._fill : self._fill + take] = arr[pos : pos + take]
            self._fill += take
            pos += take
            if self._fill == CHUNK:
                self._flush()

    def _chunk_containers(self, vals: np.ndarray) -> List[Optional[Container]]:
        """Per-slice compressed containers for one chunk of raw values."""
        out: List[Optional[Container]] = []
        for i in range(self._slice_count):
            mask = (vals >> np.uint64(i)) & np.uint64(1) == 1
            if mask.any():
                lows = np.flatnonzero(mask).astype(np.uint16)
                out.append(container_from_values(lows).run_optimize())
            else:
                out.append(None)
        return out

    def _flush(self) -> None:
        """Seal the buffered chunk into per-slice containers
        (the reference's per-2^16-rid flush, RangeBitmap.java:1462-1520)."""
        if self._fill == 0:
            return
        key = self._rows >> 16
        for i, c in enumerate(self._chunk_containers(self._buf[: self._fill])):
            if c is not None:
                self._slice_arrays[i].append(key, c)
        self._rows += self._fill
        self._fill = 0

    def build(self) -> RangeBitmap:
        """Seal into a queryable RangeBitmap (Appender.build,
        RangeBitmap.java:1415-1440).

        Non-destructive: the appender stays usable afterwards (build, keep
        appending, build again), so the partial chunk is compressed into
        temporary containers and the slice arrays are shallow-copied rather
        than shared with the returned index."""
        partial = (
            self._chunk_containers(self._buf[: self._fill])
            if self._fill
            else [None] * self._slice_count
        )
        key = self._rows >> 16
        slices: List[RoaringBitmap] = []
        for i, arr in enumerate(self._slice_arrays):
            a = RoaringArray()
            a.keys = list(arr.keys)
            a.containers = list(arr.containers)
            if partial[i] is not None:
                a.append(key, partial[i])
            bm = RoaringBitmap()
            bm.high_low_container = a
            slices.append(bm)
        return RangeBitmap(slices, self._max_value, self._rows + self._fill)

    def serialize(self) -> bytes:
        """Seal directly to bytes (Appender.serialize)."""
        return self.build().serialize()

    def clear(self) -> None:
        self._buf = np.empty(CHUNK, dtype=np.uint64)
        self._fill = 0
        self._slice_arrays = [RoaringArray() for _ in range(self._slice_count)]
        self._rows = 0
