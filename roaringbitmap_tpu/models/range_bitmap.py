"""Succinct range index: the reference's ``RangeBitmap`` (RangeBitmap.java).

Logical model: an append-only, then sealed, base-2 bit-sliced index over
row ids (RangeBitmap.java:38-66 ``appender(maxValue)`` / ``map(buffer)``),
answering lt/lte/gt/gte/eq/neq/between with ``Cardinality`` and ``context``
(pre-filter) overloads (RangeBitmap.java:111-414). Row ids are dense
0..maxRid; every appended row has a value.

TPU inversion: the reference streams per-2^16-row chunks of mapped
containers through the O'Neil slice walk (computeRange, RangeBitmap.java:551;
container decode :1084-1117) — an artifact of single-core cache-friendly
evaluation. Here the sealed index holds whole-universe slice bitmaps and
evaluates the same slice recurrence over ALL row chunks at once, through the
shared fused-device/CPU compare engine (models/bsi.py); the "chunk streaming"
is the K axis of the ``[S, K, 2048]`` device tensor.

Serialized layout (this framework's sealed form; cookie and field order
modeled on RangeBitmap.java:25's 0xF00D header, with RoaringFormatSpec
payloads instead of the Java-internal container stream — the reference's
exact byte layout is a JVM implementation detail, not a cross-language spec):
uint16 cookie 0xF00D, uint8 base(=2), uint8 sliceCount, uint64 maxValue,
uint32 maxRid, then per-slice uint32 length + RoaringFormatSpec bytes.
Values are unsigned 64-bit.
"""

from __future__ import annotations

import struct
from typing import Iterable, List, Optional, Union

import numpy as np

from ..serialization import InvalidRoaringFormat, read_into
from .bsi import Operation, RoaringBitmapSliceIndex
from .roaring import RoaringBitmap

COOKIE = 0xF00D  # RangeBitmap.java:25
_MAX64 = 1 << 64


class RangeBitmap:
    """Sealed range index; construct via ``RangeBitmap.appender`` or
    ``RangeBitmap.map``."""

    def __init__(self, index: RoaringBitmapSliceIndex, max_value: int, max_rid: int):
        self._index = index
        self._max_value = int(max_value)
        self._max_rid = int(max_rid)  # number of rows

    # ------------------------------------------------------------------
    # construction
    # ------------------------------------------------------------------
    @staticmethod
    def appender(max_value: int) -> "RangeBitmapAppender":
        """Appender sized for values in [0, max_value] (RangeBitmap.java:38)."""
        return RangeBitmapAppender(max_value)

    @staticmethod
    def map(buffer: Union[bytes, bytearray, memoryview]) -> "RangeBitmap":
        """Open a sealed buffer (RangeBitmap.map, RangeBitmap.java:66)."""
        buf = memoryview(buffer)
        if len(buf) < 16:
            raise InvalidRoaringFormat("truncated RangeBitmap header")
        cookie, base, slice_count = struct.unpack_from("<HBB", buf, 0)
        if cookie != COOKIE:
            raise InvalidRoaringFormat(f"invalid RangeBitmap cookie {cookie:#x}")
        if base != 2:
            raise InvalidRoaringFormat(f"unsupported base {base}")
        (max_value,) = struct.unpack_from("<Q", buf, 4)
        (max_rid,) = struct.unpack_from("<I", buf, 12)
        pos = 16
        slices: List[RoaringBitmap] = []
        for _ in range(slice_count):
            if pos + 4 > len(buf):
                raise InvalidRoaringFormat("truncated slice length")
            (ln,) = struct.unpack_from("<I", buf, pos)
            pos += 4
            if pos + ln > len(buf):
                raise InvalidRoaringFormat("truncated slice payload")
            bm = RoaringBitmap()
            read_into(bm, buf[pos : pos + ln])
            pos += ln
            slices.append(bm)
        index = RoaringBitmapSliceIndex()
        index.min_value, index.max_value = 0, max_value
        index.ebm = RoaringBitmap.bitmap_of_range(0, max_rid)
        index.slices = slices
        return RangeBitmap(index, max_value, max_rid)

    def serialize(self) -> bytes:
        parts = [
            struct.pack("<HBB", COOKIE, 2, len(self._index.slices)),
            struct.pack("<Q", self._max_value),
            struct.pack("<I", self._max_rid),
        ]
        for s in self._index.slices:
            payload = s.serialize()
            parts.append(struct.pack("<I", len(payload)))
            parts.append(payload)
        return b"".join(parts)

    def serialized_size_in_bytes(self) -> int:
        from ..serialization import serialized_size_in_bytes

        return 16 + sum(4 + serialized_size_in_bytes(s) for s in self._index.slices)

    def __reduce__(self):
        return RangeBitmap.map, (self.serialize(),)

    # ------------------------------------------------------------------
    # queries (RangeBitmap.java:111-414)
    # ------------------------------------------------------------------
    def _compare(self, op: Operation, value: int, end: int, context) -> RoaringBitmap:
        value = int(value)
        if value < 0:
            raise ValueError("RangeBitmap values are unsigned")
        return self._index.compare(op, value, end, context)

    def lt(self, value: int, context: Optional[RoaringBitmap] = None) -> RoaringBitmap:
        return self._compare(Operation.LT, value, 0, context)

    def lte(self, value: int, context: Optional[RoaringBitmap] = None) -> RoaringBitmap:
        return self._compare(Operation.LE, value, 0, context)

    def gt(self, value: int, context: Optional[RoaringBitmap] = None) -> RoaringBitmap:
        return self._compare(Operation.GT, value, 0, context)

    def gte(self, value: int, context: Optional[RoaringBitmap] = None) -> RoaringBitmap:
        return self._compare(Operation.GE, value, 0, context)

    def eq(self, value: int, context: Optional[RoaringBitmap] = None) -> RoaringBitmap:
        return self._compare(Operation.EQ, value, 0, context)

    def neq(self, value: int, context: Optional[RoaringBitmap] = None) -> RoaringBitmap:
        # context rows outside the index cannot hold a value; unlike the raw
        # BSI NEQ semantics, RangeBitmap clamps to existing rows
        out = self._compare(Operation.NEQ, value, 0, context)
        return RoaringBitmap.and_(out, self._index.ebm)

    def between(
        self, lo: int, hi: int, context: Optional[RoaringBitmap] = None
    ) -> RoaringBitmap:
        return self._compare(Operation.RANGE, lo, hi, context)

    # Cardinality overloads (RangeBitmap.java lteCardinality etc.)
    def lt_cardinality(self, value: int, context=None) -> int:
        return self.lt(value, context).get_cardinality()

    def lte_cardinality(self, value: int, context=None) -> int:
        return self.lte(value, context).get_cardinality()

    def gt_cardinality(self, value: int, context=None) -> int:
        return self.gt(value, context).get_cardinality()

    def gte_cardinality(self, value: int, context=None) -> int:
        return self.gte(value, context).get_cardinality()

    def eq_cardinality(self, value: int, context=None) -> int:
        return self.eq(value, context).get_cardinality()

    def neq_cardinality(self, value: int, context=None) -> int:
        return self.neq(value, context).get_cardinality()

    def between_cardinality(self, lo: int, hi: int, context=None) -> int:
        return self.between(lo, hi, context).get_cardinality()

    # ------------------------------------------------------------------
    @property
    def row_count(self) -> int:
        return self._max_rid

    def __repr__(self):
        return (
            f"RangeBitmap(rows={self._max_rid}, slices={len(self._index.slices)}, "
            f"max_value={self._max_value})"
        )


class RangeBitmapAppender:
    """Append-only builder (RangeBitmap.Appender, RangeBitmap.java:1378-1520).

    The reference flushes container slices every 2^16 rids into a growing
    buffer; here values accumulate in a numpy buffer and the slice bitmaps
    are built vectorized at ``build``/``serialize`` time — one boolean mask
    per bit over all rows at once.
    """

    def __init__(self, max_value: int):
        max_value = int(max_value)
        if not 0 <= max_value < _MAX64:
            raise ValueError("max_value outside unsigned 64-bit range")
        self._max_value = max_value
        self._slice_count = max(1, max_value.bit_length())
        self._chunks: List[np.ndarray] = []
        self._current: List[int] = []

    def add(self, value: int) -> None:
        """Append the value for the next row id (Appender.add)."""
        value = int(value)
        if not 0 <= value <= self._max_value:
            raise ValueError(
                f"value {value} outside appender range [0, {self._max_value}]"
            )
        self._current.append(value)
        if len(self._current) >= (1 << 16):
            self._chunks.append(np.array(self._current, dtype=np.uint64))
            self._current = []

    def add_many(self, values: Iterable[int]) -> None:
        arr = np.asarray(
            values if isinstance(values, np.ndarray) else np.fromiter(iter(values), dtype=np.uint64)
        )
        if np.issubdtype(arr.dtype, np.signedinteger) and arr.size and arr.min() < 0:
            raise ValueError("RangeBitmap values are unsigned")
        arr = arr.astype(np.uint64).ravel()
        if arr.size and int(arr.max()) > self._max_value:
            raise ValueError("value outside appender range")
        if self._current:  # keep row-id order when interleaved with add()
            self._chunks.append(np.array(self._current, dtype=np.uint64))
            self._current = []
        self._chunks.append(arr)

    def _values(self) -> np.ndarray:
        parts = list(self._chunks)
        if self._current:
            parts.append(np.array(self._current, dtype=np.uint64))
        return np.concatenate(parts) if parts else np.empty(0, dtype=np.uint64)

    def build(self) -> RangeBitmap:
        """Seal into a queryable RangeBitmap (Appender.build,
        RangeBitmap.java:1415-1440)."""
        values = self._values()
        n = int(values.size)
        index = RoaringBitmapSliceIndex()
        index.min_value = 0
        index.max_value = self._max_value
        index.ebm = RoaringBitmap.bitmap_of_range(0, n)
        rids = np.arange(n, dtype=np.uint32)
        slices = []
        for i in range(self._slice_count):
            mask = (values >> np.uint64(i)) & np.uint64(1) == 1
            bm = RoaringBitmap(rids[mask]) if mask.any() else RoaringBitmap()
            bm.run_optimize()
            slices.append(bm)
        index.slices = slices
        return RangeBitmap(index, self._max_value, n)

    def serialize(self) -> bytes:
        """Seal directly to bytes (Appender.serialize)."""
        return self.build().serialize()

    def clear(self) -> None:
        self._chunks = []
        self._current = []
