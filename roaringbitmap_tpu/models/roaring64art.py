"""64-bit layer, design 2 of 2: the ART-based ``Roaring64Bitmap``.

Re-expression of longlong/Roaring64Bitmap.java:29 + HighLowContainer.java:14-17:
a 64-bit value splits into a 6-byte high-48 key (longlong/LongUtils.java
high48/low16 helpers) indexed by an adaptive radix tree (``art.py``), and a
16-bit low part stored in a standard container. Container payloads live in a
two-level ``Containers`` store addressed by a packed (hi32, lo32) index
(art/Containers.java:20-32, :63-70) — the ART leaf holds the packed index,
not the container object, exactly as in the reference; the dense second
level is also the natural staging layout for packing bitmap containers to
``[N, 1024]`` device arrays (parallel/store.py).

Serialization: the reference's Roaring64Bitmap writes a private ART dump
(HighLowContainer.serialize: EMPTY_TAG/NOT_EMPTY_TAG + trie nodes) — a JVM
implementation detail, not a cross-language spec. This framework serializes
the portable 64-bit RoaringFormatSpec instead (identical to
Roaring64NavigableMap.serialize_portable, validated against
testdata/64map*.bin), grouping high-48 keys by their high 32 bits; the two
64-bit classes interoperate byte-for-byte.
"""

from __future__ import annotations

import bisect
from typing import Iterable, Iterator, List, Optional, Tuple

import numpy as np

from .art import Art
from .container import (
    ArrayContainer,
    Container,
    container_from_values,
    container_range_of_ones,
)
from .roaring import RoaringBitmap
from .roaring64 import _check64, bucketed_membership, chunk_ranges_64, group_by_high


def high48_key(x: int) -> bytes:
    """6 big-endian bytes of the high 48 bits (LongUtils.highPart)."""
    return (x >> 16).to_bytes(6, "big")


def key_to_int(key: bytes) -> int:
    return int.from_bytes(key, "big")


class Containers:
    """Two-level container store addressed by a packed index
    (art/Containers.java:20-32): high 32 bits pick the first-level page,
    low 32 bits the slot. Pages are dense lists; freed slots are reused via
    a free list."""

    PAGE_SHIFT = 16  # 2^16 slots per page keeps pages cache-friendly

    def __init__(self):
        self._pages: List[List[Optional[Container]]] = []
        self._free: List[int] = []
        self._size = 0

    def __len__(self) -> int:
        return self._size

    def add(self, c: Container) -> int:
        """Store a container, returning its packed index (Containers.addContainer)."""
        self._size += 1
        if self._free:
            idx = self._free.pop()
            self._pages[idx >> self.PAGE_SHIFT][idx & 0xFFFF] = c
            return idx
        if not self._pages or len(self._pages[-1]) >= (1 << self.PAGE_SHIFT):
            self._pages.append([])
        page = len(self._pages) - 1
        self._pages[page].append(c)
        return (page << self.PAGE_SHIFT) | (len(self._pages[page]) - 1)

    def get(self, idx: int) -> Container:
        return self._pages[idx >> self.PAGE_SHIFT][idx & 0xFFFF]

    def replace(self, idx: int, c: Container) -> None:
        """replaceContainer (HighLowContainer path)."""
        self._pages[idx >> self.PAGE_SHIFT][idx & 0xFFFF] = c

    def remove(self, idx: int) -> None:
        self._pages[idx >> self.PAGE_SHIFT][idx & 0xFFFF] = None
        self._free.append(idx)
        self._size -= 1


class Roaring64Bitmap:
    """Unsigned 64-bit Roaring bitmap over an ART high-48 index
    (longlong/Roaring64Bitmap.java:29)."""

    __slots__ = ("_art", "_containers", "_ord")

    def __init__(self, values: Optional[Iterable[int]] = None):
        self._art = Art()
        self._containers = Containers()
        self._ord = None
        if values is not None:
            self.add_many(values)

    # ------------------------------------------------------------------
    # internals
    # ------------------------------------------------------------------
    def _get(self, key: bytes) -> Optional[Container]:
        idx = self._art.find(key)
        return None if idx is None else self._containers.get(idx)

    def _put(self, key: bytes, c: Container) -> None:
        self._ord = None
        idx = self._art.find(key)
        if idx is None:
            self._art.insert(key, self._containers.add(c))
        else:
            self._containers.replace(idx, c)

    def _set_or_drop(self, key: bytes, c: Optional[Container]) -> None:
        self._ord = None
        idx = self._art.find(key)
        if c is None or c.cardinality == 0:
            if idx is not None:
                self._containers.remove(idx)
                self._art.remove(key)
            return
        if idx is None:
            self._art.insert(key, self._containers.add(c))
        else:
            self._containers.replace(idx, c)

    def _kv(self) -> Iterator[Tuple[bytes, Container]]:
        for key, idx in self._art.items():
            yield key, self._containers.get(idx)

    # ------------------------------------------------------------------
    # construction / point ops (Roaring64Bitmap.addLong :50-61)
    # ------------------------------------------------------------------
    @staticmethod
    def bitmap_of(*values: int) -> "Roaring64Bitmap":
        return Roaring64Bitmap(values)

    def _ordered(self):
        """Sorted (keys, containers, cumulative cardinalities), rebuilt
        lazily after mutation — the cached-cumulative-cardinality design of
        Roaring64NavigableMap.java:66-72 / FastRankRoaringBitmap.java:21-39
        applied to the ART variant (whose reference counterpart re-walks the
        trie per rank/select call; at ~50k sparse high-48 buckets one Python
        walk per probe is ~1500x slower than a binary search here)."""
        if self._ord is None:
            keys, conts = [], []
            for k, c in self._kv():
                keys.append(k)
                conts.append(c)
            cum = np.cumsum([c.cardinality for c in conts], dtype=np.int64)
            # int64 view of the byte keys, cached for the vectorized
            # bulk-rank searchsorted (big-endian 6-byte keys sort
            # identically to their ints)
            key_ints = np.array([key_to_int(k) for k in keys], dtype=np.int64)
            self._ord = (keys, conts, cum, key_ints)
        return self._ord

    def add(self, x: int) -> None:
        x = _check64(x)
        self._ord = None
        key = high48_key(x)
        idx = self._art.find(key)
        if idx is None:
            self._art.insert(
                key, self._containers.add(ArrayContainer([x & 0xFFFF]))
            )
        else:
            self._containers.replace(
                idx, self._containers.get(idx).add(x & 0xFFFF)
            )

    def add_many(self, values: Iterable[int]) -> None:
        groups = group_by_high(values, 16)
        if self._art.is_empty():
            # bottom-up bulk trie build: group_by_high yields ascending
            # highs (it sorts), exactly bulk_load's contract — no per-key
            # root-to-leaf descent (Art.bulk_load)
            self._ord = None
            self._art.bulk_load(
                (
                    high.to_bytes(6, "big"),
                    self._containers.add(container_from_values(lows.astype(np.uint16))),
                )
                for high, lows in groups
            )
            return
        for high, lows in groups:
            key = high.to_bytes(6, "big")
            chunk = container_from_values(lows.astype(np.uint16))
            existing = self._get(key)
            self._put(key, chunk if existing is None else existing.or_(chunk))

    def remove(self, x: int) -> None:
        x = _check64(x)
        key = high48_key(x)
        c = self._get(key)
        if c is not None:
            self._set_or_drop(key, c.remove(x & 0xFFFF))

    def contains(self, x: int) -> bool:
        x = _check64(x)
        c = self._get(high48_key(x))
        return c is not None and c.contains(x & 0xFFFF)

    def contains_many(self, values) -> np.ndarray:
        """Vectorized membership: bool array parallel to ``values``.

        The 64-bit twin of the 32-bit ``RoaringBitmap.contains_many`` (the
        reference answers batch membership one contains() at a time,
        Roaring64Bitmap.java): one container-level vectorized probe per
        distinct high-48 chunk, not a trie descent per value
        (roaring64.bucketed_membership)."""

        def probe(high, lows):
            c = self._get(high.to_bytes(6, "big"))
            return None if c is None else c.contains_many(lows.astype(np.uint16))

        return bucketed_membership(values, 16, probe)

    # ------------------------------------------------------------------
    # ranges (per-2^16-chunk walk)
    # ------------------------------------------------------------------
    @staticmethod
    def _chunk_ranges(start: int, end: int):
        return chunk_ranges_64(start, end, 16)

    def add_range(self, start: int, end: int) -> None:
        for h, lo, hi in self._chunk_ranges(start, end):
            key = h.to_bytes(6, "big")
            c = self._get(key)
            if c is None:
                self._put(key, container_range_of_ones(lo, hi))
            else:
                self._put(key, c.add_range(lo, hi))

    def remove_range(self, start: int, end: int) -> None:
        for h, lo, hi in self._chunk_ranges(start, end):
            key = h.to_bytes(6, "big")
            c = self._get(key)
            if c is not None:
                self._set_or_drop(key, c.remove_range(lo, hi))

    def flip_range(self, start: int, end: int) -> None:
        for h, lo, hi in self._chunk_ranges(start, end):
            key = h.to_bytes(6, "big")
            c = self._get(key)
            if c is None:
                self._put(key, container_range_of_ones(lo, hi))
            else:
                self._set_or_drop(key, c.flip_range(lo, hi))

    # ------------------------------------------------------------------
    # algebra — ordered merge walks over the two tries (the reference
    # aligns keys via KeyIterator shuttles; or/and/andNot/xor
    # Roaring64Bitmap.java pairwise container ops)
    # ------------------------------------------------------------------
    def _merge_walk(self, other: "Roaring64Bitmap", op: str) -> "Roaring64Bitmap":
        # two-pointer key merge emits strictly-ascending keys into a fresh
        # index, so the result trie is bulk-built bottom-up (Art.bulk_load)
        # instead of paying two root-to-leaf descents per key via _put
        out = Roaring64Bitmap()
        store = out._containers
        pairs: list = []
        emit = pairs.append
        it_a, it_b = self._kv(), other._kv()
        a = next(it_a, None)
        b = next(it_b, None)
        while a is not None or b is not None:
            if b is None or (a is not None and a[0] < b[0]):
                if op in ("or", "xor", "andnot"):
                    emit((a[0], store.add(a[1].clone())))
                a = next(it_a, None)
            elif a is None or b[0] < a[0]:
                if op in ("or", "xor"):
                    emit((b[0], store.add(b[1].clone())))
                b = next(it_b, None)
            else:
                if op == "or":
                    c = a[1].or_(b[1])
                elif op == "and":
                    c = a[1].and_(b[1])
                elif op == "xor":
                    c = a[1].xor_(b[1])
                else:
                    c = a[1].andnot(b[1])
                if c.cardinality:
                    emit((a[0], store.add(c)))
                a = next(it_a, None)
                b = next(it_b, None)
        out._art.bulk_load(pairs)
        return out

    @staticmethod
    def or_(a: "Roaring64Bitmap", b: "Roaring64Bitmap") -> "Roaring64Bitmap":
        return a._merge_walk(b, "or")

    @staticmethod
    def and_(a: "Roaring64Bitmap", b: "Roaring64Bitmap") -> "Roaring64Bitmap":
        return a._merge_walk(b, "and")

    @staticmethod
    def xor(a: "Roaring64Bitmap", b: "Roaring64Bitmap") -> "Roaring64Bitmap":
        return a._merge_walk(b, "xor")

    @staticmethod
    def andnot(a: "Roaring64Bitmap", b: "Roaring64Bitmap") -> "Roaring64Bitmap":
        return a._merge_walk(b, "andnot")

    def ior(self, other: "Roaring64Bitmap") -> "Roaring64Bitmap":
        # true in-place: only other's keys are touched; untouched containers
        # of self are never cloned (mirrors the reference's naivelazyor walk).
        # A bulk-merge rebuild was measured and rejected: cloning both
        # sides' pass-throughs costs what the avoided trie descents save
        # (A/B at 200k x 200k scattered keys: 2.45 s loop vs 2.67 s merge).
        for k, oc in list(other._kv()):
            mine = self._get(k)
            self._put(k, oc.clone() if mine is None else mine.or_(oc))
        return self

    def ixor(self, other: "Roaring64Bitmap") -> "Roaring64Bitmap":
        for k, oc in list(other._kv()):
            mine = self._get(k)
            self._set_or_drop(k, oc.clone() if mine is None else mine.xor_(oc))
        return self

    def iandnot(self, other: "Roaring64Bitmap") -> "Roaring64Bitmap":
        for k, oc in list(other._kv()):
            mine = self._get(k)
            if mine is not None:
                self._set_or_drop(k, mine.andnot(oc))
        return self

    def iand(self, other: "Roaring64Bitmap") -> "Roaring64Bitmap":
        # touches every key of self: drop keys absent from other
        for k, mine in list(self._kv()):
            oc = other._get(k)
            self._set_or_drop(k, None if oc is None else mine.and_(oc))
        return self

    or_inplace = ior
    and_inplace = iand
    xor_inplace = ixor
    andnot_inplace = iandnot

    __or__ = lambda self, o: Roaring64Bitmap.or_(self, o)
    __and__ = lambda self, o: Roaring64Bitmap.and_(self, o)
    __xor__ = lambda self, o: Roaring64Bitmap.xor(self, o)
    __sub__ = lambda self, o: Roaring64Bitmap.andnot(self, o)
    __ior__ = ior
    __iand__ = iand
    __ixor__ = ixor
    __isub__ = iandnot

    def intersects(self, other: "Roaring64Bitmap") -> bool:
        it_a, it_b = self._kv(), other._kv()
        a = next(it_a, None)
        b = next(it_b, None)
        while a is not None and b is not None:
            if a[0] < b[0]:
                a = next(it_a, None)
            elif b[0] < a[0]:
                b = next(it_b, None)
            else:
                if a[1].intersects(b[1]):
                    return True
                a = next(it_a, None)
                b = next(it_b, None)
        return False

    # ------------------------------------------------------------------
    # cardinality / order statistics
    # ------------------------------------------------------------------
    def get_cardinality(self) -> int:
        _, _, cum, _ = self._ordered()
        return int(cum[-1]) if cum.size else 0

    def is_empty(self) -> bool:
        return self._art.is_empty()

    def rank(self, x: int) -> int:
        x = _check64(x)
        key, low = high48_key(x), x & 0xFFFF
        keys, conts, cum, _ = self._ordered()
        i = bisect.bisect_left(keys, key)
        total = int(cum[i - 1]) if i else 0
        if i < len(keys) and keys[i] == key:
            total += conts[i].rank(low)
        return total

    def rank_many(self, values) -> np.ndarray:
        """Bulk rank: int64 counts aligned with ``values`` — one vectorized
        high-48 chunk resolution plus one container ``rank_many`` per
        touched chunk (bulk twin of rank; negative ints as their
        two's-complement bit patterns, like contains_many)."""
        from ..utils.order_stats import bucketed_rank_many

        vals = np.asarray(values).astype(np.uint64, copy=False).ravel()
        keys, conts, cum, key_ints = self._ordered()
        if vals.size == 0 or not keys:
            return np.zeros(vals.size, dtype=np.int64)
        lows = (vals & np.uint64(0xFFFF)).astype(np.uint16)

        def in_chunk(i, pos):
            c = conts[i]
            if pos.size < 4:  # scattered probes: scalar beats numpy setup
                return np.array([c.rank(int(v)) for v in lows[pos]], dtype=np.int64)
            return c.rank_many(lows[pos])

        return bucketed_rank_many(
            key_ints, cum, (vals >> np.uint64(16)).astype(np.int64), in_chunk
        )

    def select_many(self, ranks) -> np.ndarray:
        """Bulk select: uint64 values at the given ranks, one vectorized
        chunk resolution plus one container ``select_many`` per touched
        chunk (bulk twin of select)."""
        from ..utils.order_stats import bucketed_select_many

        _, conts, cum, key_ints = self._ordered()
        return bucketed_select_many(
            cum,
            ranks,
            lambda i, js: (np.uint64(key_ints[i]) << np.uint64(16))
            | conts[i].select_many(js).astype(np.uint64),
        )

    def select(self, j: int) -> int:
        if j < 0:
            raise IndexError(f"select({j})")
        keys, conts, cum, _ = self._ordered()
        if not keys or j >= int(cum[-1]):
            raise IndexError(f"select({j}) out of range")
        i = int(np.searchsorted(cum, j, side="right"))
        prev = int(cum[i - 1]) if i else 0
        return (key_to_int(keys[i]) << 16) | conts[i].select(j - prev)

    def first(self) -> int:
        kv = self._art.first()
        if kv is None:
            raise ValueError("empty bitmap")
        k, idx = kv
        return (key_to_int(k) << 16) | self._containers.get(idx).first()

    def last(self) -> int:
        kv = self._art.last()
        if kv is None:
            raise ValueError("empty bitmap")
        k, idx = kv
        return (key_to_int(k) << 16) | self._containers.get(idx).last()

    def next_value(self, from_value: int) -> int:
        from_value = _check64(from_value)
        key, low = high48_key(from_value), from_value & 0xFFFF
        for k, idx in self._art.items_from(key):
            c = self._containers.get(idx)
            v = c.next_value(low) if k == key else c.first()
            if v >= 0:
                return (key_to_int(k) << 16) | v
        return -1

    def previous_value(self, from_value: int) -> int:
        from_value = _check64(from_value)
        key, low = high48_key(from_value), from_value & 0xFFFF
        for k, idx in self._art.items_to(key):
            c = self._containers.get(idx)
            v = c.previous_value(low) if k == key else c.last()
            if v >= 0:
                return (key_to_int(k) << 16) | v
        return -1

    # ------------------------------------------------------------------
    # structure
    # ------------------------------------------------------------------
    def run_optimize(self) -> bool:
        self._ord = None
        changed = False
        for key, idx in self._art.items():
            c = self._containers.get(idx)
            new = c.run_optimize()
            if new is not c:
                self._containers.replace(idx, new)
                changed = True
        return changed

    def clone(self) -> "Roaring64Bitmap":
        # _kv() walks the trie in ascending key order -> bulk-build the copy
        out = Roaring64Bitmap()
        store = out._containers
        out._art.bulk_load([(k, store.add(c.clone())) for k, c in self._kv()])
        return out

    def to_array(self) -> np.ndarray:
        parts = [
            c.to_array().astype(np.uint64) | np.uint64(key_to_int(k) << 16)
            for k, c in self._kv()
        ]
        return np.concatenate(parts) if parts else np.empty(0, dtype=np.uint64)

    def __iter__(self) -> Iterator[int]:
        for k, c in self._kv():
            base = key_to_int(k) << 16
            for v in c:
                yield base | v

    def get_high_to_bitmap_count(self) -> int:
        """Container (= high-48 key) count; the ART analogue of the
        NavigableMap's bucket count."""
        return len(self._art)

    # -- reference long-tail surface (Roaring64Bitmap.java) ----------------
    def add_int(self, x: int) -> None:
        """addInt: the int zero-extended to a long."""
        self.add(int(x) & 0xFFFFFFFF)

    def get_int_cardinality(self) -> int:
        card = self.get_cardinality()
        if card > (1 << 31) - 1:
            raise OverflowError("cardinality exceeds 32-bit int")
        return card

    def get_long_iterator(self) -> Iterator[int]:
        return iter(self)

    def get_long_iterator_from(self, min_value: int) -> Iterator[int]:
        """Values >= min_value ascending (getLongIteratorFrom)."""
        min_value = int(min_value)
        min_key = min_value >> 16
        for k, c in self._kv():
            base = key_to_int(k) << 16
            if (base >> 16) < min_key:
                continue
            for v in c:
                val = base | v
                if val >= min_value:
                    yield val

    def get_reverse_long_iterator(self) -> Iterator[int]:
        for k, c in self._kv_reversed():
            base = key_to_int(k) << 16
            for v in reversed(c.to_array().tolist()):
                yield base | v

    def get_reverse_long_iterator_from(self, max_value: int) -> Iterator[int]:
        """Values <= max_value descending (getReverseLongIteratorFrom)."""
        max_value = int(max_value)
        max_key = max_value >> 16
        for k, c in self._kv_reversed():
            base = key_to_int(k) << 16
            if (base >> 16) > max_key:
                continue
            for v in reversed(c.to_array().tolist()):
                val = base | v
                if val <= max_value:
                    yield val

    def _kv_reversed(self):
        """Streaming (key, container) descending — rides the trie's
        explicit-stack BackwardShuttle (art/BackwardShuttle.java:1) in
        O(depth) memory; reverse iteration over a huge key set must not
        materialize the trie it exists to index."""
        for key, idx in self._art.items_reverse():
            yield key, self._containers.get(idx)

    def for_each(self, consumer) -> None:
        for v in self:
            consumer(v)

    @staticmethod
    def _check_range64(start: int, end: int):
        start, end = int(start), int(end)
        if not 0 <= start <= end <= (1 << 64):
            raise ValueError(f"invalid range [{start}, {end})")
        return start, end

    def for_each_in_range(self, start: int, end: int, consumer) -> None:
        """Visit present values in [start, end) ascending. NOTE: half-open
        end, not the reference's (start, length) pair."""
        start, end = self._check_range64(start, end)
        for v in self.get_long_iterator_from(start):
            if v >= end:
                break
            consumer(v)

    def for_all_in_range(self, start: int, end: int, consumer) -> None:
        """consumer(relative_pos, present) for every position in
        [start, end) — RelativeRangeConsumer contract. Values stream from
        the from-iterator; positions are a flat walk, so memory stays O(1)."""
        start, end = self._check_range64(start, end)
        it = self.get_long_iterator_from(start)
        nxt = next(it, None)
        for pos in range(end - start):
            val = start + pos
            present = nxt == val
            if present:
                nxt = next(it, None)
            consumer(pos, present)

    def limit(self, max_cardinality: int) -> "Roaring64Bitmap":
        """First max_cardinality values: whole containers are adopted and
        only the last partial one is truncated (like the 32-bit limit)."""
        out = Roaring64Bitmap()
        remaining = int(max_cardinality)
        for k, c in self._kv():
            if remaining <= 0:
                break
            if c.cardinality <= remaining:
                taken = c.clone()
            else:
                taken = container_from_values(c.to_array()[:remaining])
            out._put(k, taken)
            remaining -= taken.cardinality
        return out

    def clear(self) -> None:
        """Empty in place (Roaring64Bitmap.clear)."""
        self.__init__()

    empty = clear

    def trim(self) -> None:
        """No-op: numpy storage is exact-sized."""

    def get_size_in_bytes(self) -> int:
        return sum(8 + c.serialized_size() for _, c in self._kv())

    get_long_size_in_bytes = get_size_in_bytes

    @staticmethod
    def and_cardinality(a: "Roaring64Bitmap", b: "Roaring64Bitmap") -> int:
        """O(intersection): per-key and_cardinality, nothing materialized."""
        total = 0
        it_b = dict(b._kv())
        for k, ca in a._kv():
            cb = it_b.get(k)
            if cb is not None:
                total += ca.and_cardinality(cb)
        return total

    @staticmethod
    def or_cardinality(a: "Roaring64Bitmap", b: "Roaring64Bitmap") -> int:
        return (
            a.get_cardinality()
            + b.get_cardinality()
            - Roaring64Bitmap.and_cardinality(a, b)
        )

    @staticmethod
    def xor_cardinality(a: "Roaring64Bitmap", b: "Roaring64Bitmap") -> int:
        return (
            a.get_cardinality()
            + b.get_cardinality()
            - 2 * Roaring64Bitmap.and_cardinality(a, b)
        )

    @staticmethod
    def andnot_cardinality(a: "Roaring64Bitmap", b: "Roaring64Bitmap") -> int:
        return a.get_cardinality() - Roaring64Bitmap.and_cardinality(a, b)

    # ------------------------------------------------------------------
    # serialization — portable 64-bit spec via high-32 grouping
    # ------------------------------------------------------------------
    def _grouped_high32(self) -> Iterator[Tuple[int, RoaringBitmap]]:
        """(high32, 32-bit view) groups in key order; the view's RoaringArray
        shares this bitmap's containers (append never mutates them)."""
        current_high32 = None
        current: Optional[RoaringBitmap] = None
        for k, c in self._kv():
            k_int = key_to_int(k)
            high32, key16 = k_int >> 16, k_int & 0xFFFF
            if high32 != current_high32:
                if current is not None:
                    yield current_high32, current
                current_high32 = high32
                current = RoaringBitmap()
            current.high_low_container.append(key16, c)
        if current is not None:
            yield current_high32, current

    def serialize(self) -> bytes:
        import struct

        parts = []
        count = 0
        for high32, bm in self._grouped_high32():
            parts.append(struct.pack("<I", high32))
            parts.append(bm.serialize())
            count += 1
        return b"".join([struct.pack("<Q", count)] + parts)

    def serialized_size_in_bytes(self) -> int:
        from ..serialization import serialized_size_in_bytes

        return 8 + sum(
            4 + serialized_size_in_bytes(bm) for _, bm in self._grouped_high32()
        )

    def _absorb_spec_bucket(self, high32: int, bm: RoaringBitmap) -> None:
        """Adopt a decoded 32-bit bucket's containers under their high-48
        chunk keys (shared by the buffer and stream readers)."""
        arr = bm.high_low_container
        for i in range(arr.size):
            k = ((high32 << 16) | int(arr.keys[i])).to_bytes(6, "big")
            self._put(k, arr.containers[i])

    def _adopt_buckets(self, buckets) -> None:
        """Adopt decoded (high32, 32-bit bitmap) buckets in ascending key
        order. On an empty trie — every deserializer's case — the chunk
        keys arrive strictly ascending (bucket keys validated ascending,
        in-bucket keys sorted), so the whole trie is bulk-built bottom-up
        (Art.bulk_load) instead of one descent per chunk."""
        if not self._art.is_empty():
            for high32, bm in buckets:
                self._absorb_spec_bucket(high32, bm)
            return
        self._ord = None
        store = self._containers
        pairs = []
        for high32, bm in buckets:
            arr = bm.high_low_container
            base = high32 << 16
            for i in range(arr.size):
                k = (base | int(arr.keys[i])).to_bytes(6, "big")
                pairs.append((k, store.add(arr.containers[i])))
        self._art.bulk_load(pairs)

    @staticmethod
    def read_from(buf) -> Tuple["Roaring64Bitmap", int]:
        """Parse one portable-spec 64-bit bitmap from the head of `buf`,
        returning (bitmap, bytes consumed) — the consuming reader shared by
        deserialize and embedding formats (64-bit BSI slices)."""
        import struct

        from ..serialization import InvalidRoaringFormat, read_into

        buf = memoryview(
            bytes(buf) if not isinstance(buf, (bytes, bytearray, memoryview)) else buf
        )
        if len(buf) < 8:
            raise InvalidRoaringFormat("truncated 64-bit header")
        (count,) = struct.unpack_from("<Q", buf, 0)
        if count > len(buf) // 4:
            raise InvalidRoaringFormat(f"implausible bucket count {count}")
        pos = 8
        out = Roaring64Bitmap()
        prev_key = -1
        buckets = []
        for _ in range(count):
            if pos + 4 > len(buf):
                raise InvalidRoaringFormat("truncated bucket key")
            (high32,) = struct.unpack_from("<I", buf, pos)
            pos += 4
            if high32 <= prev_key:
                raise InvalidRoaringFormat("bucket keys not strictly increasing")
            prev_key = high32
            bm = RoaringBitmap()
            pos += read_into(bm, buf[pos:])
            buckets.append((high32, bm))
        out._adopt_buckets(buckets)
        return out, pos

    @staticmethod
    def deserialize(data) -> "Roaring64Bitmap":
        return Roaring64Bitmap.read_from(data)[0]

    def serialize_into(self, fileobj) -> int:
        """Stream overload (Roaring64Bitmap.serialize(DataOutput),
        longlong/Roaring64Bitmap.java:880); returns bytes written."""
        data = self.serialize()
        fileobj.write(data)
        return len(data)

    @staticmethod
    def deserialize_from(fileobj) -> "Roaring64Bitmap":
        """Stream twin: consumes exactly one portable-spec 64-bit bitmap,
        leaving the stream at the next byte (bucket payloads stream through
        RoaringBitmap.deserialize_from's exact-consumption contract)."""
        import struct

        from ..serialization import InvalidRoaringFormat, read_exact

        (count,) = struct.unpack("<Q", read_exact(fileobj, 8))
        if count > (1 << 32):  # u32 strictly-increasing keys cap the count
            raise InvalidRoaringFormat(f"implausible bucket count {count}")
        out = Roaring64Bitmap()
        prev_key = -1
        buckets = []
        for _ in range(count):
            (high32,) = struct.unpack("<I", read_exact(fileobj, 4))
            if high32 <= prev_key:
                raise InvalidRoaringFormat("bucket keys not strictly increasing")
            prev_key = high32
            buckets.append((high32, RoaringBitmap.deserialize_from(fileobj)))
        out._adopt_buckets(buckets)
        return out

    # ------------------------------------------------------------------
    def __eq__(self, other):
        if isinstance(other, Roaring64Bitmap):
            return np.array_equal(self.to_array(), other.to_array())
        if hasattr(other, "to_array"):
            return np.array_equal(self.to_array(), other.to_array())
        return NotImplemented

    def __hash__(self):
        return hash(self.to_array().tobytes())

    def __len__(self) -> int:
        return self.get_cardinality()

    def __contains__(self, x: int) -> bool:
        return self.contains(x)

    def __bool__(self) -> bool:
        return not self.is_empty()

    def __repr__(self) -> str:
        card = self.get_cardinality()
        head = ",".join(str(v) for v in self.to_array()[:8].tolist())
        return f"Roaring64Bitmap(card={card}, values=[{head}{'...' if card > 8 else ''}])"

    # reference facade naming aliases (Roaring64Bitmap.java addLong :50,
    # removeLong, getLongCardinality) for drop-in familiarity
    add_long = add
    remove_long = remove
    contains_long = contains
    get_long_cardinality = get_cardinality

    def __reduce__(self):
        """Pickle via the portable 64-bit wire format."""
        return Roaring64Bitmap.deserialize, (self.serialize(),)
