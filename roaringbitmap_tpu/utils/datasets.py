"""Real-roaring-dataset loader.

Mirrors the reference harness corpus access
(ZipRealDataRetriever.fetchBitPositions,
real-roaring-dataset/.../ZipRealDataRetriever.java:39-69): each zip entry is
one CSV line of sorted ints = the bit positions of one bitmap. The canonical
corpora names are RealDataset.java:10-27; this snapshot of the reference
ships census1881, census1881_srt, uscensus2000, wikileaks-noquotes,
wikileaks-noquotes_srt.

If the reference checkout is not mounted, a seeded synthetic corpus with a
census1881-like shape profile is generated instead so benchmarks stay
runnable anywhere.
"""

from __future__ import annotations

import io
import os
import zipfile
from typing import List

import numpy as np

REFERENCE_DATASET_DIR = (
    "/root/reference/real-roaring-dataset/src/main/resources/real-roaring-dataset"
)

DATASET_NAMES = [
    "census-income",
    "census-income_srt",
    "census1881",
    "census1881_srt",
    "dimension_003",
    "dimension_008",
    "dimension_033",
    "uscensus2000",
    "weather_sept_85",
    "weather_sept_85_srt",
    "wikileaks-noquotes",
    "wikileaks-noquotes_srt",
]


def dataset_available(name: str) -> bool:
    return os.path.isfile(os.path.join(REFERENCE_DATASET_DIR, name + ".zip"))


def fetch_bit_positions(name: str) -> List[np.ndarray]:
    """All bitmaps of a corpus as uint32 arrays (one per zip entry)."""
    path = os.path.join(REFERENCE_DATASET_DIR, name + ".zip")
    out: List[np.ndarray] = []
    with zipfile.ZipFile(path) as zf:
        for entry in sorted(zf.namelist()):
            with zf.open(entry) as f:
                text = io.TextIOWrapper(f, encoding="ascii").read()
            vals = np.array(
                [int(tok) for tok in text.replace("\n", "").split(",") if tok.strip()],
                dtype=np.int64,
            )
            out.append(vals.astype(np.uint32))
    return out


def fetch_bit_position_ranges(name: str) -> List[np.ndarray]:
    """Range-format corpora: each zip entry is one line of
    ``start1-end1,start2-end2,...`` pairs; returns one ``[n, 2]`` int64
    array of inclusive ranges per entry (ZipRealDataRangeRetriever
    .fetchNextRange, real-roaring-dataset/.../ZipRealDataRangeRetriever.java:39)."""
    path = os.path.join(REFERENCE_DATASET_DIR, name + ".zip")
    out: List[np.ndarray] = []
    with zipfile.ZipFile(path) as zf:
        for entry in sorted(zf.namelist()):
            with zf.open(entry) as f:
                text = io.TextIOWrapper(f, encoding="ascii").read()
            # join lines before comma-splitting: entries wrap mid-token,
            # same as fetch_bit_positions above
            pairs = [
                tok.split("-")
                for tok in "".join(text.splitlines()).split(",")
                if tok.strip()
            ]
            out.append(np.array([(int(a), int(b)) for a, b in pairs], dtype=np.int64))
    return out


def bitset_matrix_available(name: str = "bitsets_1925630_96") -> bool:
    return os.path.isfile(os.path.join(REFERENCE_DATASET_DIR, name + ".gz"))


def fetch_bitset_matrix(
    name: str = "bitsets_1925630_96", limit: int | None = None
) -> List[np.ndarray]:
    """Rows of the gz-compressed raw-bitset corpus as uint64 word arrays.

    Wire format (real-roaring-dataset README.md:24, written with Java's
    DataOutputStream, so big-endian): int32 row count, then per row an
    int32 long count followed by that many int64 words. Consumed by the
    BitSetUtil conversion benchmarks (jmh BitSetUtilBenchmark.java)."""
    import gzip
    import struct as _struct

    path = os.path.join(REFERENCE_DATASET_DIR, name + ".gz")
    out: List[np.ndarray] = []
    with gzip.open(path, "rb") as f:
        (n_rows,) = _struct.unpack(">i", f.read(4))
        take = n_rows if limit is None else min(limit, n_rows)
        for _ in range(take):
            (n_longs,) = _struct.unpack(">i", f.read(4))
            words = np.frombuffer(f.read(8 * n_longs), dtype=">i8")
            out.append(words.astype(np.int64).view(np.uint64))
    return out


def synthetic_census_like(
    n_bitmaps: int = 200, seed: int = 0xFEEF1F0
) -> List[np.ndarray]:
    """Synthetic corpus with census1881-ish shape: clustered runs + sparse
    scatter over a few million values."""
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n_bitmaps):
        parts = []
        n_clusters = int(rng.integers(1, 30))
        for _ in range(n_clusters):
            base = int(rng.integers(0, 2_000_000))
            length = int(rng.integers(1, 2000))
            parts.append(np.arange(base, base + length, dtype=np.int64))
        scatter = rng.integers(0, 2_000_000, size=int(rng.integers(10, 3000)))
        parts.append(scatter)
        out.append(np.unique(np.concatenate(parts)).astype(np.uint32))
    return out


def load_or_synthesize(name: str = "census1881", n_bitmaps_hint: int = 200):
    """Corpus bitmaps (uint32 arrays), preferring the real dataset."""
    if dataset_available(name):
        return fetch_bit_positions(name), True
    return synthetic_census_like(n_bitmaps_hint), False
