"""Shared bucketed rank/select over (sorted keys, cumulative cardinalities).

One implementation of the cached-cumulative-cardinality pattern the
reference repeats in FastRankRoaringBitmap (FastRankRoaringBitmap.java:21-39)
and Roaring64NavigableMap (Roaring64NavigableMap.java:66-72), used here by
FastRankRoaringBitmap, Roaring64Bitmap and ImmutableRoaringBitmap.
"""

from __future__ import annotations

from bisect import bisect_left
from typing import Callable, Sequence

import numpy as np


def bucketed_rank(
    keys: Sequence[int],
    cum: np.ndarray,
    high: int,
    bucket_rank: Callable[[int], int],
) -> int:
    """Rank of (high, low) given per-bucket ranks: full buckets below `high`
    via the cumulative table, plus `bucket_rank(i)` inside the matching
    bucket (the caller closes over `low`)."""
    i = bisect_left(keys, high)
    total = int(cum[i - 1]) if i > 0 else 0
    if i < len(keys) and keys[i] == high:
        total += bucket_rank(i)
    return total


def bucketed_select(
    keys: Sequence[int],
    cum: np.ndarray,
    j: int,
    bucket_select: Callable[[int, int], int],
) -> int:
    """Global j-th value: locate the bucket by cumulative cardinality, then
    `bucket_select(i, local_j)`. The caller combines the returned low value
    with keys[i]."""
    j = int(j)
    if j < 0:
        raise IndexError(j)
    i = int(np.searchsorted(cum, j + 1))
    if i >= len(keys):
        raise IndexError("select out of range")
    prior = int(cum[i - 1]) if i else 0
    return bucket_select(i, j - prior)


def group_positions(vals: np.ndarray):
    """Yield (value, positions) for each distinct entry of ``vals`` (one
    stable argsort) — the grouping idiom shared by the bulk-probe paths."""
    order = np.argsort(vals, kind="stable")
    sv = vals[order]
    bounds = np.nonzero(np.diff(sv))[0] + 1
    starts = np.concatenate(([0], bounds))
    ends = np.concatenate((bounds, [sv.size]))
    for s, e in zip(starts.tolist(), ends.tolist()):
        yield int(sv[s]), order[s:e]


def bucketed_rank_many(
    sorted_keys: np.ndarray,
    cum: np.ndarray,
    probe_keys: np.ndarray,
    in_bucket: Callable[[int, np.ndarray], np.ndarray],
) -> np.ndarray:
    """Vectorized bucketed rank, shared by every bulk rank_many: buckets
    strictly before each probe's key contribute wholesale via the exclusive
    prefix of ``cum`` (inclusive cumsum), and probes whose bucket exists add
    ``in_bucket(bucket_index, positions)`` — called once per touched
    bucket."""
    prefix = np.concatenate(([0], cum))
    idx = np.searchsorted(sorted_keys, probe_keys, side="left")
    out = prefix[idx].copy()
    n = sorted_keys.size
    hit = (idx < n) & (sorted_keys[np.minimum(idx, n - 1)] == probe_keys)
    if hit.any():
        hit_all = np.flatnonzero(hit)
        for _, rel in group_positions(idx[hit_all]):
            pos = hit_all[rel]
            out[pos] += in_bucket(int(idx[pos[0]]), pos)
    return out


def bucketed_select_many(
    cum: np.ndarray,
    ranks: np.ndarray,
    in_bucket: Callable[[int, np.ndarray], np.ndarray],
    dtype=np.uint64,
) -> np.ndarray:
    """Vectorized bucketed select, shared by every bulk select_many: each
    rank resolves to its bucket through the inclusive cumsum, and
    ``in_bucket(bucket_index, local_ranks)`` returns the finished values
    (high bits merged) — called once per touched bucket. Raises IndexError
    on any out-of-range rank, like the scalar selects."""
    js = np.asarray(ranks, dtype=np.int64).ravel()
    out = np.zeros(js.size, dtype=dtype)
    if js.size == 0:
        return out
    total = int(cum[-1]) if cum.size else 0
    if js.min() < 0 or js.max() >= total:
        raise IndexError("select out of range")
    ci = np.searchsorted(cum, js, side="right")
    base = np.concatenate(([0], cum))[ci]
    for c_idx, pos in group_positions(ci):
        out[pos] = in_bucket(c_idx, js[pos] - base[pos])
    return out
