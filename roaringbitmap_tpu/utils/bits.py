"""L0 word/array kernels (host side, numpy).

TPU-native re-expression of the reference's branchless bit hacks
(reference Util.java / BitSetUtil.java — e.g. setBitmapRange Util.java:616,
cardinalityInBitmapRange Util.java:415, select(long,int) Util.java:564).
Java expresses these as JIT-intrinsic scalar loops over ``long[]``; here the
host path is vectorized numpy over the whole 1024-word container at once and
the device path (ops/device.py) is batched XLA over ``[N, 1024]`` blocks.

A container covers a 16-bit sub-universe: 65536 bits = 1024 x uint64 words.
Word ``w`` bit ``b`` (little-endian within the word) holds value ``64*w + b``,
matching the RoaringFormatSpec serialized bitmap layout.
"""

from __future__ import annotations

import numpy as np

WORDS_PER_CONTAINER = 1024  # 65536 bits / 64-bit words (BitmapContainer.java:25)
BITS_PER_CONTAINER = 1 << 16

_U64_ONE = np.uint64(1)

# SWAR popcount constants (uint64)
_M1 = np.uint64(0x5555555555555555)
_M2 = np.uint64(0x3333333333333333)
_M4 = np.uint64(0x0F0F0F0F0F0F0F0F)
_H01 = np.uint64(0x0101010101010101)


def highbits(x):
    """High 16 bits of a 32-bit value (container key)."""
    return np.uint16(np.uint32(x) >> np.uint32(16))


def lowbits(x):
    """Low 16 bits of a 32-bit value (position within container)."""
    return np.uint16(np.uint32(x) & np.uint32(0xFFFF))


def combine(hb, lb):
    """Rebuild the 32-bit value from (high16, low16)."""
    return np.uint32(np.uint32(hb) << np.uint32(16)) | np.uint32(lb)


def popcount64(words: np.ndarray) -> np.ndarray:
    """Branchless SWAR popcount of each uint64 word (vectorized).

    Host analogue of ``Long.bitCount`` (BitmapContainer.java:17); the device
    analogue is ``jax.lax.population_count``.
    """
    v = words.astype(np.uint64, copy=True)
    v -= (v >> _U64_ONE) & _M1
    v = (v & _M2) + ((v >> np.uint64(2)) & _M2)
    v = (v + (v >> np.uint64(4))) & _M4
    return (v * _H01) >> np.uint64(56)


def cardinality_of_words(words: np.ndarray) -> int:
    """Total set-bit count of a word array."""
    return int(popcount64(words).sum())


def new_words() -> np.ndarray:
    return np.zeros(WORDS_PER_CONTAINER, dtype=np.uint64)


def is_strictly_increasing(a: np.ndarray) -> bool:
    """True when ``a`` is sorted AND duplicate-free — the bulk-ingest fast
    paths' contract. The strictness is load-bearing: a non-strict (>=)
    check would let duplicates skip the unique pass and corrupt
    containers."""
    return a.size <= 1 or bool(np.all(a[1:] > a[:-1]))


def words_from_values(values: np.ndarray) -> np.ndarray:
    """Build 1024-word bitset from sorted-or-not uint16 values."""
    return or_values_into_words(new_words(), values)


def or_values_into_words(words: np.ndarray, values: np.ndarray) -> np.ndarray:
    """OR uint16 values into an EXISTING word accumulator in place (the
    lazy-OR fold's array-container scatter; the native tier rides
    rb_words_from_values, which ORs into the caller's buffer)."""
    v = np.asarray(values, dtype=np.uint32)
    np.bitwise_or.at(words, v >> 6, _U64_ONE << (v & np.uint32(63)).astype(np.uint64))
    return words


def values_from_words(words: np.ndarray) -> np.ndarray:
    """Extract sorted uint16 values from a 1024-word bitset.

    Uses byte-level unpack (little-endian bit order) so bit i of word w maps
    to value 64*w + i.
    """
    bits = np.unpackbits(words.view(np.uint8), bitorder="little")
    return np.nonzero(bits)[0].astype(np.uint16)


def set_bit(words: np.ndarray, x: int) -> None:
    words[x >> 6] |= _U64_ONE << np.uint64(x & 63)


def clear_bit(words: np.ndarray, x: int) -> None:
    words[x >> 6] &= ~(_U64_ONE << np.uint64(x & 63))


def get_bit(words: np.ndarray, x: int) -> bool:
    return bool((words[x >> 6] >> np.uint64(x & 63)) & _U64_ONE)


def set_bitmap_range(words: np.ndarray, start: int, end: int) -> None:
    """Set bits [start, end) — vectorized analogue of Util.setBitmapRange (Util.java:616)."""
    if start >= end:
        return
    first, last = start >> 6, (end - 1) >> 6
    lo_mask = np.uint64(0xFFFFFFFFFFFFFFFF) << np.uint64(start & 63)
    hi_mask = np.uint64(0xFFFFFFFFFFFFFFFF) >> np.uint64(63 - ((end - 1) & 63))
    if first == last:
        words[first] |= lo_mask & hi_mask
        return
    words[first] |= lo_mask
    words[first + 1 : last] = np.uint64(0xFFFFFFFFFFFFFFFF)
    words[last] |= hi_mask


def clear_bitmap_range(words: np.ndarray, start: int, end: int) -> None:
    """Clear bits [start, end) (Util.resetBitmapRange analogue)."""
    if start >= end:
        return
    first, last = start >> 6, (end - 1) >> 6
    lo_mask = np.uint64(0xFFFFFFFFFFFFFFFF) << np.uint64(start & 63)
    hi_mask = np.uint64(0xFFFFFFFFFFFFFFFF) >> np.uint64(63 - ((end - 1) & 63))
    if first == last:
        words[first] &= ~(lo_mask & hi_mask)
        return
    words[first] &= ~lo_mask
    words[first + 1 : last] = np.uint64(0)
    words[last] &= ~hi_mask


def flip_bitmap_range(words: np.ndarray, start: int, end: int) -> None:
    """Flip bits [start, end) (Util.flipBitmapRange analogue)."""
    if start >= end:
        return
    first, last = start >> 6, (end - 1) >> 6
    lo_mask = np.uint64(0xFFFFFFFFFFFFFFFF) << np.uint64(start & 63)
    hi_mask = np.uint64(0xFFFFFFFFFFFFFFFF) >> np.uint64(63 - ((end - 1) & 63))
    if first == last:
        words[first] ^= lo_mask & hi_mask
        return
    words[first] ^= lo_mask
    words[first + 1 : last] ^= np.uint64(0xFFFFFFFFFFFFFFFF)
    words[last] ^= hi_mask


def cardinality_in_range(words: np.ndarray, start: int, end: int) -> int:
    """Popcount of bits [start, end) — Util.cardinalityInBitmapRange (Util.java:415)."""
    if start >= end:
        return 0
    first, last = start >> 6, (end - 1) >> 6
    lo_mask = np.uint64(0xFFFFFFFFFFFFFFFF) << np.uint64(start & 63)
    hi_mask = np.uint64(0xFFFFFFFFFFFFFFFF) >> np.uint64(63 - ((end - 1) & 63))
    if first == last:
        return int(popcount64(np.array([words[first] & lo_mask & hi_mask])).sum())
    total = int(popcount64(np.array([words[first] & lo_mask, words[last] & hi_mask])).sum())
    if last > first + 1:
        total += int(popcount64(words[first + 1 : last]).sum())
    return total


def select_in_words(words: np.ndarray, j: int) -> int:
    """Position of the j-th (0-based) set bit — Util.select(long,int) (Util.java:564)
    generalized to the whole container via a cumulative-popcount scan."""
    counts = popcount64(words)
    cum = np.cumsum(counts)
    w = int(np.searchsorted(cum, j + 1))
    if w >= len(words):
        raise IndexError(f"select({j}) out of range (cardinality {int(cum[-1]) if len(cum) else 0})")
    prior = int(cum[w - 1]) if w else 0
    word = int(words[w])
    target = j - prior
    # peel target set bits off the word
    for _ in range(target):
        word &= word - 1
    lsb = word & -word
    return (w << 6) + lsb.bit_length() - 1


def runs_from_values(values: np.ndarray):
    """(starts, lengths) runs from a sorted uint16 value array.

    ``lengths`` follows the RoaringFormatSpec convention: the run covers
    [start, start+length], i.e. length = run cardinality - 1
    (RunContainer.java's interleaved (value, length) pairs).
    """
    v = np.asarray(values, dtype=np.int64)
    if v.size == 0:
        return np.empty(0, dtype=np.uint16), np.empty(0, dtype=np.uint16)
    breaks = np.nonzero(np.diff(v) != 1)[0]
    starts_idx = np.concatenate(([0], breaks + 1))
    ends_idx = np.concatenate((breaks, [v.size - 1]))
    starts = v[starts_idx]
    lengths = v[ends_idx] - starts
    return starts.astype(np.uint16), lengths.astype(np.uint16)


def values_from_runs(starts: np.ndarray, lengths: np.ndarray) -> np.ndarray:
    """Expand (starts, lengths) runs to a sorted uint16 value array."""
    s = np.asarray(starts, dtype=np.int64)
    l = np.asarray(lengths, dtype=np.int64)
    if s.size == 0:
        return np.empty(0, dtype=np.uint16)
    total = int((l + 1).sum())
    out = np.ones(total, dtype=np.int64)
    # offsets where each run begins in the output
    run_offsets = np.concatenate(([0], np.cumsum(l + 1)[:-1]))
    out[run_offsets] = s - np.concatenate(([0], s[:-1] + l[:-1]))
    return np.cumsum(out).astype(np.uint16)


def words_from_intervals(starts: np.ndarray, ends: np.ndarray) -> np.ndarray:
    """1024-word uint64 bitset from disjoint half-open [start, end) intervals,
    via a boundary-delta cumsum (vectorized; no per-run loop)."""
    # rb-ok: dtype-discipline -- boundary deltas are ±1 per disjoint
    # interval (|delta| <= 2 where a start meets an end), far inside int8
    delta = np.zeros((1 << 16) + 1, dtype=np.int8)
    np.add.at(delta, np.asarray(starts, dtype=np.int64), 1)
    np.subtract.at(delta, np.asarray(ends, dtype=np.int64), 1)
    # rb-ok: dtype-discipline -- running sum of the deltas is bounded by
    # the interval count (<= 2^16), exact in int32; result is only a mask
    mask = np.cumsum(delta[:-1], dtype=np.int32) > 0
    return np.packbits(mask, bitorder="little").view(np.uint64)


def num_runs_in_words(words: np.ndarray) -> int:
    """Number of runs in a bitset, vectorized.

    A run starts at every 01 transition scanning LSB->MSB; equals
    popcount(x & ~(x << 1)) summed with cross-word carry — the branchless
    formulation the reference computes per-word (BitmapContainer numberOfRuns).
    """
    w = words.astype(np.uint64)
    shifted = w << _U64_ONE
    # carry in the top bit of the previous word
    carry = np.zeros_like(w)
    carry[1:] = w[:-1] >> np.uint64(63)
    starts = w & ~(shifted | carry)
    return int(popcount64(starts).sum())


def merge_sorted_unique(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Union of two sorted unique uint16 arrays (Util.unsignedUnion2by2 analogue)."""
    if a.size == 0:
        return b.copy()
    if b.size == 0:
        return a.copy()
    out = np.union1d(a, b)  # sorts+dedups; inputs already sorted so this is a merge
    return out.astype(np.uint16)


def intersect_sorted(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Intersection of two sorted unique uint16 arrays (Util.unsignedIntersect2by2)."""
    if a.size == 0 or b.size == 0:
        return np.empty(0, dtype=np.uint16)
    out = np.intersect1d(a, b, assume_unique=True)
    return out.astype(np.uint16)


def difference_sorted(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """a \\ b for sorted unique uint16 arrays (Util.unsignedDifference)."""
    if a.size == 0 or b.size == 0:
        return a.copy()
    out = np.setdiff1d(a, b, assume_unique=True)
    return out.astype(np.uint16)


def xor_sorted(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Symmetric difference of two sorted unique uint16 arrays (Util.unsignedExclusiveUnion2by2)."""
    if a.size == 0:
        return b.copy()
    if b.size == 0:
        return a.copy()
    out = np.setxor1d(a, b, assume_unique=True)
    return out.astype(np.uint16)


def lower_bound(a: np.ndarray, x: int) -> int:
    """Index of the first element >= x in a sorted uint16 array (the
    unsignedBinarySearch/gallop primitive behind point contains/rank/add;
    Util.java:697)."""
    return int(np.searchsorted(a, np.uint16(x)))


def validate_sorted_u16(values: np.ndarray) -> bool:
    """True iff strictly increasing (deserialization's array-container
    check)."""
    return not (values.size > 1 and bool(np.any(values[1:] <= values[:-1])))


def validate_runs_u16(pairs: np.ndarray) -> bool:
    """True iff interleaved (start, length) runs are sorted, disjoint,
    non-touching, and end inside the 2^16 universe."""
    starts, lengths = pairs[0::2], pairs[1::2]
    # rb-ok: dtype-discipline -- uint16 start+length <= 2*0xFFFF, exact in
    # int32; signed width is what makes the `> 0xFFFF` overflow check work
    s32 = starts.astype(np.int32)
    ends = s32 + lengths  # int32: no uint16 overflow
    return not (
        starts.size
        and (bool(np.any(s32[1:] <= ends[:-1])) or bool(np.any(ends > 0xFFFF)))
    )


# ---------------------------------------------------------------------------
# native dispatch — when the compiled C++ kernels (native/kernels.cpp) are
# available, the hot host-path entry points rebind to them. The numpy
# versions above stay reachable under *_numpy as the differential-test
# oracle (tests/test_native.py). Semantics are identical by contract.
#
# Resolution is lazy: the first *call* to any dispatched kernel triggers the
# (possibly compiling) native load, then rebinds the module attribute to the
# winner — importing the package never shells out to g++, and the pure
# device path (ops/) never touches this at all.
# ---------------------------------------------------------------------------

_DISPATCHED = (
    "intersect_sorted",
    "merge_sorted_unique",
    "difference_sorted",
    "xor_sorted",
    "cardinality_of_words",
    "values_from_words",
    "words_from_values",
    "or_values_into_words",
    "num_runs_in_words",
    "select_in_words",
    "cardinality_in_range",
    "runs_from_values",
    "words_from_intervals",
    "validate_sorted_u16",
    "validate_runs_u16",
    "lower_bound",
)

for _name in _DISPATCHED:
    globals()[_name + "_numpy"] = globals()[_name]


def _resolve_native() -> None:
    """Bind every dispatched name to its native or numpy implementation."""
    g = globals()
    try:
        from .. import native as _native

        use = _native.available()
    except Exception:  # rb-ok: exception-hygiene -- native-tier probe: toolchain missing, sandboxed, ABI skew — every failure mode must degrade to the numpy tier
        use = False
    for name in _DISPATCHED:
        g[name] = getattr(_native, name) if use else g[name + "_numpy"]


def _make_trampoline(name: str):
    def trampoline(*args, **kwargs):
        _resolve_native()
        return globals()[name](*args, **kwargs)

    trampoline.__name__ = name
    trampoline.__doc__ = globals()[name + "_numpy"].__doc__
    return trampoline


for _name in _DISPATCHED:
    globals()[_name] = _make_trampoline(_name)
del _name
