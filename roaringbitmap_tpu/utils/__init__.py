from . import bits

__all__ = ["bits"]
