"""Columnar pairwise engine: type-partitioned batched container algebra
(ISSUE 5).

Executes whole bitmap-pair ops and N-way CPU fold steps WITHOUT
per-container Python dispatch, in three vectorized stages:

1. **key plan** (keyplan.py) — one searchsorted over the two key arrays
   splits matched pairs from pass-throughs;
2. **type partition** (partition.py) — matched pairs classify into the 9
   ``(array|bitmap|run)²`` classes; array payloads gather into CSR
   ``(values, offsets)`` buffers, dense payloads stack into ``[n, 1024]``
   word matrices (runs through the batched interval fill);
3. **per-class batch kernels** (kernels.py / native ``rb_batch_*``) — one
   call per occupied class, then batched result-format selection.

Since ISSUE 10 the engine has a **device execution tier** (device.py):
the word-parallel classes run as fused jit dispatches over
PACK_CACHE-resident flat rows on accelerator backends, and the hand-tuned
cutoff is a **measured three-way cost model** (costmodel.py) choosing
per-container / columnar-CPU / columnar-device per call from operand
count, sampled class mix, and pack residency — uncalibrated it
reproduces the r11 gate verbatim.

The facade (models/roaring.py), the CPU folds (parallel/aggregation.py)
and the query kernels' CPU fallbacks route here through
``route()``/``enabled_for_fold()``; the per-container walk stays below
the cutoff and as the differential reference (fuzz family
``columnar-vs-percontainer``). Observability:
``rb_tpu_columnar_batch_total{op,class}`` +
``rb_tpu_columnar_route_total{tier}`` via
``insights.columnar_counters()``; routing provenance lands at the
``columnar.cutoff`` decision site (1-in-N sampled below the count gate).
"""

from .costmodel import MODEL, calibrate, ensure_calibrated, refit_from_outcomes
from .engine import (
    Verdict,
    and_cardinality_pair,
    config,
    disabled,
    enabled_for,
    enabled_for_fold,
    fold,
    fold_multi,
    intersects_pair,
    or_fold_words,
    outcome,
    pairwise,
    pairwise_multi,
    route,
)
from .keyplan import KeyPlan, key_plan
from .partition import CLASS_NAMES, class_histogram, classify

__all__ = [
    "config",
    "disabled",
    "enabled_for",
    "enabled_for_fold",
    "route",
    "pairwise",
    "and_cardinality_pair",
    "intersects_pair",
    "fold",
    "fold_multi",
    "or_fold_words",
    "pairwise_multi",
    "key_plan",
    "KeyPlan",
    "classify",
    "class_histogram",
    "CLASS_NAMES",
    "MODEL",
    "calibrate",
    "ensure_calibrated",
    "refit_from_outcomes",
    "outcome",
    "Verdict",
]
