"""Columnar pairwise engine: type-partitioned batched container algebra
(ISSUE 5).

Executes whole bitmap-pair ops and N-way CPU fold steps WITHOUT
per-container Python dispatch, in three vectorized stages:

1. **key plan** (keyplan.py) — one searchsorted over the two key arrays
   splits matched pairs from pass-throughs;
2. **type partition** (partition.py) — matched pairs classify into the 9
   ``(array|bitmap|run)²`` classes; array payloads gather into CSR
   ``(values, offsets)`` buffers, dense payloads stack into ``[n, 1024]``
   word matrices (runs through the batched interval fill);
3. **per-class batch kernels** (kernels.py / native ``rb_batch_*``) — one
   call per occupied class, then batched result-format selection.

The facade (models/roaring.py), the CPU folds (parallel/aggregation.py)
and the query kernels' CPU fallbacks route here above
``config.min_containers`` / ``config.min_fold_rows``; the per-container
walk stays below the cutoff and as the differential reference (fuzz
family ``columnar-vs-percontainer``). Observability:
``rb_tpu_columnar_batch_total{op,class}`` via
``insights.columnar_counters()``.
"""

from .engine import (
    and_cardinality_pair,
    config,
    disabled,
    enabled_for,
    enabled_for_fold,
    fold,
    intersects_pair,
    or_fold_words,
    pairwise,
)
from .keyplan import KeyPlan, key_plan
from .partition import CLASS_NAMES, class_histogram, classify

__all__ = [
    "config",
    "disabled",
    "enabled_for",
    "enabled_for_fold",
    "pairwise",
    "and_cardinality_pair",
    "intersects_pair",
    "fold",
    "or_fold_words",
    "key_plan",
    "KeyPlan",
    "classify",
    "class_histogram",
    "CLASS_NAMES",
]
