"""Stage 1 of the columnar pairwise engine: the key plan.

One ``searchsorted`` over the two bitmaps' (already sorted) key arrays
classifies EVERY chunk key in one shot — matched pairs vs pass-throughs —
replacing the per-key two-pointer Python merge loop of the facade
(models/roaring.py ``_merge_op``/``and_``) whose per-iteration interpreter
cost is the dispatch floor this package removes (RoaringBitmap.java:377's
``highbits`` merge, computed as a batch instead of a walk).
"""

from __future__ import annotations

from typing import List

import numpy as np

_EMPTY = np.empty(0, dtype=np.int64)


class KeyPlan:
    """Matched/pass-through split of two key arrays.

    ``ia``/``ib`` — indices of matched keys into a's and b's container
    lists (aligned; ``matched_keys = akeys[ia]``); ``a_only``/``b_only`` —
    pass-through indices (populated only when the op propagates that side:
    both for or/xor, left for andnot, neither for and).
    """

    __slots__ = ("akeys", "bkeys", "ia", "ib", "a_only", "b_only")

    def __init__(self, akeys, bkeys, ia, ib, a_only, b_only):
        self.akeys = akeys
        self.bkeys = bkeys
        self.ia = ia
        self.ib = ib
        self.a_only = a_only
        self.b_only = b_only

    @property
    def matched_keys(self) -> np.ndarray:
        return self.akeys[self.ia]


def key_plan(akeys: List[int], bkeys: List[int], op: str) -> KeyPlan:
    """Compute the matched/pass-through split for ``op`` in one vectorized
    pass. ``op`` decides which pass-through sides are materialized:
    ``and`` keeps none, ``andnot`` keeps a's, ``or``/``xor`` keep both."""
    a = np.asarray(akeys, dtype=np.int64)
    b = np.asarray(bkeys, dtype=np.int64)
    if a.size == 0 or b.size == 0:
        a_only = np.arange(a.size, dtype=np.int64) if op != "and" else _EMPTY
        b_only = (
            np.arange(b.size, dtype=np.int64) if op in ("or", "xor") else _EMPTY
        )
        return KeyPlan(a, b, _EMPTY, _EMPTY, a_only, b_only)
    pos = np.searchsorted(b, a)
    posc = np.minimum(pos, b.size - 1)
    hit = (pos < b.size) & (b[posc] == a)
    ia = np.flatnonzero(hit)
    ib = pos[ia]
    a_only = np.flatnonzero(~hit) if op != "and" else _EMPTY
    if op in ("or", "xor"):
        bmask = np.ones(b.size, dtype=bool)
        bmask[ib] = False
        b_only = np.flatnonzero(bmask)
    else:
        b_only = _EMPTY
    return KeyPlan(a, b, ia, ib, a_only, b_only)
