"""Batched per-class kernels for the columnar pairwise engine (ISSUE 5).

Every primitive here executes a WHOLE batch of container payloads in one
call — the native tier loops compiled two-pointer merges over CSR offset
arrays (native/kernels.cpp ``rb_batch_*``), and the numpy tier reaches the
same results fully vectorized via the *banding* trick: pair ``j``'s uint16
payload is lifted into its own disjoint int64 band ``j * 2^16 + value``,
after which ONE global sort / searchsorted over the concatenation performs
every pair's merge at once (bands never interleave, so a global sort IS a
per-pair merge). Semantics of the two tiers are identical; the numpy tier
is the differential oracle and the no-toolchain fallback
(``ROARINGBITMAP_TPU_NO_NATIVE=1``).

Ops are the four pairwise set operations on sorted unique uint16 arrays;
word-matrix primitives (scatter / interval fill / per-row popcount) serve
the dense classes and the N-way folds.

Since ISSUE 10 there is a THIRD kernel tier above these two: the device
tier (columnar/device.py) runs the word-parallel classes as fused jit
dispatches over PACK_CACHE-resident flat rows
(ops/pallas_kernels.pair_rows_reduce, ops/device.word_test_rows). The
host tiers here remain the differential oracle for it and the engines
for the value-sized classes (aa + bitmap-free runs) on every tier.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from ..robust import errors as _rerrors
from ..robust import faults as _faults
from ..robust import ladder as _ladder
from ..utils import bits

_WORDS = bits.WORDS_PER_CONTAINER  # 1024


def _native():
    """The native module when a compiled tier is live, else None."""
    from .. import native

    return native if native.available() else None


def _native_guard():
    """The native module for a batch kernel with an inline numpy fallback:
    the ``columnar.kernel`` fault site fires here, and a non-fatal failure
    classifies and routes to the numpy tier (returns None) instead of
    raising — the native→banded-numpy chain as one declared degradation
    (ISSUE 7). Kernels WITHOUT an inline fallback (batch_run_pairwise)
    call ``fault_point`` directly and let the engine's class-bucket
    fallback catch."""
    nat = _native()
    if nat is None:
        return None
    try:
        _faults.fault_point("columnar.kernel")
    except Exception as e:
        if _rerrors.classify(e) == _rerrors.FATAL:
            raise
        _ladder.LADDER.note_degrade("columnar.kernel", "native", "numpy", e)
        return None
    return nat


# ---------------------------------------------------------------------------
# sorted-u16 CSR batch algebra
# ---------------------------------------------------------------------------


def _banded(vals: np.ndarray, offs: np.ndarray) -> np.ndarray:
    """Lift pair j's values into band j: int64 ``(j << 16) | value``."""
    lens = np.diff(offs)
    band = np.repeat(np.arange(lens.size, dtype=np.int64), lens) << 16
    return vals.astype(np.int64) + band


def _batch_pairwise_numpy(
    avals: np.ndarray, aoffs: np.ndarray, bvals: np.ndarray, boffs: np.ndarray, op: str
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    n = aoffs.size - 1
    ag = _banded(avals, aoffs)
    bg = _banded(bvals, boffs)
    if op in ("and", "andnot"):
        if bg.size:
            pos = np.searchsorted(bg, ag)
            posc = np.minimum(pos, bg.size - 1)
            member = (pos < bg.size) & (bg[posc] == ag)
        else:
            member = np.zeros(ag.size, dtype=bool)
        kept = ag[member if op == "and" else ~member]
    elif op == "or":
        m = np.sort(np.concatenate([ag, bg]))
        keep = np.ones(m.size, dtype=bool)
        keep[1:] = m[1:] != m[:-1]
        kept = m[keep]
    else:  # xor: each side is unique, so a value appears at most twice
        m = np.sort(np.concatenate([ag, bg]))
        solo = np.ones(m.size, dtype=bool)
        solo[1:] &= m[1:] != m[:-1]
        solo[:-1] &= m[:-1] != m[1:]
        kept = m[solo]
    counts = np.bincount(kept >> 16, minlength=n)[:n]
    offs = np.concatenate(([0], np.cumsum(counts)))
    return (kept & 0xFFFF).astype(np.uint16), offs[:-1], counts


def batch_pairwise(
    avals: np.ndarray, aoffs: np.ndarray, bvals: np.ndarray, boffs: np.ndarray, op: str
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """All pairs' ``a[j] OP b[j]`` in one call.

    Returns ``(values, starts, counts)``: pair j's result values are
    ``values[starts[j] : starts[j] + counts[j]]`` (the values buffer may be
    an oversized scratch on the native tier — callers copy their slice out
    before holding it)."""
    n = aoffs.size - 1
    if n == 0:
        z = np.empty(0, dtype=np.int64)
        return np.empty(0, dtype=np.uint16), z, z
    nat = _native_guard()
    if nat is None:
        return _batch_pairwise_numpy(avals, aoffs, bvals, boffs, op)
    alens = np.diff(aoffs)
    blens = np.diff(boffs)
    if op == "and":
        bounds = np.minimum(alens, blens)
    elif op == "andnot":
        bounds = alens
    else:
        bounds = alens + blens
    starts = np.concatenate(([0], np.cumsum(bounds)))
    out, counts = nat.batch_pairwise_u16(
        avals, aoffs, bvals, boffs, op, starts[:-1], int(starts[-1])
    )
    return out, starts[:-1], counts


def has_native() -> bool:
    return _native() is not None


def batch_run_pairwise(
    as_: np.ndarray, al: np.ndarray, acnt: np.ndarray,
    bs_: np.ndarray, bl: np.ndarray, bcnt: np.ndarray,
    op: str, cards_only: bool = False,
):
    """Run-unified batch AND/ANDNOT over CSR run payloads (native tier
    only — callers fall back to the per-class numpy buckets otherwise).
    Returns ``(out_starts, out_lengths, starts, interval_counts, cards)``
    — result INTERVALS, pair j's at ``starts[j] : starts[j] +
    interval_counts[j]`` — or just per-pair cardinalities when
    ``cards_only``."""
    nat = _native()
    if nat is None:
        # the native tier vanished between the caller's has_native() check
        # and this call (a native.entry fault, or a real load failure on
        # another thread): raise the non-fatal taxonomy error so the
        # engine's classify-then-route handler absorbs it — an
        # AttributeError here would classify FATAL and escape the ladder
        raise _rerrors.TierUnavailable(
            "native batch tier unavailable for batch_run_pairwise"
        )
    # no inline fallback here: the fault raises through to the engine's
    # class-bucket router, which re-runs the batch on the numpy tiers
    _faults.fault_point("columnar.kernel")
    aoffs = np.concatenate(([0], np.cumsum(acnt)))
    boffs = np.concatenate(([0], np.cumsum(bcnt)))
    if cards_only:
        _s, _l, _counts, cards = nat.batch_run_pairwise(
            as_, al, aoffs, bs_, bl, boffs, op, None, 0
        )
        return cards
    bounds = acnt + bcnt  # an output interval ends at an input endpoint
    starts = np.concatenate(([0], np.cumsum(bounds)))
    out_s, out_l, counts, cards = nat.batch_run_pairwise(
        as_, al, aoffs, bs_, bl, boffs, op, starts[:-1], int(starts[-1])
    )
    return out_s, out_l, starts[:-1], counts, cards


def batch_and_cardinality(
    avals: np.ndarray, aoffs: np.ndarray, bvals: np.ndarray, boffs: np.ndarray
) -> np.ndarray:
    """Per-pair ``|a[j] & b[j]|`` without materialization."""
    n = aoffs.size - 1
    if n == 0:
        return np.empty(0, dtype=np.int64)
    nat = _native_guard()
    if nat is not None:
        return nat.batch_intersect_card_u16(avals, aoffs, bvals, boffs)
    ag = _banded(avals, aoffs)
    bg = _banded(bvals, boffs)
    if not bg.size:
        return np.zeros(n, dtype=np.int64)
    pos = np.searchsorted(bg, ag)
    posc = np.minimum(pos, bg.size - 1)
    member = (pos < bg.size) & (bg[posc] == ag)
    return np.bincount((ag >> 16)[member], minlength=n)[:n].astype(np.int64)


# ---------------------------------------------------------------------------
# word-matrix primitives
# ---------------------------------------------------------------------------


def popcount_rows(mat: np.ndarray) -> np.ndarray:
    """Per-row popcount of an [n, 1024] uint64 matrix (batched result
    cardinalities — ONE call for the whole batch's format selection)."""
    if mat.shape[0] == 0:
        return np.empty(0, dtype=np.int64)
    nat = _native_guard()
    if nat is not None and mat.flags.c_contiguous:
        return nat.popcount_rows(mat)
    return bits.popcount64(mat).sum(axis=1).astype(np.int64)


def scatter_values_rows(
    row_ids: np.ndarray, offsets: np.ndarray, vals: np.ndarray,
    out64: np.ndarray, op: str = "or",
) -> None:
    """Scatter concatenated array-container values into word rows with
    or/xor/clear combine; ``row_ids`` may repeat (fold accumulators)."""
    if row_ids.size == 0:
        return
    nat = _native_guard()
    if nat is not None:
        nat.scatter_values_rows(row_ids, offsets, vals, out64, op)
        return
    lens = np.diff(offsets)
    rows = np.repeat(np.asarray(row_ids, dtype=np.int64), lens)
    v = vals.astype(np.int64)
    flat = rows * _WORDS + (v >> 6)
    bit = np.uint64(1) << (v & 63).astype(np.uint64)
    flat_out = out64.reshape(-1)
    if op == "or":
        np.bitwise_or.at(flat_out, flat, bit)
    elif op == "xor":
        np.bitwise_xor.at(flat_out, flat, bit)
    else:  # clear (andnot)
        np.bitwise_and.at(flat_out, flat, ~bit)


def fill_intervals_rows(
    row_ids: np.ndarray, run_offs: np.ndarray, starts: np.ndarray,
    ends: np.ndarray, out64: np.ndarray, op: str = "or",
) -> None:
    """Expand many run containers' half-open [start, end) intervals into
    word rows in one call (``rb_fill_intervals_rows``); numpy tier loops
    per run with the shared range fills (correctness fallback)."""
    if row_ids.size == 0:
        return
    nat = _native_guard()
    if nat is not None:
        nat.fill_intervals_rows(row_ids, run_offs, starts, ends, out64, op)
        return
    fill = bits.set_bitmap_range if op == "or" else bits.flip_bitmap_range
    for j in range(row_ids.size):
        row = out64[int(row_ids[j])]
        for i in range(int(run_offs[j]), int(run_offs[j + 1])):
            fill(row, int(starts[i]), min(int(ends[i]), 1 << 16))


def run_member_mask(
    vals: np.ndarray,
    val_offs: np.ndarray,
    starts: np.ndarray,
    lengths: np.ndarray,
    run_offs: np.ndarray,
) -> np.ndarray:
    """Batched run membership WITHOUT word expansion: one banded
    right-searchsorted answers every probe of every array x run pair —
    the whole-batch form of ``_run_contains_many``.

    Probes and runs lift into band ``j << 17``; the gap above 2^16 makes
    any cross-band distance exceed the maximum run length, so a probe can
    never false-positive against the previous pair's last run."""
    n = val_offs.size - 1
    if vals.size == 0:
        return np.zeros(0, dtype=bool)
    band_v = np.repeat(np.arange(n, dtype=np.int64) << 17, np.diff(val_offs))
    vg = vals.astype(np.int64) + band_v
    band_r = np.repeat(np.arange(n, dtype=np.int64) << 17, np.diff(run_offs))
    sg = starts.astype(np.int64) + band_r
    if sg.size == 0:
        return np.zeros(vals.size, dtype=bool)
    idx = np.searchsorted(sg, vg, side="right") - 1
    idxc = np.maximum(idx, 0)
    return (idx >= 0) & (vg - sg[idxc] <= lengths.astype(np.int64)[idxc])


def member_mask(
    words_rows: np.ndarray, row_ids: np.ndarray, vals: np.ndarray
) -> np.ndarray:
    """Vectorized word-test gather: is ``vals[i]`` set in row
    ``row_ids[i]`` of the stacked word matrix? (the array x bitmap class's
    whole-batch membership probe)."""
    v = vals.astype(np.int64)
    return (
        (words_rows[row_ids, v >> 6] >> (v & 63).astype(np.uint64)) & np.uint64(1)
    ).astype(bool)


def interval_batch(
    as_: np.ndarray, al: np.ndarray, acnt: np.ndarray,
    bs_: np.ndarray, bl: np.ndarray, bcnt: np.ndarray,
    op: str, cards_only: bool = False,
):
    """Banded interval algebra for a whole bucket of (array|run) x
    (array|run) pairs: ONE global sort + four searchsorteds evaluate every
    pair's boolean combination (container.py ``_interval_op`` lifted to a
    batch — the telescoping trick: for a segment in band j, every earlier
    band's starts and ends both precede it, so the global
    #starts>#ends test sees only band j's open intervals).

    Inputs are CSR run payloads (arrays enter as length-0 runs). Returns
    the result INTERVALS — ``(out_starts, out_ends, starts, counts)`` with
    band-local half-open bounds, pair j's intervals at
    ``starts[j] : starts[j] + counts[j]`` — so run-shaped results never
    expand to values; or just per-pair cardinalities when ``cards_only``."""
    n = acnt.size
    band_a = np.repeat(np.arange(n, dtype=np.int64) << 17, acnt)
    band_b = np.repeat(np.arange(n, dtype=np.int64) << 17, bcnt)
    ga_s = as_.astype(np.int64) + band_a
    ga_e = ga_s + al.astype(np.int64) + 1
    gb_s = bs_.astype(np.int64) + band_b
    gb_e = gb_s + bl.astype(np.int64) + 1
    pts = np.unique(np.concatenate([ga_s, ga_e, gb_s, gb_e]))
    seg = pts[:-1]
    in_a = np.searchsorted(ga_s, seg, side="right") > np.searchsorted(
        ga_e, seg, side="right"
    )
    in_b = np.searchsorted(gb_s, seg, side="right") > np.searchsorted(
        gb_e, seg, side="right"
    )
    if op == "and":
        keep = in_a & in_b
    elif op == "andnot":
        keep = in_a & ~in_b
    elif op == "or":
        keep = in_a | in_b
    else:  # xor
        keep = in_a ^ in_b
    change = np.diff(keep.astype(np.int8), prepend=np.int8(0), append=np.int8(0))
    out_s = pts[change == 1]
    out_e = pts[np.nonzero(change == -1)[0]]
    if cards_only:
        return np.bincount(
            out_s >> 17, weights=out_e - out_s, minlength=n
        )[:n].astype(np.int64)
    counts = np.bincount(out_s >> 17, minlength=n)[:n]
    starts = np.concatenate(([0], np.cumsum(counts)))[:-1]
    # strip the band: local values fit 17 bits (ends may be exactly 2^16)
    return out_s & 0x1FFFF, out_e & 0x1FFFF, starts, counts


