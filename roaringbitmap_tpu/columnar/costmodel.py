"""Measured three-way cutoff model for the columnar engines (ISSUE 10).

The r07 router was a hand-tuned gate: containers in ``16..4096`` on both
sides plus a sampled dense-shape hint. That gate encodes two measured
facts (the ~10 µs plan/partition overhead, the ~2 µs per-container C
floor for tiny arrays) but misses a third the r12 profile found: on
bitmap-heavy mixes the columnar-CPU dense classes LOSE to the
per-container walk at every count in the window (word-matrix expansion +
popcount costs more than the per-pair binary searches it replaces), the
0.3-0.9x small-operand regression zone. And it cannot express the new
device tier at all — whether HBM pays depends on operand count, class
mix, AND whether the flat rows are already PACK_CACHE-resident.

This module replaces the gate with a small measured cost model:

    cost(engine) = overhead_us + n_pairs · per_pair_us[op_group][shape]

with ``shape`` the sampled class-mix bucket (``run`` > ``bitmap`` >
``array``, by which container kinds the ≤8-sample probe saw),
``op_group`` the and/andnot vs or/xor coefficient table (their class
structures cost differently), and ``n_pairs = min(na, nb)`` (an upper
bound on matched pairs; pass-through cost is engine-independent).
``choose()`` picks the argmin among per-container / columnar-CPU /
columnar-device over STEADY-STATE costs; a non-resident operand's
one-time ship is surfaced in the decision inputs (``ship_us``) but not
priced into the verdict — it is the PACK_CACHE first-touch investment,
and pricing it would leave the device tier unreachable (only device
executions establish residency). The device engine is only eligible on
accelerator backends (on the CPU backend "HBM" is host memory — the
tier would pay dispatch overhead to move nothing).

**Calibration** is measured, not guessed — like the bench's
``cold_breakeven`` rows: ``calibrate()`` times the real engines on small
synthetic working sets per (shape, count) cell and fits
``overhead + slope`` per engine. It runs at *first use on accelerator
backends* (where the device tier must be priced before the first routed
call), explicitly from bench/tests, or at import when
``RB_TPU_COLUMNAR_CAL`` names a persisted-calibration path (load if
present, write after measuring). **Uncalibrated, the model reproduces
the r11 gate verbatim** — CPU-only hosts route identically to r11 unless
someone asks for the measured model, and the decision log records which
mode produced every verdict.

The ``columnar:`` / ``columnar_device:`` twin rows the benchmarks emit
are the model's audit trail: ``accuracy()`` in bench.py replays routed
calls against per-engine measurements and reports the fraction where the
chosen engine was actually fastest.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

SCHEMA = "rb_tpu_columnar_costmodel/2"
ENGINES = ("per-container", "columnar-cpu", "columnar-device")
# class-mix buckets, cheapest-to-handle first; a pair's shape is the MAX
# over the two operands' sampled hints (runs dominate bitmaps dominate
# arrays — the per-container engine's per-pair cost rises in that order)
SHAPES = ("array", "bitmap", "run")
# coefficient tables are fit per OP GROUP: and/andnot share the gather/
# merge class structure while or/xor word-expand every non-aa pair — one
# "and"-only fit would misprice or/xor on bitmap mixes (the regression
# zone) in exactly the direction the model exists to fix
OP_GROUPS = ("and", "or")

# calibration cells: (n_containers) grid per shape; two points fit
# overhead + slope
_CAL_COUNTS = (16, 64)
_CAL_REPS = 3


def op_group(op: str) -> str:
    """The coefficient table an op prices against."""
    return "or" if op in ("or", "xor") else "and"


class CostModel:
    """Per-call engine choice from measured per-engine cost curves.

    Thread-safe: coefficients swap atomically under ``_lock``;
    ``choose()`` reads a consistent snapshot reference without locking
    (replacing the dict is atomic under the GIL)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.calibrated = False
        self.backend: Optional[str] = None
        # {op_group: {engine: {shape: [overhead_us, per_pair_us]}}}
        self.coeffs: Dict[str, Dict[str, Dict[str, List[float]]]] = {}
        # device extras: amortized host->HBM ship cost per row for a
        # non-resident operand (the residency feature's price term)
        self.ship_us_per_row: float = 0.0
        self.fold_rows_min: Optional[int] = None  # None -> config default
        # where the installed coefficients came from (ISSUE 11):
        # "calibrated" = synthetic-pair measurement (calibrate()),
        # "refit-from-traffic" = refit_from_outcomes() moved at least one
        # cell from live joined samples. Recorded into every routing
        # decision and persisted with the coefficients.
        self.provenance: str = "calibrated"
        self._device_checked = False
        self._device_ok = False

    # -- backend gate -------------------------------------------------------

    def device_eligible(self) -> bool:
        """Is the device tier worth pricing at all? Accelerator backends
        only — probed once (jax import + backend query), cached."""
        if not self._device_checked:
            ok = False
            try:
                import jax

                ok = jax.default_backend() != "cpu"
            except (ImportError, RuntimeError):
                ok = False
            self._device_ok = ok
            self._device_checked = True
        return self._device_ok

    # -- the decision -------------------------------------------------------

    def choose(
        self,
        na: int,
        nb: int,
        shape: str,
        resident,
        allow_device: Optional[bool] = None,
        op: str = "and",
    ) -> Tuple[str, dict]:
        """Pick the engine for an ``na x nb``-container pairwise ``op``
        whose sampled class mix is ``shape``; ``resident`` = are the
        operands' flat rows already PACK_CACHE-resident — a single bool
        for both sides, or a ``(resident_a, resident_b)`` pair (a
        resident 3000-row left operand carries no ship cost when only the
        fresh 64-row right side ships). Returns ``(engine, inputs)`` —
        inputs are the features + estimates the decision log records.

        The argmin compares STEADY-STATE costs: the ship of a
        non-resident operand is a one-time investment that establishes
        residency for every later call (the PACK_CACHE policy every
        resident pack in this repo follows — the agg path pays its cold
        pack on first touch too), so a pending ship is surfaced in the
        decision inputs (``ship_us``) but never prices the device tier
        out of the verdict that would win warm — otherwise the tier could
        be permanently unreachable (nothing else ever builds the rows).

        Uncalibrated this is the r11 gate verbatim (count window + dense
        hint, never device); calibrated it is an argmin over the measured
        per-op-group cost curves."""
        from . import engine as _engine

        cfg = _engine.config
        n = min(na, nb)
        if isinstance(resident, tuple):
            res_a, res_b = resident
        else:
            res_a = res_b = bool(resident)
        ship_rows = (0 if res_a else na) + (0 if res_b else nb)
        inputs = {
            "na": na, "nb": nb, "shape": shape, "op": op,
            "resident": bool(res_a and res_b),
        }
        if not self.calibrated:
            inputs["model"] = "default-gate"
            if not (
                cfg.min_containers <= na <= cfg.max_containers
                and cfg.min_containers <= nb <= cfg.max_containers
            ):
                return "per-container", inputs
            if shape == "array":
                return "per-container", inputs
            return "columnar-cpu", inputs
        if allow_device is None:
            allow_device = self.device_eligible()
        group = op_group(op)
        table = self.coeffs.get(group) or next(iter(self.coeffs.values()), {})
        costs = {}
        for eng in ENGINES:
            c = table.get(eng, {}).get(shape)
            if c is None:
                continue
            if eng == "columnar-device" and not allow_device:
                continue
            costs[eng] = c[0] + n * c[1]
        if not costs:  # calibration recorded nothing usable: r11 gate
            with self._lock:
                self.calibrated = False
            return self.choose(na, nb, shape, resident, allow_device, op=op)
        best = min(costs, key=costs.get)
        inputs["model"] = self.provenance
        inputs["est_us"] = {k: round(v, 1) for k, v in costs.items()}
        if best == "columnar-device" and ship_rows:
            inputs["ship_us"] = round(self.ship_us_per_row * ship_rows, 1)
        return best, inputs

    def fold_gate_rows(self) -> int:
        """The N-way fold row cutoff: measured when calibration ran, the
        hand-tuned ``config.min_fold_rows`` otherwise."""
        from . import engine as _engine

        v = self.fold_rows_min
        return int(v) if v else _engine.config.min_fold_rows

    # -- persistence --------------------------------------------------------

    def to_dict(self) -> dict:
        return {
            "schema": SCHEMA,
            "backend": self.backend,
            "calibrated": self.calibrated,
            "coeffs": self.coeffs,
            "ship_us_per_row": self.ship_us_per_row,
            "fold_rows_min": self.fold_rows_min,
            "provenance": self.provenance,
        }

    def save(self, path: str) -> None:
        """Persist the calibration (atomic rename — a crashed writer must
        not leave a torn JSON the next import then rejects)."""
        tmp = f"{path}.tmp.{os.getpid()}"
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(self.to_dict(), f, indent=1)
        os.replace(tmp, path)

    def from_dict(self, d: dict, check_backend: bool = True) -> bool:
        """Adopt a serialized calibration state (the ``to_dict`` shape);
        False (and untouched state) on schema/backend mismatch. The
        ``cost/`` facade's unified state lifecycle loads through here
        (ISSUE 12), same validation as a file load."""
        if not isinstance(d, dict):
            return False
        if d.get("schema") != SCHEMA or not d.get("calibrated"):
            return False
        if check_backend:
            try:
                import jax

                backend = jax.default_backend()
            except (ImportError, RuntimeError):
                backend = None
            if d.get("backend") != backend:
                return False  # coefficients are per-backend measurements
        coeffs = d.get("coeffs")
        if not isinstance(coeffs, dict) or not coeffs:
            return False
        with self._lock:
            self.coeffs = coeffs
            self.backend = d.get("backend")
            self.ship_us_per_row = float(d.get("ship_us_per_row", 0.0))
            self.fold_rows_min = d.get("fold_rows_min")
            # pre-ISSUE-11 files carry no provenance: they were written by
            # calibrate(), so "calibrated" is the truthful default
            self.provenance = str(d.get("provenance") or "calibrated")
            self.calibrated = True
        return True

    def load(self, path: str) -> bool:
        """Adopt a persisted calibration; False (and untouched state) on a
        missing/invalid/foreign-backend file — the caller falls back to
        measuring (or to the default gate)."""
        try:
            with open(path) as f:
                d = json.load(f)
        except (OSError, ValueError):
            return False
        return self.from_dict(d)

    def reset(self) -> None:
        """Back to the uncalibrated default gate (tests; also re-arms the
        first-use calibration latch)."""
        global _CAL_DONE, _ENSURED
        with self._lock:
            self.calibrated = False
            self.coeffs = {}
            self.backend = None
            self.ship_us_per_row = 0.0
            self.fold_rows_min = None
            self.provenance = "calibrated"
        _CAL_DONE = False
        _ENSURED = False


MODEL = CostModel()

_CAL_LOCK = threading.Lock()
_CAL_DONE = False


# ---------------------------------------------------------------------------
# calibration: measure the real engines on synthetic working sets
# ---------------------------------------------------------------------------


def _synthetic_pair(shape: str, n: int, rng):
    """A matched n-container pair of the given class-mix bucket — BOTH
    sides carry the same kind per chunk key, so the matched classes are
    the type-homogeneous ones the shape hint predicts (aa, aa+bb, aa+rr):
    the expensive columnar cases, not the cheap mismatched gathers. Inputs
    mirror the fuzz corpus shapes (~300-value arrays, ~9k-value bitmaps,
    run-optimized stripes)."""
    from ..models.roaring import RoaringBitmap

    def build() -> "RoaringBitmap":
        vals = []
        for k in range(n):
            base = k << 16
            if shape == "array" or (shape != "array" and k % 2):
                v = np.sort(rng.choice(1 << 16, 300, replace=False))
            elif shape == "bitmap":
                v = np.sort(rng.choice(1 << 16, 9000, replace=False))
            else:  # run stripes
                starts = np.arange(0, 1 << 16, 1 << 12)[:14]
                v = np.unique(
                    np.concatenate([np.arange(s, s + 900) for s in starts])
                )
            vals.append((v + base).astype(np.uint32))
        bm = RoaringBitmap(np.concatenate(vals))
        bm.run_optimize()
        return bm

    return build(), build()


def _time_us(fn, reps: int = _CAL_REPS) -> float:
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best * 1e6


def calibrate(
    include_device: Optional[bool] = None,
    persist: Optional[str] = None,
    seed: int = 0x10C0,
) -> CostModel:
    """Measure per-engine cost curves on synthetic pairs and install them
    (idempotent per process unless ``MODEL.reset()`` ran). ~50-150 ms on
    the CPU backend; the device cells additionally pay their one-time jit
    compiles, which is why accelerator processes should persist
    (``persist=`` path or ``RB_TPU_COLUMNAR_CAL``) and reload."""
    global _CAL_DONE
    with _CAL_LOCK:
        if _CAL_DONE and MODEL.calibrated:
            return MODEL
        from . import device as _device
        from . import engine as _engine
        from ..models.roaring import RoaringBitmap

        if include_device is None:
            include_device = MODEL.device_eligible()
        # a faulty device mid-calibration would silently install the
        # ladder's CPU-fallback timings as device coefficients (bench
        # guards its twin rows against exactly this mislabeling) — watch
        # the columnar.device degrade edge and discard the device cells
        # when it moved
        from .. import observe as _observe

        def _device_degrades() -> int:
            m = _observe.REGISTRY.get(_observe.DEGRADE_TOTAL)
            if m is None:
                return 0
            return m.series().get(
                ("columnar.device", "columnar-device", "columnar-cpu"), 0
            )

        degrades_before = _device_degrades()
        rng = np.random.default_rng(seed)
        op_of = {"and": RoaringBitmap.and_, "or": RoaringBitmap.or_}
        coeffs: Dict[str, Dict[str, Dict[str, List[float]]]] = {
            g: {e: {} for e in ENGINES} for g in OP_GROUPS
        }
        ship_samples: List[float] = []
        for shape in SHAPES:
            cells: Dict[tuple, List[float]] = {
                (g, e): [] for g in OP_GROUPS for e in ENGINES
            }
            for n in _CAL_COUNTS:
                a, b = _synthetic_pair(shape, n, rng)
                if include_device:
                    # warm rows + compiles outside the timed regions: the
                    # per-pair coefficients price the steady state, the
                    # ship term prices residency separately
                    t0 = time.perf_counter()
                    _device.rows_for(a)
                    _device.rows_for(b)
                    ship_samples.append(
                        (time.perf_counter() - t0) * 1e6 / (2 * n)
                    )
                for group in OP_GROUPS:
                    ref = op_of[group]
                    with _engine.disabled():
                        cells[(group, "per-container")].append(
                            _time_us(lambda: ref(a, b))
                        )
                    cells[(group, "columnar-cpu")].append(
                        _time_us(
                            lambda: _engine.pairwise(group, a, b, tier="cpu")
                        )
                    )
                    if include_device:
                        _engine.pairwise(group, a, b, tier="device")  # compile
                        cells[(group, "columnar-device")].append(
                            _time_us(
                                lambda: _engine.pairwise(
                                    group, a, b, tier="device"
                                )
                            )
                        )
            for (group, eng), ts in cells.items():
                if len(ts) < 2:
                    continue
                n0, n1 = _CAL_COUNTS[0], _CAL_COUNTS[-1]
                slope = max(0.0, (ts[-1] - ts[0]) / (n1 - n0))
                overhead = max(0.0, ts[0] - slope * n0)
                coeffs[group][eng][shape] = [round(overhead, 2), round(slope, 3)]
        # fold threshold: smallest row count where the columnar fold beats
        # the per-container fold on the run-mix shape (where it wins most;
        # array-only folds are priced by the same curves)
        if include_device and _device_degrades() != degrades_before:
            # at least one "device" cell actually timed the CPU fallback:
            # the device column is poisoned — calibrate the CPU engines
            # only (no device coefficients = the tier is never chosen
            # until a later healthy calibration re-prices it)
            for engines in coeffs.values():
                engines.pop("columnar-device", None)
            ship_samples = []
        fold_min = _calibrate_fold(rng)
        with MODEL._lock:
            MODEL.coeffs = {
                g: {e: s for e, s in engines.items() if s}
                for g, engines in coeffs.items()
                if any(engines.values())
            }
            MODEL.ship_us_per_row = (
                round(float(np.median(ship_samples)), 3) if ship_samples else 0.0
            )
            MODEL.fold_rows_min = fold_min
            try:
                import jax

                MODEL.backend = jax.default_backend()
            except (ImportError, RuntimeError):
                MODEL.backend = None
            MODEL.provenance = "calibrated"
            MODEL.calibrated = True
        _CAL_DONE = True
        path = persist if persist is not None else os.environ.get(
            "RB_TPU_COLUMNAR_CAL"
        )
        if path:
            try:
                MODEL.save(path)
            except OSError:
                pass  # read-only FS: run-local calibration still applies
        return MODEL


def _calibrate_fold(rng) -> Optional[int]:
    """Measured fold cutoff: time the columnar vs per-container OR folds
    at two row counts, return the crossover clamped to [16, 512] (None —
    keep the config default — when columnar never wins)."""
    from . import engine as _engine
    from ..parallel import store

    def groups_of(rows: int):
        from ..models.roaring import RoaringBitmap

        per_bm = 8
        bms = []
        for i in range(max(2, rows // per_bm)):
            v = np.concatenate(
                [
                    (np.arange(k << 16, (k << 16) + 64, 2))
                    for k in range(per_bm)
                ]
            ).astype(np.uint32)
            bm = RoaringBitmap(v + (i % 3))
            bm.run_optimize()
            bms.append(bm)
        return store.group_by_key(bms)

    samples = []
    for rows in (32, 128):
        g = groups_of(rows)
        n = sum(len(cs) for cs in g.values())
        col = _time_us(lambda: _engine.fold(g, "or"))
        from ..parallel.aggregation import _percontainer_aggregate

        pc = _time_us(lambda: _percontainer_aggregate(g, "or"))
        samples.append((n, col, pc))
    wins = [n for n, col, pc in samples if col < pc]
    if not wins:
        return None
    return int(max(16, min(512, min(wins))))


# ---------------------------------------------------------------------------
# online refit from the decision-outcome join (ISSUE 11)
# ---------------------------------------------------------------------------

# a sample whose measured cost sits this many times off its cell median is
# poisoned telemetry (a GC pause inside the measured window, a clock jump),
# not signal — refit must not learn from it
_REFIT_OUTLIER_FACTOR = 20.0


def refit_from_outcomes(
    samples: Optional[List[dict]] = None,
    min_samples: int = 4,
    persist: Optional[str] = None,
) -> dict:
    """Refit overhead+slope coefficients from live joined samples — the
    decision-outcome ledger's ``columnar.cutoff`` joins (ISSUE 11), each
    carrying the features the model fits on: op group, engine that ran,
    sampled shape, matched-pair bound ``n``, and the measured µs.

    Per (op-group, engine, shape) cell with at least ``min_samples``
    clean samples spanning >=2 distinct counts, a least-squares
    ``overhead + n·slope`` fit replaces the cell's coefficients (clamped
    non-negative, like ``calibrate()``); cells without enough traffic
    keep their calibrated values. Poisoned samples — non-finite or
    non-positive measurements, unknown engines/shapes, and measurements
    more than ``20x`` off their cell median — are rejected and counted.
    The model's provenance flips to ``"refit-from-traffic"`` when at
    least one cell moved, is recorded into every subsequent routing
    decision, and persists through the ``RB_TPU_COLUMNAR_CAL`` lifecycle
    exactly like a calibration (``persist=`` overrides the env path).

    Returns a report: per-cell before/after coefficients, sample counts,
    and the rejection tally. Refitting an UNCALIBRATED model is refused
    (report ``{"refused": ...}``) — the default gate has no coefficient
    table to move, and fabricating one from sparse traffic would replace
    a measured baseline with noise."""
    if not MODEL.calibrated:
        report = {"refused": "model is uncalibrated (default gate)",
                  "moved": {}, "rejected": 0}
        _decisions_record("costmodel.refit", "refused", rejected=0, moved=0)
        return report
    if samples is None:
        from ..observe import outcomes as _outcomes

        samples = _outcomes.samples("columnar.cutoff")
    # validate + bucket into cells
    cells: Dict[Tuple[str, str, str], List[Tuple[int, float]]] = {}
    rejected = 0
    for s in samples:
        try:
            engine = s["engine"]
            shape = s["shape"]
            n = int(s["n"])
            us = float(s["measured_us"])
            group = op_group(str(s.get("op", "and")))
        except (KeyError, TypeError, ValueError):
            rejected += 1
            continue
        if (
            engine not in ENGINES or shape not in SHAPES or n < 1
            or not np.isfinite(us) or us <= 0
        ):
            rejected += 1
            continue
        cells.setdefault((group, engine, shape), []).append((n, us))
    moved: Dict[str, dict] = {}
    with MODEL._lock:
        for (group, engine, shape), pts in sorted(cells.items()):
            med = float(np.median([us for _, us in pts]))
            clean = [
                (n, us) for n, us in pts
                if med / _REFIT_OUTLIER_FACTOR <= us <= med * _REFIT_OUTLIER_FACTOR
            ]
            rejected += len(pts) - len(clean)
            if len(clean) < min_samples:
                continue
            ns = np.array([n for n, _ in clean], dtype=np.float64)
            us = np.array([u for _, u in clean], dtype=np.float64)
            if np.unique(ns).size < 2:
                # one count cannot separate overhead from slope; move only
                # the level: keep the calibrated slope, refit the overhead
                # as the residual median (still a coefficient moving
                # toward measured truth)
                old = MODEL.coeffs.get(group, {}).get(engine, {}).get(shape)
                if old is None:
                    continue
                overhead = max(0.0, float(np.median(us - ns * old[1])))
                new = [round(overhead, 2), old[1]]
            else:
                slope, overhead = np.polyfit(ns, us, 1)
                slope = max(0.0, float(slope))
                overhead = max(0.0, float(overhead))
                new = [round(overhead, 2), round(slope, 3)]
            old = MODEL.coeffs.setdefault(group, {}).setdefault(
                engine, {}
            ).get(shape)
            if new == old:
                continue
            MODEL.coeffs[group][engine][shape] = new
            moved["/".join((group, engine, shape))] = {
                "from": old, "to": new, "samples": len(clean),
            }
        if moved:
            MODEL.provenance = "refit-from-traffic"
    report = {"moved": moved, "rejected": rejected,
              "provenance": MODEL.provenance, "samples": len(samples)}
    _decisions_record(
        "costmodel.refit", MODEL.provenance if moved else "no-change",
        moved=len(moved), rejected=rejected,
    )
    if moved:
        path = persist if persist is not None else os.environ.get(
            "RB_TPU_COLUMNAR_CAL"
        )
        if path:
            try:
                MODEL.save(path)
            except OSError:
                pass  # read-only FS: run-local refit still applies
    return report


def _decisions_record(site, decision, **inputs):
    from ..observe import decisions as _decisions

    _decisions.record_decision(site, decision, **inputs)


_ENSURED = False  # first-use latch: route() calls this per routed op


def ensure_calibrated() -> CostModel:
    """First-use hook: on accelerator backends, adopt the persisted
    calibration (``RB_TPU_COLUMNAR_CAL``) or measure one now — the device
    tier must be priced before the first routed call. On CPU-only hosts
    this resolves to the default gate (the r11 behavior) and latches, so
    the steady-state cost on the routed path is one bool check."""
    global _ENSURED
    if _ENSURED or MODEL.calibrated:
        return MODEL
    path = os.environ.get("RB_TPU_COLUMNAR_CAL")
    if path and MODEL.load(path):
        _ENSURED = True
        return MODEL
    _ENSURED = True
    if MODEL.device_eligible():
        return calibrate()
    return MODEL
