"""Device execution tier for the columnar pairwise engine (ISSUE 10
tentpole).

The CPU columnar engine (engine.py) proved that batching the 9-class
container algebra beats per-container dispatch — but its stacked
``[n, 1024]`` word matrices are exactly the flat-row pack layout the
device engines already eat (uint32 ``[n, 2048]``, ops/device.py). This
module feeds the same 9-class type partition from **PACK_CACHE-resident
flat rows** (one row per container, built by the ISSUE-8 device-side
expansion via ``store.ship_rows``) and runs the word-parallel classes on
the accelerator:

* **dense classes** — ``bb`` plus every class the CPU engine serves with
  word matrices (`br`/`rb` for and/andnot, all non-`aa` classes for
  or/xor, `ba` under andnot): ONE fused jit dispatch per bucket gathers
  both sides' rows from the resident blocks, applies the bitwise op, and
  popcounts every row (``pallas_kernels.pair_rows_reduce``) — the
  popcount-rows pass IS the batched format selection, so the host builds
  containers card-driven without re-counting;
* **array x bitmap** — an on-device word-test gather
  (``ops.device.word_test_rows``): every probe value of every pair tests
  against the resident bitmap rows in one dispatch, and only the boolean
  mask returns to the host (bytes ~ probe values, never 8 KiB rows);
* **array x array and the bitmap-free run classes** stay on the CPU
  tiers (``engine._fill_nonbm`` — the native run-unified merge / banded
  numpy): their payloads are value-sized, their per-container C floor is
  ~2 µs, and word-expanding them on device would manufacture the very
  work the run representation avoids.

The result merge re-assembles containers by the reference size rule
exactly like the CPU engine (shared ``engine.pairwise`` assembly; device
buckets emit array-or-bitmap by cardinality, the CPU buckets keep
run-shaped results compressed).

Residency: each operand's flat rows live in ``store.PACK_CACHE`` under
``("colrows", fingerprint)`` — op-independent, shared across every pair
and op touching that bitmap, delta-invalidated by the fingerprint like
every other pack. The cost model (costmodel.py) reads the same residency
bit to price the ship.

Degradation: the ``columnar.device`` fault site fires before any device
work; a non-fatal failure rides the ladder down to the columnar-CPU tier
(bit-exact by construction — both tiers feed the same partition and the
same assembly).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from ..robust import faults as _faults
from ..models.container import Container
from . import engine as _engine
from .partition import ARRAY, BITMAP, classify


def _colrows_key(hlc) -> tuple:
    """THE cache-key spelling for a bitmap's resident flat rows — builder
    and residency probes all call this one function, so the key layout
    can never drift between what ``rows_for`` stores and what the router
    probes (the silent always-non-resident failure mode)."""
    from ..models.roaring import hlc_fingerprint

    return ("colrows", hlc_fingerprint(hlc))


def rows_for(bm):
    """The bitmap's containers as PACK_CACHE-resident flat device rows
    (uint32 [n_rows, 2048]), keyed by fingerprint — built once via the
    device-side expansion (``store.ship_rows``), then every pairwise op
    over this bitmap gathers from the resident block. The container list
    pads to a pow2 row count with empty array containers (zero rows), so
    the device kernels' row-block operand shapes stay retrace-bounded
    like their index streams — heterogeneous corpora would otherwise
    compile one executable per distinct (na, nb) pair."""
    import numpy as _np

    from ..models.container import ArrayContainer
    from ..ops import device as dev
    from ..parallel import store

    key = _colrows_key(bm.high_low_container)

    def build():
        conts = list(bm.high_low_container.containers)
        pad = dev.pow2(len(conts)) - len(conts)
        if pad > 0:
            empty = _np.empty(0, dtype=_np.uint16)
            conts.extend(ArrayContainer(empty) for _ in range(pad))
        d = store.ship_rows(conts)
        return d, int(d.nbytes)

    return store.PACK_CACHE.get_or_build(
        key, build, refs=store.static_fp_refs([bm])
    )


def rows_resident_hlc(hlc) -> bool:
    """Cheap residency probe (decision provenance): are this high-low
    container's flat rows already in PACK_CACHE? One dict lookup under
    the cache lock — never builds."""
    from ..parallel import store

    return _colrows_key(hlc) in store.PACK_CACHE


def rows_resident(bm) -> bool:
    return rows_resident_hlc(bm.high_low_container)


def _build_rows_results(
    words_u32: np.ndarray, cards: np.ndarray, idx: np.ndarray, results
) -> None:
    """Card-driven container build from fetched device rows — the device
    popcount already selected every format, and the array-vs-bitmap rule
    is the engine's shared loop (one copy, tiers can never drift)."""
    words64 = np.ascontiguousarray(words_u32).view(np.uint64)
    _engine._format_rows_results(words64, cards.tolist(), idx.tolist(), results)


def _fill_dense_device(
    op: str, rows_a, ia: np.ndarray, rows_b, ib: np.ndarray,
    idx: np.ndarray, results, pending_incs: list,
) -> None:
    """Word-parallel classes on device: one fused gather+op+popcount
    dispatch over the resident flat rows (pow2-padded index streams bound
    retraces; pad rows popcount to 0 and are sliced off)."""
    if idx.size == 0:
        return
    from ..ops import pallas_kernels as pk

    with _engine._kernel_stage(op, "device_pair", int(idx.size)):
        words, cards = pk.pair_rows_reduce(
            rows_a, ia[idx], rows_b, ib[idx], op
        )
        _build_rows_results(words, cards, idx, results)
    pending_incs.append((int(idx.size), (op, "device_pair")))


def _fill_gather_device(
    op: str, probe_cs: Sequence[Container], rows_dense, dense_take: np.ndarray,
    idx: np.ndarray, results, pending_incs: list,
) -> None:
    """array x bitmap on device: the whole bucket's membership probes run
    as one word-test gather against the resident rows; only the boolean
    mask transfers back, and the host keeps/drops values exactly like the
    CPU gather class."""
    if idx.size == 0:
        return
    from ..ops import device as dev
    from .partition import gather_values

    pending_incs.append((int(idx.size), (op, "device_gather")))
    with _engine._kernel_stage(op, "device_gather", int(idx.size)):
        vals, offs = gather_values(probe_cs, idx)
        if vals.size == 0:
            return
        row_ids = np.repeat(dense_take[idx], np.diff(offs))
        mask = dev.word_test_rows_host(rows_dense, row_ids, vals)
        _engine._build_gather_results(op, vals, offs, mask, idx, results)


def matched_results_device_multi(op: str, jobs) -> List[Optional[Container]]:
    """Cross-query fused device tier (ISSUE 13): many pairs' matched
    containers against ONE combined pair of row blocks. ``jobs`` is
    ``[(x1, x2, keyplan)]``; every distinct operand's resident flat rows
    concatenate once (``pallas_kernels.concat_rows`` — one device concat,
    deduped by block identity so a hot shared operand ships no extra
    bytes), the per-pair row indices shift by their block's offset, and
    the combined inputs run through :func:`matched_results_device`
    verbatim — the dense bucket becomes one ``pair_rows_reduce`` launch
    and the probe bucket one word-test gather for the WHOLE window.
    Returns the flat result list in job order (each job's slice is its
    matched-pair count), bit-exact with per-pair execution by
    construction: same classification, same kernels, same assembly."""
    from ..ops import pallas_kernels as pk

    def _combine(side):
        blocks: List = []
        offsets: dict = {}
        idx_parts: List[np.ndarray] = []
        for x1, x2, plan in jobs:
            bm = x1 if side == 0 else x2
            idx = plan.ia if side == 0 else plan.ib
            rows = rows_for(bm)
            off = offsets.get(id(rows))
            if off is None:
                off = sum(int(b.shape[0]) for b in blocks)
                offsets[id(rows)] = off
                blocks.append(rows)
            idx_parts.append(np.asarray(idx, dtype=np.int64) + off)
        combined = pk.concat_rows(blocks)
        return combined, np.concatenate(idx_parts) if idx_parts else np.empty(
            0, dtype=np.int64
        )

    rows_a_all, ia_all = _combine(0)
    rows_b_all, ib_all = _combine(1)
    acs_all: List[Container] = []
    bcs_all: List[Container] = []
    for x1, x2, plan in jobs:
        acont = x1.high_low_container.containers
        bcont = x2.high_low_container.containers
        acs_all.extend(acont[i] for i in plan.ia.tolist())
        bcs_all.extend(bcont[i] for i in plan.ib.tolist())
    return matched_results_device(
        op, acs_all, bcs_all, ia_all, ib_all, rows_a_all, rows_b_all
    )


def matched_results_device(
    op: str,
    acs: Sequence[Container],
    bcs: Sequence[Container],
    ia: np.ndarray,
    ib: np.ndarray,
    rows_a,
    rows_b,
) -> List[Optional[Container]]:
    """Per-class execution with the word-parallel buckets on device —
    the device twin of ``engine._matched_results``. ``ia``/``ib`` map
    matched pair i to its row in the operands' resident flat blocks."""
    n = len(acs)
    results: List[Optional[Container]] = [None] * n
    if n == 0:
        return results
    _faults.fault_point("columnar.device")
    codes_a = classify(acs)
    codes_b = classify(bcs)
    hist = _engine.class_histogram(codes_a, codes_b)
    # metric increments (class counts + device-bucket series) flush only
    # after EVERY bucket succeeded: a non-fatal failure reruns the whole
    # pair on the CPU tier, whose _record would otherwise double-count
    pending_incs: list = []
    a_arr = codes_a == ARRAY
    b_arr = codes_b == ARRAY
    a_bm = codes_a == BITMAP
    b_bm = codes_b == BITMAP
    if op in ("and", "andnot"):
        # bitmap-free classes (aa/ar/ra/rr): the CPU tiers own these — the
        # native run-unified merge keeps run results compressed and the
        # value-sized payloads never justify 8 KiB device rows
        _engine._fill_nonbm(
            op, acs, bcs, codes_a, codes_b, hist, results
        )
        _fill_gather_device(
            op, acs, rows_b, ib, np.flatnonzero(a_arr & b_bm), results,
            pending_incs,
        )
        if op == "and":
            _fill_gather_device(
                op, bcs, rows_a, ia, np.flatnonzero(b_arr & a_bm), results,
                pending_incs,
            )
            dense = np.flatnonzero((a_bm & ~b_arr) | (~a_arr & b_bm))
        else:
            # ba under andnot rides the dense op too: b's array container
            # is a word row in the resident block, so a & ~b is one fused
            # dispatch instead of the CPU tier's expand + scatter-clear
            dense = np.flatnonzero(
                (a_bm & ~b_arr) | (~a_arr & b_bm) | (a_bm & b_arr)
            )
        _fill_dense_device(op, rows_a, ia, rows_b, ib, dense, results,
                           pending_incs)
    else:  # or / xor: aa stays on the CSR batch kernel, the rest is dense
        _engine._fill_aa(op, acs, bcs, np.flatnonzero(a_arr & b_arr), results)
        _fill_dense_device(
            op, rows_a, ia, rows_b, ib, np.flatnonzero(~(a_arr & b_arr)),
            results, pending_incs,
        )
    _engine._inc_classes(op, hist)
    for n_inc, labels in pending_incs:
        _engine._COLUMNAR_TOTAL.inc(n_inc, labels=labels)
    return results
