"""Stage 3 of the columnar pairwise engine: per-class batch execution.

``pairwise`` executes a whole bitmap-pair op — key plan, 9-class type
partition, one batch kernel per occupied class, batched result-format
selection — with NO per-container Python dispatch on the matched path
(the ~1-2 µs/container interpreter floor BENCH_NOTES round-5 pins as "the
region the reference's JIT'd per-key loops win by construction").
``fold``/``or_fold_words`` apply the same machinery to the N-way CPU folds
(the >=10x target's own denominator).

Result formats select in batch: the run-unified and/andnot path applies
the reference's full size rule (run iff 2+4·nruns smallest, so run-shaped
results stay compressed); the word-matrix classes normalize to
array<=4096<bitmap like ``best_container_of_words``. Either way results
are value-identical to the per-container engine (``==`` compares values,
not forms; ``run_optimize`` re-establishes RLE where a word-path result
left it). Pass-through containers keep their form: transferred unclone'd
under member-op semantics (``reuse_left``, the round-4 ior elision
extended here to xor/andnot), cloned validation-free otherwise.
"""

from __future__ import annotations

import functools
import os
import threading
from contextlib import contextmanager, nullcontext
from typing import Dict, List, Optional, Sequence

import numpy as np

from .. import observe as _observe
from ..observe import decisions as _decisions
from ..observe import outcomes as _outcomes
from ..observe import timeline as _timeline
from ..robust import errors as _rerrors
from ..robust import ladder as _ladder
from ..models.container import (
    ARRAY_MAX_SIZE,
    ArrayContainer,
    BitmapContainer,
    Container,
    RunContainer,
    _container_of_intervals,
    _wrap_u16,
)
from ..models.roaring import RoaringBitmap
from ..utils import bits
from . import costmodel as _costmodel
from . import kernels
from .keyplan import key_plan
from .partition import (
    ARRAY,
    BITMAP,
    CLASS_NAMES,
    class_histogram,
    classify,
    expand_rows,
    gather_intervals,
    gather_runs,
    gather_values,
    scatter_containers,
    stack_words,
)


class config:
    """Columnar dispatch knobs.

    ``min_containers`` — the small-operand cutoff: a pair routes columnar
    only when BOTH operands hold at least this many containers (below it
    the per-container walk's constant factor wins; the plan/partition
    overhead is ~10 µs). ``max_containers`` — the large-count cap: at
    many thousands of (necessarily tiny) containers the CSR gather's
    per-piece concatenation overtakes the already-sub-2µs per-container
    ops (the jmh identical/worstcase grids: 10k single-value containers,
    measured 0.3-0.9x), so the per-container walk keeps those too.
    ``min_fold_rows`` — row cutoff for the N-way CPU folds.
    ``ROARINGBITMAP_TPU_NO_COLUMNAR=1`` disables routing entirely (the
    per-container engine remains the differential reference)."""

    enabled: bool = not os.environ.get("ROARINGBITMAP_TPU_NO_COLUMNAR")
    min_containers: int = 16
    max_containers: int = 4096
    min_fold_rows: int = 64
    # row budget for the chunked dense-class batches: bounds peak matrix
    # memory at ~3 * 8 KiB * chunk_rows while keeping full vectorization
    chunk_rows: int = 4096
    # tests/bench only: let the calibrated cost model pick the device tier
    # on the CPU backend too (where "HBM" is host memory and the tier is
    # normally priced out of eligibility entirely)
    force_device: bool = False


_COLUMNAR_TOTAL = _observe.counter(
    _observe.COLUMNAR_BATCH_TOTAL,
    "Columnar batched container-pairs by op and (array|bitmap|run)^2 class",
    ("op", "class"),
)
# per-class kernel latency (ISSUE 6): one series per (op, execution-class
# bucket) — the flight recorder shows each bucket as a named span when
# RB_TPU_TIMELINE is active
_CLASS_SECONDS = _observe.latency_histogram(
    _observe.COLUMNAR_CLASS_SECONDS,
    "Wall time of columnar per-class batch kernels by op and execution "
    "class (aa | runs | gather | interval | dense | clear | fold | "
    "fold_words)",
    ("op", "class"),
)


def _kernel_stage(op: str, klass: str, n_pairs: int) -> "_timeline.stage":
    return _timeline.stage(
        _CLASS_SECONDS, (op, klass), "columnar." + klass, cat="columnar",
        op=op, pairs=n_pairs,
    )


def _timed_fill(klass: str, idx_pos: int, op_pos: Optional[int] = 0):
    """Wrap a ``_fill_*`` class executor so each non-empty batch records a
    per-class kernel span + latency sample. ``idx_pos``/``op_pos`` locate
    the pair-index array and op name in the positional args (``op_pos``
    None = the executor is andnot-only)."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args):
            idx = args[idx_pos]
            if idx.size == 0:
                return
            op = args[op_pos] if op_pos is not None else "andnot"
            with _kernel_stage(op, klass, int(idx.size)):
                return fn(*args)

        return wrapper

    return deco


# per-thread disable depth: disabled() must not flip process-global state
# (two overlapping threads would strand routing off — the framework's
# shared-mutable-state discipline), so the router consults a thread-local
# counter; re-entrant by construction
_TLS = threading.local()


@contextmanager
def disabled():
    """Temporarily force the per-container engines ON THIS THREAD
    (benchmark twins and differential tests); re-entrant."""
    _TLS.depth = getattr(_TLS, "depth", 0) + 1
    try:
        yield
    finally:
        _TLS.depth -= 1


def _routing_on() -> bool:
    return config.enabled and not getattr(_TLS, "depth", 0)


_SHAPE_RANK = {ArrayContainer: 0, BitmapContainer: 1, RunContainer: 2}


def _shape_hint(hlc) -> str:
    """Sampled type probe (<= 8 containers): the operand's class-mix
    bucket for the cost model — ``run`` > ``bitmap`` > ``array`` by which
    kinds the sample saw. Array-only pairs stay per-container — their
    scalar ops already sit at the C-kernel floor (~2 µs), and no gather
    can beat a floor it must first pay to assemble. Runs are where the
    per-container engine spends 5-50 µs each (batching pays most);
    bitmap-heavy mixes are the r12 regression zone the model prices
    separately."""
    conts = hlc.containers
    n = len(conts)
    step = max(1, n // 8)
    rank = 0
    for i in range(0, n, step):
        c = conts[i]
        r = _SHAPE_RANK.get(type(c))
        if r is None:
            # exotic subclass: runs rank "run", anything else ranks
            # "bitmap" — exactly the r11 dense hint's exact-type check
            # (``type(c) is not ArrayContainer`` counted it dense), so
            # the uncalibrated gate stays r11-verbatim
            r = 2 if isinstance(c, RunContainer) else 1
        if r == 2:
            return "run"
        if r > rank:
            rank = r
    return _costmodel.SHAPES[rank]


_ROUTE_TOTAL = _observe.counter(
    _observe.COLUMNAR_ROUTE_TOTAL,
    "Columnar cutoff-model verdicts by chosen engine tier",
    ("tier",),
)
# declared tier label values (the metric-naming rule rejects computed
# label values — the router's verdict set is a frozen enumeration)
_TIER_LABELS = {
    "per-container": "per-container",
    "columnar-cpu": "columnar-cpu",
    "columnar-device": "columnar-device",
}
# route() verdict -> pairwise tier argument (identity for "cpu"/"device";
# "columnar-cpu" routes the host batch engine, "columnar-device" the
# accelerator tier)
_TIER_ARG = {"columnar-cpu": "cpu", "columnar-device": "device"}

# 1-in-64 sampling of below-gate verdicts (ISSUE 10 satellite): the
# sub-gate branch sits at the per-container C floor and must not pay a
# record per call, but never recording it starved the cost model of
# calibration data from exactly the small-operand regression zone
_BELOW_GATE = _decisions.SampledSite(64)


class Verdict(str):
    """A :func:`route` verdict that compares, hashes, and renders exactly
    as its tier string but additionally carries the decision serial
    (``.seq``) for the outcome join (ISSUE 11) — call sites that only
    ever treated the verdict as a string keep working unchanged."""

    seq: Optional[int] = None


_NULL_OUTCOME = nullcontext()


def outcome(tier):
    """The measured-outcome scope for one routed verdict: the facades wrap
    the chosen engine's execution in it, and the join prices the verdict
    against what actually happened::

        tier = route(a, b, op="and")
        with outcome(tier):
            <run whichever engine tier names>

    A verdict without a serial (below-gate, ``record=False``, outcomes
    off) returns a shared null context — the per-container C floor pays
    one getattr."""
    seq = getattr(tier, "seq", None)
    if seq is None:
        return _NULL_OUTCOME
    return _outcomes.measure(seq, "columnar.cutoff", engine=str(tier))


def route(
    a_hlc, b_hlc, record: bool = True, allow_device: bool = True,
    op: str = "and", join: bool = True,
) -> str:
    """Three-way engine verdict for one pairwise ``op``:
    ``per-container`` / ``columnar-cpu`` / ``columnar-device``, from
    operand counts, the sampled class-mix shape, and per-side pack
    residency (costmodel.choose prices against the op-group coefficient
    table — and/andnot vs or/xor cost shapes differ materially;
    uncalibrated it reproduces the r11 hand-tuned gate verbatim).
    ``allow_device=False`` clamps the verdict to the CPU engines — the
    cardinality facades use it, because the count-only kernels have no
    device tier and their provenance must never claim one.

    Decision provenance (ISSUE 9/10): full verdicts record above the
    count gate, where the op costs tens of microseconds; below it the
    per-container walk sits at its ~2 µs C floor and pays one int
    compare, with a 1-in-N sampled record keeping the regression zone
    visible to the calibration data."""
    if not _routing_on():
        return "per-container"
    na, nb = a_hlc.size, b_hlc.size
    if (
        na < config.min_containers
        or nb < config.min_containers
        or na > config.max_containers
        or nb > config.max_containers
    ):
        # outside the measured window the r07 floor argument stands in
        # BOTH model modes: below it the per-container C floor wins, and
        # above the cap the calibrated two-point fit (n=16..64 cells)
        # must not extrapolate 100x past its data — the jmh 10k-container
        # grids stay per-container by construction, at one compare per
        # call plus the 1-in-N sampled record
        if record and _BELOW_GATE.tick():
            _decisions.record_decision(
                "columnar.cutoff", "per-container", reason="outside-gate",
                sampled=_BELOW_GATE.every, na=na, nb=nb,
            )
        return "per-container"
    if allow_device and _ladder.deadline_expired():
        # an expired per-query budget never starts a device attempt — and
        # that includes first-use CALIBRATION, whose device cells pay jit
        # compiles: check the deadline BEFORE ensure_calibrated, use
        # whatever model state exists (the CPU tiers are the cheapest
        # continuation, query-kernel parity)
        allow_device = False
    model = (
        _costmodel.MODEL if not allow_device else _costmodel.ensure_calibrated()
    )
    shape_a = _shape_hint(a_hlc)
    shape_b = _shape_hint(b_hlc)
    shape = max(shape_a, shape_b, key=_costmodel.SHAPES.index)
    device_arg = None if allow_device else False
    resident = (False, False)
    if allow_device and model.calibrated and (
        model.device_eligible() or config.force_device
    ):
        if config.force_device:
            device_arg = True
        if record:
            from . import device as _device_tier

            # per-side probes (decision provenance only — the verdict
            # compares steady-state costs, see costmodel.choose): skipped
            # on the record=False re-derivations, which never log
            resident = (
                _device_tier.rows_resident_hlc(a_hlc),
                _device_tier.rows_resident_hlc(b_hlc),
            )
    tier, inputs = model.choose(na, nb, shape, resident, device_arg, op=op)
    if record:
        _ROUTE_TOTAL.inc(1, (_TIER_LABELS[tier],))
        # outcome join (ISSUE 11): above-gate verdicts are measurable ops
        # (tens of µs up), so every recorded one registers for a measured
        # join — per-container verdicts included, which is what gives the
        # refit live samples from ALL engines, not only the routed winner.
        # ``join=False`` (the cardinality facades' gate probe) records
        # provenance only: their execution happens in kernels this scope
        # cannot see, and an unjoinable pending entry is pure ring litter.
        seq = _decisions.record_decision(
            "columnar.cutoff", tier, outcome=join and _outcomes.enabled(),
            **inputs,
        )
        if join and seq is not None and _outcomes.enabled():
            v = Verdict(tier)
            v.seq = seq
            return v
    return tier


def enabled_for(a_hlc, b_hlc) -> bool:
    """Does this pair leave the per-container walk? The CARDINALITY
    facades' gate (and_cardinality/intersects): their batched kernels are
    CPU-only, so the verdict is computed — and recorded — with the device
    tier excluded; the materializing facades call :func:`route` directly
    and pass the three-way verdict into ``pairwise``. ``join=False``:
    the count-only kernels run outside any scope that could resolve the
    outcome, so the verdict records provenance without parking a pending
    join (ISSUE 11)."""
    return route(a_hlc, b_hlc, allow_device=False, join=False) != "per-container"


def enabled_for_fold(n_rows: int) -> bool:
    """Route an N-way fold through the columnar batch engine? Gate is the
    measured fold cutoff when the cost model calibrated one, the config
    default otherwise. One verdict per fold (milliseconds of work), so
    both outcomes record."""
    if not _routing_on():
        return False
    gate = _costmodel.MODEL.fold_gate_rows()
    verdict = n_rows >= gate
    _decisions.record_decision(
        "columnar.cutoff", "columnar-fold" if verdict else "per-container-fold",
        rows=n_rows, min_fold_rows=gate,
        model="calibrated" if _costmodel.MODEL.fold_rows_min else "default",
    )
    return verdict


# declared fold-op label values (the metric-naming rule rejects computed
# label values — the label set is a frozen enumeration, so declare it)
_FOLD_LABELS = {"or": "fold_or", "xor": "fold_xor", "and": "fold_and"}


def _inc_classes(op: str, hist: np.ndarray) -> None:
    """Count a completed batch into the per-class metric. The device tier
    calls this only AFTER every bucket succeeded — a non-fatal device
    failure reruns the whole pair on the CPU tier, and counting at entry
    would double every degraded pair's classes."""
    for ci in np.flatnonzero(hist).tolist():
        _COLUMNAR_TOTAL.inc(int(hist[ci]), labels=(op, CLASS_NAMES[ci]))


def _record(op: str, codes_a: np.ndarray, codes_b: np.ndarray) -> np.ndarray:
    """Count the batch into the per-class metric; returns the 9-class
    histogram so callers skip the mask + CSR build for zero-pair classes
    (ISSUE 10 satellite: measured fixed cost at 16-64-pair sizes)."""
    hist = class_histogram(codes_a, codes_b)
    _inc_classes(op, hist)
    return hist


# ---------------------------------------------------------------------------
# matched-pair class execution
# ---------------------------------------------------------------------------


@_timed_fill("aa", 3)
def _fill_aa(
    op: str, acs, bcs, idx: np.ndarray, results: List[Optional[Container]]
) -> None:
    """array x array: the CSR batch kernel, then batched format selection
    (or/xor unions can overflow 4096 into bitmap)."""
    if idx.size == 0:
        return
    avals, aoffs = gather_values(acs, idx)
    bvals, boffs = gather_values(bcs, idx)
    vals, starts, counts = kernels.batch_pairwise(avals, aoffs, bvals, boffs, op)
    starts_l, counts_l = starts.tolist(), counts.tolist()
    for j, i in enumerate(idx.tolist()):
        n = counts_l[j]
        if n == 0:
            continue
        s = starts_l[j]
        chunk = vals[s : s + n]
        if n <= ARRAY_MAX_SIZE:
            # copy: the batch buffer is shared scratch; a view would pin it
            results[i] = _wrap_u16(chunk.copy())
        else:
            results[i] = BitmapContainer(bits.words_from_values(chunk), n)


def _gather_mask(probe_cs, dense_cs, idx: np.ndarray, dense_is_run: bool):
    """Shared probe machinery of the array x dense classes: one batched
    membership pass answers every probe value of every pair — a word-test
    gather against stacked bitmap rows, or the banded searchsorted against
    run payloads (NO word expansion either way)."""
    vals, offs = gather_values(probe_cs, idx)
    if dense_is_run:
        starts, lengths, roffs = gather_runs(dense_cs, idx)
        return vals, offs, kernels.run_member_mask(vals, offs, starts, lengths, roffs)
    rows_mat = stack_words(dense_cs, idx)
    row_ids = np.repeat(np.arange(idx.size, dtype=np.int64), np.diff(offs))
    return vals, offs, kernels.member_mask(rows_mat, row_ids, vals)


def _build_gather_results(
    op: str, vals: np.ndarray, offs: np.ndarray, mask: np.ndarray,
    idx: np.ndarray, results,
) -> None:
    """Shared tail of the membership-gather classes (CPU word-test and
    the device tier's on-device word-test): keep the member (and) or
    non-member (andnot) probe values per pair; results stay arrays by
    construction."""
    if op == "andnot":
        mask = ~mask
    row_ids = np.repeat(np.arange(idx.size, dtype=np.int64), np.diff(offs))
    kept = vals[mask]
    counts = np.bincount(row_ids[mask], minlength=idx.size)
    starts = np.concatenate(([0], np.cumsum(counts)))
    starts_l, counts_l = starts.tolist(), counts.tolist()
    for j, i in enumerate(idx.tolist()):
        n = counts_l[j]
        if n:
            s = starts_l[j]
            results[i] = _wrap_u16(kept[s : s + n].copy())


@_timed_fill("gather", 3)
def _fill_gather(
    op: str, probe_cs, dense_cs, idx: np.ndarray, results, dense_is_run: bool
) -> None:
    """array x dense (and/andnot): membership gather; results stay
    arrays by construction."""
    if idx.size == 0:
        return
    vals, offs, mask = _gather_mask(probe_cs, dense_cs, idx, dense_is_run)
    _build_gather_results(op, vals, offs, mask, idx, results)


@_timed_fill("runs", 3)
def _fill_runs_native(op: str, acs, bcs, idx: np.ndarray, results) -> None:
    """All bitmap-free classes (aa/ar/ra/rr) of and/andnot through ONE
    native call: payloads unify as CSR run lists (arrays are length-0
    runs), ``rb_batch_run_pairwise`` two-pointer-merges every pair in C
    emitting result intervals, and the whole batch's container formats
    are selected by the reference's size rule (run iff 2+4·nruns smallest
    — run-shaped results stay compressed; small ones expand to arrays in
    one vectorized pass)."""
    if idx.size == 0:
        return
    as_, al, acnt = gather_intervals(acs, idx)
    bs_, bl, bcnt = gather_intervals(bcs, idx)
    out_s, out_l, starts, counts, cards = kernels.batch_run_pairwise(
        as_, al, acnt, bs_, bl, bcnt, op
    )
    starts_l, counts_l, cards_l = starts.tolist(), counts.tolist(), cards.tolist()
    arr_js: List[int] = []  # pairs whose result becomes an array container
    for j, i in enumerate(idx.tolist()):
        card = cards_l[j]
        if card == 0:
            continue
        n = counts_l[j]
        run_size = 2 + 4 * n
        other = 8192 if card > ARRAY_MAX_SIZE else 2 + 2 * card
        if run_size <= other:
            s = starts_l[j]
            rc = RunContainer(out_s[s : s + n].copy(), out_l[s : s + n].copy())
            rc._card = card
            results[i] = rc
        elif card <= ARRAY_MAX_SIZE:
            arr_js.append(j)
        else:
            s = starts_l[j]
            s64 = out_s[s : s + n].astype(np.int64)
            e64 = s64 + out_l[s : s + n].astype(np.int64) + 1
            results[i] = BitmapContainer(bits.words_from_intervals(s64, e64), card)
    if arr_js:
        # one vectorized interval -> value expansion for every array result
        seg_s = np.concatenate(
            [out_s[starts_l[j] : starts_l[j] + counts_l[j]] for j in arr_js]
        ).astype(np.int64)
        seg_l = np.concatenate(
            [out_l[starts_l[j] : starts_l[j] + counts_l[j]] for j in arr_js]
        ).astype(np.int64)
        lens = seg_l + 1
        total = int(lens.sum())
        prefix = np.concatenate(([0], np.cumsum(lens)[:-1]))
        vals = (
            np.repeat(seg_s - prefix, lens) + np.arange(total, dtype=np.int64)
        ).astype(np.uint16)
        pos = 0
        idx_l = idx.tolist()
        for j in arr_js:
            card = cards_l[j]
            results[idx_l[j]] = _wrap_u16(vals[pos : pos + card].copy())
            pos += card


@_timed_fill("interval", 3)
def _fill_interval(op: str, acs, bcs, idx: np.ndarray, results) -> None:
    """run x run (plus andnot's run-minus-array), numpy tier: the banded
    interval-algebra batch — no word expansion, one global sort for the
    whole bucket; each pair's result intervals pick their container by the
    reference's size rule (``_container_of_intervals``), so run-shaped
    results stay runs."""
    if idx.size == 0:
        return
    as_, al, acnt = gather_intervals(acs, idx)
    bs_, bl, bcnt = gather_intervals(bcs, idx)
    out_s, out_e, starts, counts = kernels.interval_batch(
        as_, al, acnt, bs_, bl, bcnt, op
    )
    starts_l, counts_l = starts.tolist(), counts.tolist()
    for j, i in enumerate(idx.tolist()):
        n = counts_l[j]
        if n == 0:
            continue
        s = starts_l[j]
        results[i] = _container_of_intervals(out_s[s : s + n], out_e[s : s + n])


def _format_rows_results(
    words64: np.ndarray, cards: List[int], idx: List[int], results
) -> None:
    """The card-driven array-vs-bitmap result-format rule, shared by the
    CPU word-matrix classes and the device tier (whose popcounts arrive
    precomputed from the fused dispatch) — ONE copy of the threshold so
    the tiers' container formats can never drift."""
    for j, i in enumerate(idx):
        card = cards[j]
        if card == 0:
            continue
        if card <= ARRAY_MAX_SIZE:
            results[i] = _wrap_u16(bits.values_from_words(words64[j]))
        else:
            results[i] = BitmapContainer(words64[j].copy(), card)


def _build_words_results(
    mat: np.ndarray, idx_chunk: List[int], results
) -> None:
    """Batched format selection over a result word matrix: one popcount
    pass decides array-vs-bitmap for the whole chunk."""
    _format_rows_results(mat, kernels.popcount_rows(mat).tolist(), idx_chunk, results)


@_timed_fill("dense", 3)
def _fill_dense(
    op: str, acs, bcs, idx: np.ndarray, results
) -> None:
    """Word-matrix classes, chunked to bound peak memory:

    * and / andnot — both sides dense (runs expanded through the batched
      interval fill): expand, one ``&`` / ``& ~``, batched popcount+select.
    * and/andnot with an array RIGHT operand never lands here (gather /
      scatter-clear paths); or/xor land here for every non-aa pair — the
      left side expands, the right side combines via the same batched
      scatter/fill/reduceat machinery.
    """
    if idx.size == 0:
        return
    step = max(1, config.chunk_rows)
    for lo in range(0, idx.size, step):
        chunk = idx[lo : lo + step]
        chunk_l = chunk.tolist()
        if op in ("or", "xor"):
            mat = expand_rows(acs, chunk)
            rows = np.arange(chunk.size, dtype=np.int64)
            scatter_containers(mat, rows, [bcs[i] for i in chunk_l], op=op)
        else:
            mat = expand_rows(acs, chunk)
            right = expand_rows(bcs, chunk)
            if op == "and":
                mat &= right
            else:  # andnot
                mat &= ~right
        _build_words_results(mat, chunk_l, results)


@_timed_fill("clear", 2, op_pos=None)
def _fill_clear(acs, bcs, idx: np.ndarray, results) -> None:
    """andnot with a dense left and array right: expand the left, scatter-
    CLEAR the right's values out of it in one batched pass."""
    if idx.size == 0:
        return
    step = max(1, config.chunk_rows)
    for lo in range(0, idx.size, step):
        chunk = idx[lo : lo + step]
        mat = expand_rows(acs, chunk)
        bvals, boffs = gather_values(bcs, chunk)
        kernels.scatter_values_rows(
            np.arange(chunk.size, dtype=np.int64), boffs, bvals, mat, op="clear"
        )
        _build_words_results(mat, chunk.tolist(), results)


def _fill_nonbm(
    op: str,
    acs: Sequence[Container],
    bcs: Sequence[Container],
    codes_a: np.ndarray,
    codes_b: np.ndarray,
    hist: np.ndarray,
    results: List[Optional[Container]],
) -> None:
    """All bitmap-free classes of and/andnot (aa/ar/ra/rr) — shared by the
    CPU and device tiers (the device tier keeps these on the CPU: their
    payloads are value-sized and the run-unified merge keeps run-shaped
    results compressed). A pure array x array bucket skips the
    run-unification gather entirely and rides the CSR values kernel
    (ISSUE 10 satellite: the per-container python loop in
    ``gather_intervals`` was a measured fixed cost at 16-64-pair sizes)."""
    n_aa = int(hist[0])
    n_runish = int(hist[2] + hist[6] + hist[8])  # ar + ra + rr
    if not n_aa and not n_runish:
        return
    a_arr = codes_a == ARRAY
    b_arr = codes_b == ARRAY
    if not n_runish:
        _fill_aa(op, acs, bcs, np.flatnonzero(a_arr & b_arr), results)
        return
    a_bm = codes_a == BITMAP
    b_bm = codes_b == BITMAP
    nonbm = np.flatnonzero(~a_bm & ~b_bm)
    if kernels.has_native():
        # one run-unified native call serves every bitmap-free class;
        # a non-fatal failure (injected or real) classifies and the
        # whole bucket re-runs on the numpy tiers below (ISSUE 7)
        try:
            _fill_runs_native(op, acs, bcs, nonbm, results)
            return
        except Exception as e:
            if _rerrors.classify(e) == _rerrors.FATAL:
                raise
            _ladder.LADDER.note_degrade("columnar.kernel", "native", "numpy", e)
            for i in nonbm.tolist():  # drop any partial native writes
                results[i] = None
    a_run = ~a_arr & ~a_bm
    b_run = ~b_arr & ~b_bm
    _fill_aa(op, acs, bcs, np.flatnonzero(a_arr & b_arr), results)
    # banded run probes for the array x run directions
    _fill_gather(op, acs, bcs, np.flatnonzero(a_arr & b_run), results, True)
    if op == "and":
        _fill_gather(op, bcs, acs, np.flatnonzero(b_arr & a_run), results, True)
        iv = np.flatnonzero(a_run & b_run)  # rr
    else:
        iv = np.flatnonzero(a_run & ~b_bm)  # rr + ra
    _fill_interval(op, acs, bcs, iv, results)


def _matched_results(
    op: str, acs: Sequence[Container], bcs: Sequence[Container]
) -> List[Optional[Container]]:
    n = len(acs)
    results: List[Optional[Container]] = [None] * n
    if n == 0:
        return results
    codes_a = classify(acs)
    codes_b = classify(bcs)
    hist = _record(op, codes_a, codes_b)
    a_arr = codes_a == ARRAY
    b_arr = codes_b == ARRAY
    if op in ("and", "andnot"):
        a_bm = codes_a == BITMAP
        b_bm = codes_b == BITMAP
        _fill_nonbm(op, acs, bcs, codes_a, codes_b, hist, results)
        # hist-guarded class masks: a zero-pair class pays no flatnonzero,
        # no wrapper call, no CSR build (the 16-64-pair fixed-cost trim)
        if hist[1]:  # ab: array probe vs stacked bitmap words
            _fill_gather(op, acs, bcs, np.flatnonzero(a_arr & b_bm), results, False)
        if hist[3]:  # ba
            if op == "and":
                _fill_gather(op, bcs, acs, np.flatnonzero(b_arr & a_bm), results, False)
            else:
                # ba under andnot: expand a, scatter-CLEAR b's values
                _fill_clear(acs, bcs, np.flatnonzero(a_bm & b_arr), results)
        if hist[4] or hist[5] or hist[7]:
            # bb / br / rb: at least one bitmap, no array side -> word matrices
            _fill_dense(
                op, acs, bcs,
                np.flatnonzero((a_bm & ~b_arr) | (~a_arr & b_bm)), results,
            )
    else:  # or / xor
        if hist[0]:
            _fill_aa(op, acs, bcs, np.flatnonzero(a_arr & b_arr), results)
        if int(hist.sum()) > int(hist[0]):
            _fill_dense(op, acs, bcs, np.flatnonzero(~(a_arr & b_arr)), results)
    return results


# ---------------------------------------------------------------------------
# public pairwise entry points
# ---------------------------------------------------------------------------


def pairwise(
    op: str,
    x1: RoaringBitmap,
    x2: RoaringBitmap,
    reuse_left: bool = False,
    tier: Optional[str] = None,
) -> RoaringBitmap:
    """Whole-pair ``x1 OP x2`` through the batched engine. ``reuse_left``
    transfers x1's pass-through containers unclone'd — ONLY for the
    in-place facades (ior/ixor/iandnot), which discard x1's old index:
    the member-op semantics win, now uniform across all four ops.

    ``tier``: ``"cpu"`` (the host batch engine), ``"device"`` (the
    PACK_CACHE-fed accelerator tier, ISSUE 10), a ``route()`` verdict
    (``"columnar-cpu"``/``"columnar-device"`` — the facades pass their
    single routing verdict straight through, no second route), or None —
    consult the cost model, with a direct call defaulting to the CPU
    tier exactly as before the device tier existed. A device run rides
    the ``columnar.device`` ladder: any non-fatal failure re-executes
    the whole pair on the CPU tier, bit-exact by construction (same
    partition, same assembly)."""
    if tier is None:
        tier = route(x1.high_low_container, x2.high_low_container, record=False)
    tier = _TIER_ARG.get(tier, tier)
    if tier == "device":
        return _ladder.LADDER.run(
            "columnar.device",
            [
                ("columnar-device",
                 lambda: _pairwise_tier(op, x1, x2, reuse_left, "device")),
                ("columnar-cpu",
                 lambda: _pairwise_tier(op, x1, x2, reuse_left, "cpu")),
            ],
        )
    return _pairwise_tier(op, x1, x2, reuse_left, "cpu")


def _pairwise_tier(
    op: str, x1: RoaringBitmap, x2: RoaringBitmap, reuse_left: bool, tier: str
) -> RoaringBitmap:
    a, b = x1.high_low_container, x2.high_low_container
    plan = key_plan(a.keys, b.keys, op)
    acont, bcont = a.containers, b.containers
    acs = [acont[i] for i in plan.ia.tolist()]
    bcs = [bcont[i] for i in plan.ib.tolist()]
    if tier == "device":
        from . import device as _device_tier

        results = _device_tier.matched_results_device(
            op, acs, bcs, plan.ia, plan.ib,
            _device_tier.rows_for(x1), _device_tier.rows_for(x2),
        )
    else:
        results = _matched_results(op, acs, bcs)
    return _assemble_pairwise(op, a, b, plan, results, reuse_left)


def _assemble_pairwise(
    op: str, a, b, plan, results, reuse_left: bool
) -> RoaringBitmap:
    """Shared result assembly for one pair: matched results (any tier's)
    merge-sorted with the pass-through containers by the key plan. One
    copy serves the solo tiers AND the fused cross-query batch
    (ISSUE 13), so their container layouts can never drift."""
    acont, bcont = a.containers, b.containers
    out = RoaringBitmap()
    okeys, ocont = out.high_low_container.keys, out.high_low_container.containers
    if op == "and":
        for k, c in zip(plan.matched_keys.tolist(), results):
            if c is not None:
                okeys.append(k)
                ocont.append(c)
        return out
    a_only_l = plan.a_only.tolist()
    b_only_l = plan.b_only.tolist()
    keys_all = np.concatenate(
        [plan.matched_keys, plan.akeys[plan.a_only], plan.bkeys[plan.b_only]]
    )
    keys_l = keys_all.tolist()
    n_m = len(results)
    n_a = len(a_only_l)
    for idx in np.argsort(keys_all, kind="stable").tolist():
        if idx < n_m:
            c = results[idx]
            if c is None:
                continue
        elif idx < n_m + n_a:
            ca = acont[a_only_l[idx - n_m]]
            c = ca if reuse_left else ca.clone()
        else:
            c = bcont[b_only_l[idx - n_m - n_a]].clone()
        okeys.append(keys_l[idx])
        ocont.append(c)
    return out


def pairwise_multi(
    op: str, pairs: Sequence[tuple], tier: str = "cpu"
) -> List[RoaringBitmap]:
    """Cross-query fused pairwise tier (ISSUE 13): execute MANY
    independent ``a OP b`` pairs through ONE per-class batch pass. The
    per-pair key plans stay host-side (microseconds), but every pair's
    matched containers concatenate into one flat batch, so each occupied
    class pays ONE kernel call for the whole window — on the device tier
    the dense bucket is one fused gather+op+popcount launch over the
    concatenated resident row blocks (``matched_results_device_multi``)
    and the probe bucket one word-test gather. Results are bit-exact
    with per-pair execution by construction: the class kernels operate
    per matched pair, and the assembly is the shared
    :func:`_assemble_pairwise`."""
    plans = []
    acs_all: List[Container] = []
    bcs_all: List[Container] = []
    spans: List[tuple] = []
    jobs = []
    for x1, x2 in pairs:
        a, b = x1.high_low_container, x2.high_low_container
        plan = key_plan(a.keys, b.keys, op)
        acont, bcont = a.containers, b.containers
        acs = [acont[i] for i in plan.ia.tolist()]
        bcs = [bcont[i] for i in plan.ib.tolist()]
        plans.append((a, b, plan))
        spans.append((len(acs_all), len(acs)))
        acs_all.extend(acs)
        bcs_all.extend(bcs)
        jobs.append((x1, x2, plan))
    if tier == "device":
        from . import device as _device_tier

        results_all = _device_tier.matched_results_device_multi(op, jobs)
    else:
        results_all = _matched_results(op, acs_all, bcs_all)
    outs = []
    for (a, b, plan), (start, count) in zip(plans, spans):
        outs.append(
            _assemble_pairwise(
                op, a, b, plan, results_all[start : start + count], False
            )
        )
    return outs


def and_cardinality_pair(x1: RoaringBitmap, x2: RoaringBitmap) -> int:
    """``|x1 & x2|`` with NO materialization anywhere: the aa class runs
    the count-only batch kernel, gathers count their masks, dense pairs
    stop at the batched popcount."""
    a, b = x1.high_low_container, x2.high_low_container
    plan = key_plan(a.keys, b.keys, "and")
    acont, bcont = a.containers, b.containers
    acs = [acont[i] for i in plan.ia.tolist()]
    bcs = [bcont[i] for i in plan.ib.tolist()]
    total = 0
    for count in _cardinality_batches(acs, bcs):
        total += count
    return total


def intersects_pair(x1: RoaringBitmap, x2: RoaringBitmap) -> bool:
    """Batched intersects: same buckets as and-cardinality, short-circuits
    between class batches."""
    a, b = x1.high_low_container, x2.high_low_container
    plan = key_plan(a.keys, b.keys, "and")
    acont, bcont = a.containers, b.containers
    acs = [acont[i] for i in plan.ia.tolist()]
    bcs = [bcont[i] for i in plan.ib.tolist()]
    for count in _cardinality_batches(acs, bcs):
        if count:
            return True
    return False


def _cardinality_batches(acs, bcs):
    """Yield per-class-bucket AND cardinalities (sum = and_cardinality)."""
    if not acs:
        return
    codes_a = classify(acs)
    codes_b = classify(bcs)
    _record("and_card", codes_a, codes_b)
    a_arr = codes_a == ARRAY
    b_arr = codes_b == ARRAY
    a_bm = codes_a == BITMAP
    b_bm = codes_b == BITMAP
    nonbm = np.flatnonzero(~a_bm & ~b_bm)
    native_count = None
    if nonbm.size and kernels.has_native():
        try:
            as_, al, acnt = gather_intervals(acs, nonbm)
            bs_, bl, bcnt = gather_intervals(bcs, nonbm)
            native_count = int(
                kernels.batch_run_pairwise(
                    as_, al, acnt, bs_, bl, bcnt, "and", cards_only=True
                ).sum()
            )
        except Exception as e:
            if _rerrors.classify(e) == _rerrors.FATAL:
                raise
            _ladder.LADDER.note_degrade("columnar.kernel", "native", "numpy", e)
    if native_count is not None:
        yield native_count
    elif nonbm.size:
        a_run = ~a_arr & ~a_bm
        b_run = ~b_arr & ~b_bm
        aa = np.flatnonzero(a_arr & b_arr)
        if aa.size:
            avals, aoffs = gather_values(acs, aa)
            bvals, boffs = gather_values(bcs, aa)
            yield int(
                kernels.batch_and_cardinality(avals, aoffs, bvals, boffs).sum()
            )
        iv = np.flatnonzero(a_run & b_run)  # rr
        if iv.size:
            as_, al, acnt = gather_intervals(acs, iv)
            bs_, bl, bcnt = gather_intervals(bcs, iv)
            yield int(
                kernels.interval_batch(
                    as_, al, acnt, bs_, bl, bcnt, "and", cards_only=True
                ).sum()
            )
        for idx, probe_cs, dense_cs, dense_is_run in (
            (np.flatnonzero(a_arr & b_run), acs, bcs, True),
            (np.flatnonzero(b_arr & a_run), bcs, acs, True),
        ):
            if idx.size:
                _v, _o, mask = _gather_mask(probe_cs, dense_cs, idx, dense_is_run)
                yield int(mask.sum())
    for idx, probe_cs, dense_cs in (
        (np.flatnonzero(a_arr & b_bm), acs, bcs),
        (np.flatnonzero(b_arr & a_bm), bcs, acs),
    ):
        if idx.size:
            _v, _o, mask = _gather_mask(probe_cs, dense_cs, idx, False)
            yield int(mask.sum())
    ww = np.flatnonzero((a_bm & ~b_arr) | (~a_arr & b_bm))
    if ww.size:
        step = max(1, config.chunk_rows)
        total = 0
        for lo in range(0, ww.size, step):
            chunk = ww[lo : lo + step]
            mat = expand_rows(acs, chunk)
            mat &= expand_rows(bcs, chunk)
            total += int(kernels.popcount_rows(mat).sum())
        yield total


# ---------------------------------------------------------------------------
# N-way CPU folds
# ---------------------------------------------------------------------------


def fold(groups: Dict[int, List[Container]], op: str) -> RoaringBitmap:
    """Key-grouped N-way fold without per-container dispatch: all array
    payloads scatter in one batched call, all runs expand through one
    batched interval fill, bitmap rows reduce with one ``reduceat`` — then
    one batched popcount selects every result format. Single-container
    groups pass through as type-preserving clones (exactly the
    per-container engine's behavior)."""
    keys = sorted(groups)
    singles: Dict[int, Container] = {}
    multi_keys: List[int] = []
    multi_cs: List[List[Container]] = []
    n_rows = 0
    for k in keys:
        cs = groups[k]
        if len(cs) == 1:
            singles[k] = cs[0]
        else:
            multi_keys.append(k)
            multi_cs.append(cs)
            n_rows += len(cs)
    if n_rows:
        _COLUMNAR_TOTAL.inc(n_rows, labels=(_FOLD_LABELS[op], "rows"))
    out = RoaringBitmap()
    hlc = out.high_low_container
    results: Dict[int, Optional[Container]] = {}
    if multi_keys:
        with _kernel_stage(op, "fold", n_rows):
            if op in ("or", "xor"):
                mat = np.zeros(
                    (len(multi_keys), bits.WORDS_PER_CONTAINER), dtype=np.uint64
                )
                row_ids = np.repeat(
                    np.arange(len(multi_keys), dtype=np.int64),
                    np.fromiter((len(cs) for cs in multi_cs), np.int64, len(multi_cs)),
                )
                flat = [c for cs in multi_cs for c in cs]
                scatter_containers(mat, row_ids, flat, op=op)
            else:  # and: expand + reduceat, chunked by row budget
                mats: List[np.ndarray] = []
                step = max(1, config.chunk_rows)
                gi = 0
                while gi < len(multi_keys):
                    ge, rows = gi, 0
                    while ge < len(multi_keys) and (
                        rows == 0 or rows + len(multi_cs[ge]) <= step
                    ):
                        rows += len(multi_cs[ge])
                        ge += 1
                    chunk_cs = [c for cs in multi_cs[gi:ge] for c in cs]
                    rows_mat = expand_rows(
                        chunk_cs, np.arange(len(chunk_cs), dtype=np.int64)
                    )
                    starts = np.concatenate(
                        ([0], np.cumsum([len(cs) for cs in multi_cs[gi:ge]]))
                    )[:-1]
                    mats.append(np.bitwise_and.reduceat(rows_mat, starts, axis=0))
                    gi = ge
                mat = np.concatenate(mats, axis=0)
            cards = kernels.popcount_rows(mat).tolist()
            for j, k in enumerate(multi_keys):
                card = cards[j]
                if card == 0:
                    results[k] = None
                elif card <= ARRAY_MAX_SIZE:
                    results[k] = _wrap_u16(bits.values_from_words(mat[j]))
                else:
                    results[k] = BitmapContainer(mat[j].copy(), card)
    for k in keys:
        c = singles[k].clone() if k in singles else results[k]
        if c is not None and c.cardinality:
            hlc.append(k, c)
    return out


def fold_multi(
    groups_list: Sequence[Dict[int, List[Container]]], op: str
) -> List[RoaringBitmap]:
    """N-way or/xor folds for SEVERAL independent working sets through
    ONE multi-band pass (ISSUE 13): every set's multi-container key
    groups stack into a single matrix, one ``scatter_containers`` call
    fills them all, one popcount pass selects every result format —
    merged-tier execution for the fused executor's CPU fold steps.
    Value-identical to ``[fold(g, op) for g in groups_list]`` by
    construction (same scatter op per row, same format rule); singles
    pass through as type-preserving clones exactly like :func:`fold`."""
    if op not in ("or", "xor"):
        raise ValueError(f"fold_multi merges or/xor folds, got {op!r}")
    multi_keys: List[tuple] = []  # (set index, key)
    multi_cs: List[List[Container]] = []
    per_set_singles: List[Dict[int, Container]] = []
    per_set_keys: List[List[int]] = []
    for si, groups in enumerate(groups_list):
        keys = sorted(groups)
        per_set_keys.append(keys)
        singles: Dict[int, Container] = {}
        for k in keys:
            cs = groups[k]
            if len(cs) == 1:
                singles[k] = cs[0]
            else:
                multi_keys.append((si, k))
                multi_cs.append(cs)
        per_set_singles.append(singles)
    results: Dict[tuple, Optional[Container]] = {}
    if multi_keys:
        n_rows = sum(len(cs) for cs in multi_cs)
        _COLUMNAR_TOTAL.inc(n_rows, labels=(_FOLD_LABELS[op], "rows"))
        with _kernel_stage(op, "fold", n_rows):
            mat = np.zeros(
                (len(multi_keys), bits.WORDS_PER_CONTAINER), dtype=np.uint64
            )
            row_ids = np.repeat(
                np.arange(len(multi_keys), dtype=np.int64),
                np.fromiter(
                    (len(cs) for cs in multi_cs), np.int64, len(multi_cs)
                ),
            )
            flat = [c for cs in multi_cs for c in cs]
            scatter_containers(mat, row_ids, flat, op=op)
            cards = kernels.popcount_rows(mat).tolist()
            for j, sk in enumerate(multi_keys):
                card = cards[j]
                if card == 0:
                    results[sk] = None
                elif card <= ARRAY_MAX_SIZE:
                    results[sk] = _wrap_u16(bits.values_from_words(mat[j]))
                else:
                    results[sk] = BitmapContainer(mat[j].copy(), card)
    outs: List[RoaringBitmap] = []
    for si, keys in enumerate(per_set_keys):
        out = RoaringBitmap()
        hlc = out.high_low_container
        singles = per_set_singles[si]
        for k in keys:
            c = singles[k].clone() if k in singles else results[(si, k)]
            if c is not None and c.cardinality:
                hlc.append(k, c)
        outs.append(out)
    return outs


def or_fold_words(groups: Dict[int, List[Container]]) -> Dict[int, np.ndarray]:
    """Per-key OR of each group's containers as word rows — the batched
    core the query kernels' CPU fallbacks (n-way ANDNOT's subtrahend
    union) share with ``fold``. Returned rows are views into one matrix;
    callers consume them immediately."""
    keys = sorted(groups)
    if not keys:
        return {}
    counts = np.fromiter((len(groups[k]) for k in keys), np.int64, len(keys))
    _COLUMNAR_TOTAL.inc(int(counts.sum()), labels=("fold_or", "rows"))
    with _kernel_stage("or", "fold_words", int(counts.sum())):
        mat = np.zeros((len(keys), bits.WORDS_PER_CONTAINER), dtype=np.uint64)
        row_ids = np.repeat(np.arange(len(keys), dtype=np.int64), counts)
        flat = [c for k in keys for c in groups[k]]
        scatter_containers(mat, row_ids, flat, op="or")
        return {k: mat[g] for g, k in enumerate(keys)}
