"""Stage 2 of the columnar pairwise engine: type partitioning + gathers.

Matched container pairs are classified into the 9 ``(array|bitmap|run)²``
classes of the reference's triple-dispatch matrix (Container.java:63-98) —
but where the reference JITs 9 per-pair kernels, here each CLASS is
executed as one batch: array payloads gather into CSR-style concatenated
``(values, offsets)`` buffers, dense payloads stack into ``[n, 1024]``
uint64 word matrices (runs expanded through the batched interval fill,
``rb_fill_intervals_rows``), and stage 3 (engine.py) runs one kernel per
occupied class.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

from ..models.container import (
    ArrayContainer,
    BitmapContainer,
    Container,
    RunContainer,
)
from ..utils import bits
from . import kernels

ARRAY, BITMAP, RUN = 0, 1, 2
_TYPE_CODE = {ArrayContainer: ARRAY, BitmapContainer: BITMAP, RunContainer: RUN}

# 9-class labels, row-major (left type * 3 + right type) — the metric's
# ``class`` label and the partition bookkeeping share this order
CLASS_NAMES = ("aa", "ab", "ar", "ba", "bb", "br", "ra", "rb", "rr")

_EMPTY_U16 = np.empty(0, dtype=np.uint16)
_ZERO_OFF = np.zeros(1, dtype=np.int64)


def classify(containers: Sequence[Container]) -> np.ndarray:
    """int64 type codes (ARRAY/BITMAP/RUN) for a container list; tolerant
    of subclasses via the isinstance slow path."""
    n = len(containers)
    out = np.empty(n, dtype=np.int64)
    code = _TYPE_CODE
    for i, c in enumerate(containers):
        t = code.get(type(c))
        if t is None:
            t = (
                ARRAY
                if isinstance(c, ArrayContainer)
                else BITMAP if isinstance(c, BitmapContainer) else RUN
            )
        out[i] = t
    return out


def class_histogram(codes_a: np.ndarray, codes_b: np.ndarray) -> np.ndarray:
    """Pair counts per 9-class, aligned with CLASS_NAMES."""
    if codes_a.size == 0:
        return np.zeros(9, dtype=np.int64)
    return np.bincount(codes_a * 3 + codes_b, minlength=9)[:9]


def gather_values(
    containers: Sequence[Container], idx: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """CSR gather of array-container payloads: ``(values, offsets)`` with
    ``offsets`` of length ``len(idx) + 1``. Concatenation also normalizes
    mapped (strided / read-only) payload views to one contiguous buffer —
    exactly what the native batch kernels need."""
    if idx.size == 0:
        return _EMPTY_U16, _ZERO_OFF
    chunks = [containers[i].content for i in idx.tolist()]
    lens = np.fromiter((c.size for c in chunks), np.int64, len(chunks))
    offs = np.concatenate(([0], np.cumsum(lens)))
    return np.concatenate(chunks) if offs[-1] else _EMPTY_U16, offs


def gather_runs(
    containers: Sequence[Container], idx: np.ndarray
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """CSR gather of run-container payloads: ``(starts, lengths,
    run_offsets)`` — the banded run-membership kernel's input shape."""
    if idx.size == 0:
        z = np.empty(0, dtype=np.uint16)
        return z, z, _ZERO_OFF
    ss = [containers[i].starts for i in idx.tolist()]
    ls = [containers[i].lengths for i in idx.tolist()]
    nruns = np.fromiter((s.size for s in ss), np.int64, len(ss))
    offs = np.concatenate(([0], np.cumsum(nruns)))
    if offs[-1] == 0:
        z = np.empty(0, dtype=np.uint16)
        return z, z, offs
    return np.concatenate(ss), np.concatenate(ls), offs


# shared zero-lengths view: array containers enter the interval gather as
# length-0 runs (value..value) without per-container allocations
_ZERO_LEN = np.zeros(4096, dtype=np.uint16)


def gather_intervals(
    containers: Sequence[Container], idx: np.ndarray
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """CSR gather of array+run payloads as RUNS: ``(starts, lengths,
    run_counts)`` with arrays contributing their values as length-0 runs —
    the uniform input of the run-unified batch kernel and the banded
    interval-algebra fallback."""
    if idx.size == 0:
        z16 = np.empty(0, dtype=np.uint16)
        return z16, z16, np.empty(0, dtype=np.int64)
    s_pieces: List[np.ndarray] = []
    l_pieces: List[np.ndarray] = []
    for i in idx.tolist():
        c = containers[i]
        if isinstance(c, RunContainer):
            s_pieces.append(c.starts)
            l_pieces.append(c.lengths)
        else:
            v = c.content
            s_pieces.append(v)
            l_pieces.append(_ZERO_LEN[: v.size])
    counts = np.fromiter((p.size for p in s_pieces), np.int64, len(s_pieces))
    return np.concatenate(s_pieces), np.concatenate(l_pieces), counts


def stack_words(
    containers: Sequence[Container], idx: np.ndarray
) -> np.ndarray:
    """Stack bitmap-container word rows into one [len(idx), 1024] uint64
    matrix (pure row copies — no scatter, no interval fill)."""
    if idx.size == 0:
        return np.zeros((0, bits.WORDS_PER_CONTAINER), dtype=np.uint64)
    return np.stack([containers[i].words for i in idx.tolist()]).astype(
        np.uint64, copy=False
    )


def expand_rows(
    containers: Sequence[Container], idx: np.ndarray
) -> np.ndarray:
    """Expand the selected containers into a fresh ``[len(idx), 1024]``
    uint64 word matrix: bitmap rows bulk-copy, array rows scatter through
    ONE batched call, run rows expand through ONE batched interval fill —
    no per-container kernel dispatch anywhere."""
    out = np.zeros((idx.size, bits.WORDS_PER_CONTAINER), dtype=np.uint64)
    if idx.size == 0:
        return out
    scatter_containers(out, np.arange(idx.size, dtype=np.int64),
                       [containers[i] for i in idx.tolist()], op="or")
    return out


def scatter_containers(
    out64: np.ndarray,
    row_ids: np.ndarray,
    containers: Sequence[Container],
    op: str = "or",
) -> None:
    """Combine ``containers[j]`` into ``out64[row_ids[j]]`` with ``op``
    (or | xor), rows possibly repeating (the N-way fold accumulators).

    One batched scatter serves every array container, one batched interval
    fill every run container; bitmap rows group per target row and reduce
    with a single ``np.bitwise_<op>.reduceat`` before combining."""
    arr_rows: List[int] = []
    arr_vals: List[np.ndarray] = []
    run_rows: List[int] = []
    run_starts: List[np.ndarray] = []
    run_lens: List[np.ndarray] = []
    bm_rows: List[int] = []
    bm_words: List[np.ndarray] = []
    for r, c in zip(row_ids.tolist(), containers):
        t = _TYPE_CODE.get(type(c))
        if t == ARRAY:
            arr_rows.append(r)
            arr_vals.append(c.content)
        elif t == BITMAP:
            bm_rows.append(r)
            bm_words.append(c.words)
        elif t == RUN:
            run_rows.append(r)
            run_starts.append(c.starts)
            run_lens.append(c.lengths)
        elif isinstance(c, BitmapContainer):
            bm_rows.append(r)
            bm_words.append(c.words)
        elif isinstance(c, RunContainer):
            run_rows.append(r)
            run_starts.append(c.starts)
            run_lens.append(c.lengths)
        else:
            arr_rows.append(r)
            arr_vals.append(c.content)
    if arr_rows:
        lens = np.fromiter((v.size for v in arr_vals), np.int64, len(arr_vals))
        offs = np.concatenate(([0], np.cumsum(lens)))
        kernels.scatter_values_rows(
            np.asarray(arr_rows, dtype=np.int64), offs,
            np.concatenate(arr_vals) if offs[-1] else _EMPTY_U16, out64, op,
        )
    if run_rows:
        nruns = np.fromiter((s.size for s in run_starts), np.int64, len(run_starts))
        roffs = np.concatenate(([0], np.cumsum(nruns)))
        starts = (
            np.concatenate(run_starts).astype(np.int64)
            if roffs[-1]
            else np.empty(0, dtype=np.int64)
        )
        ends = (
            starts + np.concatenate(run_lens).astype(np.int64) + 1
            if roffs[-1]
            else starts
        )
        kernels.fill_intervals_rows(
            np.asarray(run_rows, dtype=np.int64), roffs, starts, ends, out64, op
        )
    if bm_rows:
        rows = np.asarray(bm_rows, dtype=np.int64)

        def apply(targets: np.ndarray, vals: np.ndarray) -> None:
            if op == "or":
                out64[targets] |= vals
            else:
                out64[targets] ^= vals

        # pairwise/expand_rows targets are strictly increasing (one
        # container per row): combine directly — ``reduceat`` with a
        # boundary at every row reduces nothing yet costs ~3.6x the plain
        # row-wise op (measured 488 vs 134 µs on a 16-row batch, the
        # ISSUE 10 small-operand trim). Repeated rows (fold accumulators)
        # keep the grouped-reduce path.
        if rows.size == 1 or (np.diff(rows) > 0).all():
            apply(rows, np.stack(bm_words).astype(np.uint64, copy=False))
            return
        order = np.argsort(rows, kind="stable")
        stacked = np.stack([bm_words[i] for i in order.tolist()]).astype(
            np.uint64, copy=False
        )
        sorted_rows = rows[order]
        boundaries = np.concatenate(
            ([0], np.flatnonzero(np.diff(sorted_rows)) + 1)
        )
        if boundaries.size == sorted_rows.size:  # all distinct, unsorted
            apply(sorted_rows, stacked)
            return
        ufunc = np.bitwise_or if op == "or" else np.bitwise_xor
        apply(
            sorted_rows[boundaries],
            ufunc.reduceat(stacked, boundaries, axis=0),
        )
