from . import aggregation, batch, sharding, store

__all__ = ["aggregation", "batch", "sharding", "store"]
