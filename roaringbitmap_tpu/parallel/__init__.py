from . import store, aggregation

__all__ = ["store", "aggregation"]
