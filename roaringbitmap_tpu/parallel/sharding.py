"""Multi-chip distribution: container-axis sharding over a device mesh.

The reference's only parallelism is fork-join over container keys inside one
JVM (ParallelAggregation.java:160-190; SURVEY §2.6). The TPU-native
re-expression scales the same key-group reduction over a 2D
``jax.sharding.Mesh``:

* ``containers`` axis — bitmaps/containers data-parallel across chips; the
  cross-chip combine is a bitwise-OR tree over ICI (all_gather of per-chip
  partials + local fold — OR has no psum primitive, and G partial rows of
  8 KiB make the gather negligible next to the local reduction).
* ``words`` axis — the 2048-uint32 word axis model-parallel; the word fold
  needs no communication at all, and cardinality finishes with a
  ``psum`` of per-shard popcounts.

This module is exercised multi-device by ``__graft_entry__.dryrun_multichip``
(virtual CPU mesh) and single-device on the real chip.
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map as _shard_map  # jax >= 0.6 style

    def shard_map(f, mesh, in_specs, out_specs):
        # check_vma=False: the OR-combine replicates values via all_gather +
        # identical local folds, which the varying-mesh-axes inference cannot
        # prove replicated.
        return _shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False
        )

except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map_exp

    def shard_map(f, mesh, in_specs, out_specs):
        return _shard_map_exp(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False
        )


def make_mesh(n_devices: int | None = None, words_axis: int = 2) -> Mesh:
    """2D mesh (containers, words). words_axis=1 degenerates to pure DP."""
    devices = np.array(jax.devices()[: n_devices or len(jax.devices())])
    n = len(devices)
    while words_axis > 1 and n % words_axis:
        words_axis -= 1
    return Mesh(devices.reshape(n // words_axis, words_axis), ("containers", "words"))


def distributed_wide_or_cardinality(mesh: Mesh):
    """Build a jitted (words [N, W]) -> (reduced [W], cardinality) step over
    the mesh. N must divide by the containers axis, W by the words axis."""

    def step(words):
        local = lax.reduce(words, np.uint32(0), lax.bitwise_or, (0,))  # [W_shard]
        partials = lax.all_gather(local, "containers")  # [n_chips, W_shard] over ICI
        total = lax.reduce(partials, np.uint32(0), lax.bitwise_or, (0,))
        card_shard = jnp.sum(lax.population_count(total).astype(jnp.int32))
        card = lax.psum(card_shard, "words")
        return total, card

    mapped = shard_map(
        step,
        mesh,
        in_specs=(P("containers", "words"),),
        out_specs=(P("words"), P()),
    )
    return jax.jit(mapped)


def distributed_grouped_or(mesh: Mesh):
    """Grouped variant: ([G, M, W]) -> ([G, W], [G]) with groups replicated
    along the containers axis padding dimension M sharded."""

    def step(words3):
        red = lax.reduce(words3, np.uint32(0), lax.bitwise_or, (1,))  # [G, W_shard]
        partials = lax.all_gather(red, "containers", axis=0)  # [n, G, W_shard]
        total = lax.reduce(partials, np.uint32(0), lax.bitwise_or, (0,))
        card_shard = jnp.sum(lax.population_count(total).astype(jnp.int32), axis=-1)
        card = lax.psum(card_shard, "words")
        return total, card

    mapped = shard_map(
        step,
        mesh,
        in_specs=(P(None, "containers", "words"),),
        out_specs=(P(None, "words"), P(None)),
    )
    return jax.jit(mapped)
