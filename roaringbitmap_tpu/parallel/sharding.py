"""Multi-chip distribution: container-axis sharding over a device mesh.

The reference's only parallelism is fork-join over container keys inside one
JVM (ParallelAggregation.java:160-190; SURVEY §2.6). The TPU-native
re-expression scales the same key-group reduction over a 2D
``jax.sharding.Mesh``:

* ``containers`` axis — bitmaps/containers data-parallel across chips; the
  cross-chip combine is a bitwise-OR tree over ICI (all_gather of per-chip
  partials + local fold — OR has no psum primitive, and G partial rows of
  8 KiB make the gather negligible next to the local reduction).
* ``words`` axis — the 2048-uint32 word axis model-parallel; the word fold
  needs no communication at all, and cardinality finishes with a
  ``psum`` of per-shard popcounts.

This module is exercised multi-device by ``__graft_entry__.dryrun_multichip``
(virtual CPU mesh) and single-device on the real chip.
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map as _shard_map  # jax >= 0.6 style

    def shard_map(f, mesh, in_specs, out_specs):
        # check_vma=False: the OR-combine replicates values via all_gather +
        # identical local folds, which the varying-mesh-axes inference cannot
        # prove replicated.
        return _shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False
        )

except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map_exp

    def shard_map(f, mesh, in_specs, out_specs):
        return _shard_map_exp(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False
        )


def make_mesh(n_devices: int | None = None, words_axis: int = 2) -> Mesh:
    """2D mesh (containers, words). words_axis=1 degenerates to pure DP."""
    devices = np.array(jax.devices()[: n_devices or len(jax.devices())])
    n = len(devices)
    while words_axis > 1 and n % words_axis:
        words_axis -= 1
    return Mesh(devices.reshape(n // words_axis, words_axis), ("containers", "words"))


@functools.lru_cache(maxsize=8)
def distributed_wide_or_cardinality(mesh: Mesh):
    """Build a jitted (words [N, W]) -> (reduced [W], cardinality) step over
    the mesh. N must divide by the containers axis, W by the words axis."""

    def step(words):
        local = lax.reduce(words, np.uint32(0), lax.bitwise_or, (0,))  # [W_shard]
        partials = lax.all_gather(local, "containers")  # [n_chips, W_shard] over ICI
        total = lax.reduce(partials, np.uint32(0), lax.bitwise_or, (0,))
        card_shard = jnp.sum(lax.population_count(total).astype(jnp.int32))
        card = lax.psum(card_shard, "words")
        return total, card

    mapped = shard_map(
        step,
        mesh,
        in_specs=(P("containers", "words"),),
        out_specs=(P("words"), P()),
    )
    return jax.jit(mapped)


@functools.lru_cache(maxsize=8)
def distributed_grouped_reduce(mesh: Mesh, op: str = "or"):
    """Grouped variant: ([G, M, W]) -> ([G, W], [G]) with groups replicated,
    the row axis M sharded along ``containers``. The caller pads M with the
    op identity (store.pad_groups_dense fill = dev._INIT[op]) — the same
    table the fold below uses, so identity rows fold harmlessly on every
    chip for all three ops."""
    from ..ops import device as dev

    fn, init = dev._OPS[op], dev._INIT[op]

    def step(words3):
        red = lax.reduce(words3, init, fn, (1,))  # [G, W_shard]
        partials = lax.all_gather(red, "containers", axis=0)  # [n, G, W_shard]
        total = lax.reduce(partials, init, fn, (0,))
        card_shard = jnp.sum(lax.population_count(total).astype(jnp.int32), axis=-1)
        card = lax.psum(card_shard, "words")
        return total, card

    mapped = shard_map(
        step,
        mesh,
        in_specs=(P(None, "containers", "words"),),
        out_specs=(P(None, "words"), P(None)),
    )
    return jax.jit(mapped)


@functools.lru_cache(maxsize=8)
def distributed_bsi_compare(mesh: Mesh, op_name: str):
    """Sharded O'Neil BSI compare: the [S, K, 2048] slice tensor splits
    its key-chunk axis over ``containers`` and its word axis over
    ``words``; the slice walk (models/bsi.o_neil_math) is elementwise in
    both, so the whole scan runs with ZERO inter-chip traffic — the only
    collective is a words-axis psum of the per-chunk cardinalities. This
    is the filtered-range-query north star (BASELINE.md: "bsi/ 32-slice
    range query -> TPU AND-chain") at multi-chip scale.

    Returns a jitted ``(slices_w [S,K,W], bits_rev, ebm_w [K,W],
    fixed_w [K,W]) -> (result words [K,W], cards [K])``.
    """
    from ..models.bsi import o_neil_math

    def step(slices_w, bits_rev, ebm_w, fixed_w):
        out, cards = o_neil_math(slices_w, bits_rev, ebm_w, fixed_w, op_name)
        return out, lax.psum(cards, "words")

    mapped = shard_map(
        step,
        mesh,
        in_specs=(
            P(None, "containers", "words"),
            P(),
            P("containers", "words"),
            P("containers", "words"),
        ),
        out_specs=(P("containers", "words"), P("containers")),
    )
    return jax.jit(mapped)


@functools.lru_cache(maxsize=8)
def distributed_bsi_sum(mesh: Mesh):
    """Sharded BSI sum (RoaringBitmapSliceIndex.sum, :581-592): per-slice
    popcount of ``slice AND foundSet`` — elementwise over key-chunks and
    words, with one words-axis psum. Per-(slice, chunk) counts (each
    <= 65536, int32-safe without x64) return to host, where the exact
    big-int weighting Σ 2^i · count_i runs in python ints — totals can
    exceed any JAX integer dtype, exactly like the unsharded twin
    (models/bsi._slice_masked_popcounts).

    Returns a jitted ``(slices_w [S,K,W], found_w [K,W]) -> counts [S,K]``.
    Cached per mesh so repeat queries reuse the compiled step.
    """

    def step(slices_w, found_w):
        masked = slices_w & found_w[None, :, :]
        counts = jnp.sum(lax.population_count(masked).astype(jnp.int32), axis=-1)
        return lax.psum(counts, "words")

    mapped = shard_map(
        step,
        mesh,
        in_specs=(P(None, "containers", "words"), P("containers", "words")),
        out_specs=P(None, "containers"),
    )
    return jax.jit(mapped)


@functools.lru_cache(maxsize=8)
def distributed_bsi_counts_many(mesh: Mesh, op_name: str):
    """Sharded batched multi-predicate counts (the mesh twin of
    models/bsi._o_neil_counts_batched): Q query walks vmapped over the
    predicate axis, all sharing the sharded [S, K, 2048] pack — per-query
    the same zero-traffic slice scan as distributed_bsi_compare, with one
    words-axis psum of the [Q, K] per-chunk counts at the end.

    Returns a jitted ``(slices_w [S,K,W], bits_mat [Q,S] (or [Q,2,S] for
    RANGE), ebm_w [K,W], fixed_w [K,W]) -> counts [Q,K]``.
    """
    from ..models.bsi import o_neil_math

    def one(slices_w, bits, ebm_w, fixed_w):
        _, cards = o_neil_math(slices_w, bits, ebm_w, fixed_w, op_name)
        return cards

    def step(slices_w, bits_mat, ebm_w, fixed_w):
        cards = jax.vmap(one, in_axes=(None, 0, None, None))(
            slices_w, bits_mat, ebm_w, fixed_w
        )
        return lax.psum(cards, "words")

    mapped = shard_map(
        step,
        mesh,
        in_specs=(
            P(None, "containers", "words"),
            P(),
            P("containers", "words"),
            P("containers", "words"),
        ),
        out_specs=P(None, "containers"),
    )
    return jax.jit(mapped)


def collective_details(hlo_text: str) -> list:
    """Collective instructions in optimized HLO text: one record per
    instruction (start/done pairs counted once) with its replica groups —
    the observable evidence behind "the mesh ops are ICI-efficient"
    (scripts/hlo_report.py commits the full per-family report;
    tests/test_sharding.py pins the wide-OR layout)."""
    import re

    out = []
    for line in hlo_text.splitlines():
        # match the instruction APPLICATION (opcode followed by its operand
        # list) — newer jaxlib HLO text prints operand *references* like
        # `all-gather.1` without a `%` sigil, so a bare name match counted
        # every use of a collective's result as another collective
        m = re.search(
            r"\b(all-reduce|all-gather|reduce-scatter|all-to-all|collective-permute)(-start)?\(",
            line,
        )
        if not m or "-done" in line:
            continue
        # three syntaxes: nested {{0,1},{2,3}}, flat {0,1,2,3}, and the
        # iota form [4,2]<=[8] (optionally T(...)-transposed). A lazy
        # single-brace capture truncated nested groups (code-review r4).
        groups = re.search(
            r"replica_groups=(\{\{.*?\}\}|\{[^{}]*\}|\[[^\]]*\](?:<=\[[^\]]*\])?(?:T\([^)]*\))?)",
            line,
        )
        out.append({"op": m.group(1), "replica_groups": groups.group(1) if groups else None})
    return out


def collective_summary(jitted, *args) -> dict:
    """Compile ``jitted`` for the example args and count the collectives
    XLA placed (see collective_details)."""
    hlo = jitted.lower(*args).compile().as_text()
    counts: dict = {}
    for c in collective_details(hlo):
        counts[c["op"]] = counts.get(c["op"], 0) + 1
    return counts


def initialize_multihost(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> int:
    """Join a multi-host mesh (the DCN story, SURVEY §5 "distributed
    communication backend"): wraps ``jax.distributed.initialize`` — GKE/GCE
    TPU pods auto-discover when no arguments are given — after which
    ``jax.devices()`` spans every host and the ``make_mesh``/``shard_map``
    helpers above scale unchanged: container-axis collectives ride ICI
    within a slice and DCN across slices, exactly where XLA places them.
    Returns the global device count. Safe to call when already initialized
    or single-process (returns the local count)."""
    explicit = (
        coordinator_address is not None
        or num_processes is not None
        or process_id is not None
    )
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except (RuntimeError, ValueError):
        if explicit:
            # a configured coordinator that fails must not silently degrade
            # a multi-host job into a wrong-answer single-host one
            raise
        # no-arg probe: already initialized, or a plain single-process run
    return len(jax.devices())
