"""64-bit N-way aggregation: the flagship batched reduction extended to the
ART-backed ``Roaring64Bitmap`` layer.

The reference aggregates 64-bit bitmaps only pairwise/naively
(Roaring64NavigableMap.java:730 ``naivelazyor`` fold; no 64-bit
FastAggregation exists). Here the same SoA device engine that serves the
32-bit layer applies unchanged: containers of all inputs are transposed
into high-48-key-major groups (the long-context scaling axis, SURVEY §5),
packed into one ``[N, 2048]`` device tensor, and reduced per key group in
a single fused dispatch (parallel/store.py + ops/pallas_kernels.py) —
key width only changes the host-side directory.

CPU mode folds per key group with the shared word kernels, so the two
engines cross-check each other (tests/test_roaring64.py).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from ..models.container import Container, best_container_of_words
from ..models.roaring64art import Roaring64Bitmap, key_to_int
from . import store
from .aggregation import _fold_group_words, _use_device


def _group_by_key64(
    bitmaps: Sequence[Roaring64Bitmap], keys_filter: Optional[set] = None
) -> Dict[int, List[Container]]:
    """Transpose inputs into high-48-key-major groups (the 64-bit
    ParallelAggregation.groupByKey analogue; keys become ints so the
    shared packing path applies). ``keys_filter`` keeps the workShy AND
    from gathering containers outside the key intersection."""
    groups: Dict[int, List[Container]] = {}
    for bm in bitmaps:
        for key, c in bm._kv():
            k = key_to_int(key)
            if keys_filter is not None and k not in keys_filter:
                continue
            groups.setdefault(k, []).append(c)
    return groups


def _reduce_to_pairs(groups, op: str, mode: Optional[str]):
    """Reduce key groups to sorted ``(key, Container)`` pairs on the shared
    CPU/device engines; key composition is the caller's concern (48-bit
    chunk keys for the ART design, (bucket << 16) | chunk for the
    NavigableMap), so every 64-bit aggregation is ONE dispatch regardless
    of how many buckets it spans."""
    if not groups:
        return []
    n = sum(len(v) for v in groups.values())
    if _use_device(n, mode):
        packed = store.pack_groups(groups)
        words, cards = store.reduce_packed(packed, op=op)
        return list(store.iter_group_containers(packed.group_keys, words, cards))
    out = []
    for key in sorted(groups):
        cs = groups[key]
        c = cs[0].clone() if len(cs) == 1 else best_container_of_words(
            _fold_group_words(cs, op)
        )
        if c.cardinality:
            out.append((key, c))
    return out


class FastAggregation64:
    """N-way or/xor/and over ``Roaring64Bitmap`` inputs with the shared
    CPU/device dispatcher (``mode``: 'auto' | 'cpu' | 'device')."""

    @staticmethod
    def or_(*bitmaps: Roaring64Bitmap, mode: Optional[str] = None) -> Roaring64Bitmap:
        return _aggregate64(bitmaps, "or", mode)

    @staticmethod
    def xor(*bitmaps: Roaring64Bitmap, mode: Optional[str] = None) -> Roaring64Bitmap:
        return _aggregate64(bitmaps, "xor", mode)

    @staticmethod
    def and_(*bitmaps: Roaring64Bitmap, mode: Optional[str] = None) -> Roaring64Bitmap:
        """workShy AND: intersect the key sets first, then reduce only the
        surviving groups (Util.intersectKeys / workShyAnd analogue; every
        surviving key appears in all inputs, so the filtered grouping is
        exactly the AND work set)."""
        return _aggregate64(bitmaps, "and", mode)

    @staticmethod
    def or_cardinality(*bitmaps: Roaring64Bitmap, mode: Optional[str] = None) -> int:
        return _aggregate64_cardinality(bitmaps, "or", mode)

    @staticmethod
    def xor_cardinality(*bitmaps: Roaring64Bitmap, mode: Optional[str] = None) -> int:
        return _aggregate64_cardinality(bitmaps, "xor", mode)

    @staticmethod
    def and_cardinality(*bitmaps: Roaring64Bitmap, mode: Optional[str] = None) -> int:
        return _aggregate64_cardinality(bitmaps, "and", mode)


def or_navigable(*maps, mode: Optional[str] = None):
    """N-way OR over ``Roaring64NavigableMap`` inputs: every (high-32
    bucket, chunk-key) pair becomes one composed group key, so the whole
    map set reduces in a single engine dispatch no matter how many buckets
    it spans; results reassemble bucket-wise through the append path.
    Output config (signed order, bucket supplier) follows the first
    operand, like the reference's instance or()."""
    from ..models.roaring64 import Roaring64NavigableMap

    ms: List[Roaring64NavigableMap] = (
        list(maps[0])
        if len(maps) == 1 and not isinstance(maps[0], Roaring64NavigableMap)
        else list(maps)
    )
    if not ms:
        return Roaring64NavigableMap()
    out = Roaring64NavigableMap(
        signed_longs=ms[0].signed_longs, supplier=ms[0].supplier
    )
    groups: Dict[int, List[Container]] = {}
    for m in ms:
        for hb, bm in m._buckets.items():
            hlc = bm.high_low_container
            for k, c in zip(hlc.keys, hlc.containers):
                groups.setdefault((hb << 16) | k, []).append(c)
    for gkey, c in _reduce_to_pairs(groups, "or", mode):
        hb, chunk = gkey >> 16, gkey & 0xFFFF
        bucket = out._buckets.get(hb)
        if bucket is None:
            bucket = out.supplier()
            out._buckets[hb] = bucket
        bucket.high_low_container.append(chunk, c)
    out._keys_dirty = True
    return out


def _flatten64(bitmaps) -> List[Roaring64Bitmap]:
    if len(bitmaps) == 1 and not isinstance(bitmaps[0], Roaring64Bitmap):
        return list(bitmaps[0])
    return list(bitmaps)


def _aggregate64(bitmaps, op: str, mode: Optional[str]) -> Roaring64Bitmap:
    bms = _flatten64(bitmaps)
    if not bms:
        return Roaring64Bitmap()
    if len(bms) == 1:
        return bms[0].clone()
    prepared = _prepare_groups64(bms, op)
    if prepared is None:
        return Roaring64Bitmap()
    return _reduce_groups(prepared[0], op, mode)


def _reduce_groups(groups, op: str, mode: Optional[str]) -> Roaring64Bitmap:
    out = Roaring64Bitmap()
    for key, c in _reduce_to_pairs(groups, op, mode):
        out._put(int(key).to_bytes(6, "big"), c)
    return out


def _workshy_keys(bms) -> set:
    """Intersect the high-48 key sets (Util.intersectKeys analogue); the
    shared workShy-AND prelude for the materializing and cardinality-only
    engines. Empty set = trivially empty result."""
    keys = set(key_to_int(k) for k, _ in bms[0]._kv())
    for bm in bms[1:]:
        keys &= set(key_to_int(k) for k, _ in bm._kv())
        if not keys:
            return set()
    return keys


def _prepare_groups64(bms, op: str):
    """Shared grouping prelude (the 32-bit _dispatch_prelude analogue, pre-pack-cache shape): AND goes
    through the key intersection; returns (groups, n_rows) or None when the
    result is trivially empty."""
    if op == "and":
        keys = _workshy_keys(bms)
        if not keys:
            return None
        groups = _group_by_key64(bms, keys_filter=keys)
    else:
        groups = _group_by_key64(bms)
    return groups, sum(len(v) for v in groups.values())


def _aggregate64_cardinality(bitmaps, op: str, mode: Optional[str]) -> int:
    """64-bit twin of aggregation._aggregate_cardinality: on the device
    path only the per-group popcounts come back (key groups partition the
    64-bit universe, so their sum is the aggregate cardinality)."""
    bms = _flatten64(bitmaps)
    if not bms:
        return 0
    if len(bms) == 1:
        return bms[0].get_cardinality()
    prepared = _prepare_groups64(bms, op)
    if prepared is None:
        return 0
    groups, n = prepared
    if _use_device(n, mode):
        packed = store.pack_groups(groups)
        return int(store.reduce_packed_cardinality(packed, op=op).sum())
    return sum(c.cardinality for _, c in _reduce_to_pairs(groups, op, "cpu"))
