"""Device container store: SoA packing of containers onto the TPU.

The architectural inversion at the heart of this framework (SURVEY §7): the
reference walks containers pointer-by-pointer per bitmap
(ParallelAggregation.groupByKey, ParallelAggregation.java:136-153); here all
containers of a working set are transposed host-side into key-major order and
packed into ONE dense ``uint32 [N, 2048]`` device array plus small host-side
key/group tables. Aggregations then run as a single fused XLA/Pallas
computation over the whole set (ops/device.py) instead of a per-container
virtual-dispatch fold.

Array and run containers are expanded to bitmap words during packing — the
``toBitmapContainer`` analogue (Container.java:987) — because on TPU the
dense form is the only one the VPU can chew on; results are re-compressed to
the best container form when streamed back (best_container_of_words, the
``repairAfterLazy`` + conversion step).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

import numpy as np
import jax.numpy as jnp

from ..models.container import BitmapContainer, Container
from ..models.roaring import RoaringBitmap
from ..ops import device as dev
from ..utils import bits


def container_words_u32(c: Container) -> np.ndarray:
    """Expand any container to the uint32[2048] device word layout."""
    if isinstance(c, BitmapContainer):
        w = c.words
    else:
        w = c.to_words()
    return np.ascontiguousarray(w, dtype=np.uint64).view(np.uint32)


@dataclass
class PackedGroups:
    """Key-grouped containers packed for device reduction.

    ``words``: device uint32 [N, 2048], rows sorted by group.
    ``group_keys``: int64 [G] high-16-bit chunk keys, ascending.
    ``group_offsets``: int64 [G+1] row ranges per group.
    """

    words: jnp.ndarray
    group_keys: np.ndarray
    group_offsets: np.ndarray

    @property
    def n_rows(self) -> int:
        return int(self.group_offsets[-1])

    @property
    def n_groups(self) -> int:
        return len(self.group_keys)


def group_by_key(
    bitmaps: Sequence[RoaringBitmap], keys_filter: Optional[set] = None
) -> Dict[int, List[Container]]:
    """Transpose bitmaps into key-major groups
    (ParallelAggregation.groupByKey, ParallelAggregation.java:136-153)."""
    groups: Dict[int, List[Container]] = {}
    for bm in bitmaps:
        hlc = bm.high_low_container
        for k, c in zip(hlc.keys, hlc.containers):
            if keys_filter is not None and k not in keys_filter:
                continue
            groups.setdefault(k, []).append(c)
    return groups


def intersect_keys(bitmaps: Sequence[RoaringBitmap]) -> set:
    """Keys present in every input (Util.intersectKeys analogue,
    Util.java:1244-1259) — the workShyAnd pre-filter."""
    it = iter(bitmaps)
    first = next(it)
    keys = set(first.high_low_container.keys)
    for bm in it:
        keys &= set(bm.high_low_container.keys)
        if not keys:
            break
    return keys


def pack_groups(groups: Dict[int, List[Container]]) -> PackedGroups:
    """Pack key-major groups into one device array (host -> device marshal)."""
    group_keys = np.array(sorted(groups), dtype=np.int64)
    counts = np.array([len(groups[int(k)]) for k in group_keys], dtype=np.int64)
    offsets = np.concatenate(([0], np.cumsum(counts)))
    n = int(offsets[-1])
    host = np.empty((n, dev.DEVICE_WORDS), dtype=np.uint32)
    row = 0
    for k in group_keys:
        for c in groups[int(k)]:
            host[row] = container_words_u32(c)
            row += 1
    return PackedGroups(jnp.asarray(host), group_keys, offsets)


def prepare_reduce(packed: PackedGroups, op: str = "or"):
    """Build the device reduction closure for a packed group set.

    Returns ``(run, layout)`` where ``run()`` -> (reduced [G, 2048] device
    array, cards [G] device array) and ``layout`` is ``"padded"`` or
    ``"segmented-scan"``. The choice: dense padded [G, M, 2048] + identity
    padding when padding waste is bounded, else a flagged associative scan
    (the reference's answer to skew is splitting slices across the fork-join
    pool, ParallelAggregation.java:222-228). bench.py times exactly this
    closure, so the benchmark and production always run the same path.
    """
    g = packed.n_groups
    n = packed.n_rows
    counts = np.diff(packed.group_offsets)
    m = int(counts.max()) if g else 0
    if g * m <= max(2 * n, 1024):
        fill = dev._INIT[op]
        host = np.asarray(packed.words)
        padded = np.full((g, m, dev.DEVICE_WORDS), fill, dtype=np.uint32)
        for gi in range(g):
            s, e = int(packed.group_offsets[gi]), int(packed.group_offsets[gi + 1])
            padded[gi, : e - s] = host[s:e]
        dev_arr = jnp.asarray(padded)

        def run():
            return dev.grouped_reduce_with_cardinality(dev_arr, op=op)

        return run, "padded"

    seg_start = np.zeros(n, dtype=bool)
    seg_start[packed.group_offsets[:-1]] = True
    seg = jnp.asarray(seg_start)
    end_rows = jnp.asarray(packed.group_offsets[1:] - 1)
    words = packed.words

    def run():
        vals = dev.segmented_reduce(words, seg, op=op)
        red = vals[end_rows]
        return red, dev.popcount_rows(red)

    return run, "segmented-scan"


def reduce_packed(packed: PackedGroups, op: str = "or"):
    """Reduce each key group on device; returns (words [G,2048] np.uint32,
    cards [G] np.int64)."""
    if packed.n_groups == 0:
        return (
            np.empty((0, dev.DEVICE_WORDS), dtype=np.uint32),
            np.empty((0,), dtype=np.int64),
        )
    run, _ = prepare_reduce(packed, op)
    red, card = run()
    return np.asarray(red), np.asarray(card).astype(np.int64)


def unpack_to_bitmap(
    group_keys: np.ndarray, words_u32: np.ndarray, cards: np.ndarray
) -> RoaringBitmap:
    """Stream device results back into a RoaringBitmap via the append path
    (RoaringArray.append, RoaringArray.java:111), re-compressing each chunk."""
    from ..models.container import ArrayContainer, best_container_of_words

    out = RoaringBitmap()
    words64 = np.ascontiguousarray(words_u32).view(np.uint64)
    for gi, key in enumerate(group_keys.tolist()):
        card = int(cards[gi])
        if card == 0:
            continue
        w = words64[gi]
        if card <= 4096:
            out.high_low_container.append(
                int(key), ArrayContainer(bits.values_from_words(w))
            )
        else:
            out.high_low_container.append(
                int(key), BitmapContainer(w.copy(), card)
            )
    return out
