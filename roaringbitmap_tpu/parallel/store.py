"""Device container store: SoA packing of containers onto the TPU.

The architectural inversion at the heart of this framework (SURVEY §7): the
reference walks containers pointer-by-pointer per bitmap
(ParallelAggregation.groupByKey, ParallelAggregation.java:136-153); here all
containers of a working set are transposed host-side into key-major order and
packed into ONE dense ``uint32 [N, 2048]`` device array plus small host-side
key/group tables. Aggregations then run as a single fused XLA/Pallas
computation over the whole set (ops/device.py) instead of a per-container
virtual-dispatch fold.

Array and run containers are expanded to bitmap words during packing — the
``toBitmapContainer`` analogue (Container.java:987) — because on TPU the
dense form is the only one the VPU can chew on; results are re-compressed to
the best container form when streamed back (best_container_of_words, the
``repairAfterLazy`` + conversion step).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

import numpy as np
import jax.numpy as jnp

from .. import observe as _observe

# layout observability: ("padded"|"bucketed"|"segmented-scan") -> count.
# Registry-backed since ISSUE 1 (rb_tpu_store_layout_total); the CounterMap
# keeps the legacy mapping shape for insights.dispatch_counters().
_LAYOUT_TOTAL = _observe.counter(
    _observe.STORE_LAYOUT_TOTAL,
    "prepare_reduce layout choices (padded | bucketed | segmented-scan)",
    ("layout",),
)
LAYOUT_COUNTS = _observe.CounterMap(_LAYOUT_TOTAL, scalar=True)
# default ragged-batch bucket count for the prepare_reduce cost model;
# bench.py reuses it so reported occupancy always describes the production
# bucketing
DEFAULT_BUCKETS = 3
# host->device transfer accounting in bytes (insights.dispatch_counters)
_TRANSFER_TOTAL = _observe.counter(
    _observe.STORE_TRANSFER_BYTES_TOTAL,
    "Host->device transfer bytes by route (device-built blocks tracked "
    "under their own route so the ledger stays truthful)",
    ("route",),
)
TRANSFER_BYTES = _observe.CounterMap(_TRANSFER_TOTAL, scalar=True)
# bytes of device-resident working-set tensors cached by PackedGroups
_RESIDENT_BYTES = _observe.gauge(
    _observe.STORE_RESIDENT_BYTES,
    "Device-resident cached working-set bytes by layout kind",
    ("kind",),
)

from ..models.container import ArrayContainer, BitmapContainer, Container
from ..models.roaring import RoaringBitmap
from ..ops import device as dev
from ..utils import bits


def container_words_u32(c: Container) -> np.ndarray:
    """Expand any container to the uint32[2048] device word layout."""
    if isinstance(c, BitmapContainer):
        w = c.words
    else:
        w = c.to_words()
    return np.ascontiguousarray(w, dtype=np.uint64).view(np.uint32)


def pack_rows_host(containers: Sequence[Container]) -> np.ndarray:
    """Expand containers into one uint32 [N, 2048] host array.

    Vectorized toBitmapContainer (Container.java:987) for the packing hot
    path: bitmap rows are bulk-copied, and all array-container values are
    scattered in a single ``np.bitwise_or.at`` over the flattened word
    matrix (one C-level pass over every value) instead of a per-container
    python loop; run rows (rare in working sets that were not
    run_optimized) fall back to per-container expansion."""
    from .. import tracing

    n = len(containers)
    with tracing.op_timer("store.pack_rows_host"):
        return _pack_rows_host(containers, n)


def _pack_rows_host(containers: Sequence[Container], n: int) -> np.ndarray:
    out64 = np.zeros((n, bits.WORDS_PER_CONTAINER), dtype=np.uint64)
    arr_rows: List[int] = []
    arr_vals: List[np.ndarray] = []
    for i, c in enumerate(containers):
        if isinstance(c, BitmapContainer):
            out64[i] = c.words
        elif isinstance(c, ArrayContainer):
            arr_rows.append(i)
            arr_vals.append(c.content)
        else:
            out64[i] = c.to_words()
    if arr_rows:
        from .. import native

        lens = np.fromiter((v.size for v in arr_vals), np.int64, len(arr_vals))
        vals = np.concatenate(arr_vals)
        rows_np = np.asarray(arr_rows, dtype=np.int64)
        if native.available():
            offsets = np.concatenate(([0], np.cumsum(lens)))
            native.pack_array_rows(rows_np, offsets, vals, out64)
        else:
            rows = np.repeat(rows_np, lens)
            v = vals.astype(np.int64)
            flat_idx = rows * bits.WORDS_PER_CONTAINER + (v >> 6)
            bit = np.uint64(1) << (v & 63).astype(np.uint64)
            np.bitwise_or.at(out64.reshape(-1), flat_idx, bit)
    return out64.view(np.uint32)


@dataclass
class PackedGroups:
    """Key-grouped containers packed for device reduction.

    ``words``: device uint32 [N, 2048], rows sorted by group.
    ``group_keys``: int64 [G] high-16-bit chunk keys, ascending.
    ``group_offsets``: int64 [G+1] row ranges per group.
    """

    words: np.ndarray  # host uint32 [N, 2048]; shipped to device at reduce time
    group_keys: np.ndarray
    group_offsets: np.ndarray

    @property
    def n_rows(self) -> int:
        return int(self.group_offsets[-1])

    @property
    def n_groups(self) -> int:
        return len(self.group_keys)

    def _account_resident(self, kind: str, nbytes: int) -> None:
        """Track this working set's cached device bytes so the resident
        gauge goes back DOWN when the PackedGroups (and with it the cached
        arrays) is freed — a rise-only gauge would report cumulative bytes
        ever cached, not what is resident now."""
        held = getattr(self, "_resident_held", None)
        if held is None:
            held = {}
            object.__setattr__(self, "_resident_held", held)
        held[kind] = held.get(kind, 0) + int(nbytes)
        _RESIDENT_BYTES.inc(int(nbytes), (kind,))

    def close(self) -> None:
        """Release the cached device arrays and settle the resident-bytes
        gauge NOW, instead of whenever GC runs ``__del__`` — a long-lived
        process that drops working sets without closing them misreports
        residency for as long as collection is delayed. Idempotent (safe
        alongside ``__del__``), and a closed working set stays usable: the
        caches rebuild, re-ship, and re-account on next touch."""
        held = getattr(self, "_resident_held", None)
        if held:
            for kind, nbytes in held.items():
                _RESIDENT_BYTES.dec(nbytes, (kind,))
            held.clear()
        # drop the cached device arrays so HBM actually frees with the gauge
        for attr in ("_device_words", "_padded_cache", "_bucket_cache"):
            if getattr(self, attr, None) is not None:
                object.__setattr__(self, attr, None)

    def __enter__(self) -> "PackedGroups":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:  # pragma: no cover - interpreter teardown  # rb-ok: exception-hygiene -- __del__ during teardown: modules may already be torn down; raising here aborts GC
            pass

    @property
    def device_words(self) -> jnp.ndarray:
        """The flat rows on device (transferred once, then cached)."""
        d = getattr(self, "_device_words", None)
        if d is None:
            d = jnp.asarray(self.words)
            _TRANSFER_TOTAL.inc(self.words.nbytes, ("flat_rows",))
            self._account_resident("flat_rows", self.words.nbytes)
            object.__setattr__(self, "_device_words", d)
        return d

    def padded_device(self, fill: int, row_multiple: int = 1):
        """Dense-padded [G, M, W] rows on device, built once per (fill,
        row_multiple) and cached for the lifetime of the working set (the
        BSI ``_pack_cache`` pattern; VERDICT r2 weak #8 — repeat
        aggregations must not re-pad and re-ship)."""
        cache = getattr(self, "_padded_cache", None)
        if cache is None:
            cache = {}
            object.__setattr__(self, "_padded_cache", cache)
        key = (int(fill), int(row_multiple))
        if key not in cache:
            host = pad_groups_dense(self, fill, row_multiple)
            if host is None:
                cache[key] = None
            else:
                cache[key] = jnp.asarray(host)
                _TRANSFER_TOTAL.inc(host.nbytes, ("padded_groups",))
                self._account_resident("padded_groups", host.nbytes)
        return cache[key]

    def plan_buckets(self, n_buckets: int = 3) -> List[np.ndarray]:
        """The DP bucket plan for this working set, computed once per
        ``n_buckets`` (the counts never change after packing). prepare_reduce's
        cost model, the bucketed layout builder, and bench.py's occupancy
        accounting all consult the plan — uncached, each recomputed it
        (VERDICT r4 weak #2: the bucketed cold path pays repeated plan +
        fill costs the padded layout never did)."""
        cache = getattr(self, "_plan_cache", None)
        if cache is None:
            cache = {}
            object.__setattr__(self, "_plan_cache", cache)
        k = int(n_buckets)
        if k not in cache:
            cache[k] = bucket_plan(np.diff(self.group_offsets), k)
        return cache[k]

    def padded_buckets_device(self, fill: int, n_buckets: int = 3):
        """Ragged-batched padding: groups partitioned by row count into
        ``n_buckets`` contiguous-count buckets (optimal DP split), each
        padded to its own bucket-local M — cutting the dead HBM traffic a
        single [G, max(M), W] block pays on skewed group distributions
        (census1881 flagship: 76.5% -> 93.5% occupancy at 3 buckets).

        Returns a list of ``(orig_group_idx int64[g_b], jnp [g_b, m_b, W])``
        pairs, cached per (fill, n_buckets). The fill is one vectorized
        row scatter per bucket (same shape as pad_groups_dense's), not a
        per-group copy loop, and an OR-identity fill allocates zero pages
        lazily instead of writing the whole block twice."""
        cache = getattr(self, "_bucket_cache", None)
        if cache is None:
            cache = {}
            object.__setattr__(self, "_bucket_cache", cache)
        key = (int(fill), int(n_buckets))
        if key not in cache:
            import jax

            counts = np.diff(self.group_offsets)
            on_accel = jax.default_backend() != "cpu"
            flat = self.device_words if on_accel else None  # one cached ship
            out = []
            for idx in self.plan_buckets(n_buckets):
                g_b, m_b = len(idx), int(counts[idx].max())
                # all live rows of the bucket move in ONE vectorized step:
                # group idx[slot]'s local row p lands at flat slot*m_b + p
                b_counts = counts[idx]
                n_b = int(b_counts.sum())
                slot_rows = None
                src = None
                if n_b:
                    src = np.concatenate(
                        [
                            np.arange(self.group_offsets[gi], self.group_offsets[gi + 1])
                            for gi in idx
                        ]
                    )
                    slot_of_row = np.repeat(np.arange(g_b), b_counts)
                    local = np.arange(n_b) - np.repeat(
                        np.cumsum(np.concatenate(([0], b_counts[:-1]))), b_counts
                    )
                    slot_rows = slot_of_row * m_b + local
                if on_accel:
                    # device gather-with-fill from the already-shipped flat
                    # rows: pad cells point out of range so mode="fill"
                    # writes the op identity — the host never materializes
                    # (or ships) the padded copy, and the gather rides HBM
                    src_map = np.full(g_b * m_b, self.n_rows, dtype=np.int64)
                    if n_b:
                        src_map[slot_rows] = src
                    arr = jnp.take(
                        flat, jnp.asarray(src_map), axis=0, mode="fill",
                        fill_value=np.uint32(fill),
                    ).reshape(g_b, m_b, dev.DEVICE_WORDS)
                    # no host->device transfer happened here; tracked under
                    # its own key so the transfer ledger stays truthful
                    _TRANSFER_TOTAL.inc(int(arr.nbytes), ("padded_buckets_built_on_device",))
                    self._account_resident("padded_buckets", int(arr.nbytes))
                else:
                    # CPU backend: a host fill + alias is faster than an
                    # eager gather (an OR fill allocates its zero pages
                    # lazily instead of writing the block twice)
                    shape = (g_b, m_b, dev.DEVICE_WORDS)
                    if fill == 0:
                        block = np.zeros(shape, dtype=np.uint32)
                    else:
                        block = np.full(shape, fill, dtype=np.uint32)
                    if n_b:
                        block.reshape(g_b * m_b, dev.DEVICE_WORDS)[slot_rows] = (
                            self.words[src]
                        )
                    arr = jnp.asarray(block)
                    _TRANSFER_TOTAL.inc(int(block.nbytes), ("padded_buckets",))
                    self._account_resident("padded_buckets", int(block.nbytes))
                out.append((idx, arr))
            cache[key] = out
        return cache[key]


def group_by_key(
    bitmaps: Sequence[RoaringBitmap], keys_filter: Optional[set] = None
) -> Dict[int, List[Container]]:
    """Transpose bitmaps into key-major groups
    (ParallelAggregation.groupByKey, ParallelAggregation.java:136-153)."""
    groups: Dict[int, List[Container]] = {}
    for bm in bitmaps:
        hlc = bm.high_low_container
        for k, c in zip(hlc.keys, hlc.containers):
            if keys_filter is not None and k not in keys_filter:
                continue
            groups.setdefault(k, []).append(c)
    return groups


def intersect_keys(bitmaps: Sequence[RoaringBitmap]) -> set:
    """Keys present in every input (Util.intersectKeys analogue,
    Util.java:1244-1259) — the workShyAnd pre-filter."""
    it = iter(bitmaps)
    first = next(it)
    keys = set(first.high_low_container.keys)
    for bm in it:
        keys &= set(bm.high_low_container.keys)
        if not keys:
            break
    return keys


def pack_groups(groups: Dict[int, List[Container]]) -> PackedGroups:
    """Pack key-major groups into one host SoA array; the device transfer
    happens once in prepare_reduce after the layout choice, so rows are
    shipped exactly once in whichever layout they'll be reduced in."""
    group_keys = np.array(sorted(groups), dtype=np.int64)
    counts = np.array([len(groups[int(k)]) for k in group_keys], dtype=np.int64)
    offsets = np.concatenate(([0], np.cumsum(counts)))
    rows = [c for k in group_keys for c in groups[int(k)]]
    return PackedGroups(pack_rows_host(rows), group_keys, offsets)


def bucket_plan(counts: np.ndarray, n_buckets: int) -> List[np.ndarray]:
    """Partition group indices into ≤ ``n_buckets`` buckets minimizing total
    padded rows Σ g_b·max(M_b).

    Sorted by descending count, the optimal bucketing is a contiguous
    partition of the sorted order (any bucket's cost is len·its largest
    member, so swapping non-contiguous members never helps), found by an
    O(G²·K) DP — G is the number of 2^16-key groups (≤ 66 on the flagship
    set), so this is microseconds. Degenerate cases (G ≤ n_buckets, or a
    flat distribution) fall out naturally as fewer/equal buckets."""
    if n_buckets < 1:
        raise ValueError(f"n_buckets must be >= 1, got {n_buckets}")
    g = len(counts)
    if g == 0:
        return []
    order = np.argsort(-counts, kind="stable")
    srt = counts[order]
    k_max = min(int(n_buckets), g)
    INF = float("inf")
    # dp[i][k] = min padded rows covering sorted groups i.. with k buckets
    dp = np.full((g + 1, k_max + 1), INF)
    dp[g, :] = 0.0
    choice = np.zeros((g, k_max + 1), dtype=np.int64)
    for i in range(g - 1, -1, -1):
        for k in range(1, k_max + 1):
            spans = np.arange(i + 1, g + 1)
            costs = (spans - i) * srt[i] + dp[spans, k - 1]
            j = int(np.argmin(costs))
            dp[i, k] = costs[j]
            choice[i, k] = spans[j]
    cuts, i, k = [], 0, k_max
    while i < g:
        j = int(choice[i, k])
        cuts.append(order[i:j])
        i, k = j, k - 1
    return cuts


def pad_groups_dense(
    packed: PackedGroups, fill: int, row_multiple: int = 1
) -> Optional[np.ndarray]:
    """Dense [G, M, W] padding of a packed group set, M rounded up to
    ``row_multiple``; returns None when the distribution is too skewed to
    pad (the shared guard: padded cells > max(2*rows, 1024))."""
    g = packed.n_groups
    n = packed.n_rows
    counts = np.diff(packed.group_offsets)
    m = int(counts.max()) if g else 0
    m += (-m) % row_multiple
    if g * m > max(2 * n, 1024):
        return None
    padded = np.full((g, m, dev.DEVICE_WORDS), fill, dtype=np.uint32)
    if n:
        # one vectorized scatter instead of a per-group python loop: row r of
        # group gi at local position p lands at flat row gi*m + p
        group_of_row = np.repeat(np.arange(g), counts)
        local = np.arange(n) - np.repeat(packed.group_offsets[:-1], counts)
        padded.reshape(g * m, dev.DEVICE_WORDS)[group_of_row * m + local] = packed.words
    return padded


def prepare_reduce(packed: PackedGroups, op: str = "or"):
    """Build the device reduction closure for a packed group set.

    Returns ``(run, layout)`` where ``run()`` -> (reduced [G, 2048] device
    array, cards [G] device array) and ``layout`` is ``"padded"``,
    ``"bucketed"``, or ``"segmented-scan"``. Cost-model-driven choice on
    host-side row counts (measured on chip, BENCH_NOTES "Ragged batching"):

    * single dense block when its occupancy is already >= 0.9 — one
      dispatch, no scatter-back;
    * count-bucketed ragged batching when bucketing keeps total padded
      rows <= 1.5x the live rows — this also rescues most distributions
      the single-block guard rejects (e.g. one giant group + many tiny
      ones buckets to ~100% occupancy);
    * else the segmented scan (the truly irregular tail). The reference's
      answer to skew is splitting slices across the fork-join pool
      (ParallelAggregation.java:222-228). bench.py times exactly these
      closures, so the benchmark and production always run the same path.
    """
    n = packed.n_rows
    counts = np.diff(packed.group_offsets)
    g = packed.n_groups
    single_rows = int(g * counts.max()) if g else 0
    # empty sets keep the (trivial) single-block path
    if not g or not n or single_rows <= n / 0.9:
        dev_arr = packed.padded_device(dev._INIT[op])
        if dev_arr is not None:

            def run():
                from .. import tracing
                from ..ops import pallas_kernels as pk

                with tracing.op_timer("store.reduce.padded"):
                    return pk.best_grouped_reduce(dev_arr, op=op)

            _LAYOUT_TOTAL.inc(1, ("padded",))
            return run, "padded"
    if g and n:
        bucket_rows = sum(
            len(idx) * int(counts[idx].max())
            for idx in packed.plan_buckets(DEFAULT_BUCKETS)
        )
        if bucket_rows <= 1.5 * n:
            return prepare_reduce_bucketed(packed, op=op, n_buckets=DEFAULT_BUCKETS)

    seg_start = np.zeros(n, dtype=bool)
    seg_start[packed.group_offsets[:-1]] = True
    seg = jnp.asarray(seg_start)
    end_rows = jnp.asarray(packed.group_offsets[1:] - 1)
    words = packed.device_words

    def run():
        from .. import tracing
        from ..ops import pallas_kernels as pk

        with tracing.op_timer("store.reduce.segmented-scan"):
            vals = pk.best_segmented_reduce(words, seg, op=op)
            red = vals[end_rows]
            return red, dev.popcount_rows(red)

    _LAYOUT_TOTAL.inc(1, ("segmented-scan",))
    return run, "segmented-scan"


def prepare_reduce_bucketed(packed: PackedGroups, op: str = "or", n_buckets: int = 3):
    """Ragged-batched variant of prepare_reduce: one grouped reduce per
    count bucket (all inside one jit), results scattered back to ascending
    key order. Same (run, layout) contract; layout = "bucketed"."""
    import jax

    buckets = packed.padded_buckets_device(dev._INIT[op], n_buckets)
    if not buckets:  # empty working set: same contract as reduce_packed

        def run_empty():
            return (
                jnp.empty((0, dev.DEVICE_WORDS), dtype=jnp.uint32),
                jnp.empty((0,), dtype=jnp.int32),
            )

        _LAYOUT_TOTAL.inc(1, ("bucketed",))
        return run_empty, "bucketed"
    order = np.concatenate([idx for idx, _ in buckets])
    inv = jnp.asarray(np.argsort(order))

    # the per-bucket engine is the stock XLA grouped reduce directly: the
    # probing dispatcher (best_grouped_reduce) runs Python-side try-compiles
    # and cannot sit under this outer jit — and XLA is the measured flagship
    # winner anyway (BENCH_NOTES flagship post-mortem)
    @jax.jit
    def reduce_all(arrs):
        reds, cards = [], []
        # rb-ok: trace-safety -- arrs is a tuple-of-arrays pytree: the loop
        # unrolls over static structure at trace time, not traced values
        for a in arrs:
            r, c = dev.grouped_reduce_with_cardinality(a, op=op)
            reds.append(r)
            cards.append(c)
        return jnp.concatenate(reds, axis=0)[inv], jnp.concatenate(cards)[inv]

    arrs = tuple(a for _, a in buckets)

    def run():
        from .. import tracing

        with tracing.op_timer("store.reduce.bucketed"):
            return reduce_all(arrs)

    _LAYOUT_TOTAL.inc(1, ("bucketed",))
    return run, "bucketed"


def reduce_packed(packed: PackedGroups, op: str = "or"):
    """Reduce each key group on device; returns (words [G,2048] np.uint32,
    cards [G] np.int64)."""
    if packed.n_groups == 0:
        return (
            np.empty((0, dev.DEVICE_WORDS), dtype=np.uint32),
            np.empty((0,), dtype=np.int64),
        )
    run, _ = prepare_reduce(packed, op)
    red, card = run()
    return np.asarray(red), np.asarray(card).astype(np.int64)


def reduce_packed_cardinality(packed: PackedGroups, op: str = "or") -> np.ndarray:
    """Per-group cardinalities only: the reduced words stay on device — the
    host fetch is G ints, which is what makes N-way cardinality-only
    aggregation cheaper than materialize-then-count."""
    if packed.n_groups == 0:
        return np.empty((0,), dtype=np.int64)
    run, _ = prepare_reduce(packed, op)
    _red, card = run()
    return np.asarray(card).astype(np.int64)


def unpack_to_bitmap(
    group_keys: np.ndarray, words_u32: np.ndarray, cards: np.ndarray
) -> RoaringBitmap:
    """Stream device results back into a RoaringBitmap via the append path
    (RoaringArray.append, RoaringArray.java:111), re-compressing each chunk."""
    from .. import tracing

    with tracing.op_timer("store.unpack_to_bitmap"):
        return _unpack_to_bitmap(group_keys, words_u32, cards)


def iter_group_containers(group_keys: np.ndarray, words_u32: np.ndarray, cards: np.ndarray):
    """Yield ``(key, Container)`` per non-empty group with card-driven
    construction (the device already popcounted each group) — shared by the
    32-bit unpack, the 64-bit ART rebuild, and the NavigableMap rebuild."""
    from ..models.container import ArrayContainer

    words64 = np.ascontiguousarray(words_u32).view(np.uint64)
    for gi, key in enumerate(group_keys.tolist()):
        card = int(cards[gi])
        if card == 0:
            continue
        w = words64[gi]
        if card <= 4096:
            yield int(key), ArrayContainer(bits.values_from_words(w))
        else:
            yield int(key), BitmapContainer(w.copy(), card)


def _unpack_to_bitmap(group_keys, words_u32, cards) -> RoaringBitmap:
    out = RoaringBitmap()
    for key, c in iter_group_containers(group_keys, words_u32, cards):
        out.high_low_container.append(key, c)
    return out
