"""Device container store: SoA packing of containers onto the TPU.

The architectural inversion at the heart of this framework (SURVEY §7): the
reference walks containers pointer-by-pointer per bitmap
(ParallelAggregation.groupByKey, ParallelAggregation.java:136-153); here all
containers of a working set are transposed host-side into key-major order and
packed into ONE dense ``uint32 [N, 2048]`` device array plus small host-side
key/group tables. Aggregations then run as a single fused XLA/Pallas
computation over the whole set (ops/device.py) instead of a per-container
virtual-dispatch fold.

Array and run containers are expanded to bitmap words — the
``toBitmapContainer`` analogue (Container.java:987) — because on TPU the
dense form is the only one the VPU can chew on; results are re-compressed to
the best container form when streamed back (best_container_of_words, the
``repairAfterLazy`` + conversion step).

Since ISSUE 8 the expansion no longer happens on the host at pack time:
packing collects a compact :class:`RowPayload` (zero-copy borrows of array
values, run intervals, bitmap words), and the expansion to ``[N, 2048]``
word rows runs device-side at first touch (``ops/pallas_kernels
.expand_rows_device`` on accelerators; fused expand-into-the-staging-buffer
+ ``device_put`` on the CPU backend). Delta repacks patch the resident flat
rows with a DONATED row scatter — O(k·2048) words in place, never a
full-tensor copy — and back-to-back query traffic can stage the next
working set's expansion on the overlap lane (parallel/overlap.py) while the
current reduce runs.
"""

from __future__ import annotations

import os
import threading
import time as _time
import weakref
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from .. import observe as _observe
from ..observe import decisions as _decisions
from ..observe import outcomes as _outcomes
from ..observe import timeline as _timeline
from ..robust import errors as _rerrors
from ..robust import faults as _faults
from ..robust import ladder as _ladder

# marshal stage attribution (ISSUE 6): every pack / delta-repack stage
# lands in a log-bucketed latency histogram (p50/p99 in every export) and,
# when RB_TPU_TIMELINE is active, in the flight recorder — the named,
# summable decomposition bench.py's BENCH_TIMELINE.json is built from
_PACK_STAGE_SECONDS = _observe.latency_histogram(
    _observe.STORE_PACK_STAGE_SECONDS,
    "Wall time of marshal pack stages (key_plan | group_tables | "
    "payload_build | host_words | fingerprints | provenance | "
    "dense_pad_plan | device_expand | ship | overlap_wait | padded_build | "
    "bucket_build)",
    ("stage",),
)
_DELTA_STAGE_SECONDS = _observe.latency_histogram(
    _observe.STORE_DELTA_STAGE_SECONDS,
    "Wall time of incremental delta-repack stages (dirty_scan | "
    "host_rows | scatter | republish)",
    ("stage",),
)

# layout observability: ("padded"|"bucketed"|"segmented-scan") -> count.
# Registry-backed since ISSUE 1 (rb_tpu_store_layout_total); the CounterMap
# keeps the legacy mapping shape for insights.dispatch_counters().
_LAYOUT_TOTAL = _observe.counter(
    _observe.STORE_LAYOUT_TOTAL,
    "prepare_reduce layout choices (padded | bucketed | segmented-scan)",
    ("layout",),
)
LAYOUT_COUNTS = _observe.CounterMap(_LAYOUT_TOTAL, scalar=True)
# default ragged-batch bucket count for the prepare_reduce cost model;
# bench.py reuses it so reported occupancy always describes the production
# bucketing
DEFAULT_BUCKETS = 3
# host->device transfer accounting in bytes (insights.dispatch_counters)
_TRANSFER_TOTAL = _observe.counter(
    _observe.STORE_TRANSFER_BYTES_TOTAL,
    "Host->device transfer bytes by route (device-built blocks tracked "
    "under their own route so the ledger stays truthful)",
    ("route",),
)
TRANSFER_BYTES = _observe.CounterMap(_TRANSFER_TOTAL, scalar=True)
# bytes of device-resident working-set tensors cached by PackedGroups
_RESIDENT_BYTES = _observe.gauge(
    _observe.STORE_RESIDENT_BYTES,
    "Device-resident cached working-set bytes by layout kind",
    ("kind",),
)
# resident pack cache observability (ISSUE 4): entry kinds are
# agg | bsi | andnot | threshold (the four routed consumers)
_PACK_HITS = _observe.counter(
    _observe.PACK_CACHE_HITS_TOTAL,
    "Pack-cache lookups served resident (incl. delta-refreshed entries)",
    ("kind",),
)
_PACK_MISSES = _observe.counter(
    _observe.PACK_CACHE_MISSES_TOTAL,
    "Pack-cache lookups that paid a full host pack",
    ("kind",),
)
_PACK_DELTA_ROWS = _observe.counter(
    _observe.PACK_CACHE_DELTA_ROWS_TOTAL,
    "Rows re-packed and shipped by incremental delta repacks",
    ("kind",),
)
_PACK_EVICTED_BYTES = _observe.counter(
    _observe.PACK_CACHE_EVICTED_BYTES_TOTAL,
    "Bytes released by byte-budget LRU eviction",
    ("kind",),
)
_DEMOTE_TOTAL = _observe.counter(
    _observe.DURABLE_DEMOTE_TOTAL,
    "Evictions by residency rung (mapped = working set stays "
    "re-admittable from the persisted epoch mmap | discard = cold "
    "repack on return — the pre-durable behavior)",
    ("rung",),
)
_PACK_RESIDENT = _observe.gauge(
    _observe.PACK_CACHE_RESIDENT_BYTES,
    "Bytes currently resident in the pack cache by entry kind",
    ("kind",),
)
# device-memory reconciliation (ISSUE 9): accounting drift between what
# the gauges claim and independent ground truth — "ledger" checks the
# resident gauge against the cache's own entry-byte ledger (an internal
# invariant; nonzero = an accounting bug like the donation-consumed
# buffer leak this PR fixes), "device" checks it against the jax
# backend's bytes_in_use (framework-external residency; meaningful on
# accelerators, absent on backends without memory_stats)
_HBM_DRIFT = _observe.gauge(
    _observe.HBM_ACCOUNTING_DRIFT_BYTES,
    "Device-memory accounting drift: pack-cache resident gauge minus the "
    "named reconciliation source",
    ("source",),
)

from ..models.container import (
    ArrayContainer,
    BitmapContainer,
    Container,
    RunContainer,
)
from ..models.roaring import RoaringBitmap
from ..ops import device as dev
from ..utils import bits


def container_words_u32(c: Container) -> np.ndarray:
    """Expand any container to the uint32[2048] device word layout."""
    if isinstance(c, BitmapContainer):
        w = c.words
    else:
        w = c.to_words()
    return np.ascontiguousarray(w, dtype=np.uint64).view(np.uint32)


# ---------------------------------------------------------------------------
# compact marshal payloads + expansion dispatch (ISSUE 8 tentpole, leg 1)
# ---------------------------------------------------------------------------

# bytes per flat device row (uint32 [2048])
ROW_BYTES = dev.DEVICE_WORDS * 4

# Expansion mode for the flat device rows (RB_TPU_EXPAND / configure_expansion):
#   "auto"   — CPU backend: expand straight into the transfer staging buffer
#              (one materialization) and device_put it; accelerators: ship
#              the compact payload and run the fused jit expansion kernel.
#   "device" — force the jit expansion kernel on every backend (tests).
#   "host"   — the degradation path: host word expansion + device_put ship.
#   "legacy" — the pre-ISSUE-8 pipeline verbatim (eager ``jnp.asarray``
#              ship of host words): kept as the serial twin for the bench's
#              overlap row and as an emergency escape hatch.
_EXPAND_MODES = ("auto", "device", "host", "legacy")
_EXPAND_MODE = os.environ.get("RB_TPU_EXPAND", "auto").strip().lower() or "auto"
if _EXPAND_MODE not in _EXPAND_MODES:
    raise ValueError(
        f"RB_TPU_EXPAND must be one of {_EXPAND_MODES}, got {_EXPAND_MODE!r}"
    )


def configure_expansion(mode: str) -> None:
    """Runtime override of the flat-row expansion mode (see _EXPAND_MODES)."""
    global _EXPAND_MODE
    if mode not in _EXPAND_MODES:
        raise ValueError(f"expansion mode must be one of {_EXPAND_MODES}, got {mode!r}")
    _EXPAND_MODE = mode


def expansion_mode() -> str:
    return _EXPAND_MODE


class RowPayload:
    """Compact marshal payload for one packed row block: array value
    vectors, run interval vectors, and bitmap word arrays collected (as
    zero-copy borrows of the container internals) in ONE pass, instead of
    expanding every container to 8 KiB of words on the host up front —
    the r08 ``pack.host_words`` wall. All data movement (value
    concatenation, bitmap stacking, word expansion, the host→HBM ship)
    happens at *expansion* time, on whichever side of the PCIe the
    expansion mode picks.

    Because rows are borrows, the payload snapshots container *identity*,
    not container bytes: a packed row mutated in place after packing reads
    through. That is exactly the pack-cache contract — every tracked
    mutation delta-repacks its rows (``PackedGroups.apply_delta`` row
    overrides), and untracked mutation-during-use was already unspecified
    at the bitmap level (see ``apply_delta``)."""

    __slots__ = ("n_rows", "arr_rows", "arr_vals", "bmp_rows", "bmp_list",
                 "run_rows", "run_starts", "run_lengths", "n_values",
                 "n_run_intervals", "_mat")

    def __init__(self):
        self.n_rows = 0
        self.arr_rows: List[int] = []
        self.arr_vals: List[np.ndarray] = []
        self.bmp_rows: List[int] = []
        self.bmp_list: List[np.ndarray] = []
        self.run_rows: List[int] = []
        self.run_starts: List[np.ndarray] = []
        self.run_lengths: List[np.ndarray] = []
        self.n_values = 0
        self.n_run_intervals = 0
        self._mat = None

    def append(self, c: Container) -> None:
        """Add one container as the next row (type-partitioned borrow)."""
        i = self.n_rows
        self.n_rows = i + 1
        t = c.__class__
        if t is ArrayContainer:
            self.arr_rows.append(i)
            self.arr_vals.append(c.content)
            self.n_values += len(c.content)
        elif t is BitmapContainer:
            self.bmp_rows.append(i)
            self.bmp_list.append(c.words)
        elif t is RunContainer:
            self.run_rows.append(i)
            self.run_starts.append(c.starts)
            self.run_lengths.append(c.lengths)
            self.n_run_intervals += len(c.starts)
        else:  # unknown container type: expand now, carry as a word row
            self.bmp_rows.append(i)
            self.bmp_list.append(c.to_words())

    @classmethod
    def from_containers(cls, containers: Sequence[Container]) -> "RowPayload":
        p = cls()
        for c in containers:
            p.append(c)
        return p

    @property
    def nbytes(self) -> int:
        """Wire size of the compact payload (what the device expansion
        path actually ships, vs ``n_rows * ROW_BYTES`` expanded)."""
        return (
            self.n_values * 2
            + self.n_run_intervals * 4
            + len(self.bmp_rows) * bits.WORDS_PER_CONTAINER * 8
            + (len(self.arr_rows) + len(self.run_rows) + len(self.bmp_rows)) * 8
        )

    def materialize(self):
        """Concatenate the borrows into flat numpy arrays (cached):
        ``(arr_rows, arr_offsets, arr_values, bmp_rows, bmp_words64,
        run_rows, run_counts, run_starts, run_lengths)``."""
        m = self._mat
        if m is None:
            arr_rows = np.asarray(self.arr_rows, dtype=np.int64)
            lens = np.fromiter(
                (v.size for v in self.arr_vals), np.int64, len(self.arr_vals)
            )
            arr_offsets = np.concatenate(([0], np.cumsum(lens)))
            arr_values = (
                np.concatenate(self.arr_vals)
                if self.arr_vals
                else np.empty(0, np.uint16)
            )
            bmp_rows = np.asarray(self.bmp_rows, dtype=np.int64)
            bmp_words = (
                np.stack(self.bmp_list)
                if self.bmp_list
                else np.empty((0, bits.WORDS_PER_CONTAINER), np.uint64)
            )
            run_rows = np.asarray(self.run_rows, dtype=np.int64)
            run_counts = np.fromiter(
                (s.size for s in self.run_starts), np.int64, len(self.run_starts)
            )
            run_starts = (
                np.concatenate(self.run_starts)
                if self.run_starts
                else np.empty(0, np.uint16)
            )
            run_lengths = (
                np.concatenate(self.run_lengths)
                if self.run_lengths
                else np.empty(0, np.uint16)
            )
            m = self._mat = (
                arr_rows, arr_offsets, arr_values, bmp_rows, bmp_words,
                run_rows, run_counts, run_starts, run_lengths,
            )
        return m

    def expand_host(self, aligned: bool = False) -> np.ndarray:
        """Expand to the uint32 [n, 2048] host word block — the
        ``pack.host_words`` path, and the single source of truth the
        device expansion kernel is differential-tested against. Bitmap
        rows bulk-copy; array values scatter in one C-level pass (native
        kernel or ``np.bitwise_or.at``); run rows fill per interval.

        ``aligned=True`` allocates the block 64-byte aligned — the
        transfer *staging* discipline: jax's CPU client zero-copies
        aligned host buffers on ``device_put`` (measured 0.6 ms vs 430 ms
        for 631 MB on jax 0.4.37), and on accelerators pinned/aligned
        staging is what DMA engines want anyway. Only the expansion
        staging path uses it (the buffer's sole post-ship holder is the
        device array); the retained host mirror (``.words``) stays an
        independent allocation so host-side delta writes can never alias
        a live device buffer."""
        (arr_rows, arr_offsets, arr_values, bmp_rows, bmp_words,
         run_rows, run_counts, run_starts, run_lengths) = self.materialize()
        out64 = (
            _aligned_zero_rows(self.n_rows)
            if aligned
            else np.zeros((self.n_rows, bits.WORDS_PER_CONTAINER), dtype=np.uint64)
        )
        if len(bmp_rows):
            out64[bmp_rows] = bmp_words
        if len(arr_rows):
            from .. import native

            if native.available():
                native.pack_array_rows(arr_rows, arr_offsets, arr_values, out64)
            else:
                lens = np.diff(arr_offsets)
                rows = np.repeat(arr_rows, lens)
                v = arr_values.astype(np.int64)
                flat_idx = rows * bits.WORDS_PER_CONTAINER + (v >> 6)
                bit = np.uint64(1) << (v & 63).astype(np.uint64)
                np.bitwise_or.at(out64.reshape(-1), flat_idx, bit)
        if len(run_rows):
            off = 0
            for r, cnt in zip(run_rows.tolist(), run_counts.tolist()):
                row = out64[r]
                for s, l in zip(
                    run_starts[off:off + cnt].tolist(),
                    run_lengths[off:off + cnt].tolist(),
                ):
                    bits.set_bitmap_range(row, s, s + l + 1)
                off += cnt
        return out64.view(np.uint32)

    def device_kernel_args(self):
        """Prep the (pow2-padded) host arrays for
        ``pallas_kernels.expand_rows_device``: per-value flat word indices
        + bit masks, run start/stop toggle indices into the compact
        run-row block, and the bitmap row block in device (uint32) layout.
        Out-of-range pad ids rely on scatter ``mode="drop"``."""
        (arr_rows, arr_offsets, arr_values, bmp_rows, bmp_words,
         run_rows, run_counts, run_starts, run_lengths) = self.materialize()
        if self.n_rows * dev.DEVICE_WORDS >= (1 << 31):
            raise _rerrors.TierUnavailable(
                f"payload expansion: {self.n_rows} rows overflow int32 indexing"
            )
        oob_flat = self.n_rows * dev.DEVICE_WORDS
        lens = np.diff(arr_offsets)
        rows = np.repeat(arr_rows, lens)
        v = arr_values.astype(np.int64)
        val_idx = (rows * dev.DEVICE_WORDS + (v >> 5)).astype(np.int32)
        val_bits = np.uint32(1) << (v & 31).astype(np.uint32)
        val_idx = dev.pad_pow2(val_idx, oob_flat)
        val_bits = dev.pad_pow2(val_bits, 0)
        kb = len(bmp_rows)
        kbp = dev.pow2(kb)
        bmp_rows_p = np.full(kbp, self.n_rows, dtype=np.int32)
        bmp_rows_p[:kb] = bmp_rows
        bmp_w = np.zeros((kbp, dev.DEVICE_WORDS), dtype=np.uint32)
        if kb:
            bmp_w[:kb] = np.ascontiguousarray(bmp_words).view(np.uint32).reshape(
                kb, dev.DEVICE_WORDS
            )
        kr = len(run_rows)
        krp = dev.pow2(kr)
        run_rows_p = np.full(krp, self.n_rows, dtype=np.int32)
        run_rows_p[:kr] = run_rows
        # toggle bits: start s turns the fill on, stop e+1 turns it off.
        # Starts and stops ship as SEPARATE scatter streams (the kernel
        # XORs the two accumulators): within each stream sorted disjoint
        # runs make every bit distinct, while a stop may legally land on
        # the NEXT run's start bit (adjacent runs — the portable format
        # does not forbid them) and must cancel it, not carry into the
        # neighbouring bit. A stop past the row end simply never fires.
        compact = np.repeat(np.arange(kr, dtype=np.int64), run_counts)
        s = run_starts.astype(np.int64)
        e1 = s + run_lengths.astype(np.int64) + 1
        ts_idx = (compact * dev.DEVICE_WORDS + (s >> 5)).astype(np.int32)
        ts_bit = np.uint32(1) << (s & 31).astype(np.uint32)
        in_row = e1 < (1 << 16)
        te_idx = (
            compact[in_row] * dev.DEVICE_WORDS + (e1[in_row] >> 5)
        ).astype(np.int32)
        te_bit = np.uint32(1) << (e1[in_row] & 31).astype(np.uint32)
        oob_tog = krp * dev.DEVICE_WORDS
        ts_idx = dev.pad_pow2(ts_idx, oob_tog)
        ts_bit = dev.pad_pow2(ts_bit, 0)
        te_idx = dev.pad_pow2(te_idx, oob_tog)
        te_bit = dev.pad_pow2(te_bit, 0)
        return (bmp_rows_p, bmp_w, val_idx, val_bits, run_rows_p,
                ts_idx, ts_bit, te_idx, te_bit)


def _aligned_zero_rows(n_rows: int, align: int = 64) -> np.ndarray:
    """Zeroed uint64 [n_rows, 1024] block whose base address is
    ``align``-byte aligned (see ``RowPayload.expand_host``). numpy's
    allocator only guarantees 16 bytes; over-allocate and slice."""
    n = int(n_rows) * bits.WORDS_PER_CONTAINER * 8
    raw = np.zeros(n + align, dtype=np.uint8)
    off = (-raw.ctypes.data) % align
    return raw[off:off + n].view(np.uint64).reshape(
        n_rows, bits.WORDS_PER_CONTAINER
    )


def _expand_payload_device(payload: RowPayload):
    """The device-side expansion dispatch (ISSUE 8 leg 1). On accelerators
    the compact payload ships and the fused jit kernel scatters/fills it
    into the flat rows on device (``pack.host_words`` leaves the host
    timeline entirely). On the CPU backend the "device" is host memory, so
    the honest expression is expanding straight into the transfer staging
    buffer and handing it to the device in one step — one materialization
    where the legacy path paid host-words *plus* a slow eager ship."""
    if _EXPAND_MODE != "device" and jax.default_backend() == "cpu":
        # aligned staging: the CPU client zero-copies the buffer, so the
        # expansion write IS the ship — no second materialization. The
        # staging array's only holder after this line is the device array.
        return (
            jax.device_put(payload.expand_host(aligned=True)),
            payload.n_rows * ROW_BYTES,
        )
    from ..ops import pallas_kernels as pk

    return (
        pk.expand_rows_device(payload.n_rows, *payload.device_kernel_args()),
        payload.nbytes,
    )


def _guarded_expand_payload(payload: "RowPayload"):
    """One device-side expansion through the fault model: the
    ``store.expand`` (kernel/staging failure) and ``store.hbm`` (OOM)
    sites fire here; transients retry with jittered bounded backoff
    (idempotent — the expansion builds a fresh buffer every attempt,
    unlike the donated delta scatter)."""

    def _attempt():
        _faults.fault_point("store.expand")
        _faults.fault_point("store.hbm")
        d, nbytes = _expand_payload_device(payload)
        return _timeline.fence(d), nbytes

    return _ladder.retry("store.expand", _attempt)


def _expand_host_staged(payload: "RowPayload") -> np.ndarray:
    """``payload.expand_host()`` under the ``pack.host_words`` staging."""
    from .. import tracing

    with tracing.op_timer("store.pack_rows_host"), _timeline.stage(
        _PACK_STAGE_SECONDS, "host_words", "pack.host_words", cat="pack",
        rows=payload.n_rows,
    ):
        return payload.expand_host()


def pack_rows_host(containers: Sequence[Container]) -> np.ndarray:
    """Expand containers into one uint32 [N, 2048] host array (the
    ``pack.host_words`` path — now the payload's host expansion, so the
    fallback tier and the device kernel's differential oracle are the same
    code by construction)."""
    return _expand_host_staged(RowPayload.from_containers(containers))


def _expand_rows_or_ship(payload: Optional["RowPayload"], host_words,
                         patch=None, retained_mirror=False):
    """The expand-or-degrade dispatch shared by ``ship_rows`` and
    ``PackedGroups._expand_or_ship``: returns ``(device_rows, route,
    bytes)``. Primary: device-side payload expansion (stage
    ``device_expand``, route ``payload_expand``), with ``patch`` applied to
    the freshly expanded rows while still inside the degradable region.
    Fallback (``payload`` None, mode "host"/"legacy", or a non-fatal
    expansion failure): the ``host_words`` callable's expansion + ship
    (stage ``ship``, route ``flat_rows``) — exactly the legacy staging, so
    the degraded timeline shows ``pack.host_words`` + ``pack.ship`` again.

    ``retained_mirror=True`` marks ``host_words`` as returning a block the
    caller KEEPS and later mutates in place (the ``.words`` delta mirror):
    jax's CPU client zero-copies chance-64-byte-aligned arrays on
    ``device_put``, which would alias the live device rows to the mutable
    mirror — those ship through a fresh aligned staging copy whose sole
    post-ship holder is the device array."""
    mode = _EXPAND_MODE
    if payload is not None and mode not in ("host", "legacy"):
        try:
            with _timeline.stage(
                _PACK_STAGE_SECONDS, "device_expand", "pack.device_expand",
                cat="pack", rows=payload.n_rows,
            ):
                d, nbytes = _guarded_expand_payload(payload)
            if patch is not None:
                d = patch(d)
            return d, "payload_expand", nbytes
        except Exception as e:
            if _rerrors.classify(e) == _rerrors.FATAL:
                raise
            _ladder.LADDER.note_degrade(
                "store.expand", "device-expand", "host-words", e
            )
    w = host_words()  # materializes under the host_words stage
    with _timeline.stage(
        _PACK_STAGE_SECONDS, "ship", "pack.ship", cat="pack",
        bytes=int(w.nbytes),
    ):
        if mode == "legacy":
            # the pre-ISSUE-8 eager ship, byte for byte — the bench's
            # serial overlap twin and the emergency escape hatch
            d = PackedGroups._guarded_ship(lambda: jnp.asarray(w))
        elif retained_mirror:
            staging = _aligned_zero_rows(w.shape[0]).view(np.uint32)
            np.copyto(staging, w)
            d = PackedGroups._guarded_ship(lambda: jax.device_put(staging))
        else:
            d = PackedGroups._guarded_ship(lambda: jax.device_put(w))
    return d, "flat_rows", w.nbytes


def ship_rows(containers: Sequence[Container]):
    """Expand a bare container list straight to flat device rows (uint32
    [n, 2048]) through the same expansion dispatch + fault path as the
    packed working sets — the query kernels' first-operand rows ride the
    device-side expansion too (ISSUE 8), with the host ``pack.host_words``
    + ship staging as the bit-exact degradation."""
    payload = RowPayload.from_containers(containers)
    d, route, nbytes = _expand_rows_or_ship(
        payload, lambda: _expand_host_staged(payload)
    )
    _TRANSFER_TOTAL.inc(int(nbytes), (route,))
    return d


class PackedGroups:
    """Key-grouped containers packed for device reduction.

    ``group_keys``: int64 [G] high-16-bit chunk keys, ascending.
    ``group_offsets``: int64 [G+1] row ranges per group.

    Row data lives in ONE of two forms (ISSUE 8): a compact
    :class:`RowPayload` (the marshal path — host words and device rows
    both expand lazily from it), or an eager host word block handed to the
    constructor (legacy callers and tests). ``words`` is now a *property*:
    reading it materializes the uint32 [N, 2048] host block on demand —
    the device paths never touch it, so a device-expanded working set
    skips the host-words materialization entirely.

    ``_row_overrides`` carries delta rows applied while the host block was
    not materialized (payload borrows stay pre-delta); both the host
    materialization and a device re-expansion replay them, so every view
    converges on the post-delta bits. ``_buffer_gen`` counts donated
    device-buffer replacements — a consumer that captured the flat rows
    before a delta must re-read ``device_words`` (the donated buffer is
    consumed, never served stale; see ``apply_delta``)."""

    def __init__(self, words, group_keys, group_offsets, payload=None):
        if words is None and payload is None:
            raise ValueError("PackedGroups needs host words or a RowPayload")
        self._host_words = words
        self.group_keys = group_keys
        self.group_offsets = group_offsets
        self._payload = payload
        self._row_overrides: Dict[int, np.ndarray] = {}
        self._layout_epoch = 0
        self._buffer_gen = 0
        self._device_words = None
        self._padded_cache = None
        self._bucket_cache = None
        self._plan_cache = None
        self._resident_held = None
        self._resident_cb = None
        self._cache_held = False
        self._reduce_touches: Dict[int, int] = {}

    @property
    def n_rows(self) -> int:
        return int(self.group_offsets[-1])

    @property
    def n_groups(self) -> int:
        return len(self.group_keys)

    @property
    def words_nbytes(self) -> int:
        """Expanded size of the flat row block — the working set's weight
        for cache budgeting, WITHOUT forcing the host materialization."""
        return self.n_rows * ROW_BYTES

    @property
    def words(self) -> np.ndarray:
        """The uint32 [N, 2048] host word block, materialized on demand
        from the payload (plus any delta row overrides). Device paths
        never read this; CPU-side consumers (the mesh-sharded reduce,
        differential tests) pay the expansion on first touch."""
        w = self._host_words
        if w is None:
            from .. import tracing

            with tracing.op_timer("store.pack_rows_host"), _timeline.stage(
                _PACK_STAGE_SECONDS, "host_words", "pack.host_words",
                cat="pack", rows=self.n_rows,
            ):
                w = self._payload.expand_host()
            for r, row in self._row_overrides.items():
                w[r] = row
            self._row_overrides.clear()  # the mirror is the truth now
            self._host_words = w
        return w

    def _account_resident(self, kind: str, nbytes: int) -> None:
        """Track this working set's cached device bytes so the resident
        gauge goes back DOWN when the PackedGroups (and with it the cached
        arrays) is freed — a rise-only gauge would report cumulative bytes
        ever cached, not what is resident now."""
        held = self._resident_held
        if held is None:
            held = self._resident_held = {}
        held[kind] = held.get(kind, 0) + int(nbytes)
        _RESIDENT_BYTES.inc(int(nbytes), (kind,))
        self._notify_resident(int(nbytes))

    def _notify_resident(self, delta: int) -> None:
        """Report a device-residency change to the owning pack cache (if
        any): derived layouts (flat ship, padded blocks, buckets) are built
        lazily AFTER the cache stores the entry, and a byte budget that
        only counted the host words would let real HBM run ~3x past it."""
        cb = self._resident_cb
        if cb is not None:
            cb(delta)

    def close(self) -> None:
        """Release the cached device arrays and settle the resident-bytes
        gauge NOW, instead of whenever GC runs ``__del__`` — a long-lived
        process that drops working sets without closing them misreports
        residency for as long as collection is delayed. Idempotent (safe
        alongside ``__del__``), and a closed working set stays usable: the
        caches rebuild, re-ship, and re-account on next touch.

        Cache-aware (ISSUE 4): while the working set is resident in the
        pack cache, the CACHE owns lifetime — a consumer's ``close()`` (or
        ``__del__``) is a no-op, because yanking the device arrays out from
        under every other consumer sharing the entry would silently
        re-pack/re-ship on their next touch. The cache's evictor releases
        ownership first and then really closes."""
        if self._cache_held:
            return
        self._drop_derived()
        held = self._resident_held
        if held:
            for kind, nbytes in held.items():
                _RESIDENT_BYTES.dec(nbytes, (kind,))
                self._notify_resident(-int(nbytes))
            held.clear()
        # drop the flat device rows so HBM actually frees with the gauge
        self._device_words = None

    def _drop_derived(self) -> None:
        """Drop the padded/bucketed layout caches (and settle their share of
        the resident gauge) while keeping the flat device rows — the delta
        repack path updates the flat rows in place and lets the derived
        layouts rebuild from them on next touch (on accelerators that is a
        device-side gather, zero host transfer)."""
        held = self._resident_held
        if held:
            for kind in ("padded_groups", "padded_buckets"):
                nbytes = held.pop(kind, None)
                if nbytes:
                    _RESIDENT_BYTES.dec(nbytes, (kind,))
                    self._notify_resident(-int(nbytes))
        self._padded_cache = None
        self._bucket_cache = None

    def _drop_flat(self) -> None:
        """Drop the flat device rows AND settle their resident accounting
        (gauge + cache byte ledger) in the same step. The delta path's
        donation-failure branches used to null ``_device_words`` bare,
        leaving ``flat_rows`` bytes on the gauge with no backing array —
        the next ``device_words`` rebuild then re-accounted the same rows
        and the gauge drifted one block high per failed delta (ISSUE 9
        satellite; the ``hbm_reconciliation`` ledger check now watches
        for exactly this class of leak)."""
        self._device_words = None
        held = self._resident_held
        if held:
            nbytes = held.pop("flat_rows", None)
            if nbytes:
                _RESIDENT_BYTES.dec(nbytes, ("flat_rows",))
                self._notify_resident(-int(nbytes))

    def apply_delta(self, rows: np.ndarray, new_words_u32: np.ndarray) -> None:
        """Incremental repack: replace ``rows`` of the flat layout with
        freshly expanded container words. The host view updates in place
        when materialized (row *overrides* otherwise — the compact payload
        stays untouched and both later materializations replay them), and
        the resident device rows are patched with ONE **donated** row
        scatter (``pallas_kernels.scatter_rows_donated``): XLA reuses the
        existing HBM buffer, so a k-row delta writes O(k·2048) words
        instead of copying the whole flat tensor — the r08 ``delta.scatter``
        inversion fix. Ships O(len(rows)) bytes; the group structure
        (keys, offsets, bucket plans) is unchanged by contract —
        structural changes take the full-repack path in PackCache.

        Donation consumes the old device array: ``_buffer_gen`` bumps and
        every derived layout drops, so the cache can never serve the
        donated-away buffer (a consumer still holding it gets a loud
        deleted-buffer error, never stale bits — the aliasing guard the
        lazy builders' retry loop rides on).

        The epoch bump FIRST: any lazy layout build in flight on another
        thread snapshots the epoch before reading the flat rows and
        discards (or retries) its result on mismatch, so a concurrent
        build can never publish a pre-delta (or torn) array as this
        entry's current layout. (A caller racing a mutation against its
        own query still gets unspecified transient results — that race
        exists at the bitmap level already.)"""
        self._layout_epoch = self._epoch() + 1
        with _timeline.stage(
            _DELTA_STAGE_SECONDS, "scatter", "delta.scatter", cat="delta",
            rows=len(rows), bytes=int(new_words_u32.nbytes),
        ):
            if self._host_words is not None:
                self._host_words[rows] = new_words_u32
            else:
                for r, row in zip(rows.tolist(), new_words_u32):
                    self._row_overrides[int(r)] = np.array(row, copy=True)
                # override mass beyond a quarter of the block: fold into a
                # real host mirror once instead of carrying it forever
                if len(self._row_overrides) * ROW_BYTES > max(
                    1 << 20, self.words_nbytes // 4
                ):
                    _ = self.words  # materializes + clears the overrides
            d = self._device_words
            if d is not None:
                from ..ops import pallas_kernels as pk

                def _ship_delta():
                    # single-shot guard (no retry): donation consumes the
                    # input buffer, so a second attempt would scatter into
                    # a dead array — transients degrade to a re-ship below
                    _faults.fault_point("store.ship")
                    _faults.fault_point("store.hbm")
                    return _timeline.fence(
                        pk.scatter_rows_donated(d, rows, new_words_u32)
                    )

                try:
                    shipped = _ship_delta()
                except Exception as e:
                    if d.is_deleted():
                        # the failed scatter consumed the buffer: never
                        # leave a poisoned array published (accounting
                        # settled too — see _drop_flat)
                        self._drop_flat()
                    if _rerrors.classify(e) == _rerrors.FATAL:
                        raise
                    # the host view is already updated; dropping the device
                    # rows degrades the next consumer to a re-ship instead
                    # of serving a stale resident tensor — with the
                    # flat_rows bytes released alongside, so the resident
                    # gauge never carries a donation-consumed buffer
                    _ladder.LADDER.note_degrade("store.ship", "device", "re-ship", e)
                    self._drop_flat()
                else:
                    self._device_words = shipped
                    self._buffer_gen += 1
                    _TRANSFER_TOTAL.inc(int(new_words_u32.nbytes), ("pack_delta",))
        with _timeline.stage(
            _DELTA_STAGE_SECONDS, "republish", "delta.republish", cat="delta"
        ):
            self._drop_derived()

    def __enter__(self) -> "PackedGroups":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:  # pragma: no cover - interpreter teardown  # rb-ok: exception-hygiene -- __del__ during teardown: modules may already be torn down; raising here aborts GC
            pass

    def _epoch(self) -> int:
        """Layout epoch, bumped by every apply_delta. Lazy layout builders
        snapshot it before reading ``words`` and refuse to PUBLISH (cache /
        account) a build that raced a delta — the racing consumer still
        gets a usable snapshot for its own call, but a possibly-stale array
        can never outlive the race as the entry's current layout."""
        return self._layout_epoch

    @staticmethod
    def _guarded_ship(build):
        """Run one host->HBM ship/build through the fault model (ISSUE 7):
        the ``store.ship`` (transient transfer) and ``store.hbm`` (OOM)
        fault sites fire here, transients retry with jittered bounded
        backoff, and anything that survives retry propagates to the tier
        ladder above — which rides the aggregation down to a CPU tier
        instead of failing the caller."""

        def _attempt():
            _faults.fault_point("store.ship")
            _faults.fault_point("store.hbm")
            return _timeline.fence(build())

        return _ladder.retry("store.ship", _attempt)

    @property
    def device_words(self) -> jnp.ndarray:
        """The flat rows on device (built once, then cached). Built by
        device-side payload expansion when the working set carries a
        compact payload (ISSUE 8 leg 1) — the ``store.expand`` fault site
        covers that path, and any non-fatal failure degrades to the host
        ``pack.host_words`` expansion + ship, bit-exact by construction."""
        d = self._device_words
        if d is None:
            epoch = self._epoch()
            d, route, nbytes = self._expand_or_ship()
            if self._epoch() != epoch:
                return d  # raced a delta repack: do not publish
            _TRANSFER_TOTAL.inc(int(nbytes), (route,))
            self._account_resident("flat_rows", self.words_nbytes)
            self._device_words = d
        return d

    def _expand_or_ship(self):
        """Build the flat device rows: ``(array, transfer_route, bytes)``
        via the shared :func:`_expand_rows_or_ship` dispatch — the payload
        leg only when the host mirror is not already materialized, with
        any pre-materialization delta rows replayed onto the freshly
        expanded block (donated: it has no other holders yet)."""

        def _replay_overrides(d):
            if not self._row_overrides:
                return d
            from ..ops import pallas_kernels as pk

            rows = np.fromiter(
                self._row_overrides, np.int64, len(self._row_overrides)
            )
            delta = np.stack([self._row_overrides[int(r)] for r in rows])
            return pk.scatter_rows_donated(d, rows, delta)

        return _expand_rows_or_ship(
            self._payload if self._host_words is None else None,
            lambda: self.words,  # materializes under the host_words stage
            patch=_replay_overrides,
            retained_mirror=True,  # .words takes in-place delta writes
        )

    def _gather_guard(self, epoch: int, attempt: int, exc: Exception) -> bool:
        """The donated-buffer race guard for lazy layout builds: a delta's
        donated scatter may CONSUME the flat buffer a build captured (the
        gather then raises a deleted-buffer error instead of reading stale
        rows — the aliasing guarantee). When the epoch moved, the attempt
        was invalid anyway: retry against the current rows. Same-epoch
        failures propagate."""
        return self._epoch() != epoch and attempt < 4

    def padded_device(self, fill: int, row_multiple: int = 1):
        """Dense-padded [G, M, W] rows on device, built once per (fill,
        row_multiple) and cached for the lifetime of the working set (the
        BSI pack-cache pattern; VERDICT r2 weak #8 — repeat aggregations
        must not re-pad and re-ship). Built by a device-side gather from
        the already-resident flat rows on EVERY backend (ISSUE 8: the flat
        rows are device-built now, so the old host fill would be a second
        full materialization) — a delta repack that patched the flat rows
        rebuilds this layout with ZERO host transfer."""
        cache = self._padded_cache
        if cache is None:
            cache = self._padded_cache = {}
        key = (int(fill), int(row_multiple))
        attempt = 0
        while key not in cache:
            attempt += 1
            epoch = self._epoch()
            g, n = self.n_groups, self.n_rows
            plan = dense_pad_plan(self.group_offsets, row_multiple)
            if plan is None:  # the shared skew guard
                cache[key] = None
                break
            if _EXPAND_MODE == "legacy" and jax.default_backend() == "cpu":
                # the pre-ISSUE-8 CPU staging verbatim (host fill + eager
                # asarray ship) — the bench's serial overlap twin measures
                # the whole legacy marshal, not just the flat leg
                with _timeline.stage(
                    _PACK_STAGE_SECONDS, "padded_build", "pack.padded_build",
                    cat="pack", groups=self.n_groups, on_device=0,
                ):
                    host = pad_groups_dense(self, int(fill), row_multiple)
                    arr = self._guarded_ship(lambda: jnp.asarray(host))
                if self._epoch() != epoch:
                    return arr
                _TRANSFER_TOTAL.inc(int(host.nbytes), ("padded_groups",))
                self._account_resident("padded_groups", int(host.nbytes))
                cache[key] = arr
                break
            try:
                with _timeline.stage(
                    _PACK_STAGE_SECONDS, "padded_build", "pack.padded_build",
                    cat="pack", groups=g, on_device=1,
                ):
                    m, slots = plan
                    flat = self.device_words  # one cached expansion/ship
                    src_map = np.full(g * m, n, dtype=np.int64)
                    src_map[slots] = np.arange(n)
                    arr = self._guarded_ship(
                        lambda: jnp.take(
                            flat, jnp.asarray(src_map), axis=0, mode="fill",
                            fill_value=np.uint32(fill),
                        ).reshape(g, m, dev.DEVICE_WORDS)
                    )
            except Exception as e:
                if self._gather_guard(epoch, attempt, e):
                    continue
                raise
            if self._epoch() != epoch:
                return arr  # raced a delta repack: do not publish
            _TRANSFER_TOTAL.inc(int(arr.nbytes), ("padded_groups_built_on_device",))
            self._account_resident("padded_groups", int(arr.nbytes))
            cache[key] = arr
        return cache[key]

    def plan_buckets(self, n_buckets: int = 3) -> List[np.ndarray]:
        """The DP bucket plan for this working set, computed once per
        ``n_buckets`` (the counts never change after packing). prepare_reduce's
        cost model, the bucketed layout builder, and bench.py's occupancy
        accounting all consult the plan — uncached, each recomputed it
        (VERDICT r4 weak #2: the bucketed cold path pays repeated plan +
        fill costs the padded layout never did)."""
        cache = self._plan_cache
        if cache is None:
            cache = self._plan_cache = {}
        k = int(n_buckets)
        if k not in cache:
            cache[k] = bucket_plan(np.diff(self.group_offsets), k)
        return cache[k]

    def padded_buckets_device(self, fill: int, n_buckets: int = 3):
        """Ragged-batched padding: groups partitioned by row count into
        ``n_buckets`` contiguous-count buckets (optimal DP split), each
        padded to its own bucket-local M — cutting the dead HBM traffic a
        single [G, max(M), W] block pays on skewed group distributions
        (census1881 flagship: 76.5% -> 93.5% occupancy at 3 buckets).

        Returns a list of ``(orig_group_idx int64[g_b], jnp [g_b, m_b, W])``
        pairs, cached per (fill, n_buckets). Every bucket is ONE device
        gather-with-fill from the already-resident flat rows on every
        backend (ISSUE 8: the flat rows are device-built, so the old CPU
        host-fill branch would re-materialize the whole block on the host
        and pay a second full ship — the r09 48 s ``bucket_build_s``)."""
        cache = self._bucket_cache
        if cache is None:
            cache = self._bucket_cache = {}
        key = (int(fill), int(n_buckets))
        attempt = 0
        legacy_cpu = _EXPAND_MODE == "legacy" and jax.default_backend() == "cpu"
        while key not in cache:
            attempt += 1
            epoch = self._epoch()
            try:
                with _timeline.stage(
                    _PACK_STAGE_SECONDS, "bucket_build", "pack.bucket_build",
                    cat="pack", buckets=int(n_buckets), groups=self.n_groups,
                ):
                    counts = np.diff(self.group_offsets)
                    # legacy CPU staging (serial overlap twin): host fill +
                    # eager asarray ship per bucket, no resident flat rows
                    flat = None if legacy_cpu else self.device_words
                    out = []
                    pending_account = []  # published only if no delta raced
                    for idx in self.plan_buckets(n_buckets):
                        g_b, m_b = len(idx), int(counts[idx].max())
                        # all live rows of the bucket move in ONE vectorized
                        # gather: group idx[slot]'s local row p lands at flat
                        # slot*m_b + p; pad cells point out of range so
                        # mode="fill" writes the op identity — the host never
                        # materializes (or ships) the padded copy
                        b_counts = counts[idx]
                        n_b = int(b_counts.sum())
                        slot_rows = None
                        src = None
                        if n_b:
                            src = np.concatenate(
                                [
                                    np.arange(
                                        self.group_offsets[gi],
                                        self.group_offsets[gi + 1],
                                    )
                                    for gi in idx
                                ]
                            )
                            slot_of_row = np.repeat(np.arange(g_b), b_counts)
                            local = np.arange(n_b) - np.repeat(
                                np.cumsum(np.concatenate(([0], b_counts[:-1]))),
                                b_counts,
                            )
                            slot_rows = slot_of_row * m_b + local
                        if legacy_cpu:
                            # pre-ISSUE-8 CPU staging verbatim: host fill +
                            # eager asarray ship of the whole padded block
                            shape = (g_b, m_b, dev.DEVICE_WORDS)
                            if fill == 0:
                                block = np.zeros(shape, dtype=np.uint32)
                            else:
                                block = np.full(shape, fill, dtype=np.uint32)
                            if n_b:
                                block.reshape(g_b * m_b, dev.DEVICE_WORDS)[
                                    slot_rows
                                ] = self.words[src]
                            arr = self._guarded_ship(lambda: jnp.asarray(block))
                            pending_account.append(
                                ("padded_buckets", int(block.nbytes))
                            )
                            out.append((idx, arr))
                            continue
                        src_map = np.full(g_b * m_b, self.n_rows, dtype=np.int64)
                        if n_b:
                            src_map[slot_rows] = src
                        arr = self._guarded_ship(
                            lambda: jnp.take(
                                flat, jnp.asarray(src_map), axis=0, mode="fill",
                                fill_value=np.uint32(fill),
                            ).reshape(g_b, m_b, dev.DEVICE_WORDS)
                        )
                        pending_account.append(
                            ("padded_buckets_built_on_device", int(arr.nbytes))
                        )
                        out.append((idx, arr))
            except Exception as e:
                if self._gather_guard(epoch, attempt, e):
                    continue
                raise
            if self._epoch() != epoch:
                return out  # raced a delta repack: do not publish
            for route, nbytes in pending_account:
                _TRANSFER_TOTAL.inc(nbytes, (route,))
                self._account_resident("padded_buckets", nbytes)
            cache[key] = out
        return cache[key]


def group_by_key(
    bitmaps: Sequence[RoaringBitmap], keys_filter: Optional[set] = None
) -> Dict[int, List[Container]]:
    """Transpose bitmaps into key-major groups
    (ParallelAggregation.groupByKey, ParallelAggregation.java:136-153)."""
    with _timeline.stage(
        _PACK_STAGE_SECONDS, "key_plan", "pack.key_plan", cat="pack",
        bitmaps=len(bitmaps),
    ):
        groups: Dict[int, List[Container]] = {}
        for bm in bitmaps:
            hlc = bm.high_low_container
            for k, c in zip(hlc.keys, hlc.containers):
                if keys_filter is not None and k not in keys_filter:
                    continue
                groups.setdefault(k, []).append(c)
        return groups


def intersect_keys(bitmaps: Sequence[RoaringBitmap]) -> set:
    """Keys present in every input (Util.intersectKeys analogue,
    Util.java:1244-1259) — the workShyAnd pre-filter."""
    it = iter(bitmaps)
    first = next(it)
    keys = set(first.high_low_container.keys)
    for bm in it:
        keys &= set(bm.high_low_container.keys)
        if not keys:
            break
    return keys


def pack_groups(groups: Dict[int, List[Container]]) -> PackedGroups:
    """Pack key-major groups into a compact :class:`RowPayload` working
    set (ISSUE 8): the pack stage only collects type-partitioned borrows
    of the container internals — word expansion and the transfer happen
    once, lazily, on whichever side the expansion mode picks
    (``PackedGroups.device_words`` / ``.words``). This is what took
    ``pack.host_words`` (92 % of the r08 cold pack) off the marshal
    critical path."""
    with _timeline.stage(
        _PACK_STAGE_SECONDS, "group_tables", "pack.group_tables", cat="pack",
        groups=len(groups),
    ):
        group_keys = np.array(sorted(groups), dtype=np.int64)
        counts = np.array([len(groups[int(k)]) for k in group_keys], dtype=np.int64)
        offsets = np.concatenate(([0], np.cumsum(counts)))
    payload = RowPayload()
    with _timeline.stage(
        _PACK_STAGE_SECONDS, "payload_build", "pack.payload_build", cat="pack",
        rows=int(offsets[-1]),
    ):
        for k in group_keys:
            for c in groups[int(k)]:
                payload.append(c)
    return PackedGroups(None, group_keys, offsets, payload=payload)


def bucket_plan(counts: np.ndarray, n_buckets: int) -> List[np.ndarray]:
    """Partition group indices into ≤ ``n_buckets`` buckets minimizing total
    padded rows Σ g_b·max(M_b).

    Sorted by descending count, the optimal bucketing is a contiguous
    partition of the sorted order (any bucket's cost is len·its largest
    member, so swapping non-contiguous members never helps), found by an
    O(G²·K) DP — G is the number of 2^16-key groups (≤ 66 on the flagship
    set), so this is microseconds. Degenerate cases (G ≤ n_buckets, or a
    flat distribution) fall out naturally as fewer/equal buckets."""
    if n_buckets < 1:
        raise ValueError(f"n_buckets must be >= 1, got {n_buckets}")
    g = len(counts)
    if g == 0:
        return []
    order = np.argsort(-counts, kind="stable")
    srt = counts[order]
    k_max = min(int(n_buckets), g)
    INF = float("inf")
    # dp[i][k] = min padded rows covering sorted groups i.. with k buckets
    dp = np.full((g + 1, k_max + 1), INF)
    dp[g, :] = 0.0
    choice = np.zeros((g, k_max + 1), dtype=np.int64)
    for i in range(g - 1, -1, -1):
        for k in range(1, k_max + 1):
            spans = np.arange(i + 1, g + 1)
            costs = (spans - i) * srt[i] + dp[spans, k - 1]
            j = int(np.argmin(costs))
            dp[i, k] = costs[j]
            choice[i, k] = spans[j]
    cuts, i, k = [], 0, k_max
    while i < g:
        j = int(choice[i, k])
        cuts.append(order[i:j])
        i, k = j, k - 1
    return cuts


def dense_pad_plan(
    group_offsets: np.ndarray, row_multiple: int = 1
) -> Optional[Tuple[int, np.ndarray]]:
    """``(m, slots)`` for the dense [G, M, W] layout — ``slots[i]`` is the
    g*m-grid position of packed row i (row r of group gi at local position
    p lands at gi*m + p), M rounded up to ``row_multiple``. None when the
    distribution is too skewed to pad (the guard: padded cells >
    max(2*rows, 1024)). Single source of truth for the host scatter
    (pad_groups_dense) and the device gather (PackedGroups.padded_device)
    so the two paths can never drift apart."""
    with _timeline.stage(
        _PACK_STAGE_SECONDS, "dense_pad_plan", "pack.dense_pad_plan", cat="pack"
    ):
        counts = np.diff(group_offsets)
        g = len(counts)
        n = int(group_offsets[-1])
        m = int(counts.max()) if g else 0
        m += (-m) % row_multiple
        if g * m > max(2 * n, 1024):
            return None
        if n:
            group_of_row = np.repeat(np.arange(g), counts)
            local = np.arange(n) - np.repeat(group_offsets[:-1], counts)
            slots = group_of_row * m + local
        else:
            slots = np.empty(0, dtype=np.int64)
        return m, slots


def pad_groups_dense(
    packed: PackedGroups, fill: int, row_multiple: int = 1
) -> Optional[np.ndarray]:
    """Dense [G, M, W] padding of a packed group set (layout + skew guard
    from dense_pad_plan); one vectorized scatter, no per-group loop."""
    plan = dense_pad_plan(packed.group_offsets, row_multiple)
    if plan is None:
        return None
    m, slots = plan
    g, n = packed.n_groups, packed.n_rows
    padded = np.full((g, m, dev.DEVICE_WORDS), fill, dtype=np.uint32)
    if n:
        padded.reshape(g * m, dev.DEVICE_WORDS)[slots] = packed.words
    return padded


def prepare_reduce(packed: PackedGroups, op: str = "or"):
    """Build the device reduction closure for a packed group set.

    Returns ``(run, layout)`` where ``run()`` -> (reduced [G, 2048] device
    array, cards [G] device array) and ``layout`` is ``"padded"``,
    ``"bucketed"``, or ``"segmented-scan"``. Cost-model-driven choice on
    host-side row counts (measured on chip, BENCH_NOTES "Ragged batching"):

    * single dense block when its occupancy is already >= 0.9 — one
      dispatch, no scatter-back;
    * count-bucketed ragged batching when bucketing keeps total padded
      rows <= 1.5x the live rows — this also rescues most distributions
      the single-block guard rejects (e.g. one giant group + many tiny
      ones buckets to ~100% occupancy);
    * else the segmented scan (the truly irregular tail). The reference's
      answer to skew is splitting slices across the fork-join pool
      (ParallelAggregation.java:222-228). bench.py times exactly these
      closures, so the benchmark and production always run the same path.
    """
    n = packed.n_rows
    counts = np.diff(packed.group_offsets)
    g = packed.n_groups
    single_rows = int(g * counts.max()) if g else 0
    # empty sets keep the (trivial) single-block path
    if not g or not n or single_rows <= n / 0.9:
        fill = int(dev._INIT[op])
        # cold one-shot tiering (ISSUE 8): the FIRST reduce of a freshly
        # packed working set fuses the dense-pad gather into the reduction
        # (pallas_kernels.fused_gather_reduce) instead of materializing
        # the padded block it would use exactly once — half the memory
        # traffic, the dominant cost of a cold back-to-back query. The
        # SECOND touch builds the resident [G, M, W] block and every
        # later reduce rides the cheaper steady-state path (the closure
        # itself re-checks, so min-of-reps timing loops converge too).
        # Legacy expansion mode keeps the r09 pipeline verbatim.
        built = (
            packed._padded_cache is not None
            and (fill, 1) in packed._padded_cache
        )
        touches = packed._reduce_touches
        first_prepare = not touches.get(fill, 0)
        touches[fill] = touches.get(fill, 0) + 1
        if g and n and not built and first_prepare and _EXPAND_MODE != "legacy":
            plan = dense_pad_plan(packed.group_offsets, 1)
            if plan is not None:
                m, slots = plan
                src_map = np.full(g * m, n, dtype=np.int64)
                src_map[slots] = np.arange(n)
                calls = [0]

                def run_fused():
                    from .. import tracing
                    from ..ops import pallas_kernels as pk

                    calls[0] += 1
                    if calls[0] == 1 and not (
                        packed._padded_cache is not None
                        and (fill, 1) in packed._padded_cache
                    ):
                        # ops.dispatch fault site fires inside the helper
                        with tracing.op_timer("store.reduce.padded_fused"):
                            return pk.fused_gather_reduce(
                                packed.device_words, src_map, g, int(m),
                                op=op, fill=fill,
                            )
                    arr = packed.padded_device(fill)
                    with tracing.op_timer("store.reduce.padded"):
                        return pk.best_grouped_reduce(arr, op=op)

                _LAYOUT_TOTAL.inc(1, ("padded",))
                return run_fused, "padded"
        dev_arr = packed.padded_device(dev._INIT[op])
        if dev_arr is not None:

            def run():
                from .. import tracing
                from ..ops import pallas_kernels as pk

                # ops.dispatch fault site fires inside best_grouped_reduce
                with tracing.op_timer("store.reduce.padded"):
                    return pk.best_grouped_reduce(dev_arr, op=op)

            _LAYOUT_TOTAL.inc(1, ("padded",))
            return run, "padded"
    if g and n:
        bucket_rows = sum(
            len(idx) * int(counts[idx].max())
            for idx in packed.plan_buckets(DEFAULT_BUCKETS)
        )
        if bucket_rows <= 1.5 * n:
            return prepare_reduce_bucketed(packed, op=op, n_buckets=DEFAULT_BUCKETS)

    seg_start = np.zeros(n, dtype=bool)
    seg_start[packed.group_offsets[:-1]] = True
    seg = jnp.asarray(seg_start)
    end_rows = jnp.asarray(packed.group_offsets[1:] - 1)
    words = packed.device_words

    def run():
        from .. import tracing
        from ..ops import pallas_kernels as pk

        # ops.dispatch fault site fires inside best_segmented_reduce
        with tracing.op_timer("store.reduce.segmented-scan"):
            vals = pk.best_segmented_reduce(words, seg, op=op)
            red = vals[end_rows]
            return red, dev.popcount_rows(red)

    _LAYOUT_TOTAL.inc(1, ("segmented-scan",))
    return run, "segmented-scan"


def prepare_reduce_bucketed(packed: PackedGroups, op: str = "or", n_buckets: int = 3):
    """Ragged-batched variant of prepare_reduce: one grouped reduce per
    count bucket (all inside one jit), results scattered back to ascending
    key order. Same (run, layout) contract; layout = "bucketed"."""
    import jax

    buckets = packed.padded_buckets_device(dev._INIT[op], n_buckets)
    if not buckets:  # empty working set: same contract as reduce_packed

        def run_empty():
            return (
                jnp.empty((0, dev.DEVICE_WORDS), dtype=jnp.uint32),
                jnp.empty((0,), dtype=jnp.int32),
            )

        _LAYOUT_TOTAL.inc(1, ("bucketed",))
        return run_empty, "bucketed"
    order = np.concatenate([idx for idx, _ in buckets])
    inv = jnp.asarray(np.argsort(order))

    # the per-bucket engine is the stock XLA grouped reduce directly: the
    # probing dispatcher (best_grouped_reduce) runs Python-side try-compiles
    # and cannot sit under this outer jit — and XLA is the measured flagship
    # winner anyway (BENCH_NOTES flagship post-mortem)
    @jax.jit
    @_observe.compilewatch.tracked("store.reduce_all_bucketed")
    def reduce_all(arrs):
        reds, cards = [], []
        # rb-ok: trace-safety -- arrs is a tuple-of-arrays pytree: the loop
        # unrolls over static structure at trace time, not traced values
        for a in arrs:
            r, c = dev.grouped_reduce_with_cardinality(a, op=op)
            reds.append(r)
            cards.append(c)
        return jnp.concatenate(reds, axis=0)[inv], jnp.concatenate(cards)[inv]

    arrs = tuple(a for _, a in buckets)

    def run():
        from .. import tracing

        _faults.fault_point("ops.dispatch")
        with tracing.op_timer("store.reduce.bucketed"):
            return reduce_all(arrs)

    _LAYOUT_TOTAL.inc(1, ("bucketed",))
    return run, "bucketed"


def reduce_packed(packed: PackedGroups, op: str = "or"):
    """Reduce each key group on device; returns (words [G,2048] np.uint32,
    cards [G] np.int64)."""
    if packed.n_groups == 0:
        return (
            np.empty((0, dev.DEVICE_WORDS), dtype=np.uint32),
            np.empty((0,), dtype=np.int64),
        )
    run, _ = prepare_reduce(packed, op)
    red, card = run()
    return np.asarray(red), np.asarray(card).astype(np.int64)


def reduce_packed_cardinality(packed: PackedGroups, op: str = "or") -> np.ndarray:
    """Per-group cardinalities only: the reduced words stay on device — the
    host fetch is G ints, which is what makes N-way cardinality-only
    aggregation cheaper than materialize-then-count."""
    if packed.n_groups == 0:
        return np.empty((0,), dtype=np.int64)
    run, _ = prepare_reduce(packed, op)
    _red, card = run()
    return np.asarray(card).astype(np.int64)


def unpack_to_bitmap(
    group_keys: np.ndarray, words_u32: np.ndarray, cards: np.ndarray
) -> RoaringBitmap:
    """Stream device results back into a RoaringBitmap via the append path
    (RoaringArray.append, RoaringArray.java:111), re-compressing each chunk."""
    from .. import tracing

    with tracing.op_timer("store.unpack_to_bitmap"):
        return _unpack_to_bitmap(group_keys, words_u32, cards)


def iter_group_containers(group_keys: np.ndarray, words_u32: np.ndarray, cards: np.ndarray):
    """Yield ``(key, Container)`` per non-empty group with card-driven
    construction (the device already popcounted each group) — shared by the
    32-bit unpack, the 64-bit ART rebuild, and the NavigableMap rebuild."""
    from ..models.container import ArrayContainer

    words64 = np.ascontiguousarray(words_u32).view(np.uint64)
    for gi, key in enumerate(group_keys.tolist()):
        card = int(cards[gi])
        if card == 0:
            continue
        w = words64[gi]
        if card <= 4096:
            yield int(key), ArrayContainer(bits.values_from_words(w))
        else:
            yield int(key), BitmapContainer(w.copy(), card)


def _unpack_to_bitmap(group_keys, words_u32, cards) -> RoaringBitmap:
    out = RoaringBitmap()
    for key, c in iter_group_containers(group_keys, words_u32, cards):
        out.high_low_container.append(key, c)
    return out


# ---------------------------------------------------------------------------
# Resident pack cache (ISSUE 4 tentpole)
# ---------------------------------------------------------------------------


def pack_groups_with_provenance(
    bitmaps: Sequence[RoaringBitmap], keys_filter: Optional[set] = None
) -> Tuple[PackedGroups, Dict[Tuple[int, int], int]]:
    """``pack_groups(group_by_key(...))`` plus the row provenance the delta
    repack needs: ``{(bitmap_index, chunk_key): packed_row}``. Row order
    matches pack_groups exactly — rows sorted by group key, and within a
    group in bitmap order (the group_by_key append order)."""
    groups = group_by_key(bitmaps, keys_filter=keys_filter)
    packed = pack_groups(groups)
    with _timeline.stage(
        _PACK_STAGE_SECONDS, "provenance", "pack.provenance", cat="pack",
        rows=packed.n_rows,
    ):
        pos = {
            int(k): int(off)
            for k, off in zip(packed.group_keys, packed.group_offsets[:-1])
        }
        row_map: Dict[Tuple[int, int], int] = {}
        for bi, bm in enumerate(bitmaps):
            for k in bm.high_low_container.keys:
                if keys_filter is not None and k not in keys_filter:
                    continue
                row_map[(bi, k)] = pos[k]
                pos[k] += 1
        return packed, row_map


class _PackEntry:
    __slots__ = ("key", "kind", "value", "nbytes", "pins", "fps", "row_map", "refs")

    def __init__(self, key, kind, value, nbytes, fps=None, row_map=None, refs=()):
        self.key = key
        self.kind = kind
        self.value = value
        self.nbytes = int(nbytes)
        self.pins = 0           # pin refcount: >0 exempts from eviction
        self.fps = fps          # agg entries: fingerprints at pack time
        self.row_map = row_map  # agg entries: (bitmap_idx, key) -> row
        # container arrays behind ("static", id) fingerprints: held so the
        # id cannot be recycled by GC while the entry is resident (an id
        # reused by a different immutable bitmap would be a silent stale
        # hit; (gen, version) fingerprints are process-unique and need no
        # pinning)
        self.refs = refs


def _fp_ident(fp: tuple):
    """The mutation-invariant part of a fingerprint: the array generation
    for (gen, version) fingerprints; static fingerprints never mutate, so
    the whole fingerprint is the identity (tagged to avoid an int id()
    colliding with a generation int)."""
    if fp[0] == "static":
        return ("s",) + fp[1:]
    return ("g", fp[0])


def _walk_fingerprints(bitmaps) -> Tuple[tuple, tuple]:
    """ONE fused pass over the working set producing ``(fps, idents)``
    with per-hlc caching (ISSUE 11 satellite: the warm/delta path walked
    fingerprints once and identities again — 2 method calls + 2 tuple
    allocations per bitmap per lookup, the dominant stage of the O(k)
    delta wall at 10k operands). Fingerprint tuples cache on the array
    (invalidated per version bump); identity tuples depend only on the
    generation and cache for the array's lifetime. A warm lookup
    therefore allocates nothing per bitmap."""
    fps: List[tuple] = []
    idents: List[tuple] = []
    fps_append, idents_append = fps.append, idents.append
    for bm in bitmaps:
        hlc = bm.high_low_container
        fp = getattr(hlc, "_fp", None)
        if fp is None:
            gen = getattr(hlc, "_gen", None)
            if gen is None:  # static (mapped/immutable): never mutates
                fp = ("static", id(hlc))
                fps_append(fp)
                idents_append(("s",) + fp[1:])
                continue
            fp = (gen, hlc._version)
            try:
                hlc._fp = fp
            except AttributeError:  # foreign mutable hlc without the slot
                fps_append(fp)
                idents_append(("g", gen))
                continue
        # guarded like _fp: a foreign mutable hlc with a __dict__ caches
        # _fp successfully yet has no _fp_ident until we store one
        ident = getattr(hlc, "_fp_ident", None)
        if ident is None:
            ident = ("g", hlc._gen)
            try:
                hlc._fp_ident = ident
            except AttributeError:
                pass
        fps_append(fp)
        idents_append(ident)
    return tuple(fps), tuple(idents)


def static_fp_refs(bitmaps: Sequence[RoaringBitmap]) -> tuple:
    """The container arrays of operands with ("static", id) fingerprints —
    cache entries hold these so the ids stay live (see _PackEntry.refs)."""
    return tuple(
        bm.high_low_container
        for bm in bitmaps
        if bm.fingerprint()[0] == "static"
    )


def _repack_estimate_s(kind: str):
    """The residency authority's learned re-pack cost for ``kind``
    (ISSUE 12) — None until evict-regret traffic taught the curve, or
    when the cost facade is unavailable (pricing an eviction must never
    be able to fail the eviction)."""
    try:
        from ..cost import residency as _residency

        return _residency.MODEL.repack_estimate(kind)
    except Exception:  # rb-ok: exception-hygiene -- the eviction itself must proceed unpriced rather than fail on a diagnostics import/path error
        return None


def _readmit_estimate_s(kind: str):
    """The residency authority's learned mmap re-admit cost for ``kind``
    (ISSUE 17, the mapped rung) — the cheaper return path a demotion
    prices against the cold repack. Same never-fail contract as
    :func:`_repack_estimate_s`."""
    try:
        from ..cost import residency as _residency

        return _residency.MODEL.readmit_estimate(kind)
    except Exception:  # rb-ok: exception-hygiene -- the eviction itself must proceed unpriced rather than fail on a diagnostics import/path error
        return None


# ISSUE 17: the durable store's demotion probe. Installed once a
# persisted epoch artifact exists; it answers whether an evicted entry
# of ``kind`` remains re-admittable from the epoch mmap. With a probe
# answering True, eviction DEMOTES to the residency ladder's fourth
# rung (mapped-but-not-resident: device bytes freed, payload still one
# zero-copy readmit away) instead of discarding outright. None = no
# durable artifact; every eviction is a discard, the pre-durable
# behavior.
_DEMOTE_PROBE = None


def set_demotion_probe(probe) -> None:
    """Install (or clear with ``None``) the mapped-rung demotion probe
    — ``probe(kind) -> bool``. durable/store.py installs it after the
    first completed persist."""
    global _DEMOTE_PROBE
    _DEMOTE_PROBE = probe


class PackCache:
    """Process-wide device-resident working-set cache (ISSUE 4 tentpole).

    Packed working sets — ``PackedGroups`` with their flat/padded/bucketed
    device layouts, plus the BSI slice tensors and the query kernels'
    packs — stay resident in HBM across calls, keyed by the participating
    bitmaps' ``fingerprint()`` tuples. A byte-budget LRU evicts cold
    entries (pinned entries are skipped); ``close()`` frees everything.

    Invalidation is *incremental* for aggregation entries: when the same
    bitmaps return with moved versions (same generations), the per-key
    dirty sets from ``RoaringArray.dirty_keys_since`` identify exactly
    which packed rows changed, and ``PackedGroups.apply_delta`` re-packs
    and ships only those rows (one scatter) instead of rebuilding the
    whole working set. Structural changes — chunk keys added/removed, an
    AND key-intersection that grew or shrank, wholesale mutations — fall
    back to a full repack.

    Thread-safe: one lock around the entry map; full packs build outside
    the lock (concurrent builders race benignly, first store wins), delta
    repacks run under it. The lock nests over the metrics-registry lock
    only (pack.cache -> observe.registry), witnessed cycle-free by the
    tests/test_pack_cache.py lock hammer.
    """

    def __init__(self, max_bytes: Optional[int] = None):
        if max_bytes is None:
            max_bytes = int(
                os.environ.get("RB_TPU_PACK_CACHE_BYTES", str(2 << 30))
            )
        # RLock: the delta path drops derived layouts under the lock, and
        # their residency callbacks re-enter to settle the byte accounting
        self._lock = threading.RLock()
        with _ALL_CACHES_LOCK:  # WeakSet add vs reconcile iteration race
            _ALL_CACHES.add(self)
        self.max_bytes = int(max_bytes)  # guarded-by: self._lock
        self._entries: "OrderedDict[tuple, _PackEntry]" = OrderedDict()  # guarded-by: self._lock
        self._ident: Dict[tuple, tuple] = {}  # guarded-by: self._lock
        # recently evicted working sets -> eviction decision serial
        # (ISSUE 11): a miss that re-packs a remembered eviction joins the
        # evict decision with the re-pack wall as measured regret — the
        # eviction was wrong exactly when its key came back while we still
        # remember throwing it out. Bounded ring, oldest forgotten.
        self._evicted_seqs: "OrderedDict[tuple, int]" = OrderedDict()  # guarded-by: self._lock
        # per-THREAD route of the most recent get_packed (ISSUE 15): the
        # epoch flip's lineage needs per-working-set delta-vs-full
        # evidence, and a diff of the global hit/miss counters would
        # race every concurrent cache user; thread-local needs no lock
        self._route_tls = threading.local()
        self._bytes = 0  # guarded-by: self._lock
        self.hits = 0  # guarded-by: self._lock
        self.misses = 0  # guarded-by: self._lock
        self.delta_rows = 0  # guarded-by: self._lock
        self.evictions = 0  # guarded-by: self._lock

    _EVICTED_SEQS_CAP = 256

    # -- public API --------------------------------------------------------

    def get_packed(
        self, bitmaps: Sequence[RoaringBitmap], keys_filter: Optional[set] = None
    ) -> PackedGroups:
        """The resident pack for this working set, delta-refreshed or
        rebuilt as needed. ``keys_filter``, when given, must be the AND
        key-intersection of ``bitmaps`` (the workShyAnd pre-filter) — the
        delta validator relies on that to detect intersection changes."""
        bitmaps = list(bitmaps)
        marker = "all" if keys_filter is None else "and"
        self._route_tls.route = None  # set on every exit path below
        # stage-attributed (ISSUE 8): with the delta scatter at O(k) the
        # fingerprint walk is a visible share of the delta wall — the
        # timeline must name it, not leave it as unattributed residue.
        # Since ISSUE 11 it is ONE fused, per-hlc-cached pass producing
        # fingerprints AND identities (zero allocations per bitmap warm).
        with _timeline.stage(
            _PACK_STAGE_SECONDS, "fingerprints", "pack.fingerprints",
            cat="pack", operands=len(bitmaps),
        ):
            fps, idents = _walk_fingerprints(bitmaps)
        key = ("agg", marker, fps)
        if self.max_bytes <= 0:  # disabled: always a fresh uncached pack
            with self._lock:
                self.misses += 1
            _PACK_MISSES.inc(1, ("agg",))
            self._route_tls.route = ("disabled", 0)
            # no entry will exist, so skip the (discarded) row provenance
            return pack_groups(group_by_key(bitmaps, keys_filter=keys_filter))
        ident = ("agg", marker, idents)
        with self._lock:
            e = self._entries.get(key)
            if e is not None:
                self._entries.move_to_end(key)
                self.hits += 1
                _PACK_HITS.inc(1, ("agg",))
                _timeline.instant(
                    "pack_cache.hit", "cache", kind="agg", bytes=e.nbytes
                )
                self._route_tls.route = ("hit", 0)
                return e.value
            old_key = self._ident.get(ident)
            if old_key is not None:
                e = self._entries.get(old_key)
                if e is not None:
                    rows = self._try_delta(e, bitmaps, keys_filter, fps)
                    if rows is not None:
                        del self._entries[old_key]
                        e.key = key
                        e.fps = fps
                        self._entries[key] = e
                        self._ident[ident] = key
                        self.hits += 1
                        self.delta_rows += len(rows)
                        _PACK_HITS.inc(1, ("agg",))
                        _timeline.instant(
                            "pack_cache.delta_hit", "cache", kind="agg",
                            rows=len(rows),
                        )
                        if rows:
                            _PACK_DELTA_ROWS.inc(len(rows), ("agg",))
                        self._route_tls.route = ("delta", len(rows))
                        return e.value
        # full repack outside the lock (packing dominates; a racing thread
        # packing the same key is benign — first store wins)
        _timeline.instant("pack_cache.miss", "cache", kind="agg")
        with self._lock:
            evict_seq = self._evicted_seqs.pop(ident, None)
        t0 = _time.perf_counter()
        packed, row_map = pack_groups_with_provenance(bitmaps, keys_filter)
        if evict_seq is not None:
            # the evicted working set came back while its eviction is
            # still remembered: the re-pack wall is the eviction's
            # measured regret (ISSUE 11 — the decision-outcome join's
            # measured-counterfactual form)
            repack_s = _time.perf_counter() - t0
            _outcomes.resolve(
                evict_seq, "pack_cache.evict", repack_s, engine="repack",
                regret_s=repack_s,
            )
        with self._lock:
            self.misses += 1
        _PACK_MISSES.inc(1, ("agg",))
        entry = _PackEntry(
            key, "agg", packed, packed.words_nbytes, fps=fps, row_map=row_map,
            refs=static_fp_refs(bitmaps),
        )
        self._route_tls.route = ("full", 0)
        return self._store(entry, ident=ident).value

    def last_route(self) -> Optional[tuple]:
        """``(route, delta_rows)`` of THIS thread's most recent
        :meth:`get_packed` — ``route`` is ``"hit"`` | ``"delta"`` |
        ``"full"`` | ``"disabled"``, ``delta_rows`` is nonzero only on
        the delta route. Thread-local by design: the epoch flip
        (serve/epochs.py) classifies each working-set refresh for its
        lineage record, and a diff of the global hit/miss counters would
        race every concurrent cache user. ``None`` before any call on
        this thread."""
        return getattr(self._route_tls, "route", None)

    def get_or_build(self, key: tuple, build: Callable[[], tuple], refs: tuple = ()):
        """Generic resident entry (BSI slice tensors, query-kernel packs,
        the columnar device tier's per-bitmap ``colrows`` flat-row blocks):
        ``key`` must start with the kind marker and embed every input
        fingerprint; ``build()`` returns ``(value, nbytes)``. Exact-key hit
        or full rebuild — no delta path. ``refs`` pins the container
        arrays behind any ("static", id) fingerprints in the key (see
        ``static_fp_refs``)."""
        kind = str(key[0])
        if self.max_bytes <= 0:
            with self._lock:
                self.misses += 1
            _PACK_MISSES.inc(1, (kind,))
            value, _nbytes = build()
            return value
        with self._lock:
            e = self._entries.get(key)
            if e is not None:
                self._entries.move_to_end(key)
                self.hits += 1
                _PACK_HITS.inc(1, (kind,))
                _timeline.instant(
                    "pack_cache.hit", "cache", kind=kind, bytes=e.nbytes
                )
                return e.value
        _timeline.instant("pack_cache.miss", "cache", kind=kind)
        with self._lock:
            evict_seq = self._evicted_seqs.pop(key, None)
        t0 = _time.perf_counter()
        value, nbytes = build()
        if evict_seq is not None:
            # re-build of a remembered eviction: measured regret (ISSUE 11)
            rebuild_s = _time.perf_counter() - t0
            _outcomes.resolve(
                evict_seq, "pack_cache.evict", rebuild_s, engine="rebuild",
                regret_s=rebuild_s,
            )
        with self._lock:
            self.misses += 1
        _PACK_MISSES.inc(1, (kind,))
        return self._store(_PackEntry(key, kind, value, nbytes, refs=refs)).value

    def pin_packed(
        self, bitmaps: Sequence[RoaringBitmap], keys_filter: Optional[set] = None
    ) -> PackedGroups:
        """Get (building if needed) and pin this working set's pack: pinned
        entries are never byte-budget-evicted (serving traffic's standing
        indexes). Pins are a REFCOUNT — every ``pin_packed`` needs a
        matching ``unpin_packed`` (two consumers pinning the same working
        set must both release before it becomes evictable); ``close``
        releases everything regardless."""
        packed = self.get_packed(bitmaps, keys_filter)
        with self._lock:
            e = self._agg_entry(bitmaps, keys_filter)
            if e is not None:
                e.pins += 1
                _timeline.instant(
                    "pack_cache.pin", "cache", kind="agg", pins=e.pins
                )
        return packed

    def unpin_packed(
        self, bitmaps: Sequence[RoaringBitmap], keys_filter: Optional[set] = None
    ) -> None:
        with self._lock:
            e = self._agg_entry(bitmaps, keys_filter)
            if e is not None:
                e.pins = max(0, e.pins - 1)
                _timeline.instant(
                    "pack_cache.unpin", "cache", kind="agg", pins=e.pins
                )
                if e.pins == 0:
                    self._evict_over_budget()

    def _agg_entry(self, bitmaps, keys_filter) -> Optional[_PackEntry]:
        """Resolve this working set's entry by exact fingerprints OR by
        identity (generations) — pin/unpin must find the entry even when
        the bitmaps mutated after it was pinned (an exact-only lookup
        would silently leak the pin forever). Caller holds self._lock."""
        marker = "all" if keys_filter is None else "and"
        fps = tuple(bm.fingerprint() for bm in bitmaps)
        e = self._entries.get(("agg", marker, fps))
        if e is not None:
            return e
        ident = ("agg", marker, tuple(_fp_ident(fp) for fp in fps))
        key = self._ident.get(ident)
        return self._entries.get(key) if key is not None else None

    def discard(self, key: tuple) -> None:
        """Drop one entry by exact key (no eviction metrics): for builders
        that discover post-store that the pack cannot serve their device
        path (e.g. threshold's too-skewed-to-pad fallback) and must not
        leave a useless resident entry squatting on the budget."""
        with self._lock:
            e = self._entries.get(key)
            if e is not None and e.kind == "agg":
                ident = ("agg", e.key[1], tuple(_fp_ident(fp) for fp in e.fps))
                if self._ident.get(ident) == key:
                    del self._ident[ident]
            self._drop(key)

    def close(self) -> None:
        """Release every resident entry (pinned included) and settle the
        resident gauge; the cache stays usable and refills on next use."""
        with self._lock:
            for e in self._entries.values():
                self._release(e)
            self._entries.clear()
            self._ident.clear()
            self._bytes = 0

    def __del__(self):
        # a dropped secondary cache (tests, fuzz campaigns) must settle
        # the process-wide resident gauge: its entries' PackedGroups are
        # _cache_held, so their own __del__ is a deliberate no-op and only
        # this release path returns the bytes (hbm_reconciliation's
        # ledger check counts LIVE caches — an unsettled dead one would
        # read as permanent drift)
        try:
            self.close()
        except Exception:  # pragma: no cover - interpreter teardown  # rb-ok: exception-hygiene -- __del__ during teardown: modules may already be torn down; raising here aborts GC
            pass

    def configure(self, max_bytes: int) -> None:
        """Set the byte budget and evict down to it. ``max_bytes <= 0``
        disables caching AND releases every resident entry (pinned
        included) — the disabled lookup path never touches the entry map,
        so anything left behind would squat on HBM until process exit."""
        with self._lock:
            self.max_bytes = int(max_bytes)
            if self.max_bytes <= 0:
                for e in self._entries.values():
                    self._release(e)
                self._entries.clear()
                self._ident.clear()
                self._bytes = 0
            else:
                self._evict_over_budget()

    def stats(self) -> dict:
        with self._lock:
            return {
                "hits": self.hits,
                "misses": self.misses,
                "delta_rows": self.delta_rows,
                "evictions": self.evictions,
                "entries": len(self._entries),
                "bytes": self._bytes,
                "pinned": sum(1 for e in self._entries.values() if e.pins),
            }

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def __contains__(self, key: tuple) -> bool:
        with self._lock:
            return key in self._entries

    def resident_bytes_for(self, fps) -> int:
        """Resident bytes attributable to a working set: the sum of
        entry bytes whose key (or recorded fingerprint tuple) embeds any
        of the given leaf ``fingerprint()`` tuples. An entry serving
        several overlapping working sets is charged to each caller — the
        serving tier's per-tenant byte-share accounting (ISSUE 14) wants
        shares, not a partition, so the shares may sum past the resident
        total by design."""
        want = set(fps)
        if not want:
            return 0
        total = 0
        with self._lock:
            for e in self._entries.values():
                efps = e.fps
                if efps and any(fp in want for fp in efps):
                    total += e.nbytes
                    continue
                hit = False
                for el in e.key:
                    if el in want:
                        hit = True
                        break
                    if isinstance(el, tuple) and any(fp in want for fp in el):
                        hit = True
                        break
                if hit:
                    total += e.nbytes
        return total

    # -- internals ---------------------------------------------------------

    def _store(self, entry: _PackEntry, ident: Optional[tuple] = None) -> _PackEntry:
        try:
            # budget-pressure fault site (ISSUE 7): real or injected HBM
            # pressure at admission time must degrade — spill cold entries
            # and serve this working set uncached — never fail the caller
            _faults.fault_point("pack_cache.budget")
        except Exception as e:
            if _rerrors.classify(e) == _rerrors.FATAL:
                raise
            with self._lock:
                self._evict_to(self.max_bytes // 2)
            _ladder.LADDER.note_degrade("pack_cache.budget", "resident", "uncached", e)
            _timeline.instant(
                "pack_cache.pressure", "cache", kind=entry.kind,
                bytes=entry.nbytes,
            )
            _decisions.record_decision(
                "pack_cache.admit", "spill-and-serve-uncached",
                kind=entry.kind, bytes=entry.nbytes,
                target_bytes=self.max_bytes // 2,
            )
            return entry  # consumer-owned: never marked cache-held
        with self._lock:
            existing = self._entries.get(entry.key)
            if existing is not None:
                # a racing builder stored first; keep theirs, drop ours
                self._entries.move_to_end(entry.key)
                if isinstance(entry.value, PackedGroups):
                    entry.value.close()
                return existing
            if ident is not None:
                superseded = self._ident.pop(ident, None)
                if superseded is not None and superseded in self._entries:
                    self._drop(superseded)
                self._ident[ident] = entry.key
            for pg in self._packed_parts(entry.value):
                pg._cache_held = True
                pg._resident_cb = self._resident_cb(entry)
            self._entries[entry.key] = entry
            self._bytes += entry.nbytes
            _PACK_RESIDENT.inc(entry.nbytes, (entry.kind,))
            _decisions.record_decision(
                "pack_cache.admit", "resident", kind=entry.kind,
                bytes=entry.nbytes, cache_bytes=self._bytes,
            )
            self._evict_over_budget()
            return entry

    @staticmethod
    def _packed_parts(value):
        if isinstance(value, PackedGroups):
            return (value,)
        if isinstance(value, tuple):
            return tuple(p for p in value if isinstance(p, PackedGroups))
        return ()

    def _resident_cb(self, entry: _PackEntry):
        """Byte-accounting callback for a cache-owned PackedGroups: derived
        device layouts (flat ship, padded blocks, buckets) are built lazily
        AFTER the entry is stored, so their bytes must flow into the
        entry's weight and the budget — otherwise real HBM runs multiples
        past max_bytes before the evictor notices."""

        def cb(delta: int) -> None:
            with self._lock:
                if self._entries.get(entry.key) is not entry:
                    return  # raced with eviction: no longer resident
                entry.nbytes += delta
                self._bytes += delta
                _PACK_RESIDENT.inc(delta, (entry.kind,))
                if delta > 0:
                    self._evict_over_budget()

        return cb

    def _drop(self, key: tuple) -> None:
        # caller holds self._lock (private helper of the locked regions)
        e = self._entries.pop(key, None)
        if e is None:
            return
        self._bytes -= e.nbytes
        self._release(e)

    def _release(self, e: _PackEntry) -> None:
        # caller holds self._lock; settles the gauge and really closes
        # cache-owned device arrays (consumers holding refs keep them
        # alive). The residency callback is detached FIRST: e.nbytes
        # already includes the derived-layout bytes, so close() reporting
        # them again would double-subtract.
        _PACK_RESIDENT.dec(e.nbytes, (e.kind,))
        for pg in self._packed_parts(e.value):
            pg._resident_cb = None
            pg._cache_held = False
            pg.close()

    def _evict_over_budget(self) -> None:
        self._evict_to(self.max_bytes)

    def _evict_to(self, target: int) -> None:
        # caller holds self._lock; LRU order, pinned entries skipped. At
        # least one UNPINNED entry always survives: a single working set
        # larger than the whole budget would otherwise thrash
        # store->evict on every call (the ResultCache max_bytes
        # discipline) — the north star's 308k-container flat pack alone
        # is ~2.4 GB. Counting pinned entries toward the survivor quota
        # would re-introduce exactly that thrash for every unpinned
        # working set once a standing pinned index fills the budget.
        # ``target < max_bytes`` is the budget-pressure spill path
        # (_store's degrade), freeing headroom instead of failing.
        if self._bytes <= target:
            return
        unpinned = sum(1 for e in self._entries.values() if not e.pins)
        for key in list(self._entries):
            if self._bytes <= target or unpinned <= 1:
                break
            e = self._entries[key]
            if e.pins:
                continue
            unpinned -= 1
            del self._entries[key]
            self._bytes -= e.nbytes
            self.evictions += 1
            _PACK_EVICTED_BYTES.inc(e.nbytes, (e.kind,))
            # ISSUE 17: with a durable epoch artifact on disk the evicted
            # bytes demote to the mapped rung (re-admittable from the
            # mmap at the readmit curve's price) instead of discarding —
            # the residency ladder's fourth rung
            probe = _DEMOTE_PROBE
            mapped = False
            if probe is not None:
                try:
                    mapped = bool(probe(e.kind))
                except Exception:  # rb-ok: exception-hygiene -- a broken probe must not turn evictions into failures; fall back to the discard rung
                    mapped = False
            rung = "mapped" if mapped else "discard"
            _DEMOTE_TOTAL.inc(1, (rung,))
            _timeline.instant(
                "pack_cache.evict", "cache", kind=e.kind, bytes=e.nbytes,
                rung=rung,
            )
            # the residency authority's learned re-pack cost prices this
            # eviction (ISSUE 12): the evict-regret join then scores the
            # pricing (predicted vs measured re-pack wall) exactly like
            # the other pricing authorities' verdicts
            est_repack_s = _repack_estimate_s(e.kind)
            evict_inputs = {"kind": e.kind, "bytes": e.nbytes,
                            "target_bytes": target, "rung": rung}
            if est_repack_s:
                evict_inputs["est_us"] = {
                    "repack": round(est_repack_s * 1e6, 1),
                    "rebuild": round(est_repack_s * 1e6, 1),
                }
            if mapped:
                # the demotion's priced return path: the learned mmap
                # readmit cost (None until durable.readmit traffic
                # taught the curve)
                est_readmit_s = _readmit_estimate_s(e.kind)
                if est_readmit_s:
                    evict_inputs.setdefault("est_us", {})["readmit"] = round(
                        est_readmit_s * 1e6, 1
                    )
            seq = _decisions.record_decision(
                "pack_cache.evict",
                "demote-mapped" if mapped else "lru",
                outcome=True, **evict_inputs,
            )
            ident = ("agg", e.key[1], tuple(_fp_ident(fp) for fp in e.fps)) \
                if e.kind == "agg" else None
            if ident is not None and self._ident.get(ident) == key:
                del self._ident[ident]
            if seq is not None:
                # remember the eviction by its identity (agg: the gen
                # tuple, so a delta-mutated return still matches) for the
                # miss-side regret join
                self._evicted_seqs[ident if ident is not None else key] = seq
                while len(self._evicted_seqs) > self._EVICTED_SEQS_CAP:
                    self._evicted_seqs.popitem(last=False)
            self._release(e)

    def _try_delta(self, e, bitmaps, keys_filter, new_fps):
        """Validate and apply an incremental repack of entry ``e`` for the
        new fingerprints; returns the re-packed row list, or None when only
        a full repack is sound (gen changed, wholesale mutation, or any
        structural change to the group layout). Caller holds self._lock."""
        if len(new_fps) != len(e.fps):
            return None
        packed: PackedGroups = e.value
        # cheap pre-pass (ISSUE 8 satellite): a generation change or a
        # wholesale mutation (mark_all_dirty) already forces the full
        # repack — decide from the version counters alone instead of
        # paying the per-key dirty scan first (the wasted
        # ``delta.dirty_scan`` time r09's timeline showed on structural
        # fallbacks)
        for bi, (old_fp, new_fp) in enumerate(zip(e.fps, new_fps)):
            if old_fp == new_fp:
                continue
            if old_fp[0] != new_fp[0]:  # generation changed (or static id)
                return None
            wholesale = getattr(
                bitmaps[bi].high_low_container, "wholesale_since", None
            )
            if wholesale is not None and wholesale(old_fp[1]):
                return None
        with _timeline.stage(
            _DELTA_STAGE_SECONDS, "dirty_scan", "delta.dirty_scan",
            cat="delta", operands=len(new_fps),
        ):
            packed_keys = {int(k) for k in packed.group_keys}
            dirty_rows: Dict[int, Tuple[int, int]] = {}
            for bi, (old_fp, new_fp) in enumerate(zip(e.fps, new_fps)):
                if old_fp == new_fp:
                    continue
                if old_fp[0] != new_fp[0]:  # generation changed (or static id)
                    return None
                hlc = bitmaps[bi].high_low_container
                dirty_of = getattr(hlc, "dirty_keys_since", None)
                dirty = dirty_of(old_fp[1]) if dirty_of is not None else None
                if dirty is None:  # wholesale / unattributed mutation
                    return None
                for k in dirty:
                    present_now = hlc.get_index(k) >= 0
                    if keys_filter is not None:  # "and": filter = key intersection
                        if k in packed_keys:
                            if not present_now:
                                return None  # intersection shrank
                            dirty_rows[e.row_map[(bi, k)]] = (bi, k)
                        elif present_now and all(
                            b.high_low_container.get_index(k) >= 0 for b in bitmaps
                        ):
                            return None  # intersection grew
                    else:
                        was_packed = (bi, k) in e.row_map
                        if was_packed != present_now:
                            return None  # container added or removed
                        if present_now:
                            dirty_rows[e.row_map[(bi, k)]] = (bi, k)
        if not dirty_rows:
            return ()
        rows = sorted(dirty_rows)
        with _timeline.stage(
            _DELTA_STAGE_SECONDS, "host_rows", "delta.host_rows",
            cat="delta", rows=len(rows),
        ):
            containers = [
                bitmaps[bi].high_low_container.get_container(k)
                for bi, k in (dirty_rows[r] for r in rows)
            ]
            host_rows = pack_rows_host(containers)
        packed.apply_delta(np.asarray(rows, dtype=np.int64), host_rows)
        return rows


# Every live cache instance, for gauge reconciliation: the resident-bytes
# gauge is process-global while entry ledgers are per-cache, so the ledger
# drift check must sum over ALL live caches (tests and fuzz campaigns run
# secondary instances; a dead one settles its share via __del__ -> close).
# The lock covers add-vs-iterate: WeakSet iteration defers removals but a
# concurrent add raises "set changed size during iteration".
_ALL_CACHES_LOCK = threading.Lock()
_ALL_CACHES: "weakref.WeakSet[PackCache]" = weakref.WeakSet()  # guarded-by: _ALL_CACHES_LOCK

# The process-wide cache every routed consumer shares (aggregation engines,
# BSI device packs, query kernels) — ONE eviction budget for all of them.
# RB_TPU_PACK_CACHE_BYTES overrides the 2 GiB default; 0 disables caching.
PACK_CACHE = PackCache()


def packed_for(
    bitmaps: Sequence[RoaringBitmap], keys_filter: Optional[set] = None
) -> PackedGroups:
    """The cache-routed replacement for ``pack_groups(group_by_key(...))``
    on device paths: warm working sets come back resident (zero host work),
    mutated ones delta-repack O(changed rows)."""
    return PACK_CACHE.get_packed(bitmaps, keys_filter)


def hbm_reconciliation() -> dict:
    """Reconcile the pack cache's resident-bytes accounting against
    independent ground truth (ISSUE 9 tentpole, leg 3c) and export the
    drift as ``rb_tpu_hbm_accounting_drift_bytes{source}``:

    * ``ledger`` — the ``rb_tpu_pack_cache_resident_bytes`` gauge total
      vs the cache's internal entry-byte ledger. These are maintained by
      the same locked code paths, so nonzero drift means an accounting
      bug (the donation-consumed-buffer leak this PR fixes was exactly
      such a bug — one block of phantom bytes per failed delta scatter);
    * ``device`` — the gauge total vs the jax backend's reported
      ``bytes_in_use``. The device holds more than the pack cache (jit
      executables, scratch, other consumers), so this drift is expected
      to be *negative or zero-crossing noise is a red flag the other
      way*: the gauge claiming MORE than the device holds (positive
      drift) means the cache is accounting for freed arrays. Absent on
      backends without ``memory_stats`` (the CPU client).

    Returns the reconciliation report; ``scripts/rb_top.py`` renders it
    and bench.py snapshots it into the metrics sidecar.

    The ledger side sums over every LIVE cache instance (the gauge is
    process-global; tests/fuzz run secondary caches, and a dropped cache
    settles its share via ``__del__`` -> ``close``)."""

    def _sides():
        lb = en = es = 0
        with _ALL_CACHES_LOCK:
            caches = list(_ALL_CACHES)
        for cache in caches:
            with cache._lock:
                lb += cache._bytes
                en += len(cache._entries)
                es += sum(e.nbytes for e in cache._entries.values())
        return lb, en, es, sum(_PACK_RESIDENT.series().values())

    def _stable_sides():
        # the ledger scan and the gauge read are not one atomic snapshot:
        # an admit/evict completing between them shows as phantom drift on
        # a path whose contract is "nonzero = accounting bug". Two
        # CONSECUTIVE equal reads mean no mutation straddled the pair —
        # retry briefly until stable (a diagnostics read polled under
        # churn keeps the last pair rather than spinning forever).
        prev = _sides()
        for _ in range(4):
            cur = _sides()
            if cur == prev:
                return cur
            prev = cur
        return prev

    ledger_bytes, entries, entry_sum, gauge_bytes = _stable_sides()
    if gauge_bytes != ledger_bytes:
        # apparent drift may be a dropped secondary cache whose __del__
        # has not run (reference cycles): collect and re-read before
        # reporting. The collect is deliberately NOT unconditional — this
        # sits on polled monitoring read paths (rb_top, observatory), and
        # a full cyclic-GC pass per clean snapshot would be pure tax.
        import gc

        gc.collect()
        ledger_bytes, entries, entry_sum, gauge_bytes = _stable_sides()
    ledger_drift = int(gauge_bytes - ledger_bytes)
    _HBM_DRIFT.set(ledger_drift, ("ledger",))
    report = {
        "gauge_bytes": int(gauge_bytes),
        "ledger_bytes": int(ledger_bytes),
        "entry_sum_bytes": int(entry_sum),
        "entries": entries,
        "ledger_drift_bytes": ledger_drift,
    }
    try:
        stats = jax.devices()[0].memory_stats()
    except (RuntimeError, AttributeError, IndexError):  # no usable backend
        stats = None
    if stats and "bytes_in_use" in stats:
        in_use = int(stats["bytes_in_use"])
        device_drift = int(gauge_bytes - in_use)
        _HBM_DRIFT.set(device_drift, ("device",))
        report.update(
            device_bytes_in_use=in_use, device_drift_bytes=device_drift
        )
    return report
