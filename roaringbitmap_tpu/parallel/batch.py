"""Batched one-vs-many pairwise algebra on device.

The retrieval/filtered-ANN pattern (BASELINE.md config 5): one filter
bitmap intersected against MANY small sets at once. The reference can
only loop pairwise ops; here all right-hand operands marshal into a
``[Q, K, 2048]`` tensor over the union of their chunk keys and the whole
batch runs as one fused dispatch (AND/ANDNOT + per-query popcount).

Host marshal is O(total values); results come back either as counts
(no materialization) or as re-compressed RoaringBitmaps.
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np

from ..models.roaring import RoaringBitmap
from ..ops import device as dev
from . import store


def _pack_one_vs_many(one: RoaringBitmap, many: Sequence[RoaringBitmap]):
    """(filter words [K, 2048], batch words [Q, K, 2048], keys) over the
    union of the right-hand operands' chunk keys."""
    import jax.numpy as jnp

    keys = sorted({k for c in many for k in c.high_low_container.keys})
    kidx = {k: i for i, k in enumerate(keys)}
    filt = np.zeros((max(1, len(keys)), dev.DEVICE_WORDS), dtype=np.uint32)
    hlc = one.high_low_container
    fk = {k: c for k, c in zip(hlc.keys, hlc.containers)}
    present = [k for k in keys if k in fk]
    if present:
        filt[[kidx[k] for k in present]] = store.pack_rows_host([fk[k] for k in present])
    # one expansion pass over EVERY query container, then scatter rows into
    # the [Q, K] layout — pack_rows_host's single-dispatch design is the
    # whole point of the marshal path
    all_containers: List = []
    flat_slots: List[int] = []
    n_keys = max(1, len(keys))
    for qi, c in enumerate(many):
        ch = c.high_low_container
        for k, cont in zip(ch.keys, ch.containers):
            all_containers.append(cont)
            flat_slots.append(qi * n_keys + kidx[k])
    batch = np.zeros((len(many) * n_keys, dev.DEVICE_WORDS), dtype=np.uint32)
    if all_containers:
        batch[np.asarray(flat_slots)] = store.pack_rows_host(all_containers)
    batch = batch.reshape(len(many), n_keys, dev.DEVICE_WORDS)
    return jnp.asarray(filt), jnp.asarray(batch), np.asarray(keys, dtype=np.int64)


_steps = {}

_MASK_FNS = {
    "and": lambda b, f: b & f[None],
    "andnot": lambda b, f: b & ~f[None],
}


def _step(op: str, cards_only: bool):
    """cards_only lets XLA fuse mask+popcount into a reduction without
    materializing the masked [Q, K, 2048] tensor; the materializing
    variant also returns per-(query, key) popcounts for unpacking."""
    fn = _steps.get((op, cards_only))
    if fn is None:
        import jax
        import jax.numpy as jnp

        mask_fn = _MASK_FNS[op]

        # per-(query, key) counts are <= 2^16 so int32 is safe; the final
        # per-query sum happens host-side in int64 — an in-jit (1,2)-axis
        # int32 sum could overflow past 2^31 set bits per query
        if cards_only:

            @jax.jit
            def run(batch, filt):
                masked = mask_fn(batch, filt)
                return jnp.sum(
                    jax.lax.population_count(masked).astype(jnp.int32), axis=2
                )

        else:

            @jax.jit
            def run(batch, filt):
                masked = mask_fn(batch, filt)
                row_cards = jnp.sum(
                    jax.lax.population_count(masked).astype(jnp.int32), axis=2
                )
                return masked, row_cards

        fn = _steps[(op, cards_only)] = run
    return fn


def prepare_batched_cardinality(
    one: RoaringBitmap, many: Sequence[RoaringBitmap], op: str = "and"
):
    """Marshal once, query repeatedly: returns a closure computing
    ``[|many[i] OP one|]`` from the resident device tensors (the
    steady-state retrieval loop; mirror of store.prepare_reduce)."""
    filt, batch, _ = _pack_one_vs_many(one, many)
    step = _step(op, cards_only=True)

    def run() -> np.ndarray:
        row_cards = np.asarray(step(batch, filt)).astype(np.int64)
        return row_cards.sum(axis=1)

    return run


def batched_cardinality(
    one: RoaringBitmap, many: Sequence[RoaringBitmap], op: str = "and"
) -> np.ndarray:
    """``[|many[i] OP one|]`` for every i, one fused dispatch; op in
    {'and', 'andnot'} (andnot = many[i] minus one)."""
    if not many:
        return np.empty(0, dtype=np.int64)
    return prepare_batched_cardinality(one, many, op)()


def batched_intersects(one: RoaringBitmap, many: Sequence[RoaringBitmap]) -> np.ndarray:
    """Boolean mask: does many[i] intersect the filter?"""
    return batched_cardinality(one, many, op="and") > 0


def batched_op(
    one: RoaringBitmap, many: Sequence[RoaringBitmap], op: str = "and"
) -> List[RoaringBitmap]:
    """Materialized ``many[i] OP one`` for every i (results re-compressed
    through the append path)."""
    if not many:
        return []
    filt, batch, keys = _pack_one_vs_many(one, many)
    masked, row_cards = _step(op, cards_only=False)(batch, filt)
    masked_np = np.asarray(masked)
    row_cards_np = np.asarray(row_cards).astype(np.int64)
    return [
        store.unpack_to_bitmap(keys, masked_np[qi], row_cards_np[qi])
        for qi in range(len(many))
    ]
