"""Batched one-vs-many pairwise algebra on device.

The retrieval/filtered-ANN pattern (BASELINE.md config 5): one filter
bitmap intersected against MANY small sets at once. The reference can
only loop pairwise ops; here all right-hand operands marshal into a
``[Q, K, 2048]`` tensor over the union of their chunk keys and the whole
batch runs as one fused dispatch (AND/ANDNOT + per-query popcount).

Host marshal is O(total values); results come back either as counts
(no materialization) or as re-compressed RoaringBitmaps.
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np

from .. import observe as _observe
from ..models.roaring import RoaringBitmap
from ..ops import device as dev
from . import store

# observability: which engine served each pairwise-matrix dispatch
# ("mxu" | "vpu"), surfaced via insights.dispatch_counters()["pairwise"].
# Registry-backed since ISSUE 1 (rb_tpu_batch_pairwise_total).
_PAIRWISE_TOTAL = _observe.counter(
    _observe.BATCH_PAIRWISE_TOTAL,
    "Pairwise-matrix dispatches by engine (mxu | vpu)",
    ("impl",),
)
PAIRWISE_COUNTS = _observe.CounterMap(_PAIRWISE_TOTAL, scalar=True)


def _pack_one_vs_many(one: RoaringBitmap, many: Sequence[RoaringBitmap]):
    """(filter words [K, 2048], batch words [Q, K, 2048], keys) over the
    union of the right-hand operands' chunk keys."""
    import jax.numpy as jnp

    keys = sorted({k for c in many for k in c.high_low_container.keys})
    kidx = {k: i for i, k in enumerate(keys)}
    filt = np.zeros((max(1, len(keys)), dev.DEVICE_WORDS), dtype=np.uint32)
    hlc = one.high_low_container
    fk = {k: c for k, c in zip(hlc.keys, hlc.containers)}
    present = [k for k in keys if k in fk]
    if present:
        filt[[kidx[k] for k in present]] = store.pack_rows_host([fk[k] for k in present])
    # one expansion pass over EVERY query container, then scatter rows into
    # the [Q, K] layout — pack_rows_host's single-dispatch design is the
    # whole point of the marshal path (shared with the pairwise matrices)
    batch = _pack_sets(many, keys, kidx)
    return jnp.asarray(filt), jnp.asarray(batch), np.asarray(keys, dtype=np.int64)


_steps = {}

_MASK_FNS = {
    "and": lambda b, f: b & f[None],
    "andnot": lambda b, f: b & ~f[None],
}


def _step(op: str, cards_only: bool):
    """cards_only lets XLA fuse mask+popcount into a reduction without
    materializing the masked [Q, K, 2048] tensor; the materializing
    variant also returns per-(query, key) popcounts for unpacking."""
    fn = _steps.get((op, cards_only))
    if fn is None:
        import jax
        import jax.numpy as jnp

        mask_fn = _MASK_FNS[op]

        # per-(query, key) counts are <= 2^16 so int32 is safe; the final
        # per-query sum happens host-side in int64 — an in-jit (1,2)-axis
        # int32 sum could overflow past 2^31 set bits per query
        if cards_only:

            @jax.jit
            def run(batch, filt):
                masked = mask_fn(batch, filt)
                return jnp.sum(
                    jax.lax.population_count(masked).astype(jnp.int32), axis=2
                )

        else:

            @jax.jit
            def run(batch, filt):
                masked = mask_fn(batch, filt)
                row_cards = jnp.sum(
                    jax.lax.population_count(masked).astype(jnp.int32), axis=2
                )
                return masked, row_cards

        fn = _steps[(op, cards_only)] = run
    return fn


def prepare_batched_cardinality(
    one: RoaringBitmap, many: Sequence[RoaringBitmap], op: str = "and"
):
    """Marshal once, query repeatedly: returns a closure computing
    ``[|many[i] OP one|]`` from the resident device tensors (the
    steady-state retrieval loop; mirror of store.prepare_reduce).

    The closure exposes its resident tensors and jitted step as
    ``run.device_tensors == (batch, filt)`` and ``run.step`` so callers
    timing steady-state loops (benchmarks/filtered_ann.py) can reuse the
    one marshalled copy instead of re-packing."""
    filt, batch, _ = _pack_one_vs_many(one, many)
    step = _step(op, cards_only=True)

    def run() -> np.ndarray:
        from .. import tracing

        with tracing.op_timer(f"batch.one_vs_many.{op}"):
            row_cards = np.asarray(step(batch, filt)).astype(np.int64)
            return row_cards.sum(axis=1)

    run.device_tensors = (batch, filt)
    run.step = step
    return run


def batched_cardinality(
    one: RoaringBitmap, many: Sequence[RoaringBitmap], op: str = "and"
) -> np.ndarray:
    """``[|many[i] OP one|]`` for every i, one fused dispatch; op in
    {'and', 'andnot'} (andnot = many[i] minus one)."""
    if not many:
        return np.empty(0, dtype=np.int64)
    return prepare_batched_cardinality(one, many, op)()


def batched_intersects(one: RoaringBitmap, many: Sequence[RoaringBitmap]) -> np.ndarray:
    """Boolean mask: does many[i] intersect the filter?"""
    return batched_cardinality(one, many, op="and") > 0


def batched_op(
    one: RoaringBitmap, many: Sequence[RoaringBitmap], op: str = "and"
) -> List[RoaringBitmap]:
    """Materialized ``many[i] OP one`` for every i (results re-compressed
    through the append path)."""
    if not many:
        return []
    filt, batch, keys = _pack_one_vs_many(one, many)
    masked, row_cards = _step(op, cards_only=False)(batch, filt)
    masked_np = np.asarray(masked)
    row_cards_np = np.asarray(row_cards).astype(np.int64)
    return [
        store.unpack_to_bitmap(keys, masked_np[qi], row_cards_np[qi])
        for qi in range(len(many))
    ]


# ---------------------------------------------------------------------------
# many-vs-many: pairwise intersection matrices (similarity analytics)
# ---------------------------------------------------------------------------


def _pack_sets(sets: Sequence[RoaringBitmap], keys, kidx):
    n_keys = max(1, len(keys))
    containers: List = []
    slots: List[int] = []
    for si, bm in enumerate(sets):
        hlc = bm.high_low_container
        for k, cont in zip(hlc.keys, hlc.containers):
            slot = kidx.get(k)
            if slot is None:  # outside the shared key set: cannot intersect
                continue
            containers.append(cont)
            slots.append(si * n_keys + slot)
    out = np.zeros((len(sets) * n_keys, dev.DEVICE_WORDS), dtype=np.uint32)
    if containers:
        out[np.asarray(slots)] = store.pack_rows_host(containers)
    return out.reshape(len(sets), n_keys, dev.DEVICE_WORDS)


_pair_step = None


def _pairwise_step():
    """[nb, K, W] x [m, K, W] -> [nb, m] intersection cardinalities, one
    fused dispatch per left tile (broadcast AND + popcount reduction —
    every pair computed in parallel on the VPU lanes)."""
    global _pair_step
    if _pair_step is None:
        import jax
        import jax.numpy as jnp

        @jax.jit
        def run(left, right):
            masked = left[:, None] & right[None, :]  # [nb, m, K, W]
            # per-(pair, key) counts are <= 65536 so int32 is safe; the
            # key-axis sum happens host-side in int64 (same overflow
            # discipline as _step above — int64 is unavailable in-jit
            # without the x64 flag)
            return jnp.sum(
                jax.lax.population_count(masked).astype(jnp.int32), axis=3
            )

        _pair_step = run
    return _pair_step


_pair_mxu_step = None


def _pairwise_mxu_step():
    """The MXU formulation: popcount(a AND b) over 0/1 bit-vectors IS the
    dot product bits(a) . bits(b) — so the whole overlap matrix is a chain
    of [n, 65536] @ [65536, m] bf16 matmuls, one per key chunk, on the
    systolic array. Exactness: 0/1 are exact in bf16, each per-chunk
    partial is <= 65536 (exact in f32), and the CROSS-chunk accumulation
    runs in int32 after casting each exact partial — so the bound is the
    int32 range (intersections < 2^31), not f32's 2^24 (round 4: the old
    f32 accumulator capped usable cardinalities at 16.7M)."""
    global _pair_mxu_step
    if _pair_mxu_step is None:
        import jax
        import jax.numpy as jnp
        from jax import lax

        shifts = jnp.arange(32, dtype=jnp.uint32)

        @jax.jit
        def run(left, right):  # [n, K, W] u32, [m, K, W] u32
            def bits_of(x):  # [s, W] -> [s, W*32] 0/1 bf16
                b = (x[..., None] >> shifts) & jnp.uint32(1)
                return b.reshape(x.shape[0], -1).astype(jnp.bfloat16)

            def body(acc, kslice):
                lk, rk = kslice
                part = jnp.dot(
                    bits_of(lk),
                    bits_of(rk).T,
                    preferred_element_type=jnp.float32,
                )
                return acc + part.astype(jnp.int32), None

            init = jnp.zeros((left.shape[0], right.shape[0]), jnp.int32)
            acc, _ = lax.scan(
                body, init, (left.transpose(1, 0, 2), right.transpose(1, 0, 2))
            )
            return acc

        _pair_mxu_step = run
    return _pair_mxu_step


def pairwise_and_cardinality(
    lefts: Sequence[RoaringBitmap],
    rights: Sequence[RoaringBitmap],
    tile_bytes: int = 256 << 20,
    impl: str = "auto",
) -> np.ndarray:
    """``out[i, j] = |lefts[i] AND rights[j]|`` as one batched device
    computation — the all-pairs overlap matrix behind similarity joins and
    Jaccard analytics, which the reference can only assemble with n*m
    pairwise andCardinality calls.

    ``impl``: 'vpu' broadcasts AND + popcount (left axis tiled so the
    [nb, m, K, 2048] intermediate stays under ``tile_bytes``); 'mxu'
    expresses popcounts as 0/1 bf16 matmuls over the systolic array —
    the shape that makes this matrix a native TPU workload. 'auto' picks
    mxu on accelerators (when every cardinality is inside the exact
    int32-accumulation bound, 2^31), vpu on CPU."""
    if impl not in ("auto", "vpu", "mxu"):
        raise ValueError(f"impl must be 'auto', 'vpu', or 'mxu', got {impl!r}")
    n, m = len(lefts), len(rights)
    if n == 0 or m == 0:
        return np.zeros((n, m), dtype=np.int64)
    import jax
    import jax.numpy as jnp

    keys = sorted(
        {k for c in lefts for k in c.high_low_container.keys}
        & {k for c in rights for k in c.high_low_container.keys}
    )
    if not keys:  # no shared chunk: every intersection is empty
        return np.zeros((n, m), dtype=np.int64)
    def _exact():
        # int32 accumulation exactness bound for the bit-matmul: each
        # per-chunk partial is exact in f32 (<= 65536) and cross-chunk
        # sums run in int32, so only intersections >= 2^31 could wrap —
        # impossible when every operand is smaller than that
        return all(b.get_cardinality() < (1 << 31) for b in (*lefts, *rights))

    if impl == "auto":
        try:
            on_acc = jax.default_backend() != "cpu"
        except RuntimeError:  # backend init failure -> VPU path (CPU-safe)
            on_acc = False
        impl = "mxu" if (on_acc and _exact()) else "vpu"
    elif impl == "mxu" and not _exact():
        raise ValueError(
            "impl='mxu' needs every cardinality < 2^31 (int32 accumulation "
            "exactness); use impl='vpu' or 'auto' for larger sets"
        )
    from .. import tracing

    kidx = {k: i for i, k in enumerate(keys)}
    lw = _pack_sets(lefts, keys, kidx)
    rw_host = _pack_sets(rights, keys, kidx)
    _PAIRWISE_TOTAL.inc(1, (impl,))
    with tracing.op_timer(f"batch.pairwise.{impl}"):
        if impl == "mxu":
            return (
                np.asarray(_pairwise_mxu_step()(jnp.asarray(lw), jnp.asarray(rw_host)))
                .astype(np.int64)
            )
        rw = jnp.asarray(rw_host)
        step = _pairwise_step()
        per_row = 4 * m * len(keys) * dev.DEVICE_WORDS
        nb = max(1, min(n, tile_bytes // max(1, per_row)))
        out = np.empty((n, m), dtype=np.int64)
        for s in range(0, n, nb):
            per_key = np.asarray(step(jnp.asarray(lw[s : s + nb]), rw))
            out[s : s + nb] = per_key.astype(np.int64).sum(axis=2)
        return out


def _inclusion_exclusion(op: str, inter: np.ndarray, lefts, rights) -> np.ndarray:
    """Derive an or/xor/andnot cardinality matrix from the AND matrix and
    the per-set cardinalities — exact in int64 (|A|+|B|-|A&B|,
    |A|+|B|-2|A&B|, |A|-|A&B|). One formula source for pairwise_cardinality
    and pairwise_jaccard."""
    lc = np.array([b.get_cardinality() for b in lefts], dtype=np.int64)
    if op == "andnot":
        return lc[:, None] - inter
    rc = np.array([b.get_cardinality() for b in rights], dtype=np.int64)
    return lc[:, None] + rc[None, :] - (2 if op == "xor" else 1) * inter


def prepare_pairwise_mxu(
    lefts: Sequence[RoaringBitmap], rights: Sequence[RoaringBitmap]
):
    """Marshal once for repeated MXU overlap-matrix dispatches: returns a
    closure computing the [n, m] intersection-cardinality matrix from
    resident device tensors, exposing ``run.device_tensors == (lw, rw)``
    and ``run.step`` (the jitted bit-matmul) for steady-state timing.
    Exactness bound as pairwise_and_cardinality(impl='mxu')."""
    import jax.numpy as jnp

    keys = sorted(
        {k for c in lefts for k in c.high_low_container.keys}
        & {k for c in rights for k in c.high_low_container.keys}
    )
    if not keys:
        n, m = len(lefts), len(rights)

        def run_empty() -> np.ndarray:
            return np.zeros((n, m), dtype=np.int64)

        run_empty.device_tensors = None
        run_empty.step = None
        return run_empty
    if not all(b.get_cardinality() < (1 << 31) for b in (*lefts, *rights)):
        raise ValueError("MXU path needs every cardinality < 2^31")
    kidx = {k: i for i, k in enumerate(keys)}
    lw = jnp.asarray(_pack_sets(lefts, keys, kidx))
    rw = jnp.asarray(_pack_sets(rights, keys, kidx))
    step = _pairwise_mxu_step()

    def run() -> np.ndarray:
        return np.asarray(step(lw, rw)).astype(np.int64)

    run.device_tensors = (lw, rw)
    run.step = step
    return run


def pairwise_jaccard(
    lefts: Sequence[RoaringBitmap], rights: Sequence[RoaringBitmap]
) -> np.ndarray:
    """``out[i, j] = |L_i & R_j| / |L_i | R_j|`` (0 for two empty sets):
    the similarity matrix via one intersection-matrix dispatch plus
    inclusion-exclusion from the per-set cardinalities."""
    inter = pairwise_and_cardinality(lefts, rights)
    union = _inclusion_exclusion("or", inter, lefts, rights).astype(np.float64)
    with np.errstate(invalid="ignore"):
        sim = np.where(union > 0, inter / np.maximum(union, 1e-300), 0.0)
    return sim


def pairwise_cardinality(
    lefts: Sequence[RoaringBitmap],
    rights: Sequence[RoaringBitmap],
    op: str = "and",
    impl: str = "auto",
) -> np.ndarray:
    """All-pairs cardinality matrix for any of the four ops — the batched
    twin of the reference's scalar ``andCardinality/orCardinality/...``
    statics (RoaringBitmap.java:413-944), which can only assemble a matrix
    with n*m pairwise calls.

    One device dispatch computes the AND matrix; OR/XOR/ANDNOT follow by
    exact int64 inclusion-exclusion — no second dispatch."""
    if op not in ("and", "or", "xor", "andnot"):
        raise ValueError(f"op must be one of and/or/xor/andnot, got {op!r}")
    inter = pairwise_and_cardinality(lefts, rights, impl=impl)
    if op == "and":
        return inter
    return _inclusion_exclusion(op, inter, lefts, rights)
