"""Double-buffered host→HBM shipping lane (ISSUE 8 tentpole, leg 3).

Back-to-back device queries used to serialize marshal and compute: pack,
ship, reduce, repeat — the device idles while the host marshals, the host
idles while the device reduces. This module overlaps them: ``prefetch``
stages the NEXT query's pack + device expansion on a dedicated shipping
thread (the work lands in ``store.PACK_CACHE``, so the consumer's normal
``packed_for`` lookup comes back resident), and ``wait`` joins the staged
work under the ``overlap_wait`` pack stage — the only marshal time the
consumer still pays is whatever the previous query's compute did not hide.

Double-buffered, not queued: at most ``depth`` (default 1) stagings are in
flight; a prefetch past the window is dropped (returns None) rather than
growing an unbounded backlog of multi-GB working sets. JAX async dispatch
does the same for the device side; explicit fences
(``observe.timeline.fence``) keep the traced twin rows truthful.

Adaptive threading: a shipping lane only hides marshal time when there is
a second core (or a DMA engine) to run it on. On a single-core host the
lane thread just time-slices against the consumer's reduce — same total
work plus context-switch and cache-thrash tax (measured ~7% of the
4-query twin wall on the 1-core bench host). ``threading_mode`` therefore
defaults to ``"auto"``: threaded when ``os.cpu_count() > 1``, standing
down to inline staging otherwise (``prefetch`` returns None and the
consumer's normal ``packed_for`` packs synchronously — the same bits, no
lane tax). ``configure("on"/"off")`` pins it for tests and tuning.

Fault threading (ISSUE 8 satellite): the staging job runs the REAL
pipeline, so the ``store.expand`` / ``store.ship`` / ``store.hbm`` fault
sites fire on the lane thread. A failed staging never propagates from
``prefetch``; ``wait`` classifies it — FATAL re-raises (degradation must
never launder a wrong-answer bug), anything else degrades to synchronous
packing on the consumer thread (``rb_tpu_degrade_total{site="store.expand",
from="lane",to="sync"}``) which is bit-exact by construction.

``rb_tpu_store_overlap_ratio`` gauges the cumulative fraction of staged
marshal wall hidden behind compute: 0 = the consumer waited out every
staging (fully serial), 1 = every staging finished before the consumer
arrived (fully hidden).

Lock discipline: the lane lock is a leaf over the staging bookkeeping only
— the staged job itself runs OUTSIDE it (it takes the pack-cache lock), so
lane -> pack.cache never nests.
"""

from __future__ import annotations

import os
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence, Tuple

from .. import observe as _observe
from ..observe import context as _context
from ..observe import timeline as _timeline
from ..robust import errors as _rerrors
from ..robust import ladder as _ladder
from . import store

_OVERLAP_RATIO = _observe.gauge(
    _observe.STORE_OVERLAP_RATIO,
    "Fraction of staged marshal wall hidden behind compute by the overlap "
    "shipping lane (cumulative)",
    ("lane",),
)


class _Staging:
    __slots__ = ("future", "t_submit", "duration_s", "trace", "flow")

    def __init__(self, future: Future):
        self.future = future
        self.t_submit = time.monotonic()
        self.duration_s = 0.0  # staged marshal wall, set by the lane thread
        # explicit trace handoff (ISSUE 9): contextvars do not cross the
        # lane-thread boundary, so the submitter's query trace id rides the
        # staging and the lane adopts it — every recorder event the staged
        # pack emits carries the originating query's id
        self.trace = None
        self.flow = 0


class ShipLane:
    """The double-buffered shipping lane (module singleton ``LANE``)."""

    _MODES = ("auto", "on", "off")

    def __init__(self, depth: int = 1, threading_mode: str = "auto"):
        if depth < 1:
            raise ValueError(f"lane depth must be >= 1, got {depth}")
        if threading_mode not in self._MODES:
            raise ValueError(
                f"lane threading_mode must be one of {self._MODES}, "
                f"got {threading_mode!r}"
            )
        self.depth = int(depth)
        self.threading_mode = threading_mode
        self._lock = threading.Lock()
        self._pending: Dict[tuple, _Staging] = {}  # guarded-by: self._lock
        self._staged_s = 0.0  # guarded-by: self._lock
        self._hidden_s = 0.0  # guarded-by: self._lock
        self._pool: Optional[ThreadPoolExecutor] = None  # guarded-by: self._lock

    def configure(self, threading_mode: str) -> None:
        """Pin the lane's threading decision (see the module docstring)."""
        if threading_mode not in self._MODES:
            raise ValueError(
                f"lane threading_mode must be one of {self._MODES}, "
                f"got {threading_mode!r}"
            )
        self.threading_mode = threading_mode

    def threaded(self) -> bool:
        """Is there parallelism for the lane to exploit? (``auto``: yes iff
        the host has more than one core.)"""
        mode = self.threading_mode
        if mode == "on":
            return True
        if mode == "off":
            return False
        return (os.cpu_count() or 1) > 1

    # -- internals ---------------------------------------------------------

    def _executor(self) -> ThreadPoolExecutor:
        with self._lock:
            if self._pool is None:
                # eager thread-name registration (ISSUE 9 satellite): a
                # lane thread that only ever emits instants must still be
                # named in the Perfetto export, so register at thread
                # start, not lazily at first record
                self._pool = ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="rb-ship-lane",
                    initializer=_timeline.register_thread,
                )
            return self._pool

    @staticmethod
    def _key(bitmaps: Sequence, marker: str) -> tuple:
        return (marker, tuple(bm.fingerprint() for bm in bitmaps))

    def _stage(self, bitmaps: List, keys_filter: Optional[set], st: _Staging):
        """Runs on the lane thread: the REAL pack + device expansion (all
        fault sites live), fenced so the staging duration is truthful.
        Adopts the submitting query's trace id (explicit handoff — see
        ``_Staging.trace``) so every recorder event underneath carries
        it, and marks the flow step linking the prefetch to this span."""
        t0 = time.monotonic()
        try:
            with _context.adopt(st.trace):
                _timeline.flow_point("overlap.handoff", "t", st.flow)
                with _timeline.tspan(
                    "overlap.stage", "overlap", n=len(bitmaps)
                ):
                    packed = store.packed_for(bitmaps, keys_filter)
                    _timeline.fence(packed.device_words)
            return packed
        finally:
            st.duration_s = time.monotonic() - t0

    # -- public API --------------------------------------------------------

    def prefetch(self, bitmaps: Sequence, keys_filter: Optional[set] = None):
        """Stage this working set's pack + expansion on the lane thread.
        Returns the staging ticket, or None when the window is full (the
        double-buffer discipline) or the set is already staged/resident.
        Staging failures surface at ``wait``/``join`` — the only exception
        this can raise is a FATAL parked in an orphaned staging it reaps
        (same contract as ``drain``)."""
        if not self.threaded():
            # single-core stand-down: staging would time-slice against the
            # consumer's compute for the same total work plus switch tax —
            # the consumer's synchronous pack is strictly cheaper
            _timeline.instant("overlap.inline", "overlap")
            return None
        bitmaps = list(bitmaps)
        key = self._key(bitmaps, "all" if keys_filter is None else "and")
        reaped: List[_Staging] = []
        with self._lock:
            if key in self._pending:
                return self._pending[key]
            if len(self._pending) >= self.depth:
                # self-healing: a staging whose consumer never joined (e.g.
                # its bitmaps mutated, so the join key no longer matches)
                # must not wedge the window forever — reap finished futures
                # before declaring the window full (results stay in the
                # pack cache; only the bookkeeping is dropped, like drain)
                for k in [
                    k for k, s in self._pending.items() if s.future.done()
                ]:
                    reaped.append(self._pending.pop(k))
            full = len(self._pending) >= self.depth
        # discard orphans BEFORE inserting our own staging: a FATAL parked
        # in one re-raises here, and an already-inserted entry would be a
        # never-submitted Future that wedges every later wait on its key
        for orphan in reaped:
            _timeline.instant("overlap.reap", "overlap")
            try:
                orphan.future.result()
            except Exception as e:  # rb-ok: exception-hygiene -- reap mirrors drain's non-fatal discard; FATAL re-raises (degradation must never launder a wrong-answer bug)
                if _rerrors.classify(e) == _rerrors.FATAL:
                    raise
        if full:
            _timeline.instant("overlap.window_full", "overlap")
            return None
        with self._lock:
            st = self._pending.get(key)
            if st is not None:
                return st
            if len(self._pending) >= self.depth:  # lost a concurrent race
                _timeline.instant("overlap.window_full", "overlap")
                return None
            st = _Staging(Future())
            st.trace = _context.current_trace()
            st.flow = _timeline.flow_id(st.trace, key)
            self._pending[key] = st
        # flow start at the submitter: Perfetto draws the handoff arrow
        # from here to the lane's staging span and on to the consumer's
        # overlap_wait (no-op while recording is off)
        _timeline.flow_point("overlap.handoff", "s", st.flow)
        # submit OUTSIDE the lock: executor init + enqueue take their own
        # locks, and the job itself takes the pack-cache lock
        def _run():
            try:
                st.future.set_result(self._stage(bitmaps, keys_filter, st))
            except BaseException as e:  # rb-ok: exception-hygiene -- lane boundary: the exception is parked in the Future and classified at wait(); FATAL re-raises there, everything else degrades to the synchronous pack
                st.future.set_exception(e)

        try:
            self._executor().submit(_run)
        except BaseException:
            # a failed enqueue must not leave a never-completed Future in
            # the window (wait on it would block forever)
            with self._lock:
                self._pending.pop(key, None)
            raise
        return st

    def wait(self, bitmaps: Sequence, keys_filter: Optional[set] = None):
        """Join this working set's staging (if any): returns the resident
        pack, or None when nothing was staged or the staging failed
        non-fatally — the caller's normal ``packed_for`` then packs
        synchronously, bit-exact. Accounts the ``overlap_wait`` stage and
        the overlap-ratio gauge."""
        return self._join(
            self._key(list(bitmaps), "all" if keys_filter is None else "and")
        )

    def join(self, bitmaps: Sequence, op: str = "or"):
        """``wait`` addressed by the op instead of the prelude's keys
        filter: the lane key only distinguishes AND's key-filtered pack
        from all-keys packs, so a consumer that has not (yet) paid the
        dispatch prelude — the AND key intersection the consuming engine
        will compute anyway — can still pop its staging."""
        return self._join(
            self._key(list(bitmaps), "and" if op == "and" else "all")
        )

    def _join(self, key: tuple):
        with self._lock:
            st = self._pending.pop(key, None)
        if st is None:
            return None
        t0 = time.monotonic()
        try:
            with _timeline.stage(
                store._PACK_STAGE_SECONDS, "overlap_wait", "pack.overlap_wait",
                cat="pack",
            ):
                packed = st.future.result()
                _timeline.flow_point("overlap.handoff", "f", st.flow)
        except Exception as e:
            if _rerrors.classify(e) == _rerrors.FATAL:
                raise
            _ladder.LADDER.note_degrade("store.expand", "lane", "sync", e)
            return None
        waited = time.monotonic() - t0
        with self._lock:
            self._staged_s += st.duration_s
            self._hidden_s += max(0.0, st.duration_s - waited)
            ratio = self._hidden_s / self._staged_s if self._staged_s else 0.0
        _OVERLAP_RATIO.set(round(ratio, 4), ("ship",))
        return packed

    def drain(self) -> None:
        """Join every in-flight staging and drop the bookkeeping (tests,
        mode flips): staged results stay in the pack cache, failures are
        discarded here exactly like a non-fatal wait."""
        with self._lock:
            pending = list(self._pending.values())
            self._pending.clear()
        for st in pending:
            try:
                st.future.result()
            except Exception as e:  # rb-ok: exception-hygiene -- drain mirrors wait's non-fatal discard; FATAL would have re-raised at a real wait and the staging result is unused here
                if _rerrors.classify(e) == _rerrors.FATAL:
                    raise

    def stats(self) -> dict:
        with self._lock:
            return {
                "staged_s": self._staged_s,
                "hidden_s": self._hidden_s,
                "pending": len(self._pending),
            }


LANE = ShipLane()


def run_pipelined(
    jobs: Sequence[Tuple[Sequence, str]], mode: Optional[str] = None
) -> List:
    """Run back-to-back N-way aggregations with the marshal lane: for each
    ``(bitmaps, op)`` job, the NEXT job's pack + device expansion stages on
    the lane thread while the current job reduces — steady-state traffic
    never idles the device on the host marshal (ISSUE 8 leg 3).

    Equivalent to ``[FastAggregation.<op>(*bitmaps, mode=mode), ...]`` —
    same engines, same ladder, same bits; only the staging overlaps.

    Trace attribution (ISSUE 9): every job gets its own pre-assigned
    trace id, and job i+1's *prefetch* runs under job i+1's id even
    though job i's loop iteration drives it — the staged lane work is the
    consumer query's marshal, so that is the query it must attribute to."""
    from . import aggregation

    jobs = [(list(bms), op) for bms, op in jobs]
    tids = [_context.new_trace_id() for _ in jobs]
    out = []
    for i, (bms, op) in enumerate(jobs):
        with _context.trace_scope(tids[i]):
            # join our own staging (overlap_wait) by op marker — the
            # dispatch prelude (AND key intersection) is left to
            # _aggregate, which pays it exactly once per job
            LANE.join(bms, op)
        if i + 1 < len(jobs):
            with _context.trace_scope(tids[i + 1]):
                aggregation.prefetch(jobs[i + 1][0], jobs[i + 1][1], mode=mode)
        with _context.trace_scope(tids[i]):
            out.append(aggregation._aggregate(bms, op, mode))
    return out
