"""L4' aggregation engines: N-way AND/OR/XOR with algorithm selection.

API parity with FastAggregation (FastAggregation.java:15) and
ParallelAggregation (ParallelAggregation.java:39). The reference picks among
fold strategies (naive lazy fold :541, horizontal priority-queue merge :183,
workShyAnd key-intersection :356); here the strategic choice is CPU vs
device:

* **CPU path** — key-major transpose, then an in-place word fold per group
  with one popcount at the end: the direct analogue of the lazy-OR protocol
  (Container.lazyIOR Container.java:717, repairAfterLazy :873) expressed as
  vectorized numpy.
* **Device path** — pack all groups into one ``[N, 2048]`` uint32 device
  array (parallel/store.py) and run a single fused batched reduction +
  popcount (ops/device.py, ops/pallas_kernels.py). This is the north-star
  configuration (BASELINE.md).

`workShyAnd`'s key trick (intersect keys first, only then touch containers,
FastAggregation.java:356-396) is kept verbatim in spirit: AND packs only the
key-intersection groups, which also makes every group exactly B rows — a
dense, padding-free device layout.

ParallelAggregation re-expresses the reference's fork-join per-key reduce as
a thread pool over key groups on CPU (numpy releases the GIL) and as the
same single batched kernel on device — the degenerate case where the
"fork-join pool" is the VPU grid itself.
"""

from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..models.container import (
    ArrayContainer,
    BitmapContainer,
    Container,
    RunContainer,
    best_container_of_words,
)
from ..models.roaring import RoaringBitmap
from ..observe import context as _context
from ..observe import decisions as _decisions
from ..observe import sentinel as _sentinel
from ..observe import timeline as _timeline
from ..robust import errors as _rerrors
from ..robust import ladder as _ladder
from ..utils import bits
from . import store


class config:
    """Dispatcher knobs (the reference's analogue is compile-time constants +
    the >10-input workShyAnd switch, FastAggregation.java:37-42)."""

    mode: str = "auto"  # 'auto' | 'cpu' | 'device'
    min_device_containers: int = 64
    # Optional jax.sharding.Mesh: when set (e.g. sharding.make_mesh()), the
    # device OR path runs the mesh-sharded reduction (container axis data
    # parallel over ICI) instead of the single-chip kernel.
    mesh = None


def _use_device(n_containers: int, mode: Optional[str]) -> bool:
    mode = mode or config.mode
    if mode == "cpu":
        return False
    if mode == "device":
        return True
    try:
        import jax

        backend = jax.default_backend()
    except (ImportError, RuntimeError):
        # jax missing, or present but no usable backend (RuntimeError from
        # backend init, e.g. stale JAX_PLATFORMS) — the CPU word-fold path
        # needs no jax at all.
        return False
    return backend != "cpu" and n_containers >= config.min_device_containers


# ---------------------------------------------------------------------------
# CPU word folds (lazy-OR protocol analogue)
# ---------------------------------------------------------------------------


def _ior_container_into(acc: np.ndarray, c: Container) -> None:
    """OR one container into a word accumulator without materializing it
    (the lazy-OR protocol's per-type fast paths)."""
    if isinstance(c, BitmapContainer):
        acc |= c.words
    elif isinstance(c, ArrayContainer):
        bits.or_values_into_words(acc, c.content)
    else:
        for s, l in zip(c.starts.tolist(), c.lengths.tolist()):
            bits.set_bitmap_range(acc, s, s + l + 1)


def _fold_group_words(cs: List[Container], op: str) -> np.ndarray:
    """In-place word fold of one key group; popcount deferred to the caller."""
    first = cs[0]
    acc = first.to_words()  # always a copy
    if op == "or":
        for c in cs[1:]:
            _ior_container_into(acc, c)
    elif op == "and":
        for c in cs[1:]:
            acc &= c.words if isinstance(c, BitmapContainer) else c.to_words()
    else:  # xor
        for c in cs[1:]:
            acc ^= c.words if isinstance(c, BitmapContainer) else c.to_words()
    return acc


def _percontainer_aggregate(
    groups: Dict[int, List[Container]], op: str, pool: Optional[ThreadPoolExecutor] = None
) -> RoaringBitmap:
    """The per-container tier: per-key word-fold walk (optionally on the
    shared pool) — no columnar batching, no device."""
    out = RoaringBitmap()
    keys = sorted(groups)

    def reduce_key(k: int) -> Container:
        cs = groups[k]
        if len(cs) == 1:
            return cs[0].clone()
        return best_container_of_words(_fold_group_words(cs, op))

    if pool is None:
        results = [reduce_key(k) for k in keys]
    else:
        results = list(pool.map(reduce_key, keys))
    for k, c in zip(keys, results):
        if c.cardinality:
            out.high_low_container.append(k, c)
    return out


def _device_aggregate(
    bitmaps: Sequence[RoaringBitmap], keys_filter, op: str
) -> RoaringBitmap:
    """Device reduce via the resident pack cache (ISSUE 4): a warm working
    set skips the host transpose + pack entirely; a mutated one re-ships
    only its dirty rows. The pack is op-independent (fill values live in
    the per-layout caches), so OR/XOR/AND-cardinality over the same
    bitmaps share one resident entry."""
    with _timeline.tspan(
        "agg.device", "agg", trace=True, op=op, n=len(bitmaps)
    ):
        packed = store.packed_for(bitmaps, keys_filter)
        if config.mesh is not None:
            words, cards = _sharded_reduce(packed, op)
        else:
            words, cards = store.reduce_packed(packed, op=op)
        return store.unpack_to_bitmap(packed.group_keys, words, cards)


def _sharded_reduce(packed: "store.PackedGroups", op: str, cards_only: bool = False):
    """Mesh-sharded grouped reduce (or/and/xor): pad each group's row count
    to the mesh's container-axis size with the op identity
    (store.pad_groups_dense, the shared layout + skew guard) and run the
    ICI combine (sharding.py). Too-skewed distributions fall back to the
    single-device segmented layout. With ``cards_only`` the reduced words
    stay on device (returned as None) and only the [G] counts transfer."""
    import jax
    import jax.numpy as jnp

    from ..ops import device as dev
    from . import sharding

    mesh = config.mesh
    if any(d.process_index != jax.process_index() for d in mesh.devices.flat):
        # the padded tensor is built process-locally; forming the global
        # array on a multi-host mesh needs per-process shards
        # (jax.make_array_from_process_local_data) — route such jobs through
        # sharding.distributed_grouped_reduce directly with pre-sharded inputs
        raise NotImplementedError(
            "config.mesh routing supports single-host meshes; for multi-host "
            "use parallel.sharding.distributed_grouped_reduce with a globally "
            "formed array"
        )
    padded = store.pad_groups_dense(
        packed, int(dev._INIT[op]), row_multiple=mesh.devices.shape[0]
    )
    if padded is None:
        if cards_only:
            return None, store.reduce_packed_cardinality(packed, op=op)
        return store.reduce_packed(packed, op=op)
    red, cards = sharding.distributed_grouped_reduce(mesh, op)(jnp.asarray(padded))
    if cards_only:
        return None, np.asarray(cards).astype(np.int64)
    return np.asarray(red), np.asarray(cards).astype(np.int64)


def _dispatch_prelude(bitmaps: Sequence[RoaringBitmap], op: str):
    """Shared dispatch prelude for the materializing and cardinality-only
    engines: the AND key intersection (FastAggregation.workShyAnd) and the
    working-set row count — WITHOUT building the key-major transpose, so a
    warm device path (resident pack-cache hit) never pays the group walk.
    Returns ``(keys_filter, n_rows)``; keys_filter is None for or/xor and
    an empty set when the AND intersection is empty (trivial result)."""
    if op == "and":
        keys = store.intersect_keys(bitmaps)
        if not keys:
            return set(), 0
        n = sum(
            sum(1 for k in bm.high_low_container.keys if k in keys)
            for bm in bitmaps
        )
        return keys, n
    return None, sum(bm.high_low_container.size for bm in bitmaps)


def prefetch(bitmaps, op: str = "or", mode: Optional[str] = None):
    """Stage a working set's pack + host→HBM expansion on the overlap
    shipping lane (ISSUE 8 leg 3): call with the NEXT query's operands
    while the current query reduces, and its eventual dispatch finds the
    pack resident. The SAME dispatch prelude as the engines (AND key
    intersection, device cost gate), so only working sets that would ride
    the device path stage — a CPU-bound job never burns lane time.
    Returns the staging ticket, or None when nothing stages (CPU route,
    trivial AND, lane window full)."""
    bitmaps = _flatten((bitmaps,)) if hasattr(bitmaps, "high_low_container") \
        else [b for b in bitmaps]
    if len(bitmaps) < 2:
        return None
    keys, n = _dispatch_prelude(bitmaps, op)
    if keys is not None and not keys:
        return None  # trivial AND: nothing will pack
    if not _use_device(n, mode):
        return None
    from . import overlap

    return overlap.LANE.prefetch(bitmaps, keys)


def _pure_python_fold(bitmaps: Sequence[RoaringBitmap], op: str) -> RoaringBitmap:
    """The bottom ladder rung: the reference's naive sequential folds with
    every batching layer (columnar router included) pinned off — the
    engine of last resort, kept deliberately free of the machinery whose
    failure would land traffic here."""
    from .. import columnar

    with columnar.disabled():
        if op == "or":
            return FastAggregation.naive_or(*bitmaps)
        if op == "xor":
            return FastAggregation.naive_xor(*bitmaps)
        return FastAggregation.naive_and(*bitmaps)


def _cpu_tiers(
    bitmaps: Sequence[RoaringBitmap],
    keys: Optional[set],
    n: int,
    op: str,
    pool: Optional[ThreadPoolExecutor] = None,
):
    """The CPU rungs of the aggregation ladder, cost-model-gated exactly
    like the pre-ladder dispatch: the columnar batched fold for large
    OR/XOR working sets (AND's columnar variant measured ~2x slower than
    the lazy per-group fold, so AND starts per-container), the per-key
    word-fold walk, and the pure-python naive fold as last resort. The
    key-major transpose builds lazily ONCE and is shared by whichever
    rung ends up running."""
    from .. import columnar

    box: Dict[str, Dict[int, List[Container]]] = {}

    def _groups():
        if "g" not in box:
            box["g"] = store.group_by_key(bitmaps, keys_filter=keys)
        return box["g"]

    tiers = []
    if op != "and" and columnar.enabled_for_fold(n):

        def _columnar_tier():
            with _timeline.tspan("agg.cpu", "agg", op=op, rows=n):
                return columnar.fold(_groups(), op)

        tiers.append(("columnar-cpu", _columnar_tier))

    def _percontainer_tier():
        with _timeline.tspan("agg.cpu", "agg", op=op, rows=n):
            return _percontainer_aggregate(_groups(), op, pool=pool)

    tiers.append(("per-container", _percontainer_tier))
    tiers.append(("pure-python", lambda: _pure_python_fold(bitmaps, op)))
    return tiers


def _aggregate(
    bitmaps: Sequence[RoaringBitmap],
    op: str,
    mode: Optional[str] = None,
    pool: Optional[ThreadPoolExecutor] = None,
) -> RoaringBitmap:
    """N-way aggregation through the degradation ladder (ISSUE 7): the
    cost model still picks the STARTING tier (device vs columnar vs
    per-container, exactly the pre-ladder dispatch); the ladder owns what
    happens when a tier fails — classify, record tier health, ride the
    next tier down, emit ``rb_tpu_degrade_total`` — one code path for
    every degradation instead of per-site try/except scatter. Every tier
    computes the same bits (the fuzz oracle family pins this).

    Top-level trace entry (ISSUE 9): opens a query trace scope (reusing
    the ambient one when called from the query executor) and records the
    start-tier decision with the cost-model inputs that drove it."""
    bitmaps = [b for b in bitmaps]
    if not bitmaps:
        return RoaringBitmap()
    if len(bitmaps) == 1:
        return bitmaps[0].clone()
    # inline sentinel pacing (ISSUE 12): single-threaded serving loops get
    # health supervision on the dispatch path; off (default) = one bool
    _sentinel.maybe_tick()
    with _context.trace_scope():
        keys, n = _dispatch_prelude(bitmaps, op)
        if keys is not None and not keys:
            return RoaringBitmap()
        tiers = []
        if _use_device(n, mode):
            tiers.append(("device", lambda: _device_aggregate(bitmaps, keys, op)))
        tiers.extend(_cpu_tiers(bitmaps, keys, n, op, pool=pool))
        from .. import columnar

        # outcome=True (ISSUE 11): the ladder resolves this decision with
        # the tier that actually absorbed the traffic + its measured wall
        seq = _decisions.record_decision(
            "agg.dispatch", tiers[0][0], outcome=True, op=op, rows=n,
            operands=len(bitmaps), mode=mode or config.mode,
            # cost-model provenance (ISSUE 10): the measured fold gate the
            # CPU-tier choice consulted (config default when uncalibrated)
            fold_gate=columnar.MODEL.fold_gate_rows(),
        )
        return _ladder.LADDER.run(
            "agg", tiers, outcome_seq=seq, outcome_site="agg.dispatch"
        )


# ---------------------------------------------------------------------------
# public engines
# ---------------------------------------------------------------------------


class FastAggregation:
    """N-way aggregation (FastAggregation.java:15). All strategy entry points
    of the reference are kept as callable names; they share the batched
    engine (the strategy distinction that matters here is CPU vs device,
    chosen by the dispatcher)."""

    @staticmethod
    def or_(*bitmaps: RoaringBitmap, mode: Optional[str] = None) -> RoaringBitmap:
        """FastAggregation.or (FastAggregation.java:602)."""
        return _aggregate(_flatten(bitmaps), "or", mode)

    @staticmethod
    def and_(*bitmaps: RoaringBitmap, mode: Optional[str] = None) -> RoaringBitmap:
        """FastAggregation.and — workShy key intersection for many inputs
        (FastAggregation.java:37-42, :356-396)."""
        return _aggregate(_flatten(bitmaps), "and", mode)

    @staticmethod
    def xor(*bitmaps: RoaringBitmap, mode: Optional[str] = None) -> RoaringBitmap:
        return _aggregate(_flatten(bitmaps), "xor", mode)

    # ---- distinct strategy engines (cross-checking oracles, like the
    # reference's: equivalence of naive vs horizontal vs priority-queue is a
    # fuzz invariant, SURVEY §4) -------------------------------------------

    @staticmethod
    def naive_or(*bitmaps: RoaringBitmap) -> RoaringBitmap:
        """Sequential lazy fold (FastAggregation.naive_or :541 +
        Container.lazyIOR protocol): accumulate words per key left to right,
        popcount once at the end."""
        bms = _flatten(bitmaps)
        acc: Dict[int, np.ndarray] = {}
        for bm in bms:
            hlc = bm.high_low_container
            for k, c in zip(hlc.keys, hlc.containers):
                words = acc.get(k)
                if words is None:
                    acc[k] = c.to_words()
                else:
                    _ior_container_into(words, c)
        out = RoaringBitmap()
        for k in sorted(acc):
            c = best_container_of_words(acc[k])
            if c.cardinality:
                out.high_low_container.append(k, c)
        return out

    @staticmethod
    def naive_xor(*bitmaps: RoaringBitmap) -> RoaringBitmap:
        bms = _flatten(bitmaps)
        acc: Dict[int, np.ndarray] = {}
        for bm in bms:
            hlc = bm.high_low_container
            for k, c in zip(hlc.keys, hlc.containers):
                words = acc.get(k)
                if words is None:
                    acc[k] = c.to_words()
                else:
                    words ^= c.words if isinstance(c, BitmapContainer) else c.to_words()
        out = RoaringBitmap()
        for k in sorted(acc):
            c = best_container_of_words(acc[k])
            if c.cardinality:
                out.high_low_container.append(k, c)
        return out

    @staticmethod
    def naive_and(*bitmaps: RoaringBitmap) -> RoaringBitmap:
        """Pairwise left fold (FastAggregation.naive_and)."""
        bms = _flatten(bitmaps)
        if not bms:
            return RoaringBitmap()
        acc = bms[0].clone()
        for bm in bms[1:]:
            acc.iand(bm)
            if acc.is_empty():
                break
        return acc

    @staticmethod
    def horizontal_or(*bitmaps: RoaringBitmap) -> RoaringBitmap:
        """Priority-queue merge of ContainerPointer cursors
        (FastAggregation.horizontal_or :183-230): a heap of (key, cursor)
        pairs; all same-key containers are folded lazily, repaired once."""
        import heapq

        bms = _flatten(bitmaps)
        heap = []  # (key, seq, bitmap_idx, container_idx)
        for bi, bm in enumerate(bms):
            hlc = bm.high_low_container
            if hlc.size:
                heapq.heappush(heap, (hlc.keys[0], bi, 0))
        out = RoaringBitmap()
        while heap:
            key, bi, ci = heapq.heappop(heap)
            group = [bms[bi].high_low_container.containers[ci]]
            hlc = bms[bi].high_low_container
            if ci + 1 < hlc.size:
                heapq.heappush(heap, (hlc.keys[ci + 1], bi, ci + 1))
            while heap and heap[0][0] == key:
                _, bj, cj = heapq.heappop(heap)
                hlc_j = bms[bj].high_low_container
                group.append(hlc_j.containers[cj])
                if cj + 1 < hlc_j.size:
                    heapq.heappush(heap, (hlc_j.keys[cj + 1], bj, cj + 1))
            if len(group) == 1:
                c = group[0].clone()
            else:
                words = group[0].to_words()
                for c2 in group[1:]:
                    _ior_container_into(words, c2)
                c = best_container_of_words(words)
            if c.cardinality:
                out.high_low_container.append(key, c)
        return out

    @staticmethod
    def horizontal_xor(*bitmaps: RoaringBitmap) -> RoaringBitmap:
        """Heap-ordered key merge, XOR fold per group (FastAggregation
        .horizontal_xor :243) — a genuinely independent engine from the
        transpose-based xor, usable as a cross-checking oracle."""
        import heapq

        bms = _flatten(bitmaps)
        heap = []
        for bi, bm in enumerate(bms):
            hlc = bm.high_low_container
            if hlc.size:
                heapq.heappush(heap, (hlc.keys[0], bi, 0))
        out = RoaringBitmap()
        while heap:
            key, bi, ci = heapq.heappop(heap)
            hlc = bms[bi].high_low_container
            acc = hlc.containers[ci].to_words()
            if ci + 1 < hlc.size:
                heapq.heappush(heap, (hlc.keys[ci + 1], bi, ci + 1))
            while heap and heap[0][0] == key:
                _, bj, cj = heapq.heappop(heap)
                hlc_j = bms[bj].high_low_container
                c2 = hlc_j.containers[cj]
                acc ^= c2.words if isinstance(c2, BitmapContainer) else c2.to_words()
                if cj + 1 < hlc_j.size:
                    heapq.heappush(heap, (hlc_j.keys[cj + 1], bj, cj + 1))
            c = best_container_of_words(acc)
            if c.cardinality:
                out.high_low_container.append(key, c)
        return out

    @staticmethod
    def priorityqueue_or(*bitmaps: RoaringBitmap) -> RoaringBitmap:
        """Repeatedly OR the two smallest bitmaps (by serialized size) —
        FastAggregation.priorityqueue_or (FastAggregation.java:675)."""
        import heapq

        bms = _flatten(bitmaps)
        if not bms:
            return RoaringBitmap()
        if len(bms) == 1:
            return bms[0].clone()
        heap = [(bm.get_size_in_bytes(), i, bm) for i, bm in enumerate(bms)]
        heapq.heapify(heap)
        seq = len(bms)
        while len(heap) > 1:
            _, _, a = heapq.heappop(heap)
            _, _, b = heapq.heappop(heap)
            m = RoaringBitmap.or_(a, b)
            heapq.heappush(heap, (m.get_size_in_bytes(), seq, m))
            seq += 1
        return heap[0][2]

    @staticmethod
    def workshy_and(*bitmaps: RoaringBitmap, mode: Optional[str] = None) -> RoaringBitmap:
        """Key-intersection-first AND (FastAggregation.workShyAnd :356-396):
        only containers whose key survives the key intersection are touched."""
        return _aggregate(_flatten(bitmaps), "and", mode)

    @staticmethod
    def andnot(
        first: RoaringBitmap, *rest: RoaringBitmap, mode: Optional[str] = None
    ) -> RoaringBitmap:
        """N-way difference ``first \\ (rest_1 | rest_2 | ...)`` — API
        parity with the reference's ``andNot`` surface extended the way
        ``or``/``and`` already are. Delegates to the query engine's n-way
        kernel (query/kernels.py): one word fold per surviving key on CPU,
        a fused grouped-OR + mask dispatch on device."""
        from ..query import kernels

        bms = _flatten((first,) + rest)
        if not bms:
            return RoaringBitmap()
        return kernels.andnot_nway(bms[0], *bms[1:], mode=mode)

    @staticmethod
    def andnot_cardinality(
        first: RoaringBitmap, *rest: RoaringBitmap, mode: Optional[str] = None
    ) -> int:
        """``|first \\ (rest_1 | ...)|`` — the device path fetches only
        per-group popcounts (FastAggregation.andNotCardinality analogue)."""
        from ..query import kernels

        bms = _flatten((first,) + rest)
        if not bms:
            return 0
        return kernels.andnot_nway_cardinality(bms[0], *bms[1:], mode=mode)

    @staticmethod
    def and_cardinality(*bitmaps: RoaringBitmap, mode: Optional[str] = None) -> int:
        """FastAggregation.andCardinality (FastAggregation.java:71). On the
        device path only the per-group popcounts come back to host — no
        result words, no container rebuild."""
        return _aggregate_cardinality(_flatten(bitmaps), "and", mode)

    @staticmethod
    def or_cardinality(*bitmaps: RoaringBitmap, mode: Optional[str] = None) -> int:
        """FastAggregation.orCardinality (FastAggregation.java:90)."""
        return _aggregate_cardinality(_flatten(bitmaps), "or", mode)

    @staticmethod
    def xor_cardinality(*bitmaps: RoaringBitmap, mode: Optional[str] = None) -> int:
        return _aggregate_cardinality(_flatten(bitmaps), "xor", mode)


def _flatten(bitmaps) -> List[RoaringBitmap]:
    # single non-bitmap argument = an iterable of bitmaps (heap or mapped)
    if len(bitmaps) == 1 and not hasattr(bitmaps[0], "high_low_container"):
        return list(bitmaps[0])
    return list(bitmaps)


def _aggregate_cardinality(bitmaps: List[RoaringBitmap], op: str, mode) -> int:
    """N-way cardinality without materializing the result on the device
    path: the group reduction's popcounts (ints, one per key group) are the
    ONLY thing fetched — no [G, 2048] stream-back, no container rebuild.
    The aggregate cardinality is their sum because key groups partition the
    universe. CPU-path calls fold and count like the reference."""
    if not bitmaps:
        return 0
    if len(bitmaps) == 1:
        return bitmaps[0].get_cardinality()
    with _context.trace_scope():
        keys, n = _dispatch_prelude(bitmaps, op)
        if keys is not None and not keys:
            return 0
        tiers = []
        if _use_device(n, mode):

            def _device_tier() -> int:
                packed = store.packed_for(bitmaps, keys)  # resident-cache routed
                if config.mesh is not None:  # same ICI-sharded reduce as _device_aggregate
                    _none, cards = _sharded_reduce(packed, op, cards_only=True)
                else:
                    cards = store.reduce_packed_cardinality(packed, op=op)
                return int(cards.sum())

            tiers.append(("device", _device_tier))
        # the SAME cpu rungs as _aggregate (so degrade/breaker series name
        # the tier that actually absorbs the traffic), counted instead of
        # kept
        tiers.extend(
            (name, (lambda fn=fn: fn().get_cardinality()))
            for name, fn in _cpu_tiers(bitmaps, keys, n, op)
        )
        seq = _decisions.record_decision(
            "agg.dispatch", tiers[0][0], outcome=True, op=op, rows=n,
            operands=len(bitmaps), mode=mode or config.mode,
            cardinality_only=True,
        )
        return _ladder.LADDER.run(
            "agg", tiers, outcome_seq=seq, outcome_site="agg.dispatch"
        )


class ParallelAggregation:
    """Fork-join N-way OR/XOR (ParallelAggregation.java:39).

    On CPU the per-key reduction runs on a thread pool (numpy word folds
    release the GIL); on device it is the same single batched kernel as
    FastAggregation — the TPU grid is the pool. No parallel AND, matching
    the reference's judgement (ParallelAggregation.java:16-17); `and_`
    delegates to FastAggregation."""

    _POOL_SIZE = 8
    _POOL: Optional[ThreadPoolExecutor] = None  # guarded-by: _POOL_LOCK
    _POOL_LOCK = threading.Lock()

    @classmethod
    def _shared_pool(cls) -> ThreadPoolExecutor:
        """Lazily-created shared pool — the reference uses the JVM commonPool
        (ParallelAggregation.java:23-25); building an executor per call paid
        thread startup on every aggregation (VERDICT r2 weak #7). Lock guards
        first-call races (commonPool init is thread-safe too)."""
        if cls._POOL is None:
            with cls._POOL_LOCK:
                if cls._POOL is None:
                    cls._POOL = ThreadPoolExecutor(
                        max_workers=cls._POOL_SIZE, thread_name_prefix="rb-agg"
                    )
        return cls._POOL

    @staticmethod
    def group_by_key(*bitmaps: RoaringBitmap) -> Dict[int, List[Container]]:
        """ParallelAggregation.groupByKey (ParallelAggregation.java:136)."""
        return store.group_by_key(_flatten(bitmaps))

    @staticmethod
    def or_(*bitmaps: RoaringBitmap, mode: Optional[str] = None) -> RoaringBitmap:
        """ParallelAggregation.or (ParallelAggregation.java:160)."""
        return ParallelAggregation._run(_flatten(bitmaps), "or", mode)

    @staticmethod
    def xor(*bitmaps: RoaringBitmap, mode: Optional[str] = None) -> RoaringBitmap:
        """ParallelAggregation.xor (ParallelAggregation.java:180)."""
        return ParallelAggregation._run(_flatten(bitmaps), "xor", mode)

    @staticmethod
    def and_(*bitmaps: RoaringBitmap, mode: Optional[str] = None) -> RoaringBitmap:
        return FastAggregation.and_(*bitmaps, mode=mode)

    @staticmethod
    def _run(bitmaps, op, mode):
        # same ladder-routed engine as FastAggregation (the "fork-join
        # pool" distinction is the shared thread pool on the
        # per-container tier) — one dispatch path, not two (ISSUE 7)
        return _aggregate(
            bitmaps, op, mode, pool=ParallelAggregation._shared_pool()
        )
