"""Property/invariant fuzzing harness — the reference's ``fuzz-tests`` module
(Fuzzer.verifyInvariance, fuzz-tests/.../Fuzzer.java:31-120;
RandomisedTestData.java:17-52).

``verify_invariance(name, predicate, arity)`` runs the predicate over
randomized shape-diverse bitmaps (rle/dense/sparse chunk mix); on failure the
offending bitmaps are dumped as base64 RoaringFormatSpec payloads so any
failure reproduces from the report alone (the reference's ``Reporter``
behavior). Iteration count comes from ``ROARINGBITMAP_TPU_FUZZ_ITERATIONS``
(the sysprop analogue, RandomisedTestData.java:12).
"""

from __future__ import annotations

import base64
import os
from typing import Callable, List, Optional

import numpy as np

from .models.roaring import RoaringBitmap

def default_iterations() -> int:
    """Read at call time so late env changes take effect (sysprop analogue).

    Default matches the reference's fuzz intensity
    (RandomisedTestData.java:12 ITERATIONS=10000); the unit suite passes
    explicit small counts, full campaigns run ``python -m
    roaringbitmap_tpu.fuzz``."""
    return int(os.environ.get("ROARINGBITMAP_TPU_FUZZ_ITERATIONS", "10000"))


class InvarianceFailure(AssertionError):
    """Raised with base64 repro payloads when an invariant breaks."""

    def __init__(self, name: str, bitmaps: List[RoaringBitmap], detail: str = ""):
        self.repro = [base64.b64encode(bm.serialize()).decode() for bm in bitmaps]
        msg = (
            f"invariant '{name}' failed{': ' + detail if detail else ''}\n"
            + "\n".join(
                f"  bitmap[{i}] (base64 RoaringFormatSpec): {r}"
                for i, r in enumerate(self.repro)
            )
        )
        super().__init__(msg)


def reproduce(b64: str) -> RoaringBitmap:
    """Rebuild a bitmap from a failure report payload."""
    return RoaringBitmap.deserialize(base64.b64decode(b64))


def _rle_region(rng) -> np.ndarray:
    starts = rng.choice(np.arange(0, 1 << 16, 64), size=int(rng.integers(1, 30)), replace=False)
    parts = [
        np.arange(s, min(s + int(rng.integers(1, 64)), 1 << 16), dtype=np.int64)
        for s in np.sort(starts)
    ]
    return np.unique(np.concatenate(parts))


def _dense_region(rng) -> np.ndarray:
    return np.sort(rng.choice(1 << 16, size=int(rng.integers(4097, 60000)), replace=False))


def _sparse_region(rng) -> np.ndarray:
    return np.sort(rng.choice(1 << 16, size=int(rng.integers(1, 4096)), replace=False))


def random_bitmap(rng, max_keys: int = 4, optimize_prob: float = 0.3) -> RoaringBitmap:
    """Shape-diverse random bitmap (RandomisedTestData.randomBitmap)."""
    n_keys = int(rng.integers(1, max_keys + 1))
    keys = np.sort(rng.choice(64, size=n_keys, replace=False))
    regions = [_rle_region, _dense_region, _sparse_region]
    parts = [
        regions[int(rng.integers(0, 3))](rng) + (int(k) << 16) for k in keys
    ]
    bm = RoaringBitmap(np.concatenate(parts).astype(np.uint32))
    if rng.random() < optimize_prob:
        bm.run_optimize()
    return bm


def verify_invariance(
    name: str,
    predicate: Callable[..., bool],
    arity: int = 1,
    iterations: Optional[int] = None,
    seed: Optional[int] = None,
    max_keys: int = 4,
) -> None:
    """Run ``predicate(*bitmaps) -> bool`` over random inputs
    (Fuzzer.verifyInvariance, Fuzzer.java:31)."""
    rng = np.random.default_rng(seed)
    for _ in range(iterations or default_iterations()):
        bitmaps = [random_bitmap(rng, max_keys=max_keys) for _ in range(arity)]
        try:
            ok = predicate(*bitmaps)
        except Exception as e:  # predicate crash is also a failure
            raise InvarianceFailure(name, bitmaps, detail=repr(e)) from e
        if not ok:
            raise InvarianceFailure(name, bitmaps)


def verify_buffer_invariance(
    name: str,
    predicate: Callable[..., bool],
    arity: int = 1,
    iterations: Optional[int] = None,
    seed: Optional[int] = None,
) -> None:
    """Buffer-twin fuzzing (BufferFuzzer.java): each random bitmap is
    serialized and handed to the predicate as a zero-copy
    ImmutableRoaringBitmap alongside its heap original —
    ``predicate(mapped..., heap...)``; report payloads reproduce both."""
    from .models.immutable import ImmutableRoaringBitmap

    rng = np.random.default_rng(seed)
    for _ in range(iterations or default_iterations()):
        heap = [random_bitmap(rng) for _ in range(arity)]
        mapped = [ImmutableRoaringBitmap(b.serialize()) for b in heap]
        try:
            ok = predicate(*mapped, *heap)
        except Exception as e:
            raise InvarianceFailure(name, heap, detail=repr(e)) from e
        if not ok:
            raise InvarianceFailure(name, heap)


def random_working_set(rng, layout: str) -> List[RoaringBitmap]:
    """Working set whose key distribution forces a specific device layout
    by construction against store.prepare_reduce's cost model (round 4):
    single dense block when its occupancy >= 0.9; count-bucketed ragged
    batching when 3-bucket padding stays <= 1.5x live rows; else the
    segmented scan. The round-2 fuzzers never produced skewed group shapes,
    so the scan path went unfuzzed (VERDICT r2 #6); round 4 adds the
    bucketed middle regime.

    ``layout='padded'``: every bitmap covers the same few keys — groups
    perfectly balanced, occupancy 1.0. ``layout='bucketed'``: one hot key
    shared by many bitmaps plus many singleton keys — one block would pad
    every singleton group to the hot count (rejected), but 3 buckets pad to
    ~100%. ``layout='segmented-scan'``: a 7-level geometric count pyramid
    (2^j-sized groups, equal mass per level) — every contiguous 3-bucket
    split of a geometric spectrum pays >= 1.86x the live rows (any bucket
    spanning s levels costs ~(2^s - 1)/s per live row), defeating the
    bucket rescue."""
    if layout == "padded":
        keys = np.sort(rng.choice(32, size=int(rng.integers(1, 4)), replace=False))
        out = []
        for _ in range(int(rng.integers(4, 12))):
            parts = [
                _sparse_region(rng) + (int(k) << 16) for k in keys
            ]
            out.append(RoaringBitmap(np.concatenate(parts).astype(np.uint32)))
        return out
    if layout == "bucketed":
        hot = int(rng.integers(0, 8))
        n_hot = int(rng.integers(33, 48))
        n_single = int(rng.integers(64, 90))
        out = [
            RoaringBitmap((_sparse_region(rng) + (hot << 16)).astype(np.uint32))
            for _ in range(n_hot)
        ]
        for j in range(n_single):
            key = 16 + j  # distinct, disjoint from the hot key range
            out.append(
                RoaringBitmap((_sparse_region(rng) + (key << 16)).astype(np.uint32))
            )
        return out
    if layout == "segmented-scan":
        levels = 7
        # group sizes 2^j, 2^(levels-1-j) groups per level; columnar build:
        # bitmap b holds every group whose count exceeds b
        group_counts: List[int] = []
        for j in range(levels):
            group_counts += [2 ** j] * (2 ** (levels - 1 - j))
        n_bitmaps = max(group_counts)
        parts: List[List[np.ndarray]] = [[] for _ in range(n_bitmaps)]
        for key, count in enumerate(group_counts):
            for b in range(count):
                parts[b].append(_sparse_region(rng) + (key << 16))
        return [
            RoaringBitmap(np.concatenate(p).astype(np.uint32)) for p in parts
        ]
    raise ValueError(f"unknown layout {layout}")


def verify_layout_invariance(
    name: str,
    op: str = "or",
    iterations: Optional[int] = None,
    seed: Optional[int] = None,
) -> None:
    """Device-layout fuzzing: for all three layouts — padded, bucketed,
    segmented-scan — (forced by construction, asserted against
    prepare_reduce's actual choice), the device reduction must agree with
    every CPU engine (naive / horizontal / priorityqueue for OR; the
    reference's cross-engine oracle, Fuzzer.java + jmh smoke tests)."""
    from .parallel import aggregation, store

    if op not in ("or", "xor"):
        # AND is not per-key decomposable (a key absent from one input
        # annihilates the whole-key result, while the grouped reduce only
        # folds present containers) and other ops have no grouped engine.
        # The AND path (workShy key intersection) is fuzzed via
        # FastAggregation equivalence invariants instead.
        raise ValueError("layout fuzzing supports decomposable ops: 'or', 'xor'")
    rng = np.random.default_rng(seed)
    for i in range(iterations or default_iterations()):
        layout = ("padded", "bucketed", "segmented-scan")[i % 3]
        bms = random_working_set(rng, layout)
        packed = store.pack_groups(store.group_by_key(bms))
        run, chosen = store.prepare_reduce(packed, op=op)
        if chosen != layout:
            raise InvarianceFailure(
                name, bms, detail=f"constructed {layout}, dispatcher chose {chosen}"
            )
        red, cards = run()
        got = store.unpack_to_bitmap(packed.group_keys, np.asarray(red), np.asarray(cards))
        if op == "or":
            oracles = [
                aggregation.FastAggregation.naive_or(*bms),
                aggregation.FastAggregation.horizontal_or(*bms),
                aggregation.FastAggregation.priorityqueue_or(*bms),
            ]
        else:  # "xor" (the guard above admits only or/xor)
            oracles = [aggregation.FastAggregation.naive_xor(*bms)]
        for j, want in enumerate(oracles):
            if got != want:
                raise InvarianceFailure(
                    name, bms, detail=f"{layout} device result != cpu engine {j}"
                )


def _random_mutation(rng, bm: RoaringBitmap) -> None:
    """One random mutation drawn from every family the delta validator must
    classify: in-place container edits (delta rows), key insertions and
    removals (structural -> full repack), and container-form rewrites."""
    kind = int(rng.integers(0, 5))
    hlc = bm.high_low_container
    if kind == 0 and hlc.size:  # point add within an existing chunk
        hb = hlc.keys[int(rng.integers(0, hlc.size))]
        bm.add((int(hb) << 16) | int(rng.integers(0, 1 << 16)))
    elif kind == 1 and not bm.is_empty():  # point remove (may drop the key)
        arr = bm.to_array()
        bm.remove(int(arr[int(rng.integers(0, arr.size))]))
    elif kind == 2:  # brand-new chunk key: structural
        bm.add(int(rng.integers(100, 200)) << 16 | int(rng.integers(0, 1 << 16)))
    elif kind == 3:  # container-form rewrite (set_container_at_index dirty)
        bm.run_optimize()
    else:  # bulk add spanning existing + possibly new chunks
        vals = rng.integers(0, 80 << 16, size=int(rng.integers(1, 64)))
        bm.add_many(vals.astype(np.uint32))


def verify_pack_cache_invariance(
    name: str,
    iterations: Optional[int] = None,
    seed: Optional[int] = None,
) -> None:
    """The resident pack cache differential (ISSUE 4): across randomized
    mutation sequences, the cache-returned pack — whether exact hit,
    incremental delta repack, or full rebuild — must be byte-identical to
    a from-scratch ``pack_groups(group_by_key(...))`` of the current
    bitmaps, on both the unfiltered (OR/XOR) and the AND key-intersection
    layouts. A wrong delta classification fails exactly like a wrong
    kernel."""
    from .parallel import store

    rng = np.random.default_rng(seed)
    for _ in range(iterations or default_iterations()):
        bms = [random_bitmap(rng, max_keys=4) for _ in range(int(rng.integers(2, 6)))]
        cache = store.PackCache(max_bytes=1 << 30)
        for _step in range(int(rng.integers(1, 5))):
            for bi in rng.choice(len(bms), size=int(rng.integers(1, 3)), replace=False):
                _random_mutation(rng, bms[int(bi)])
            keys_filter = None
            if rng.random() < 0.4:
                keys_filter = store.intersect_keys(bms)
                if not keys_filter:
                    continue
            try:
                got = cache.get_packed(bms, keys_filter)
                want = store.pack_groups(
                    store.group_by_key(bms, keys_filter=keys_filter)
                )
                ok = (
                    np.array_equal(got.words, want.words)
                    and np.array_equal(got.group_keys, want.group_keys)
                    and np.array_equal(got.group_offsets, want.group_offsets)
                )
            except Exception as e:  # predicate crash is also a failure
                raise InvarianceFailure(name, bms, detail=repr(e)) from e
            if not ok:
                raise InvarianceFailure(
                    name, bms,
                    detail=f"cached pack != fresh pack (filter={keys_filter is not None})",
                )
        cache.close()


def verify_columnar_invariance(
    name: str,
    iterations: Optional[int] = None,
    seed: Optional[int] = None,
) -> None:
    """The columnar pairwise engine differential (ISSUE 5): under
    randomized op sequences — static and member-semantics (reuse_left)
    pairwise ops, cardinality-only probes, and N-way CPU folds, over
    shape-diverse operands including run-optimized and mapped (buffer)
    ones — the batched engine's result must be value-identical to the
    per-container engine's at every step. Both accumulators then advance
    with their own engine's output, so a divergence compounds and cannot
    cancel out."""
    from . import columnar
    from .models.immutable import ImmutableRoaringBitmap
    from .models.roaring import RoaringBitmap as RB
    from .parallel import store
    from .parallel.aggregation import FastAggregation as FA

    rng = np.random.default_rng(seed)
    ops = ("and", "or", "xor", "andnot")
    for _ in range(iterations or default_iterations()):
        seed_bm = random_bitmap(rng)
        acc_col, acc_ref = seed_bm.clone(), seed_bm.clone()
        repro = [seed_bm]
        try:
            for _step in range(int(rng.integers(2, 6))):
                b = random_bitmap(rng)
                repro.append(b)
                operand = (
                    ImmutableRoaringBitmap(b.serialize())
                    if rng.random() < 0.3
                    else b
                )
                kind = int(rng.integers(0, 4))
                if kind == 3:  # cardinality-only + intersects probes
                    got_c = columnar.and_cardinality_pair(acc_col, operand)
                    got_i = columnar.intersects_pair(acc_col, operand)
                    with columnar.disabled():
                        want_c = RB.and_cardinality(acc_ref, operand)
                        want_i = RB.intersects(acc_ref, operand)
                    if got_c != want_c or got_i != want_i:
                        raise InvarianceFailure(
                            name, repro, detail=f"card {got_c}!={want_c}"
                        )
                    continue
                op = ops[int(rng.integers(0, 4))]
                # kind 1 = member-op semantics: acc's pass-throughs transfer
                got = columnar.pairwise(op, acc_col, operand, reuse_left=kind == 1)
                with columnar.disabled():
                    want = {
                        "and": RB.and_, "or": RB.or_,
                        "xor": RB.xor, "andnot": RB.andnot,
                    }[op](acc_ref, operand)
                if got != want:
                    raise InvarianceFailure(name, repro, detail=f"op {op}")
                acc_col, acc_ref = got, want
                if rng.random() < 0.3:
                    acc_col.run_optimize()
                    acc_ref.run_optimize()
            # N-way fold step: batched fold vs the naive oracle
            if rng.random() < 0.5:
                bms = [acc_ref] + [random_bitmap(rng) for _ in range(2)]
                groups = store.group_by_key(bms)
                if columnar.fold(groups, "or") != FA.naive_or(*bms):
                    raise InvarianceFailure(name, repro, detail="fold or")
        except InvarianceFailure:
            raise
        except Exception as e:  # engine crash is also a failure
            raise InvarianceFailure(name, repro, detail=repr(e)) from e


def random_fault_schedule(rng) -> list:
    """1-3 random fault rules over random registered sites: error kind
    drawn from the taxonomy (transient / resource / simulated XLA OOM),
    trigger drawn from every=/after=/prob= (seeded)."""
    from .robust import faults as rfaults
    from .robust.errors import ResourceExhausted, TransientDeviceError, simulated_oom

    rules = []
    for _ in range(int(rng.integers(1, 4))):
        site = rfaults.SITES[int(rng.integers(0, len(rfaults.SITES)))]
        exc = (TransientDeviceError, ResourceExhausted, simulated_oom)[
            int(rng.integers(0, 3))
        ]
        kind = int(rng.integers(0, 3))
        kw: dict = {}
        if kind == 0:
            kw["every"] = int(rng.integers(1, 4))
        elif kind == 1:
            kw["after"] = int(rng.integers(0, 3))
        else:
            kw["prob"] = float(rng.uniform(0.1, 0.9))
            kw["seed"] = int(rng.integers(0, 1 << 16))
        rules.append((site, exc, kw))
    return rules


def verify_fault_schedule_invariance(
    name: str,
    iterations: Optional[int] = None,
    seed: Optional[int] = None,
) -> None:
    """Fuzz family 26 (ISSUE 7): random op/query sequences under random
    seeded fault schedules must be bit-exact with the no-fault oracle
    (computed mid-schedule inside ``faults.suspended()``) and must never
    raise past the degradation ladder. A fault that corrupts a result, a
    tier that isn't bit-exact, or an exception that escapes a ladder all
    fail identically."""
    from contextlib import ExitStack

    from .models.roaring import RoaringBitmap as RB
    from .parallel import store
    from .parallel.aggregation import FastAggregation as FA
    from .query import evaluate_naive, execute
    from .robust import faults as rfaults
    from .robust import ladder as rladder

    rng = np.random.default_rng(seed)
    for _ in range(iterations or default_iterations()):
        bms = [random_bitmap(rng) for _ in range(int(rng.integers(2, 5)))]
        sched = random_fault_schedule(rng)
        rfaults.clear()  # fresh per-site hit counters: schedules replay
        rladder.LADDER.reset()
        store.PACK_CACHE.close()
        try:
            with ExitStack() as stack:
                for site, exc, kw in sched:
                    stack.enter_context(rfaults.inject(site, exc, **kw))
                for _step in range(int(rng.integers(1, 4))):
                    kind = int(rng.integers(0, 4))
                    if kind == 0:  # N-way aggregation, any dispatch mode
                        mode = ("cpu", "device", None)[int(rng.integers(0, 3))]
                        op = ("or_", "and_", "xor")[int(rng.integers(0, 3))]
                        got = getattr(FA, op)(*bms, mode=mode)
                        with rfaults.suspended():
                            want = getattr(FA, op)(*bms, mode="cpu")
                    elif kind == 1:  # pairwise facade (columnar router)
                        got = RB.and_(bms[0], bms[1])
                        with rfaults.suspended():
                            want = RB.and_(bms[0], bms[1])
                    elif kind == 2:  # n-way andnot kernel, device-routed
                        got = FA.andnot(bms[0], *bms[1:], mode="device")
                        with rfaults.suspended():
                            want = FA.andnot(bms[0], *bms[1:], mode="cpu")
                    else:  # full query DAG, sometimes deadline-cancelled
                        expr = random_expression(rng, bms, max_depth=3)
                        deadline = (None, 0.0)[int(rng.integers(0, 2))]
                        got = execute(expr, cache=None, deadline_s=deadline)
                        with rfaults.suspended():
                            want = evaluate_naive(expr)
                    if got != want:
                        raise InvarianceFailure(
                            name, bms,
                            detail=f"fault-schedule result diverged "
                            f"(step kind={kind}, schedule={sched})",
                        )
        except InvarianceFailure:
            raise
        except Exception as e:  # rb-ok: exception-hygiene -- the family's whole point: ANY escape past the ladder is a failure, re-wrapped with the repro schedule
            raise InvarianceFailure(
                name, bms,
                detail=f"exception escaped the ladder: {e!r} (schedule={sched})",
            ) from e
        finally:
            rfaults.clear()


def verify_fusion_invariance(
    name: str,
    iterations: Optional[int] = None,
    seed: Optional[int] = None,
    faults_prob: float = 0.25,
) -> None:
    """Fuzz family 27 (ISSUE 13): random OVERLAPPING expression sets
    executed through the fusion window must be bit-exact with the serial
    per-query oracle. Overlap is constructed two ways each iteration —
    a shared random subexpression grafted under several queries' roots
    (the hash-consed DAG makes it ONE node across plans, exercising the
    window dedup), and duplicate whole queries (exercising the in-flight
    join). Every other iteration arms a random seeded fault schedule
    drawn over the registered sites INCLUDING the new ``query.fusion``
    site — a fault there must degrade the whole window to per-query
    serial execution bit-exactly (the ladder's batch rung), and no
    exception may escape. The oracle is computed mid-schedule inside
    ``faults.suspended()`` with the serial executor (itself pinned
    against naive evaluation by family ``query-planner-vs-naive``).

    Mixed latency classes (ISSUE 19): each iteration ALSO drives the
    same query set through a live :class:`FusionExecutor` with
    alternating ``interactive``/``batch`` slack declarations, so the
    SLO-priced submit path — deadline-aware window close, the
    ``fusion.hedge`` verdict, and hedged solo dispatch through the
    in-flight table (fault site ``"query.hedge"``, which must degrade
    back to the window rung bit-exactly) — is fuzzed under the same
    schedules as the plain batch entry."""
    from contextlib import ExitStack

    from .query import Q, ResultCache, execute, fusion
    from .robust import faults as rfaults
    from .robust import ladder as rladder

    rng = np.random.default_rng(seed)
    for it in range(iterations or default_iterations()):
        bms = [random_bitmap(rng) for _ in range(int(rng.integers(3, 6)))]
        shared = random_expression(rng, bms, max_depth=2)
        queries = []
        for _ in range(int(rng.integers(2, 6))):
            own = random_expression(rng, bms, max_depth=2)
            kind = int(rng.integers(0, 3))
            if kind == 0:
                queries.append(Q.or_(shared, own))
            elif kind == 1:
                queries.append(Q.andnot(own, shared))
            else:
                queries.append(own)
        if len(queries) > 2 and rng.random() < 0.5:
            queries.append(queries[int(rng.integers(0, len(queries)))])
        sched = random_fault_schedule(rng) if it % 2 else []
        rfaults.clear()
        rladder.LADDER.reset()
        try:
            with ExitStack() as stack:
                for site, exc, kw in sched:
                    stack.enter_context(rfaults.inject(site, exc, **kw))
                with rfaults.suspended():
                    want = [execute(q, cache=None) for q in queries]
                got = fusion.execute_fused(
                    queries, cache=ResultCache(max_entries=64)
                )
                for gi, (g, w) in enumerate(zip(got, want)):
                    if g != w:
                        raise InvarianceFailure(
                            name, bms,
                            detail=f"fused query {gi} diverged from the "
                            f"serial oracle (schedule={sched})",
                        )
                # the SLO-priced submit path under the same schedule:
                # alternating latency classes, tight interactive slack so
                # the hedge verdict actually fires solo dispatches
                with fusion.FusionExecutor(
                    cache=ResultCache(max_entries=64)
                ) as execu:
                    futs = [
                        execu.submit(
                            q,
                            slack_ms=(5.0, 1000.0)[qi % 2],
                            latency_class=("interactive", "batch")[qi % 2],
                        )
                        for qi, q in enumerate(queries)
                    ]
                    hedged = [f.result() for f in futs]
                for gi, (g, w) in enumerate(zip(hedged, want)):
                    if g != w:
                        raise InvarianceFailure(
                            name, bms,
                            detail=f"SLO-priced submit query {gi} diverged "
                            f"from the serial oracle (schedule={sched})",
                        )
        except InvarianceFailure:
            raise
        except Exception as e:  # rb-ok: exception-hygiene -- the family's whole point: ANY escape past the fusion ladder is a failure, re-wrapped with the repro schedule
            raise InvarianceFailure(
                name, bms,
                detail=f"exception escaped the fusion ladder: {e!r} "
                f"(schedule={sched})",
            ) from e
        finally:
            rfaults.clear()


def verify_serve_invariance(
    name: str,
    iterations: Optional[int] = None,
    seed: Optional[int] = None,
) -> None:
    """Fuzz family 28 (ISSUE 14): seeded multi-tenant traffic through
    the serving harness (admission -> fusion window, 2-4 worker threads)
    must be bit-exact with the same query multiset executed serially —
    the request schedule is a pure function of the seed, so the serial
    oracle replays the exact multiset the concurrent run served.
    Quotas are generous (no shed): every request must produce a result
    identical to ``execute(q, cache=None)`` computed inside
    ``faults.suspended()``. Every other iteration arms a random seeded
    fault schedule over the registered sites INCLUDING ``serve.admit``
    (which must fail OPEN — admission is load management, never a
    correctness gate) and ``query.fusion`` (which degrades the window to
    per-query serial). A stale cross-request publication, a fault that
    drops or corrupts a request, and an escaped exception all fail
    identically, with the schedule in the repro detail."""
    from contextlib import ExitStack

    from .robust import faults as rfaults
    from .robust import ladder as rladder
    from .serve import (
        AdmissionController, LoadHarness, TenantProfile, build_requests,
    )
    from .serve import slo as sslo

    rng = np.random.default_rng(seed)
    for it in range(iterations or default_iterations()):
        bms = [random_bitmap(rng) for _ in range(int(rng.integers(4, 7)))]
        n_tenants = int(rng.integers(2, 4))
        profiles = [
            TenantProfile(
                f"fz-t{i}", weight=float(rng.uniform(0.5, 2.0)),
                quota_qps=1e6, burst=1e6,
            )
            for i in range(n_tenants)
        ]
        sched = random_fault_schedule(rng) if it % 2 else []
        rfaults.clear()
        rladder.LADDER.reset()
        sslo.reset()
        try:
            harness = LoadHarness(
                bms, profiles,
                threads=int(rng.integers(2, 5)),
                window=int(rng.integers(2, 6)),
                admission=AdmissionController(
                    max_inflight=int(rng.integers(1, 9)), queue_limit=64
                ),
            )
            requests = build_requests(
                bms, profiles, int(rng.integers(4, 13)),
                seed=int(rng.integers(0, 1 << 16)),
            )
            with ExitStack() as stack:
                for site, exc, kw in sched:
                    stack.enter_context(rfaults.inject(site, exc, **kw))
                with rfaults.suspended():
                    want = harness.run_serial(requests)
                report = harness.run(requests)
                for gi, (g, w) in enumerate(zip(report.results, want)):
                    if g != w:
                        raise InvarianceFailure(
                            name, bms,
                            detail=f"served request {gi} diverged from the "
                            f"serial oracle (schedule={sched})",
                        )
        except InvarianceFailure:
            raise
        except Exception as e:  # rb-ok: exception-hygiene -- the family's whole point: ANY escape past the serving harness/ladder is a failure, re-wrapped with the repro schedule
            raise InvarianceFailure(
                name, bms,
                detail=f"exception escaped the serving harness: {e!r} "
                f"(schedule={sched})",
            ) from e
        finally:
            rfaults.clear()
            sslo.reset()


def verify_epoch_invariance(
    name: str,
    iterations: Optional[int] = None,
    seed: Optional[int] = None,
) -> None:
    """Fuzz family 29 (ISSUE 15): threaded queries under CONCURRENT
    ingest + epoch flips must each be bit-exact with the snapshot of the
    epoch they were admitted under — zero torn reads. Each iteration
    runs query threads (each pinning an epoch via ``EpochStore.reader``
    and executing a seeded random DAG) against a writer thread
    submitting stamped mutation batches and forcing flips; every other
    iteration arms a random seeded fault schedule over the registered
    sites INCLUDING the new ``epoch.flip`` site (which must fail CLOSED
    to the old epoch — an aborted flip leaves readers on a stale but
    consistent snapshot, never a torn one). The oracle replays the
    published lineage over a pre-run clone: epoch state k+1 = state k +
    the lineage record's batches, and each query's result must equal its
    admitted epoch's state (the expression is rebuilt over the clone
    from the query's own seed). A result matching neither snapshot, a
    flip that tears a reader, and an escaped exception all fail
    identically, with the schedule in the repro detail."""
    import threading
    from contextlib import ExitStack

    from .query import exec as qexec
    from .robust import faults as rfaults
    from .robust import ladder as rladder
    from .serve import ingest as singest
    from .serve import slo as sslo
    from .serve.epochs import EpochStore

    rng = np.random.default_rng(seed)
    for it in range(iterations or default_iterations()):
        n_bms = int(rng.integers(4, 7))
        bms = [random_bitmap(rng) for _ in range(n_bms)]
        clone = [b.clone() for b in bms]
        n_queries = int(rng.integers(3, 8))
        q_seeds = [int(rng.integers(0, 1 << 16)) for _ in range(n_queries)]
        exprs = [
            random_expression(np.random.default_rng(s), bms, max_depth=3)
            for s in q_seeds
        ]
        write_muts = [
            {
                int(rng.integers(0, n_bms)): rng.integers(
                    0, 1 << 18, size=int(rng.integers(1, 16))
                )
            }
            for _ in range(int(rng.integers(1, 4)))
        ]
        sched = random_fault_schedule(rng) if it % 2 else []
        rfaults.clear()
        rladder.LADDER.reset()
        sslo.reset()
        sslo.TENANTS.declare("fz-writer", quota_qps=1e6, burst=1e6)
        es = EpochStore(bms)
        results: List[Optional[tuple]] = [None] * n_queries
        submitted = {}
        errors: List[BaseException] = []

        def _query(qi):
            try:
                with es.reader() as tk:
                    results[qi] = (tk.epoch, qexec.execute(exprs[qi], cache=None))
            except BaseException as e:  # rb-ok: exception-hygiene -- the family's whole point: ANY escape past the epoch machinery/ladder is a failure, re-wrapped with the repro schedule below
                errors.append(e)

        def _writer():
            try:
                for muts in write_muts:
                    b = es.submit("fz-writer", muts)
                    if b is not None:
                        submitted[b.batch_id] = b
                    es.flip(reason="fuzz")
            except BaseException as e:  # rb-ok: exception-hygiene -- same re-wrap contract as the query workers
                errors.append(e)

        threads = [
            threading.Thread(target=_query, args=(qi,), daemon=True)
            for qi in range(n_queries)
        ] + [threading.Thread(target=_writer, daemon=True)]
        try:
            with ExitStack() as stack:
                for site, exc, kw in sched:
                    stack.enter_context(rfaults.inject(site, exc, **kw))
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
            if errors:
                raise errors[0]
            # the lineage replay: epoch state k+1 = state k + the
            # record's batches, applied to a pre-run clone
            states = {0: clone}
            cur = clone
            for rec in (r for r in es.lineage() if r["outcome"] == "flipped"):
                cur = [b.clone() for b in cur]
                singest.apply_batches(
                    cur, [submitted[bid] for bid in rec["batches"]]
                )
                states[rec["epoch"]] = cur
            with rfaults.suspended():
                for qi, r in enumerate(results):
                    if r is None:
                        raise InvarianceFailure(
                            name, bms,
                            detail=f"query {qi} produced no result and no "
                            f"error (schedule={sched})",
                        )
                    ep, got = r
                    snap = states.get(ep)
                    if snap is None:
                        raise InvarianceFailure(
                            name, bms,
                            detail=f"query {qi} admitted under unpublished "
                            f"epoch {ep} (schedule={sched})",
                        )
                    want = qexec.execute(
                        random_expression(
                            np.random.default_rng(q_seeds[qi]), snap,
                            max_depth=3,
                        ),
                        cache=None,
                    )
                    if got != want:
                        raise InvarianceFailure(
                            name, bms,
                            detail=f"TORN READ: query {qi} under epoch {ep} "
                            f"matches no legal snapshot (schedule={sched})",
                        )
        except InvarianceFailure:
            raise
        except Exception as e:  # rb-ok: exception-hygiene -- the family's whole point: ANY escape past the epoch machinery/ladder is a failure, re-wrapped with the repro schedule
            raise InvarianceFailure(
                name, bms,
                detail=f"exception escaped the epoch machinery: {e!r} "
                f"(schedule={sched})",
            ) from e
        finally:
            rfaults.clear()
            sslo.reset()


def verify_compaction_invariance(
    name: str,
    iterations: Optional[int] = None,
    seed: Optional[int] = None,
) -> None:
    """Fuzz family 30 (ISSUE 16): background compaction may change
    *representation*, never *content*. Each iteration drives a random
    ingest sequence (mixing scatter batches with run-friendly contiguous
    ranges, so ``run_optimize`` has real rewrites to make) through an
    ``EpochStore`` with FORCED maintenance passes interleaved between
    flips; every other iteration arms a random seeded fault schedule
    biased to include the new ``serve.maintain`` site (which must fail
    CLOSED — an aborted pass leaves the uncompacted epoch serving
    exactly the bits it had). The oracle is a no-compaction twin: the
    pre-run clone plus every published lineage record's batches, with
    every fault suspended — the live corpus must equal the twin
    bit-exactly, and the passes' own bit-identity audits must report
    zero anomalies (a nonzero count means ``run_optimize`` changed bits
    and only the audit saved the corpus)."""
    from contextlib import ExitStack

    from .observe import structure as ostructure
    from .robust import faults as rfaults
    from .robust import ladder as rladder
    from .robust.errors import TransientDeviceError
    from .serve import ingest as singest
    from .serve import maintain as smaintain
    from .serve import slo as sslo
    from .serve.epochs import EpochStore

    rng = np.random.default_rng(seed)
    for it in range(iterations or default_iterations()):
        n_bms = int(rng.integers(3, 6))
        bms = [random_bitmap(rng) for _ in range(n_bms)]
        clone = [b.clone() for b in bms]
        write_muts = []
        for _ in range(int(rng.integers(2, 6))):
            muts: dict = {}
            for _ in range(int(rng.integers(1, 3))):
                tgt = int(rng.integers(0, n_bms))
                if rng.random() < 0.5:
                    # a contiguous run so format re-selection has work
                    start = int(rng.integers(0, 1 << 17))
                    vals = np.arange(start, start + int(rng.integers(64, 2048)))
                else:
                    vals = rng.integers(0, 1 << 18, size=int(rng.integers(1, 32)))
                muts[tgt] = np.union1d(
                    muts.get(tgt, np.empty(0, np.int64)), vals
                )
            write_muts.append(muts)
        sched = random_fault_schedule(rng) if it % 2 else []
        if sched and rng.random() < 0.7:
            # bias toward the site under test: the pass entry's fail-closed
            # gate is the family's whole point
            sched.append(
                ("serve.maintain", TransientDeviceError,
                 {"prob": float(rng.uniform(0.2, 0.9)),
                  "seed": int(rng.integers(0, 1 << 16))})
            )
        rfaults.clear()
        rladder.LADDER.reset()
        sslo.reset()
        sslo.TENANTS.declare("fz-writer", quota_qps=1e6, burst=1e6)
        ostructure.LEDGER.reset()
        smaintain.reset()
        es = EpochStore(bms)
        ostructure.LEDGER.watch("fz-compact", bms)
        submitted = {}
        anomalies = 0
        try:
            with ExitStack() as stack:
                for site, exc, kw in sched:
                    stack.enter_context(rfaults.inject(site, exc, **kw))
                for muts in write_muts:
                    try:
                        b = es.submit("fz-writer", muts)
                    except Exception:  # rb-ok: exception-hygiene -- an injected fault at submit leaves the batch unsubmitted; the twin replays only PUBLISHED lineage, so a lost batch stays consistent
                        b = None
                    if b is not None:
                        submitted[b.batch_id] = b
                    try:
                        es.flip(reason="fuzz")
                    except Exception:  # rb-ok: exception-hygiene -- an aborted flip (injected epoch.flip fault) keeps the old epoch; the lineage replay below only sees published flips
                        pass
                    rec = smaintain.run_pass(
                        store=es, reason="fuzz", force=True,
                    )
                    anomalies += int(rec.get("anomalies") or 0)
            # the no-compaction twin: pre-run clone + every PUBLISHED
            # record's batches, faults suspended (invisible to schedules)
            with rfaults.suspended():
                twin = [b.clone() for b in clone]
                for rec in (
                    r for r in es.lineage() if r["outcome"] == "flipped"
                ):
                    singest.apply_batches(
                        twin, [submitted[bid] for bid in rec["batches"]]
                    )
                if anomalies:
                    raise InvarianceFailure(
                        name, bms,
                        detail=f"bit-identity audit caught {anomalies} lossy "
                        f"rewrite(s): run_optimize changed content "
                        f"(schedule={sched})",
                    )
                for i, (got, want) in enumerate(zip(es.corpus, twin)):
                    if got != want:
                        raise InvarianceFailure(
                            name, bms,
                            detail=f"compacted corpus[{i}] diverged from the "
                            f"no-compaction twin (schedule={sched})",
                        )
        except InvarianceFailure:
            raise
        except Exception as e:  # rb-ok: exception-hygiene -- the family's whole point: ANY escape past the maintenance tier's fail-closed gate is a failure, re-wrapped with the repro schedule
            raise InvarianceFailure(
                name, bms,
                detail=f"exception escaped the maintenance tier: {e!r} "
                f"(schedule={sched})",
            ) from e
        finally:
            rfaults.clear()
            sslo.reset()
            ostructure.LEDGER.reset()
            smaintain.reset()


def _durable_plan(plan_seed: int):
    """The seeded deterministic workload family 31 replays on BOTH sides
    of the process boundary: the parent derives the oracle from the same
    plan the (killed) child executed, so nothing needs to survive the
    crash except the durable artifacts under test."""
    rng = np.random.default_rng(plan_seed)
    bms = [random_bitmap(rng) for _ in range(int(rng.integers(3, 6)))]
    muts = [
        {
            int(rng.integers(0, len(bms))): rng.integers(
                0, 1 << 18, size=int(rng.integers(1, 16))
            )
        }
        for _ in range(int(rng.integers(2, 5)))
    ]
    return bms, muts


def _durable_child(root: str, plan_seed: int, kill_hit: int) -> None:
    """Family 31's subprocess body: replay the seeded plan (one submit +
    one flip per batch, every flip force-persisted) and die WITHOUT
    UNWINDING at the ``kill_hit``-th ``durable.persist`` crash point — a
    simulated power cut at exactly that persist stage (``os._exit`` from
    the injected exception's constructor, so no ``finally`` blocks, no
    fsyncs, no atexit handlers run). ``kill_hit=0`` runs to completion.
    Prints ``PERSISTED <epoch>`` after each completed persist; the
    parent's recovery floor."""
    import contextlib
    import os as _os

    from .durable import DurableStore
    from .robust import faults as rfaults
    from .serve import slo as sslo
    from .serve.epochs import EpochStore

    class _PowerCut(BaseException):
        def __init__(self, *args):
            _os._exit(137)

    bms, muts = _durable_plan(plan_seed)
    sslo.TENANTS.declare("fz-durable", quota_qps=1e6, burst=1e6)
    es = EpochStore(bms)
    ds = DurableStore(root)
    ctx = (
        rfaults.inject("durable.persist", _PowerCut, after=kill_hit - 1)
        if kill_hit
        else contextlib.nullcontext()
    )
    with ctx:
        for m in muts:
            es.submit("fz-durable", m)
            es.flip(reason="fuzz-durable")
            # persist() directly (not the priced maybe_persist) so the
            # crash-point schedule is deterministic: exactly 5 hits per
            # flip, and the chosen kill_hit lands in a known stage
            ds.persist(es, reason="fuzz-durable")
            print(f"PERSISTED {es.current()}", flush=True)


def verify_durable_crash_invariance(
    name: str,
    iterations: Optional[int] = None,
    seed: Optional[int] = None,
) -> None:
    """Fuzz family 31 (ISSUE 17): a process killed at ANY persist crash
    point must recover bit-exactly to the last PUBLISHED epoch — never a
    torn one, never an older one than a persist that completed. Each
    iteration spawns a subprocess replaying a seeded plan
    (:func:`_durable_plan`) whose persist is killed without unwinding at
    a random ``durable.persist`` hit (``os._exit`` mid-stage; hit 0 is
    the clean control run). The parent then recovers from the child's
    root and checks, against the family-29-style deterministic replay
    oracle (epoch *k* = seed corpus + the first *k* mutation batches):

    * recovery epoch >= every epoch the child logged as persisted
      (durability floor: a completed persist survives the crash), and
      <= the plan's flip count (no invented epochs);
    * the recovered mapped corpus is bit-exact with the oracle replay at
      the recovered epoch (zero torn artifacts served);
    * a clean child (kill_hit 0) exits 0 and recovers its final epoch."""
    import shutil
    import subprocess
    import sys as _sys
    import tempfile

    from .durable import recover as _drecover
    from .serve import ingest as singest

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    child_code = (
        "import sys; from roaringbitmap_tpu.fuzz import _durable_child; "
        "_durable_child(sys.argv[1], int(sys.argv[2]), int(sys.argv[3]))"
    )
    rng = np.random.default_rng(seed)
    for it in range(iterations or default_iterations()):
        plan_seed = int(rng.integers(0, 1 << 16))
        bms, muts = _durable_plan(plan_seed)
        n_flips = len(muts)
        # 5 crash points per persist call x one persist per flip; 0 = the
        # clean control run (child must then exit 0 with the final epoch)
        kill_hit = int(rng.integers(0, 5 * n_flips + 1))
        root = tempfile.mkdtemp(prefix="fz_durable_")
        try:
            env = dict(os.environ)
            env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
            env.setdefault("JAX_PLATFORMS", "cpu")
            proc = subprocess.run(
                [_sys.executable, "-c", child_code,
                 root, str(plan_seed), str(kill_hit)],
                capture_output=True, text=True, env=env, timeout=300,
            )
            logged = [
                int(line.split()[1])
                for line in proc.stdout.splitlines()
                if line.startswith("PERSISTED ")
            ]
            if kill_hit == 0 and proc.returncode != 0:
                raise InvarianceFailure(
                    name, bms,
                    detail=f"clean child (seed={plan_seed}) exited "
                    f"{proc.returncode}: {proc.stderr[-500:]}",
                )
            last_logged = max(logged) if logged else 0
            rec = _drecover(root)
            if rec is None:
                if last_logged:
                    raise InvarianceFailure(
                        name, bms,
                        detail=f"DURABILITY LOST: child persisted epoch "
                        f"{last_logged} (seed={plan_seed}, "
                        f"kill_hit={kill_hit}) but recovery found nothing",
                    )
                continue  # killed before the first publish: legal
            if not last_logged <= rec.epoch <= n_flips:
                raise InvarianceFailure(
                    name, bms,
                    detail=f"recovered epoch {rec.epoch} outside "
                    f"[{last_logged}, {n_flips}] (seed={plan_seed}, "
                    f"kill_hit={kill_hit})",
                )
            oracle = [b.clone() for b in bms]
            singest.apply_batches(
                oracle,
                [singest.MutationBatch("fz-durable", m)
                 for m in muts[: rec.epoch]],
            )
            got = rec.corpus.bitmaps()
            torn = len(got) != len(oracle) or any(
                g.to_mutable() != w for g, w in zip(got, oracle)
            )
            # release the zero-copy views before closing the map (close
            # fails loudly while exported buffers are alive — by design)
            del got
            if torn:
                raise InvarianceFailure(
                    name, bms,
                    detail=f"TORN EPOCH: recovered corpus at epoch "
                    f"{rec.epoch} is not bit-exact with the replay oracle "
                    f"(seed={plan_seed}, kill_hit={kill_hit})",
                )
            if kill_hit == 0 and rec.epoch != n_flips:
                raise InvarianceFailure(
                    name, bms,
                    detail=f"clean run recovered epoch {rec.epoch}, "
                    f"wanted the final epoch {n_flips} (seed={plan_seed})",
                )
            rec.corpus.close()
        finally:
            shutil.rmtree(root, ignore_errors=True)


def random_expression(rng, leaves: List[RoaringBitmap], max_depth: int = 4):
    """Random query DAG over the given leaf bitmaps: every node kind
    (and/or/xor/n-ary andnot/not-over-explicit-universe/threshold), biased
    toward reusing leaves so hash-consing and CSE paths are exercised. The
    universe for ``not`` is the union of all leaves (a realistic "all
    users" set)."""
    from .query import Q

    universe = Q.or_(*[Q.leaf(b) for b in leaves]) if len(leaves) > 1 else Q.leaf(leaves[0])

    def build(depth: int):
        if depth >= max_depth or rng.random() < 0.3:
            return Q.leaf(leaves[int(rng.integers(0, len(leaves)))])
        kind = int(rng.integers(0, 6))
        n = int(rng.integers(2, 5))
        subs = [build(depth + 1) for _ in range(n)]
        if kind == 0:
            return Q.and_(*subs)
        if kind == 1:
            return Q.or_(*subs)
        if kind == 2:
            return Q.xor(*subs)
        if kind == 3:
            return Q.andnot(subs[0], *subs[1:])
        if kind == 4:
            return Q.not_(subs[0], universe)
        # k spans the interesting range including k == n and k > n
        return Q.threshold(int(rng.integers(1, n + 2)), *subs)

    return build(0)


def verify_query_invariance(
    name: str,
    iterations: Optional[int] = None,
    seed: Optional[int] = None,
    mode: Optional[str] = None,
) -> None:
    """The query-engine differential invariant: for every sampled DAG,
    planner + executor output must equal naive recursive set-algebra
    evaluation (query.evaluate_naive). Runs with a small shared result
    cache so memoization and eviction are under test too — an execution
    served from a stale cache entry fails exactly like a wrong engine."""
    from .query import ResultCache, evaluate_naive, execute

    rng = np.random.default_rng(seed)
    cache = ResultCache(max_entries=32)
    for _ in range(iterations or default_iterations()):
        leaves = [random_bitmap(rng) for _ in range(int(rng.integers(2, 5)))]
        expr = random_expression(rng, leaves)
        try:
            got = execute(expr, cache=cache, mode=mode)
            want = evaluate_naive(expr)
            ok = got == want
        except Exception as e:
            raise InvarianceFailure(name, leaves, detail=f"{expr!r}: {e!r}") from e
        if not ok:
            raise InvarianceFailure(name, leaves, detail=repr(expr))


def random_bitmap64(rng, max_buckets: int = 3):
    """Shape-diverse 64-bit bitmap spanning several high-32 buckets."""
    from .models.roaring64 import Roaring64NavigableMap

    out = Roaring64NavigableMap()
    buckets = rng.choice(1 << 12, size=int(rng.integers(1, max_buckets + 1)), replace=False)
    for b in buckets:
        vals = random_bitmap(rng, max_keys=2).to_array().astype(np.uint64)
        out.add_many(vals | (np.uint64(int(b)) << np.uint64(32)))
    return out


def verify_invariance64(
    name: str,
    predicate: Callable[..., bool],
    arity: int = 1,
    iterations: Optional[int] = None,
    seed: Optional[int] = None,
) -> None:
    """64-bit fuzzing over both designs: the predicate gets
    Roaring64NavigableMap inputs; equivalence with the ART design is
    itself a good invariant (cross-implementation oracle, SURVEY §4)."""
    rng = np.random.default_rng(seed)
    for _ in range(iterations or default_iterations()):
        bitmaps = [random_bitmap64(rng) for _ in range(arity)]
        # InvarianceFailure only needs .serialize(), which the 64-bit
        # facades provide — repro payloads are portable-64 bytes
        try:
            ok = predicate(*bitmaps)
        except Exception as e:
            raise InvarianceFailure(name, bitmaps, detail=repr(e)) from e
        if not ok:
            raise InvarianceFailure(name, bitmaps)


def run_campaign(iterations: Optional[int] = None, verbose: bool = True) -> dict:
    """Full fuzz campaign at reference intensity (``python -m
    roaringbitmap_tpu.fuzz``; Fuzzer.java's invariant suite, default 10k
    iterations per invariant). Returns {invariant: iterations_run}."""
    from .models.roaring import RoaringBitmap as RB
    from .parallel.aggregation import FastAggregation as FA

    n = iterations or default_iterations()
    results = {}

    def _run(name, fn, actual=None):
        import time

        ran = actual if actual is not None else n
        t0 = time.time()
        fn()
        results[name] = ran
        if verbose:
            print(f"  {name}: {ran} iterations ok ({time.time()-t0:.1f}s)", flush=True)

    _run(
        "and-distributes-over-or",
        lambda: verify_invariance(
            "and-distributes-over-or",
            lambda a, b, c: RB.and_(a, RB.or_(b, c))
            == RB.or_(RB.and_(a, b), RB.and_(a, c)),
            arity=3, iterations=n, seed=1,
        ),
    )
    _run(
        "xor-involution",
        lambda: verify_invariance(
            "xor-involution",
            lambda a, b: RB.xor(RB.xor(a, b), b) == a,
            arity=2, iterations=n, seed=2,
        ),
    )
    _run(
        "inclusion-exclusion",
        lambda: verify_invariance(
            "inclusion-exclusion",
            lambda a, b: RB.or_cardinality(a, b)
            == a.get_cardinality() + b.get_cardinality() - RB.and_cardinality(a, b),
            arity=2, iterations=n, seed=3,
        ),
    )
    _run(
        "serde-roundtrip",
        lambda: verify_invariance(
            "serde-roundtrip",
            lambda a: RB.deserialize(a.serialize()) == a
            and RB.deserialize(a.serialize()).serialize() == a.serialize(),
            arity=1, iterations=n, seed=5,
        ),
    )
    _run(
        "rank-select-inverse",
        lambda: verify_invariance(
            "rank-select-inverse",
            lambda a: all(
                a.rank(a.select(j)) == j + 1
                for j in {0, a.get_cardinality() // 2, a.get_cardinality() - 1}
            ),
            arity=1, iterations=n, seed=6,
        ),
    )
    _run(
        "wide-or-engines-agree",
        lambda: verify_invariance(
            "wide-or-engines-agree",
            lambda a, b, c: FA.or_(a, b, c, mode="cpu")
            == RB.or_(RB.or_(a, b), c)
            and FA.or_(a, b, c, mode="device") == RB.or_(RB.or_(a, b), c),
            arity=3, iterations=n, seed=8,
        ),
    )
    def _card_engines_agree(a, b, c):
        for fn, naive in (
            (FA.or_cardinality, FA.naive_or),
            (FA.and_cardinality, FA.naive_and),
            (FA.xor_cardinality, FA.naive_xor),
        ):
            want = naive(a, b, c).get_cardinality()  # one oracle per op
            if any(fn(a, b, c, mode=m) != want for m in ("cpu", "device")):
                return False
        return True

    _run(
        "cardinality-only-engines-agree",
        lambda: verify_invariance(
            "cardinality-only-engines-agree",
            _card_engines_agree,
            arity=3, iterations=max(1, n // 4), seed=9,
        ),
        actual=max(1, n // 4),
    )
    _run(
        "addOffset-roundtrip",
        lambda: verify_invariance(
            "addOffset-roundtrip",
            lambda a: RB.add_offset(RB.add_offset(a, 1 << 20), -(1 << 20)) == a,
            arity=1, iterations=n, seed=41,
        ),
    )
    _run(
        "selectRange-matches-slice",
        lambda: verify_invariance(
            "selectRange-matches-slice",
            _select_range_pred,
            arity=1, iterations=n, seed=42,
        ),
    )
    _run(
        "iterators-agree",
        lambda: verify_invariance(
            "iterators-agree", _iterators_pred, arity=1, iterations=max(1, n // 4), seed=43
        ),
        actual=max(1, n // 4),
    )
    _run(
        "subset-and-intersects",
        lambda: verify_invariance(
            "subset-and-intersects",
            lambda a, b: a.contains_bitmap(RB.and_(a, b))
            and RB.or_(a, b).contains_bitmap(a)
            and RB.intersects(a, b) == (RB.and_cardinality(a, b) > 0),
            arity=2, iterations=n, seed=44,
        ),
    )
    # device-layout invariance: both layouts by construction, all CPU engines
    # (segmented-scan fuzzed by construction on odd iterations)
    _run(
        "device-layouts-vs-cpu-engines(or)",
        lambda: verify_layout_invariance(
            "device-layouts-vs-cpu-engines(or)", op="or", iterations=n, seed=31
        ),
    )
    _run(
        "device-layouts-vs-cpu-engines(xor)",
        lambda: verify_layout_invariance(
            "device-layouts-vs-cpu-engines(xor)", op="xor", iterations=max(1, n // 4), seed=32
        ),
        actual=max(1, n // 4),
    )
    _run(
        "buffer-heap-equivalence",
        lambda: verify_buffer_invariance(
            "buffer-heap-equivalence",
            lambda ma, mb, ha, hb: ma.serialize() == ha.serialize()
            and RB.and_cardinality(ma, mb) == RB.and_cardinality(ha, hb),
            arity=2, iterations=max(1, n // 4), seed=21,
        ),
        actual=max(1, n // 4),
    )
    _run(
        "64bit-cross-design",
        lambda: verify_invariance64(
            "64bit-cross-design",
            lambda a, b: _cross64(a, b),
            arity=2, iterations=max(1, n // 8), seed=22,
        ),
        actual=max(1, n // 8),
    )
    # round-4 surfaces: reference wire-format roundtrip, bulk reads,
    # 64-bit vectorized membership
    _run(
        "rangebitmap-wire-roundtrip",
        lambda: verify_invariance(
            "rangebitmap-wire-roundtrip",
            _rangebitmap_wire_pred,
            arity=1, iterations=max(1, n // 8), seed=45,
        ),
        actual=max(1, n // 8),
    )
    _run(
        "bsi-bulk-reads-agree",
        lambda: verify_invariance(
            "bsi-bulk-reads-agree",
            _bsi_bulk_pred,
            arity=1, iterations=max(1, n // 8), seed=46,
        ),
        actual=max(1, n // 8),
    )
    _run(
        "contains-many-64-agrees",
        lambda: verify_invariance(
            "contains-many-64-agrees",
            _contains_many64_pred,
            arity=1, iterations=max(1, n // 8), seed=47,
        ),
        actual=max(1, n // 8),
    )
    # round-4 second-session surfaces: the batched multi-predicate counts
    # and the ranged andNot facade overload
    _run(
        "batched-counts-agree",
        lambda: verify_invariance(
            "batched-counts-agree",
            _batched_counts_pred,
            arity=1, iterations=max(1, n // 8), seed=48,
        ),
        actual=max(1, n // 8),
    )
    _run(
        "ranged-andnot-agrees",
        lambda: verify_invariance(
            "ranged-andnot-agrees",
            _ranged_andnot_pred,
            arity=2, iterations=max(1, n // 8), seed=49,
        ),
        actual=max(1, n // 8),
    )
    _run(
        "bulk-order-stats-agree",
        lambda: verify_invariance(
            "bulk-order-stats-agree",
            _bulk_order_stats_pred,
            arity=1, iterations=max(1, n // 8), seed=50,
        ),
        actual=max(1, n // 8),
    )
    # ISSUE 2: query engine (planner + executor + cache) vs naive algebra,
    # on both forced regimes (device engines run on the CPU backend too)
    _run(
        "query-planner-vs-naive",
        lambda: verify_query_invariance(
            "query-planner-vs-naive", iterations=max(1, n // 4), seed=51
        ),
        actual=max(1, n // 4),
    )
    _run(
        "query-planner-vs-naive(device)",
        lambda: verify_query_invariance(
            "query-planner-vs-naive(device)",
            iterations=max(1, n // 8), seed=52, mode="device",
        ),
        actual=max(1, n // 8),
    )
    # ISSUE 4: resident pack cache — delta repack vs from-scratch pack on
    # randomized mutation sequences (both unfiltered and AND-filtered)
    _run(
        "pack-cache-delta-vs-full-repack",
        lambda: verify_pack_cache_invariance(
            "pack-cache-delta-vs-full-repack", iterations=max(1, n // 8), seed=53
        ),
        actual=max(1, n // 8),
    )
    # ISSUE 5: columnar batched pairwise engine vs the per-container
    # engine under randomized op sequences (incl. mapped + run operands)
    _run(
        "columnar-vs-percontainer",
        lambda: verify_columnar_invariance(
            "columnar-vs-percontainer", iterations=max(1, n // 4), seed=54
        ),
        actual=max(1, n // 4),
    )
    # ISSUE 7: random op/query sequences under random seeded fault
    # schedules vs the no-fault oracle — bit-exact, nothing escapes the
    # degradation ladder (derated: each iteration is a multi-step sequence
    # with per-step oracle recomputation)
    _run(
        "fault-schedule-vs-oracle",
        lambda: verify_fault_schedule_invariance(
            "fault-schedule-vs-oracle", iterations=max(1, n // 8), seed=55
        ),
        actual=max(1, n // 8),
    )
    # ISSUE 13: random overlapping expression sets through the fusion
    # window vs the serial per-query oracle, incl. seeded fault schedules
    # over the query.fusion site (derated: each iteration executes a
    # whole multi-query window plus its per-query oracle)
    _run(
        "fused-concurrent-vs-serial",
        lambda: verify_fusion_invariance(
            "fused-concurrent-vs-serial", iterations=max(1, n // 8), seed=57
        ),
        actual=max(1, n // 8),
    )
    # ISSUE 14: seeded multi-tenant traffic through the serving harness
    # (admission -> fusion window, multi-threaded) vs the same query
    # multiset executed serially, incl. seeded fault schedules over the
    # serve.admit and query.fusion sites (derated: each iteration runs a
    # whole threaded harness window plus its serial oracle)
    _run(
        "concurrent-serve-vs-serial",
        lambda: verify_serve_invariance(
            "concurrent-serve-vs-serial", iterations=max(1, n // 8), seed=58
        ),
        actual=max(1, n // 8),
    )
    # ISSUE 15: threaded queries under concurrent ingest + epoch flips
    # (incl. seeded fault schedules over the epoch.flip site) must each
    # match the snapshot of the epoch they were admitted under — zero
    # torn reads (derated: each iteration runs a threaded window plus a
    # per-epoch lineage-replay oracle)
    _run(
        "concurrent-ingest-vs-epoch-oracle",
        lambda: verify_epoch_invariance(
            "concurrent-ingest-vs-epoch-oracle", iterations=max(1, n // 8),
            seed=59,
        ),
        actual=max(1, n // 8),
    )
    # ISSUE 16: randomized ingest with FORCED maintenance passes (incl.
    # seeded fault schedules biased toward the serve.maintain site, which
    # must fail closed) vs a no-compaction twin — compaction may change
    # representation, never content, and the passes' bit-identity audits
    # must report zero anomalies (derated: each iteration replays its
    # whole lineage into the twin)
    _run(
        "compaction-vs-identity-oracle",
        lambda: verify_compaction_invariance(
            "compaction-vs-identity-oracle", iterations=max(1, n // 8),
            seed=60,
        ),
        actual=max(1, n // 8),
    )
    # ISSUE 17: a subprocess killed WITHOUT UNWINDING at a random
    # durable.persist crash point must recover bit-exactly to the last
    # published epoch vs the deterministic replay oracle (derated hard:
    # every iteration pays a full interpreter spawn + import)
    _run(
        "crash-at-any-flip-stage-vs-recovery-oracle",
        lambda: verify_durable_crash_invariance(
            "crash-at-any-flip-stage-vs-recovery-oracle",
            iterations=max(1, n // 64), seed=61,
        ),
        actual=max(1, n // 64),
    )
    return results


def _batched_counts_pred(a) -> bool:
    """compare_cardinality_many must agree with the single-predicate engine
    on a BSI derived from the fuzz bitmap, across ops, modes, and a RANGE
    batch with per-query ends."""
    from .models.bsi import Operation, RoaringBitmapSliceIndex

    cols = a.to_array()
    if cols.size == 0:
        return True
    vals = (cols.astype(np.int64) * 2654435761) % (1 << 22)
    b = RoaringBitmapSliceIndex()
    b.set_values((cols, vals))
    qs = [int(vals[0]), int(vals.min()), int(vals.max()) + 7, 0]
    for op in (Operation.GE, Operation.LT, Operation.NEQ):
        want = [b.compare_cardinality(op, q, 0, None, mode="cpu") for q in qs]
        for mode in ("cpu", "device"):
            if b.compare_cardinality_many(op, qs, mode=mode).tolist() != want:
                return False
    ends = [q + 1000 for q in qs]
    want = [
        b.compare_cardinality(Operation.RANGE, q, e, None, mode="cpu")
        for q, e in zip(qs, ends)
    ]
    return (
        b.compare_cardinality_many(Operation.RANGE, qs, ends=ends, mode="device").tolist()
        == want
    )


def _bulk_order_stats_pred(a) -> bool:
    """rank_many/select_many/contains_many must agree with a sorted-array
    numpy oracle on the heap bitmap, the mapped immutable view, and (via
    a 64-bit lift) both 64-bit designs. Probes mix in-domain misses with
    exact members so the <= boundary is pinned on every surface."""
    from .models.immutable import ImmutableRoaringBitmap
    from .models.roaring64 import Roaring64NavigableMap
    from .models.roaring64art import Roaring64Bitmap

    arr = a.to_array()
    if arr.size == 0:
        return True
    u = np.sort(arr)
    rng = np.random.default_rng(int(u[0]) + u.size)
    ranks = rng.integers(0, u.size, 64)
    # in-domain misses + exact members (the <= boundary case)
    probes = np.concatenate(
        [rng.integers(0, int(u[-1]) + 2, 48).astype(np.uint32), u[ranks[:16]]]
    )
    want_rank = np.searchsorted(u, probes, side="right")
    want_in = np.isin(probes, u)
    for bm in (a, ImmutableRoaringBitmap(a.serialize())):
        if not np.array_equal(bm.rank_many(probes), want_rank):
            return False
        if not np.array_equal(bm.select_many(ranks), u[ranks]):
            return False
        if not np.array_equal(bm.contains_many(probes), want_in):
            return False
    lifted = (u.astype(np.uint64) << np.uint64(20)) | np.uint64(5)
    p64 = np.concatenate(
        [probes.astype(np.uint64) << np.uint64(20), lifted[ranks[:16]]]
    )
    want64 = np.searchsorted(lifted, p64, side="right")
    for bm64 in (Roaring64NavigableMap(), Roaring64Bitmap()):
        bm64.add_many(lifted)
        if not np.array_equal(bm64.rank_many(p64), want64):
            return False
        if not np.array_equal(bm64.select_many(ranks), lifted[ranks]):
            return False
        if not np.array_equal(bm64.contains_many(p64), np.isin(p64, lifted)):
            return False
    return True


def _ranged_andnot_pred(a, b) -> bool:
    """andnot_range == (a \\ b) masked to the range, built through an
    independent construction (bitmap_of_range AND)."""
    from .models.roaring import RoaringBitmap as RB

    last = a.last() if not a.is_empty() else 1000
    lo, hi = last // 3, max(last // 3 + 1, (2 * last) // 3)
    got = RB.andnot_range(a, b, lo, hi)
    mask = RB.bitmap_of_range(lo, hi)
    want = RB.and_(RB.andnot(a, b), mask)
    return got == want


def _select_range_pred(a) -> bool:
    arr = a.to_array()
    card = arr.size
    lo, hi = card // 4, max(card // 4 + 1, (3 * card) // 4)
    got = a.select_range(lo, hi)
    return np.array_equal(got.to_array(), arr[lo:hi])


def _iterators_pred(a) -> bool:
    arr = a.to_array()
    it = a.get_int_iterator()
    fwd = []
    while it.has_next():
        fwd.append(it.next())
    if not np.array_equal(np.array(fwd, dtype=np.int64), arr.astype(np.int64)):
        return False
    batches = []
    for b in a.batch_iterator(257):
        batches.append(b)
    got = np.concatenate(batches) if batches else np.empty(0, dtype=np.uint32)
    return np.array_equal(got, arr)


def _rangebitmap_wire_pred(a) -> bool:
    """RangeBitmap reference-format invariants: the bitmap's values become
    a value column; the sealed index must answer identically through the
    builder, the mapped reference bytes, the mapped native bytes, and a
    native->reference re-encode (the wire inversion is an involution)."""
    from .models.range_bitmap import RangeBitmap

    arr = a.to_array()
    if arr.size == 0:
        return True
    vals = (arr.astype(np.uint64) * np.uint64(2654435761)) % np.uint64(100_000)
    app = RangeBitmap.appender(99_999)
    app.add_many(vals)
    built = app.build()
    q = int(vals[len(vals) // 2])
    want = built.lte(q).to_array()
    want_between = built.between_cardinality(q // 2, q)
    java, native = built.serialize(form="java"), built.serialize(form="native")
    for data in (java, native, RangeBitmap.map(native).serialize(form="java")):
        m = RangeBitmap.map(data)
        if not np.array_equal(m.lte(q).to_array(), want):
            return False
        if m.between_cardinality(q // 2, q) != want_between:
            return False
    return RangeBitmap.map(java).serialize() == java


def _bsi_bulk_pred(a) -> bool:
    """BSI bulk get_values must agree with per-column get_value on a probe
    mix of present and absent columns (and the 64-bit twin likewise)."""
    from .models.bsi import RoaringBitmapSliceIndex
    from .models.bsi64 import Roaring64BitmapSliceIndex

    cols = a.to_array()
    if cols.size == 0:
        return True
    vals = (cols.astype(np.int64) * 7919) % (1 << 20)
    b = RoaringBitmapSliceIndex()
    b.set_values((cols, vals))
    probe = np.concatenate([cols[::7][:64], (cols[:32].astype(np.int64) + 1).astype(np.uint32)])
    got_v, got_e = b.get_values(probe)
    for p, v, e in zip(probe.tolist(), got_v.tolist(), got_e.tolist()):
        if (v, e) != b.get_value(p):
            return False
    b64 = Roaring64BitmapSliceIndex()
    cols64 = cols[:128].astype(np.uint64) << np.uint64(17)
    b64.set_values((cols64, vals[:128]))
    probe64 = np.concatenate([cols64[::3], cols64[:8] + np.uint64(1)])
    v64, e64 = b64.get_values(probe64)
    for p, v, e in zip(probe64.tolist(), list(v64), e64.tolist()):
        if (v, e) != b64.get_value(int(p)):
            return False
    return True


def _contains_many64_pred(a) -> bool:
    """Vectorized 64-bit membership agrees with scalar contains on both
    designs, over hits, misses, and cross-bucket probes."""
    from .models.roaring64 import Roaring64NavigableMap
    from .models.roaring64art import Roaring64Bitmap

    arr = a.to_array()
    if arr.size == 0:
        return True
    # size-capped: the <<33 spread scatters values across thousands of
    # high-48 chunks, so uncapped construction (not the probes) dominated
    # the family's wall clock; diversity across iterations matters more
    vals = (arr.astype(np.uint64) | (arr.astype(np.uint64) << np.uint64(33)))[:2048]
    probe = np.concatenate(
        [vals[::11][:32], vals[:16] ^ np.uint64(1 << 63), vals[:8] + np.uint64(1)]
    )
    for cls in (Roaring64Bitmap, Roaring64NavigableMap):
        bm = cls(vals)
        got = bm.contains_many(probe)
        for p, g in zip(probe.tolist(), got.tolist()):
            if g != bm.contains(int(p)):
                return False
    return True


def _cross64(a, b) -> bool:
    from .models.roaring64art import Roaring64Bitmap

    aa = Roaring64Bitmap(a.to_array())
    bb = Roaring64Bitmap(b.to_array())
    union = a.clone()
    union.ior(b)
    return union.serialize() == Roaring64Bitmap.or_(aa, bb).serialize()


if __name__ == "__main__":
    import sys
    import time

    import jax

    # default to the host backend: fuzz shapes are tiny and diverse, and
    # shipping each through the TPU tunnel would make 10k iterations take
    # days (set RB_FUZZ_BACKEND to override)
    jax.config.update("jax_platforms", os.environ.get("RB_FUZZ_BACKEND", "cpu"))

    n_arg = int(sys.argv[1]) if len(sys.argv) > 1 else None
    t0 = time.time()
    print(f"fuzz campaign: {n_arg or default_iterations()} iterations/invariant")
    res = run_campaign(n_arg)
    print(
        f"campaign green: {len(res)} invariants x up to {max(res.values())} "
        f"iterations in {time.time()-t0:.0f}s"
    )
