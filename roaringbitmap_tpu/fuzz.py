"""Property/invariant fuzzing harness — the reference's ``fuzz-tests`` module
(Fuzzer.verifyInvariance, fuzz-tests/.../Fuzzer.java:31-120;
RandomisedTestData.java:17-52).

``verify_invariance(name, predicate, arity)`` runs the predicate over
randomized shape-diverse bitmaps (rle/dense/sparse chunk mix); on failure the
offending bitmaps are dumped as base64 RoaringFormatSpec payloads so any
failure reproduces from the report alone (the reference's ``Reporter``
behavior). Iteration count comes from ``ROARINGBITMAP_TPU_FUZZ_ITERATIONS``
(the sysprop analogue, RandomisedTestData.java:12).
"""

from __future__ import annotations

import base64
import os
from typing import Callable, List, Optional

import numpy as np

from .models.roaring import RoaringBitmap

def default_iterations() -> int:
    """Read at call time so late env changes take effect (sysprop analogue)."""
    return int(os.environ.get("ROARINGBITMAP_TPU_FUZZ_ITERATIONS", "64"))


class InvarianceFailure(AssertionError):
    """Raised with base64 repro payloads when an invariant breaks."""

    def __init__(self, name: str, bitmaps: List[RoaringBitmap], detail: str = ""):
        self.repro = [base64.b64encode(bm.serialize()).decode() for bm in bitmaps]
        msg = (
            f"invariant '{name}' failed{': ' + detail if detail else ''}\n"
            + "\n".join(
                f"  bitmap[{i}] (base64 RoaringFormatSpec): {r}"
                for i, r in enumerate(self.repro)
            )
        )
        super().__init__(msg)


def reproduce(b64: str) -> RoaringBitmap:
    """Rebuild a bitmap from a failure report payload."""
    return RoaringBitmap.deserialize(base64.b64decode(b64))


def _rle_region(rng) -> np.ndarray:
    starts = rng.choice(np.arange(0, 1 << 16, 64), size=int(rng.integers(1, 30)), replace=False)
    parts = [
        np.arange(s, min(s + int(rng.integers(1, 64)), 1 << 16), dtype=np.int64)
        for s in np.sort(starts)
    ]
    return np.unique(np.concatenate(parts))


def _dense_region(rng) -> np.ndarray:
    return np.sort(rng.choice(1 << 16, size=int(rng.integers(4097, 60000)), replace=False))


def _sparse_region(rng) -> np.ndarray:
    return np.sort(rng.choice(1 << 16, size=int(rng.integers(1, 4096)), replace=False))


def random_bitmap(rng, max_keys: int = 4, optimize_prob: float = 0.3) -> RoaringBitmap:
    """Shape-diverse random bitmap (RandomisedTestData.randomBitmap)."""
    n_keys = int(rng.integers(1, max_keys + 1))
    keys = np.sort(rng.choice(64, size=n_keys, replace=False))
    regions = [_rle_region, _dense_region, _sparse_region]
    parts = [
        regions[int(rng.integers(0, 3))](rng) + (int(k) << 16) for k in keys
    ]
    bm = RoaringBitmap(np.concatenate(parts).astype(np.uint32))
    if rng.random() < optimize_prob:
        bm.run_optimize()
    return bm


def verify_invariance(
    name: str,
    predicate: Callable[..., bool],
    arity: int = 1,
    iterations: Optional[int] = None,
    seed: Optional[int] = None,
    max_keys: int = 4,
) -> None:
    """Run ``predicate(*bitmaps) -> bool`` over random inputs
    (Fuzzer.verifyInvariance, Fuzzer.java:31)."""
    rng = np.random.default_rng(seed)
    for _ in range(iterations or default_iterations()):
        bitmaps = [random_bitmap(rng, max_keys=max_keys) for _ in range(arity)]
        try:
            ok = predicate(*bitmaps)
        except Exception as e:  # predicate crash is also a failure
            raise InvarianceFailure(name, bitmaps, detail=repr(e)) from e
        if not ok:
            raise InvarianceFailure(name, bitmaps)


def verify_buffer_invariance(
    name: str,
    predicate: Callable[..., bool],
    arity: int = 1,
    iterations: Optional[int] = None,
    seed: Optional[int] = None,
) -> None:
    """Buffer-twin fuzzing (BufferFuzzer.java): each random bitmap is
    serialized and handed to the predicate as a zero-copy
    ImmutableRoaringBitmap alongside its heap original —
    ``predicate(mapped..., heap...)``; report payloads reproduce both."""
    from .models.immutable import ImmutableRoaringBitmap

    rng = np.random.default_rng(seed)
    for _ in range(iterations or default_iterations()):
        heap = [random_bitmap(rng) for _ in range(arity)]
        mapped = [ImmutableRoaringBitmap(b.serialize()) for b in heap]
        try:
            ok = predicate(*mapped, *heap)
        except Exception as e:
            raise InvarianceFailure(name, heap, detail=repr(e)) from e
        if not ok:
            raise InvarianceFailure(name, heap)


def random_bitmap64(rng, max_buckets: int = 3):
    """Shape-diverse 64-bit bitmap spanning several high-32 buckets."""
    from .models.roaring64 import Roaring64NavigableMap

    out = Roaring64NavigableMap()
    buckets = rng.choice(1 << 12, size=int(rng.integers(1, max_buckets + 1)), replace=False)
    for b in buckets:
        vals = random_bitmap(rng, max_keys=2).to_array().astype(np.uint64)
        out.add_many(vals | (np.uint64(int(b)) << np.uint64(32)))
    return out


def verify_invariance64(
    name: str,
    predicate: Callable[..., bool],
    arity: int = 1,
    iterations: Optional[int] = None,
    seed: Optional[int] = None,
) -> None:
    """64-bit fuzzing over both designs: the predicate gets
    Roaring64NavigableMap inputs; equivalence with the ART design is
    itself a good invariant (cross-implementation oracle, SURVEY §4)."""
    rng = np.random.default_rng(seed)
    for _ in range(iterations or default_iterations()):
        bitmaps = [random_bitmap64(rng) for _ in range(arity)]
        # InvarianceFailure only needs .serialize(), which the 64-bit
        # facades provide — repro payloads are portable-64 bytes
        try:
            ok = predicate(*bitmaps)
        except Exception as e:
            raise InvarianceFailure(name, bitmaps, detail=repr(e)) from e
        if not ok:
            raise InvarianceFailure(name, bitmaps)
