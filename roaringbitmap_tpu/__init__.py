"""roaringbitmap_tpu — a TPU-native compressed-bitmap framework.

Brand-new JAX/XLA/Pallas implementation with the capabilities of the Java
RoaringBitmap library (reference: /root/reference, ponder-lab/RoaringBitmap).
The logical model is preserved — a 32-bit universe split into 2^16-value
chunks keyed by the high 16 bits, each chunk stored as a sorted-array,
1024x64-bit-word bitset, or run-length container (reference
README.md:135-139) — but the physical execution model is inverted for TPU:
containers are packed into dense ``[N, 1024]``-word device arrays and wide
aggregations, BSI compare chains and cardinalities run as batched XLA
reductions and Pallas kernels, with a pure-numpy CPU path for small or
irregular operations.

Public surface mirrors the reference's L3-L7 layers (SURVEY.md section 1).
"""

from .models.container import (
    ArrayContainer,
    BitmapContainer,
    RunContainer,
    container_from_values,
    container_range_of_ones,
)
from .models.roaring import RoaringBitmap
from .models.roaring64 import Roaring64NavigableMap
from .models.roaring64art import Roaring64Bitmap
from .models.art import Art
from .models.bitset import RoaringBitSet
from .models.fastrank import FastRankRoaringBitmap
from .models.immutable import ImmutableRoaringBitmap
from .models.buffer import (
    BufferFastAggregation,
    BufferParallelAggregation,
    MutableRoaringBitmap,
)
from .models.writer import RoaringBitmapWriter
from .models.bsi import Operation, RoaringBitmapSliceIndex
from .models.bsi64 import Roaring64BitmapSliceIndex
from .models.bsi_buffer import ImmutableBitSliceIndex, MutableBitSliceIndex
from .models.range_bitmap import RangeBitmap
from .models.iterators import (
    BatchIntIterator,
    PeekableIntIterator,
    PeekableIntRankIterator,
    ReverseIntIterator,
    RoaringBatchIterator,
)
from .serialization import InvalidRoaringFormat
from .parallel.aggregation import FastAggregation, ParallelAggregation
from .parallel.aggregation64 import FastAggregation64
from .parallel.batch import (
    batched_cardinality,
    batched_intersects,
    batched_op,
    pairwise_and_cardinality,
    pairwise_cardinality,
    pairwise_jaccard,
    prepare_batched_cardinality,
)
from . import insights
from . import fuzz
from . import observe
from . import tracing
from . import query
from .query import Q

__version__ = "0.1.0"

__all__ = [
    "ArrayContainer",
    "BitmapContainer",
    "RunContainer",
    "container_from_values",
    "container_range_of_ones",
    "RoaringBitmap",
    "MutableRoaringBitmap",
    "Roaring64Bitmap",
    "Roaring64NavigableMap",
    "Art",
    "RoaringBitSet",
    "FastRankRoaringBitmap",
    "ImmutableRoaringBitmap",
    "RoaringBitmapWriter",
    "Operation",
    "RoaringBitmapSliceIndex",
    "Roaring64BitmapSliceIndex",
    "MutableBitSliceIndex",
    "ImmutableBitSliceIndex",
    "RangeBitmap",
    "InvalidRoaringFormat",
    "PeekableIntIterator",
    "PeekableIntRankIterator",
    "ReverseIntIterator",
    "RoaringBatchIterator",
    "BatchIntIterator",
    "FastAggregation",
    "FastAggregation64",
    "ParallelAggregation",
    "BufferFastAggregation",
    "BufferParallelAggregation",
    "batched_cardinality",
    "batched_intersects",
    "batched_op",
    "prepare_batched_cardinality",
    "pairwise_and_cardinality",
    "pairwise_cardinality",
    "pairwise_jaccard",
    "insights",
    "fuzz",
    "observe",
    "tracing",
    "query",
    "Q",
]
