"""Pack/ship residency-pricing authority (ISSUE 12).

The fourth pricing authority has always been implicit: PACK_CACHE's
byte-budget LRU decides what stays HBM-resident, the marshal path pays a
measured ship cost per row (``columnar.MODEL.ship_us_per_row`` — the
SHARED coefficient this authority exposes rather than re-measuring), and
an eviction's true price is the re-pack wall paid when the working set
comes back. Since ISSUE 11 that price is *measured*: a re-pack of a
remembered eviction joins the evict decision with its wall as regret.

This model turns those joins into curves: a per-kind geometric EWMA of
the measured re-pack/rebuild cost (``repack_s``), refit from the
``pack_cache.evict`` ledger samples — each of which carries the evicted
entry's ``kind`` and ``bytes`` in the decision inputs (parallel/store.py
records them at eviction time). The curve is what a future admission/
protection policy prices against (ROADMAP item 1's per-tenant budget
partitions); today it already powers the drift view — a cache whose
re-pack costs are drifting up is one whose budget no longer fits the
traffic, and the sentinel surfaces that through the same facade as every
other authority.

ISSUE 17 adds the fourth residency rung: **mapped-but-not-resident**.
When a durable epoch artifact is on disk, an evicted working set can be
re-admitted from the mmap (zero-copy deserialize + pack) instead of a
cold host repack — a cheaper return path the eviction policy prices via
``readmit_estimate``. The per-kind ``readmit_s`` EWMA learns from joined
``durable.readmit`` samples (recovery.py records a readmit decision per
working set and joins its measured wall), exactly parallel to the
evict-regret ``repack_s`` curve it competes against.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, List, Optional

SCHEMA = "rb_tpu_cost_residency/1"
# EWMA weight for the per-kind repack cost: evictions are rare events, so
# adapt faster than the per-join drift EWMA (~8-sample memory)
_ALPHA = 0.25


class ResidencyModel:
    """Per-kind measured re-pack cost EWMAs + the shared ship pricing."""

    def __init__(self):
        self._lock = threading.Lock()
        self.repack_s: Dict[str, float] = {}  # guarded-by: self._lock
        self.readmit_s: Dict[str, float] = {}  # guarded-by: self._lock
        self.samples: Dict[str, int] = {}  # guarded-by: self._lock
        self.provenance = "static"  # guarded-by: self._lock
        self.backend: Optional[str] = None  # guarded-by: self._lock
        # highest decision serial already folded into the EWMAs: the
        # sentinel re-runs refit_all every cooldown against the SAME
        # retained ledger tail, and re-folding consumed joins would walk
        # the EWMA and double-count samples on every pass (idempotence:
        # a refit over an unchanged ledger is a no-op)
        self._seen_seq = 0  # guarded-by: self._lock

    def curves_view(self) -> dict:
        from ..columnar import costmodel as _costmodel

        with self._lock:
            repack = {k: round(v, 6) for k, v in sorted(self.repack_s.items())}
            readmit = {
                k: round(v, 6) for k, v in sorted(self.readmit_s.items())
            }
        view = {
            # the ship coefficient is SHARED with the columnar calibration
            # (one curve, two consumers — the unification ROADMAP item 4
            # asked for), not a second measurement that could disagree
            "ship_us_per_row": _costmodel.MODEL.ship_us_per_row,
            "repack_s": repack,
            "readmit_s": readmit,
        }
        try:
            from ..parallel import store as _store

            view["budget_bytes"] = _store.PACK_CACHE.max_bytes
        except Exception:  # rb-ok: exception-hygiene -- a curves read must not fail because the cache is mid-teardown; the pricing curves above are still valid
            pass
        return view

    def repack_estimate(self, kind: str) -> Optional[float]:
        """The learned re-pack cost (seconds) for one cache kind — what
        the pack cache prices an eviction of that kind at (None until
        evict-regret traffic has taught the curve). The evict decision
        records it as ``est_us`` so the ledger join scores the residency
        authority's pricing exactly like the other three (ISSUE 12)."""
        with self._lock:
            return self.repack_s.get(kind)

    def readmit_estimate(self, kind: str) -> Optional[float]:
        """The learned mmap re-admit cost (seconds) for one cache kind —
        the cheaper return path a mapped-rung demotion prices against
        the cold ``repack_estimate`` (None until ``durable.readmit``
        traffic has taught the curve)."""
        with self._lock:
            return self.readmit_s.get(kind)

    def drift(self) -> Dict[str, float]:
        """Latest-sample vs EWMA ratio per kind — a kind whose newest
        measured re-pack sits far off its learned curve is drifting."""
        latest: Dict[str, float] = {}
        for e in _evict_samples():
            kind = (e.get("inputs") or {}).get("kind")
            if kind and e.get("measured_s"):
                latest[str(kind)] = float(e["measured_s"])  # newest wins
        with self._lock:
            ewma = dict(self.repack_s)
        out = {}
        for kind, s in sorted(latest.items()):
            base = ewma.get(kind)
            if base and base > 0 and s > 0:
                out[kind] = round(s / base, 4)
        return out

    def refit_from_outcomes(
        self, samples: Optional[List[dict]] = None, min_samples: int = 1
    ) -> dict:
        """Fold joined evict-regret samples into the per-kind EWMAs.
        Ledger-sourced samples (carrying a decision ``seq``) are consumed
        AT MOST ONCE across calls — re-refitting an unchanged ledger is a
        no-op; explicit caller-owned sample lists without serials are
        folded as given. Returns the facade-shape report."""
        moved: Dict[str, dict] = {}
        rejected = 0
        by_kind: Dict[str, List[float]] = {}
        readmit_by_kind: Dict[str, List[float]] = {}
        with self._lock:
            seen = self._seen_seq
        max_seq = seen
        for e in _ledger_samples(samples):
            if e.get("site") == "durable.readmit":
                pool = readmit_by_kind
            else:
                pool = by_kind
            seq = e.get("seq")
            if seq is not None:
                if seq <= seen:
                    continue  # already folded by an earlier refit
                max_seq = max(max_seq, seq)
            kind = (e.get("inputs") or {}).get("kind")
            s = e.get("measured_s")
            if kind is None or s is None:
                rejected += 1
                continue
            s = float(s)
            if not math.isfinite(s) or s <= 0:
                rejected += 1
                continue
            pool.setdefault(str(kind), []).append(s)
        with self._lock:
            self._seen_seq = max(self._seen_seq, max_seq)
            for curve, pool, label in (
                (self.repack_s, by_kind, ""),
                (self.readmit_s, readmit_by_kind, "readmit:"),
            ):
                for kind, ss in sorted(pool.items()):
                    if len(ss) < min_samples:
                        continue
                    old = curve.get(kind)
                    cur = old
                    for s in ss:
                        if cur is None or cur <= 0:
                            cur = s
                        else:
                            cur = math.exp(
                                (1 - _ALPHA) * math.log(cur)
                                + _ALPHA * math.log(s)
                            )
                    cur = round(cur, 9)
                    key = label + kind
                    self.samples[key] = self.samples.get(key, 0) + len(ss)
                    if cur != old:
                        curve[kind] = cur
                        moved[key] = {
                            "from": old, "to": cur, "samples": len(ss)
                        }
            if moved:
                self.provenance = "refit-from-traffic"
                self.backend = _current_backend()
            prov = self.provenance
        return {"moved": moved, "rejected": rejected, "provenance": prov,
                "samples": sum(len(s) for s in by_kind.values())
                + sum(len(s) for s in readmit_by_kind.values())}

    def to_dict(self) -> dict:
        with self._lock:
            return {
                "schema": SCHEMA,
                "backend": self.backend,
                "repack_s": {k: v for k, v in sorted(self.repack_s.items())},
                "readmit_s": {k: v for k, v in sorted(self.readmit_s.items())},
                "samples": dict(self.samples),
                "provenance": self.provenance,
            }

    def from_dict(self, d: dict) -> bool:
        if not isinstance(d, dict) or d.get("schema") != SCHEMA:
            return False
        # re-pack walls are per-host measurements: a state measured on a
        # different backend must not price this host's evictions (the
        # columnar model's per-backend discipline)
        if d.get("backend") is not None and d["backend"] != _current_backend():
            return False
        repack = d.get("repack_s")
        if not isinstance(repack, dict):
            return False
        clean: Dict[str, float] = {}
        for kind, v in repack.items():
            try:
                v = float(v)
            except (TypeError, ValueError):
                return False
            if not (math.isfinite(v) and v > 0):
                return False
            clean[str(kind)] = v
        # readmit_s is absent from pre-ISSUE-17 persisted states — an
        # empty curve, not a schema break
        clean_readmit: Dict[str, float] = {}
        for kind, v in (d.get("readmit_s") or {}).items():
            try:
                v = float(v)
            except (TypeError, ValueError):
                return False
            if not (math.isfinite(v) and v > 0):
                return False
            clean_readmit[str(kind)] = v
        with self._lock:
            self.repack_s = clean
            self.readmit_s = clean_readmit
            self.samples = {
                str(k): int(v) for k, v in (d.get("samples") or {}).items()
            }
            self.provenance = str(d.get("provenance") or "static")
            self.backend = d.get("backend")
        return True

    def reset(self) -> None:
        with self._lock:
            self.repack_s = {}
            self.readmit_s = {}
            self.samples = {}
            self.provenance = "static"
            self.backend = None
            self._seen_seq = 0


def _current_backend() -> Optional[str]:
    try:
        import jax

        return jax.default_backend()
    except (ImportError, RuntimeError):
        return None


def _evict_samples(samples: Optional[List[dict]] = None) -> List[dict]:
    if samples is not None:
        return list(samples)
    from ..observe import outcomes as _outcomes

    return [e for e in _outcomes.tail() if e.get("site") == "pack_cache.evict"]


def _ledger_samples(samples: Optional[List[dict]] = None) -> List[dict]:
    """Both curves' joined samples: evict-regret AND mmap re-admits."""
    if samples is not None:
        return list(samples)
    from ..observe import outcomes as _outcomes

    return [
        e for e in _outcomes.tail()
        if e.get("site") in ("pack_cache.evict", "durable.readmit")
    ]


MODEL = ResidencyModel()
