"""The compaction pricing authority: compact-now vs let-it-ride
(ISSUE 16 — the eighth cost authority).

The background maintenance pass (serve/maintain.py) trades **structure
drift** against **pass wall**: compacting now re-runs format selection
over write-hot keys, merges accumulated epoch deltas, and re-packs cold
working sets — reclaiming the bytes the warm delta path leaked past the
size rule — but pays a rewrite wall inside the epoch-flip machinery;
riding lets ingest keep the hot path O(1) but the bytes-vs-optimal
drift ratio and the delta accretion depth grow without bound.
``serve.maintain`` prices both sides through this model and records the
verdict as a priced ``serve.maintain`` decision; a taken pass is joined
with its measured wall in the decision–outcome ledger, so the
error-ratio rows score the curve and :meth:`refit_from_outcomes` moves
the coefficients toward this host's measured truth — the same
measured-not-guessed discipline as every other authority, behind the
same ``cost/`` facade protocol.

Model shape::

    compact: pass_overhead_us + keys * rewrite_key_us
             + batches * merge_batch_us                      (joined)
    ride:    excess_kb * drift_us_per_kb * depth             (not joined)

``pass_overhead_us`` (epoch-flip brackets: drain + publish + the
bit-identity audit), ``rewrite_key_us`` (per dirty chunk key re-run
through ``run_optimize`` — serialize + compare + rebuild scale with the
touched set), and ``merge_batch_us`` (per accumulated epoch delta batch
folded into the base) are HOST constants the refit learns from joined
passes. ``drift_us_per_kb`` is the declared **exchange rate** — how
many µs of rewrite work one KiB of bytes-over-optimal drift is worth
per decision. It is policy, not physics: no measured wall can refit it,
so it is excluded from the refit and persisted as declared (operators
tune it against their memory budget; the ``structure-drift`` sentinel
rule is the backstop when the rate is set too patient).

Ride verdicts are decision-logged but never joined (nothing executes);
the structure gauges own the cost of waiting.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, List, Optional

SCHEMA = "rb_tpu_compaction_cost/1"

ENGINES = ("compact", "ride")

# structural-prior defaults (µs): a pass drains readers, rewrites the
# dirty keys through run_optimize, folds pending delta batches, and
# audits bit-identity; first joined passes refit the host constants
DEFAULT_COEFFS = {
    "pass_overhead_us": 3000.0,
    "rewrite_key_us": 40.0,
    "merge_batch_us": 500.0,
    # declared exchange rate, never refit: one KiB of bytes-over-optimal
    # drift is worth 50 µs of rewrite work per decision. Patient enough
    # that a freshly-flushed working set is never churned for noise,
    # eager enough that the structure-drift rule (1.3x warn band) only
    # pages when the authority is wedged, not when it is merely thrifty
    "drift_us_per_kb": 50.0,
}
# refit clamps (the house admission-model discipline)
MAX_STEP = 8.0
MAX_SCALE = 256.0
# the refit learns these; drift_us_per_kb stays declared
REFIT_KEYS = ("pass_overhead_us", "rewrite_key_us", "merge_batch_us")


class CompactionModel:
    """Thread-safe compaction cost curves. Reads are lock-free dict gets
    (atomic under the GIL); refits swap under a leaf lock."""

    def __init__(self):
        self._lock = threading.Lock()
        self.coeffs: Dict[str, float] = dict(DEFAULT_COEFFS)
        self.provenance = "default"

    # -- pricing -------------------------------------------------------------

    def predict_us(self, verdict: str, keys: int = 0, batches: int = 0) -> float:
        """Predicted pass wall (µs) for rewriting ``keys`` dirty chunk
        keys now with ``batches`` accumulated epoch delta batches to
        fold — what the ``serve.maintain`` decision records as
        ``est_us["compact"]`` and the outcome join scores."""
        c = self.coeffs
        if verdict != "compact":
            raise ValueError(f"predict_us prices the compact engine, got {verdict!r}")
        return round(
            c["pass_overhead_us"]
            + max(0, int(keys)) * c["rewrite_key_us"]
            + max(0, int(batches)) * c["merge_batch_us"],
            3,
        )

    def ride_cost_us(self, excess_bytes: float, depth: int = 1) -> float:
        """The let-it-ride side: bytes-over-optimal drift priced at the
        declared exchange rate, scaled by the delta accretion depth
        (more batches accreted = more rewrite debt per byte of
        patience)."""
        c = self.coeffs
        return round(
            max(0.0, float(excess_bytes)) / 1024.0 * c["drift_us_per_kb"]
            * max(1, int(depth)),
            3,
        )

    # -- refit from the decision-outcome ledger ------------------------------

    def refit_from_outcomes(
        self, samples: Optional[List[dict]] = None, min_samples: int = 2
    ) -> dict:
        """Scale the compact-side coefficients by the geometric mean of
        measured/predicted over the joined ``serve.maintain`` samples
        (the curve SHAPE is structural; the refit learns this host's
        constants). The declared drift exchange rate never moves."""
        if samples is None:
            from ..observe import outcomes as _outcomes

            samples = _outcomes.tail()
        ratios: List[float] = []
        rejected = 0
        for s in samples:
            if s.get("site") != "serve.maintain" or s.get("engine") != "compact":
                continue
            predicted = s.get("predicted_us")
            measured_s = s.get("measured_s")
            try:
                predicted = float(predicted)
                measured_us = float(measured_s) * 1e6
            except (TypeError, ValueError):
                rejected += 1
                continue
            if not (
                predicted > 0 and measured_us > 0
                and math.isfinite(predicted) and math.isfinite(measured_us)
            ):
                rejected += 1
                continue
            r = measured_us / predicted
            if not (2.0 ** -20 <= r <= 2.0 ** 20):
                rejected += 1  # corrupt telemetry, not bias
                continue
            ratios.append(r)
        moved: Dict[str, dict] = {}
        with self._lock:
            coeffs = dict(self.coeffs)
            if len(ratios) >= min_samples:
                step = math.exp(sum(math.log(r) for r in ratios) / len(ratios))
                step = min(MAX_STEP, max(1.0 / MAX_STEP, step))
                for key in REFIT_KEYS:
                    default = DEFAULT_COEFFS[key]
                    new = coeffs[key] * step
                    new = min(default * MAX_SCALE, max(default / MAX_SCALE, new))
                    if new != coeffs[key]:
                        moved[key] = {
                            "from": round(coeffs[key], 3),
                            "to": round(new, 3),
                            "samples": len(ratios),
                        }
                        coeffs[key] = new
            if moved:
                self.coeffs = coeffs
                self.provenance = "refit-from-traffic"
            provenance = self.provenance
        return {"moved": moved, "rejected": rejected, "provenance": provenance}

    def drift(self) -> Dict[str, float]:
        """{engine: geomean(measured/predicted)} over the ledger's
        current ``serve.maintain`` joins — 1.0 means the compaction
        curve still prices live passes truthfully. Stateless like the
        epoch authority's drift: derived from the ledger tail so a
        refit naturally re-bases as new passes join."""
        from ..observe import outcomes as _outcomes

        logs: List[float] = []
        for s in _outcomes.tail():
            if s.get("site") != "serve.maintain" or s.get("engine") != "compact":
                continue
            err = s.get("error_ratio")  # predicted / measured
            if err and err > 0:
                logs.append(math.log(1.0 / err))
        if not logs:
            return {}
        return {"compact": round(math.exp(sum(logs) / len(logs)), 4)}

    # -- one persistence lifecycle (cost facade protocol) --------------------

    def to_dict(self) -> dict:
        with self._lock:
            return {
                "schema": SCHEMA,
                "coeffs": dict(self.coeffs),
                "provenance": self.provenance,
            }

    def from_dict(self, d: dict) -> bool:
        if not isinstance(d, dict) or d.get("schema") != SCHEMA:
            return False
        coeffs = d.get("coeffs")
        if not isinstance(coeffs, dict):
            return False
        clean = dict(DEFAULT_COEFFS)
        for key, default in DEFAULT_COEFFS.items():
            c = coeffs.get(key, default)
            try:
                c = float(c)
            except (TypeError, ValueError):
                return False
            if not (default / MAX_SCALE <= c <= default * MAX_SCALE):
                return False
            clean[key] = c
        with self._lock:
            self.coeffs = clean
            self.provenance = str(d.get("provenance") or "default")
        return True

    def reset(self) -> None:
        with self._lock:
            self.coeffs = dict(DEFAULT_COEFFS)
            self.provenance = "default"

    def curves_view(self) -> dict:
        with self._lock:
            return {
                "coeffs": dict(self.coeffs),
                "engines": list(ENGINES),
                "refit_keys": list(REFIT_KEYS),
            }


MODEL = CompactionModel()
