"""The epoch-flip pricing authority: flip-now vs accumulate-more
(ISSUE 15 — the seventh cost authority).

The epoch flip (serve/epochs.py) trades **staleness** against **repack
amortization**: flipping early makes pending mutations queryable sooner
(freshness) but pays the flip wall (reader drain + writer stream + O(k)
delta repack) more often; accumulating amortizes the flip over more
batches but lets ingest->queryable lag grow. ``EpochStore.maybe_flip``
prices both sides through this model and records the verdict as a
priced ``epoch.flip`` decision; a taken flip is joined with its measured
wall in the decision–outcome ledger, so the error-ratio rows score the
curve and :meth:`refit_from_outcomes` moves the coefficients toward this
host's measured truth — the same measured-not-guessed discipline as
every other authority, behind the same ``cost/`` facade protocol.

Model shape::

    flip:       flip_overhead_us + values * repack_value_us
                + readers * drain_reader_us                   (joined)
    accumulate: staleness_s * staleness_us_per_s * depth      (not joined)

``flip_overhead_us`` (seal + publish bookkeeping), ``repack_value_us``
(per pending mutation value — the writer stream + delta scatter scale
with the drained volume), and ``drain_reader_us`` (per in-flight reader
pin the drain stage must wait out — under concurrent load the drain
wait IS the flip wall, exactly like the admission model's per-slot
queue term) are HOST constants the refit learns from joined flips. ``staleness_us_per_s`` is the declared
**exchange rate** — how many µs of flip work one batch-second of
staleness is worth. It is policy, not physics: no measured wall can
refit it, so it is excluded from the refit and persisted as declared
(operators tune it against their freshness SLO; the
``freshness-lag-breach`` sentinel rule is the backstop when the rate is
set too patient).

Accumulate verdicts are decision-logged but never joined (nothing
executes); the freshness histograms own the cost of waiting.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, List, Optional

SCHEMA = "rb_tpu_epoch_cost/1"

ENGINES = ("flip", "accumulate")
# the durable half (ISSUE 17): persist-now writes the published epoch's
# frozen artifact to disk; skip leaves it memory-only and exposed
PERSIST_ENGINES = ("persist", "skip")

# structural-prior defaults (µs): a flip drains readers (condition
# round-trip), streams the merged values through the writer, and patches
# k rows in place; first joined flips refit the host constants
DEFAULT_COEFFS = {
    "flip_overhead_us": 2000.0,
    "repack_value_us": 2.0,
    "drain_reader_us": 2000.0,  # ~one request service time per pin
    # declared exchange rate, never refit: one batch-second of staleness
    # is worth 10 ms of flip work. With the x-depth multiplier this
    # yields a flip period of sqrt(flip_us / (rate_us_per_s * writes_per_s))
    # — patient enough that a quiescent flip's wall amortizes below the
    # 10% ingest-tax budget at serving load, eager enough that the
    # freshness-lag-breach rule (2 s warn) never has to page first
    "staleness_us_per_s": 10000.0,
    # durable persist (ISSUE 17): an atomic snapshot pays a fixed
    # tmp-dir + manifest + rename overhead plus a per-KiB serialize +
    # write + fsync rate; joined durable.persist outcomes refit both
    "persist_overhead_us": 5000.0,
    "persist_kb_us": 30.0,
    # declared exchange rate, never refit: each published-but-unpersisted
    # epoch is worth 20 ms of persist work per flip tick — a crash loses
    # exactly the unpersisted suffix, so exposure scales with how many
    # epochs of lineage sit only in RAM. Policy, not physics (operators
    # tune it against their durability SLO; the epoch-persist-stall
    # sentinel rule is the backstop when the rate is set too patient)
    "durability_us_per_epoch": 20000.0,
}
# refit clamps (the house admission-model discipline)
MAX_STEP = 8.0
MAX_SCALE = 256.0
# the refit learns these; staleness_us_per_s stays declared
REFIT_KEYS = ("flip_overhead_us", "repack_value_us", "drain_reader_us")
# persist-side host constants, refit from a SEPARATE durable.persist
# ratio pool (disk bandwidth and flip wall drift independently);
# durability_us_per_epoch stays declared
PERSIST_REFIT_KEYS = ("persist_overhead_us", "persist_kb_us")


class EpochFlipModel:
    """Thread-safe epoch-flip cost curves. Reads are lock-free dict gets
    (atomic under the GIL); refits swap under a leaf lock."""

    def __init__(self):
        self._lock = threading.Lock()
        self.coeffs: Dict[str, float] = dict(DEFAULT_COEFFS)
        self.provenance = "default"

    # -- pricing -------------------------------------------------------------

    def predict_us(self, verdict: str, rows: int = 0, readers: int = 0) -> float:
        """Predicted flip wall (µs) for draining ``rows`` pending
        mutation values now, with ``readers`` in-flight pins the drain
        stage must wait out — what the ``epoch.flip`` decision records
        as ``est_us["flip"]`` and the outcome join scores."""
        c = self.coeffs
        if verdict != "flip":
            raise ValueError(f"predict_us prices the flip engine, got {verdict!r}")
        return round(
            c["flip_overhead_us"]
            + max(0, int(rows)) * c["repack_value_us"]
            + max(0, int(readers)) * c["drain_reader_us"],
            3,
        )

    def predict_persist_us(self, artifact_kb: float) -> float:
        """Predicted persist wall (µs) for snapshotting an epoch whose
        frozen artifact is ``artifact_kb`` KiB — what the
        ``durable.persist`` decision records as ``est_us["persist"]``
        and the outcome join scores against the measured wall."""
        c = self.coeffs
        return round(
            c["persist_overhead_us"]
            + max(0.0, float(artifact_kb)) * c["persist_kb_us"],
            3,
        )

    def exposure_cost_us(self, epochs_behind: int) -> float:
        """The skip side: published-but-unpersisted lineage priced at the
        declared durability exchange rate. Scales with the unpersisted
        suffix depth — a crash loses exactly those epochs' warm state."""
        c = self.coeffs
        return round(
            max(0, int(epochs_behind)) * c["durability_us_per_epoch"], 3
        )

    def staleness_cost_us(self, staleness_s: float, depth: int = 1) -> float:
        """The accumulate side: pending staleness priced at the declared
        exchange rate, scaled by the number of waiting batches (more
        batches waiting = more data stale per second of patience)."""
        c = self.coeffs
        return round(
            max(0.0, float(staleness_s)) * c["staleness_us_per_s"]
            * max(1, int(depth)),
            3,
        )

    # -- refit from the decision-outcome ledger ------------------------------

    def refit_from_outcomes(
        self, samples: Optional[List[dict]] = None, min_samples: int = 2
    ) -> dict:
        """Scale the flip-side coefficients by the geometric mean of
        measured/predicted over the joined ``epoch.flip`` samples (the
        curve SHAPE is structural; the refit learns this host's
        constants). The declared staleness exchange rate never moves."""
        if samples is None:
            from ..observe import outcomes as _outcomes

            samples = _outcomes.tail()
        # two independent ratio pools: flip walls and persist walls are
        # different hardware (CPU drain/stream vs disk write + fsync)
        pools: Dict[str, List[float]] = {"flip": [], "persist": []}
        rejected = 0
        for s in samples:
            if s.get("site") == "epoch.flip" and s.get("engine") == "flip":
                pool = pools["flip"]
            elif (
                s.get("site") == "durable.persist"
                and s.get("engine") == "persist"
            ):
                pool = pools["persist"]
            else:
                continue
            predicted = s.get("predicted_us")
            measured_s = s.get("measured_s")
            try:
                predicted = float(predicted)
                measured_us = float(measured_s) * 1e6
            except (TypeError, ValueError):
                rejected += 1
                continue
            if not (
                predicted > 0 and measured_us > 0
                and math.isfinite(predicted) and math.isfinite(measured_us)
            ):
                rejected += 1
                continue
            r = measured_us / predicted
            if not (2.0 ** -20 <= r <= 2.0 ** 20):
                rejected += 1  # corrupt telemetry, not bias
                continue
            pool.append(r)
        moved: Dict[str, dict] = {}
        with self._lock:
            coeffs = dict(self.coeffs)
            for pool_name, keys in (
                ("flip", REFIT_KEYS),
                ("persist", PERSIST_REFIT_KEYS),
            ):
                ratios = pools[pool_name]
                if len(ratios) < min_samples:
                    continue
                step = math.exp(sum(math.log(r) for r in ratios) / len(ratios))
                step = min(MAX_STEP, max(1.0 / MAX_STEP, step))
                for key in keys:
                    default = DEFAULT_COEFFS[key]
                    new = coeffs[key] * step
                    new = min(default * MAX_SCALE, max(default / MAX_SCALE, new))
                    if new != coeffs[key]:
                        moved[key] = {
                            "from": round(coeffs[key], 3),
                            "to": round(new, 3),
                            "samples": len(ratios),
                        }
                        coeffs[key] = new
            if moved:
                self.coeffs = coeffs
                self.provenance = "refit-from-traffic"
            provenance = self.provenance
        return {"moved": moved, "rejected": rejected, "provenance": provenance}

    def drift(self) -> Dict[str, float]:
        """{engine: geomean(measured/predicted)} over the ledger's
        current ``epoch.flip`` joins — 1.0 means the flip curve still
        prices live traffic truthfully. Stateless like the admission
        authority's drift: derived from the ledger tail so a refit
        naturally re-bases as new flips join."""
        from ..observe import outcomes as _outcomes

        logs: Dict[str, List[float]] = {"flip": [], "persist": []}
        for s in _outcomes.tail():
            if s.get("site") == "epoch.flip" and s.get("engine") == "flip":
                pool = logs["flip"]
            elif (
                s.get("site") == "durable.persist"
                and s.get("engine") == "persist"
            ):
                pool = logs["persist"]
            else:
                continue
            err = s.get("error_ratio")  # predicted / measured
            if err and err > 0:
                pool.append(math.log(1.0 / err))
        return {
            engine: round(math.exp(sum(pool) / len(pool)), 4)
            for engine, pool in logs.items()
            if pool
        }

    # -- one persistence lifecycle (cost facade protocol) --------------------

    def to_dict(self) -> dict:
        with self._lock:
            return {
                "schema": SCHEMA,
                "coeffs": dict(self.coeffs),
                "provenance": self.provenance,
            }

    def from_dict(self, d: dict) -> bool:
        if not isinstance(d, dict) or d.get("schema") != SCHEMA:
            return False
        coeffs = d.get("coeffs")
        if not isinstance(coeffs, dict):
            return False
        clean = dict(DEFAULT_COEFFS)
        for key, default in DEFAULT_COEFFS.items():
            c = coeffs.get(key, default)
            try:
                c = float(c)
            except (TypeError, ValueError):
                return False
            if not (default / MAX_SCALE <= c <= default * MAX_SCALE):
                return False
            clean[key] = c
        with self._lock:
            self.coeffs = clean
            self.provenance = str(d.get("provenance") or "default")
        return True

    def reset(self) -> None:
        with self._lock:
            self.coeffs = dict(DEFAULT_COEFFS)
            self.provenance = "default"

    def curves_view(self) -> dict:
        with self._lock:
            return {
                "coeffs": dict(self.coeffs),
                "engines": list(ENGINES),
                "refit_keys": list(REFIT_KEYS),
                "persist_engines": list(PERSIST_ENGINES),
                "persist_refit_keys": list(PERSIST_REFIT_KEYS),
            }


MODEL = EpochFlipModel()
