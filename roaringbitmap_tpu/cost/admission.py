"""The admission pricing authority: predicted queue wait vs measured
(ISSUE 14 — the sixth cost authority).

The admission controller (serve/admission.py) decides, per request,
whether to admit immediately, park the request in the backpressure
queue, or shed it. The first two verdicts have a measurable execution —
the admission wall from verdict to grant — and the controller records
each as a priced ``serve.admit`` decision: ``est_us`` carries the
predicted wall for the verdict actually available (an admit predicts the
fixed bookkeeping cost; a queue verdict predicts ``depth *
queue_slot_us`` — one in-flight slot's expected service time per
request ahead). The decision–outcome ledger joins the measured wall
against the prediction, the error-ratio rows score the curve, and
:meth:`refit_from_outcomes` moves the coefficients toward this host's
measured truth — the same measured-not-guessed discipline as every
other authority, behind the same ``cost/`` facade protocol, so
``cost.refit_all()`` and the sentinel's drift actuation cover the
admission curve without special cases.

Model shape (two curves, engines ``admit`` | ``queue``)::

    admit: admit_us                      (fixed verdict bookkeeping)
    queue: depth * queue_slot_us         (expected wait per queued slot)

Shed verdicts are decision-logged but never joined (a shed has no
execution to measure); they are priced implicitly — the saturation
telemetry and the ``tenant-saturation`` sentinel rule own that signal.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, List, Optional

SCHEMA = "rb_tpu_admission_cost/1"

ENGINES = ("admit", "queue")

# structural-prior defaults (µs): an admit is token-bucket arithmetic +
# a decision record; a queued request waits roughly one request service
# time per queue slot ahead of it — first traffic refits both
DEFAULT_COEFFS = {
    "admit_us": 30.0,
    "queue_slot_us": 2000.0,
}
# refit clamps (the house CARD_MODEL discipline): one window cannot move
# a coefficient more than MAX_STEP, and coefficients stay within a sane
# band of the structural prior
MAX_STEP = 8.0
MAX_SCALE = 256.0


class AdmissionModel:
    """Thread-safe admission cost curves. Reads are lock-free dict gets
    (atomic under the GIL); refits swap under a leaf lock."""

    def __init__(self):
        self._lock = threading.Lock()
        self.coeffs: Dict[str, float] = dict(DEFAULT_COEFFS)
        self.provenance = "default"

    # -- pricing -------------------------------------------------------------

    def predict_us(self, verdict: str, depth: int = 0) -> float:
        """Predicted admission wall (µs) for one verdict at the current
        queue depth — what the ``serve.admit`` decision records as its
        ``est_us[verdict]`` and the outcome join scores."""
        c = self.coeffs
        if verdict == "queue":
            return round(max(1, int(depth) + 1) * c["queue_slot_us"], 3)
        return round(c["admit_us"], 3)

    # -- refit from the decision-outcome ledger ------------------------------

    def refit_from_outcomes(
        self, samples: Optional[List[dict]] = None, min_samples: int = 2
    ) -> dict:
        """Scale each engine's coefficient by the geometric mean of
        measured/predicted over its joined ``serve.admit`` samples (the
        curve SHAPE is structural; the refit learns this host's
        constants)."""
        if samples is None:
            from ..observe import outcomes as _outcomes

            samples = _outcomes.tail()
        ratios: Dict[str, List[float]] = {}
        rejected = 0
        for s in samples:
            if s.get("site") != "serve.admit":
                continue
            engine = s.get("engine")
            predicted = s.get("predicted_us")
            measured_s = s.get("measured_s")
            if engine not in ENGINES:
                continue
            try:
                predicted = float(predicted)
                measured_us = float(measured_s) * 1e6
            except (TypeError, ValueError):
                rejected += 1
                continue
            if not (
                predicted > 0 and measured_us > 0
                and math.isfinite(predicted) and math.isfinite(measured_us)
            ):
                rejected += 1
                continue
            r = measured_us / predicted
            if not (2.0 ** -20 <= r <= 2.0 ** 20):
                rejected += 1  # corrupt telemetry, not bias
                continue
            ratios.setdefault(engine, []).append(r)
        moved: Dict[str, dict] = {}
        scaled_keys = {"admit": "admit_us", "queue": "queue_slot_us"}
        with self._lock:
            coeffs = dict(self.coeffs)
            for engine, rs in ratios.items():
                if len(rs) < min_samples:
                    continue
                step = math.exp(sum(math.log(r) for r in rs) / len(rs))
                step = min(MAX_STEP, max(1.0 / MAX_STEP, step))
                key = scaled_keys[engine]
                default = DEFAULT_COEFFS[key]
                new = coeffs[key] * step
                new = min(default * MAX_SCALE, max(default / MAX_SCALE, new))
                if new != coeffs[key]:
                    moved[key] = {
                        "from": round(coeffs[key], 3),
                        "to": round(new, 3),
                        "samples": len(rs),
                    }
                    coeffs[key] = new
            if moved:
                self.coeffs = coeffs
                self.provenance = "refit-from-traffic"
            provenance = self.provenance
        return {"moved": moved, "rejected": rejected, "provenance": provenance}

    def drift(self) -> Dict[str, float]:
        """{engine: geomean(measured/predicted)} over the ledger's
        current ``serve.admit`` joins — 1.0 means the admission curve
        still prices live traffic truthfully. Stateless, like the fusion
        authority's drift: derived from the ledger tail so a refit
        naturally re-bases it as new traffic arrives."""
        from ..observe import outcomes as _outcomes

        sums: Dict[str, List[float]] = {}
        for s in _outcomes.tail():
            if s.get("site") != "serve.admit":
                continue
            err = s.get("error_ratio")  # predicted / measured
            engine = s.get("engine")
            if engine in ENGINES and err and err > 0:
                sums.setdefault(engine, []).append(math.log(1.0 / err))
        return {
            engine: round(math.exp(sum(ls) / len(ls)), 4)
            for engine, ls in sorted(sums.items())
        }

    # -- one persistence lifecycle (cost facade protocol) --------------------

    def to_dict(self) -> dict:
        with self._lock:
            return {
                "schema": SCHEMA,
                "coeffs": dict(self.coeffs),
                "provenance": self.provenance,
            }

    def from_dict(self, d: dict) -> bool:
        if not isinstance(d, dict) or d.get("schema") != SCHEMA:
            return False
        coeffs = d.get("coeffs")
        if not isinstance(coeffs, dict):
            return False
        clean = dict(DEFAULT_COEFFS)
        for key, default in DEFAULT_COEFFS.items():
            c = coeffs.get(key, default)
            try:
                c = float(c)
            except (TypeError, ValueError):
                return False
            if not (default / MAX_SCALE <= c <= default * MAX_SCALE):
                return False
            clean[key] = c
        with self._lock:
            self.coeffs = clean
            self.provenance = str(d.get("provenance") or "default")
        return True

    def reset(self) -> None:
        with self._lock:
            self.coeffs = dict(DEFAULT_COEFFS)
            self.provenance = "default"

    def curves_view(self) -> dict:
        with self._lock:
            return {"coeffs": dict(self.coeffs), "engines": list(ENGINES)}


MODEL = AdmissionModel()
