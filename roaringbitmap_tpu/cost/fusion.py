"""The fusion-batch pricing authority: batch-vs-solo cost curves for the
micro-batching executor (ISSUE 13).

The fusion executor (query/fusion.py) must decide, per drained window,
whether coalescing the queries' plan steps into merged per-tier
dispatches beats running the queries back-to-back. Like every other
pricing authority it predicts in microseconds from linear curves, records
its verdict at a decision site (``fusion.batch``) with the per-engine
estimates, and is scored by the decision–outcome ledger: the measured
batch wall joins against the prediction, mispricings show up as regret
and error-ratio rows, and :meth:`refit_from_outcomes` moves the
coefficients toward measured truth from live traffic — the same
measured-not-guessed discipline as ``columnar.costmodel``, behind the
same ``cost/`` facade protocol (curves / provenance / drift / refit /
state), so ``cost.refit_all()`` and the sentinel's drift actuation cover
it without special cases.

Model shape (two curves, engines ``fused`` | ``per-query``)::

    per-query: steps * solo_step_us          (every step pays a dispatch)
    fused:     tiers * tier_us + steps * merge_step_us
               (one dispatch per merged tier + per-step merge overhead;
                `steps` here is the post-dedup unique step count, so the
                shared-subexpression saving prices in by construction)

The defaults encode the structural prior (per-dispatch overhead is the
dominant per-step cost; merging N same-class steps pays one dispatch and
a small per-step concat) and deliberately predict ``fused`` ahead for
any window with more steps than tiers — first traffic then calibrates
the real slopes via refit, with provenance recorded.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, List, Optional, Tuple

SCHEMA = "rb_tpu_fusion_cost/1"

ENGINES = ("fused", "per-query")

# structural-prior defaults (µs): a solo plan step costs about one
# columnar-engine call's fixed overhead; a merged tier costs one such
# dispatch plus a small per-step concat/slice tax. ``slack_penalty`` is
# the latency-penalty term (ISSUE 19): every predicted µs past a
# request's slack counts this many extra µs in the joint window-vs-solo
# verdict — dimensionless, policy-shaped, deliberately NOT refit-scaled
# (the refit learns execution constants; how much an SLO breach hurts is
# a declared preference, not a measurable).
DEFAULT_COEFFS = {
    "solo_step_us": 120.0,
    "tier_us": 150.0,
    "merge_step_us": 25.0,
    "slack_penalty": 4.0,
}
# refit clamps, the CARD_MODEL discipline: one window cannot invert the
# verdict ordering outright, and coefficients stay in a sane decade band
MAX_STEP = 8.0
MAX_SCALE = 64.0


class FusionBatchModel:
    """Thread-safe batch-vs-solo cost curves. Reads are lock-free dict
    gets (atomic under the GIL); refits swap under a leaf lock."""

    def __init__(self):
        self._lock = threading.Lock()
        self.coeffs: Dict[str, float] = dict(DEFAULT_COEFFS)
        self.provenance = "default"

    # -- pricing -------------------------------------------------------------

    def estimate(self, steps: int, tiers: int) -> Dict[str, float]:
        """Per-engine predicted wall (µs) for a window of ``steps`` unique
        plan steps merging into ``tiers`` dispatches — the ``est_us`` dict
        the decision site records and the outcome join prices against."""
        c = self.coeffs
        steps = max(1, int(steps))
        tiers = max(1, int(tiers))
        return {
            "per-query": round(steps * c["solo_step_us"], 3),
            "fused": round(
                tiers * c["tier_us"] + steps * c["merge_step_us"], 3
            ),
        }

    def choose(self, steps: int, tiers: int) -> str:
        est = self.estimate(steps, tiers)
        return "fused" if est["fused"] <= est["per-query"] else "per-query"

    # -- the joint latency-priced verdict (ISSUE 19) -------------------------

    def hedge_estimate(
        self, steps: int, queue_depth: int, wait_us: float
    ) -> Dict[str, float]:
        """Predicted completion wall (µs) for ONE request against a
        forming window: ``window`` = the remaining window hold
        (``wait_us``) plus the fused estimate of the window it would
        join (``queue_depth`` earlier members approximated at this
        request's step count, merge classes collapsing to one tier per
        step-class); ``solo`` = this request's own per-query curve.
        These are the RAW curves — the est_us dict the ``fusion.hedge``
        decision records and the outcome join prices, so regret rows
        measure curve error, not penalty policy."""
        steps = max(1, int(steps))
        n = max(0, int(queue_depth)) + 1
        window_exec = self.estimate(steps * n, steps)["fused"]
        return {
            "window": round(max(0.0, float(wait_us)) + window_exec, 3),
            "solo": self.estimate(steps, steps)["per-query"],
        }

    def choose_dispatch(
        self, steps: int, queue_depth: int, wait_us: float, slack_us: float
    ) -> Tuple[str, Dict[str, float]]:
        """The joint priced batch-vs-solo verdict for one request with
        ``slack_us`` of latency budget left: each path's raw completion
        estimate plus the latency penalty (``slack_penalty`` extra µs per
        predicted µs past the slack) — device efficiency and the
        tenant's declared budget priced in ONE comparison. Returns
        ``(verdict, raw_est)`` with verdict ``"solo"`` when hedging out
        of the window is the cheaper priced outcome."""
        est = self.hedge_estimate(steps, queue_depth, wait_us)
        pen = self.coeffs["slack_penalty"]
        slack_us = float(slack_us)
        priced = {
            path: us + pen * max(0.0, us - slack_us)
            for path, us in est.items()
        }
        # the window keeps ties: hedging duplicates dispatch overhead the
        # window exists to amortize, so solo must WIN, not draw
        verdict = "solo" if priced["solo"] < priced["window"] else "window"
        return verdict, est

    def window_for_budget(
        self, budget_us: float, steps_per_query: float = 2.0
    ) -> int:
        """Largest window size whose predicted fused wall fits inside
        ``budget_us`` under the CURRENT (possibly refitted) curves — the
        serving-p99-pressure actuation's shrink/regrow bound. Structural
        shape: a window of ``w`` average queries runs ``w *
        steps_per_query`` merged steps over ``~steps_per_query`` tiers
        (merge classes collapse across queries), so
        ``fused(w) = steps_per_query * tier_us + w * steps_per_query *
        merge_step_us``; floor 2 (a 1-window is just solo dispatch)."""
        c = self.coeffs
        fixed = steps_per_query * c["tier_us"]
        per_q = steps_per_query * c["merge_step_us"]
        if float(budget_us) <= fixed or per_q <= 0:
            return 2
        return max(2, int((float(budget_us) - fixed) / per_q))

    # -- refit from the decision-outcome ledger ------------------------------

    def refit_from_outcomes(
        self, samples: Optional[List[dict]] = None, min_samples: int = 2
    ) -> dict:
        """Scale each engine's curve by the geometric mean of
        measured/predicted over its joined ``fusion.batch`` samples (the
        CARD_MODEL's multiplicative-correction discipline: the curve
        SHAPE is structural, the refit learns this host's constants).
        ``per-query`` scales ``solo_step_us``; ``fused`` scales
        ``tier_us`` and ``merge_step_us`` together (their ratio is the
        structural prior; the join cannot separate them)."""
        if samples is None:
            from ..observe import outcomes as _outcomes

            samples = _outcomes.tail()
        ratios: Dict[str, List[float]] = {}
        rejected = 0
        for s in samples:
            site = s.get("site")
            if site not in ("fusion.batch", "fusion.hedge"):
                continue
            engine = s.get("engine")
            if site == "fusion.hedge":
                # a hedged solo dispatch measures exactly the per-query
                # curve (ISSUE 19); window-verdict joins are queue-wait
                # dominated (policy, not curve) and don't refit anything
                if engine != "solo":
                    continue
                engine = "per-query"
            predicted = s.get("predicted_us")
            measured_s = s.get("measured_s")
            if engine not in ENGINES:
                continue
            try:
                predicted = float(predicted)
                measured_us = float(measured_s) * 1e6
            except (TypeError, ValueError):
                rejected += 1
                continue
            if not (
                predicted > 0 and measured_us > 0
                and math.isfinite(predicted) and math.isfinite(measured_us)
            ):
                rejected += 1
                continue
            r = measured_us / predicted
            if not (2.0 ** -20 <= r <= 2.0 ** 20):
                rejected += 1  # corrupt telemetry, not bias
                continue
            ratios.setdefault(engine, []).append(r)
        moved: Dict[str, dict] = {}
        scaled_keys = {
            "per-query": ("solo_step_us",),
            "fused": ("tier_us", "merge_step_us"),
        }
        with self._lock:
            coeffs = dict(self.coeffs)
            for engine, rs in ratios.items():
                if len(rs) < min_samples:
                    continue
                step = math.exp(sum(math.log(r) for r in rs) / len(rs))
                step = min(MAX_STEP, max(1.0 / MAX_STEP, step))
                for key in scaled_keys[engine]:
                    default = DEFAULT_COEFFS[key]
                    new = coeffs[key] * step
                    new = min(default * MAX_SCALE, max(default / MAX_SCALE, new))
                    if new != coeffs[key]:
                        moved[key] = {
                            "from": round(coeffs[key], 3),
                            "to": round(new, 3),
                            "samples": len(rs),
                        }
                        coeffs[key] = new
            if moved:
                self.coeffs = coeffs
                self.provenance = "refit-from-traffic"
            provenance = self.provenance
        return {"moved": moved, "rejected": rejected, "provenance": provenance}

    def drift(self) -> Dict[str, float]:
        """{engine: geomean(measured/predicted)} over the ledger's current
        ``fusion.batch`` joins — 1.0 means the curves still price live
        windows truthfully. Stateless: derived from the ledger tail, so a
        refit (which consumes the same joins) naturally re-bases it as
        new traffic arrives under the new coefficients."""
        from ..observe import outcomes as _outcomes

        sums: Dict[str, List[float]] = {}
        for s in _outcomes.tail():
            if s.get("site") != "fusion.batch":
                continue
            err = s.get("error_ratio")  # predicted / measured
            engine = s.get("engine")
            if engine in ENGINES and err and err > 0:
                sums.setdefault(engine, []).append(math.log(1.0 / err))
        return {
            engine: round(math.exp(sum(ls) / len(ls)), 4)
            for engine, ls in sorted(sums.items())
        }

    # -- one persistence lifecycle (cost facade protocol) --------------------

    def to_dict(self) -> dict:
        with self._lock:
            return {
                "schema": SCHEMA,
                "coeffs": dict(self.coeffs),
                "provenance": self.provenance,
            }

    def from_dict(self, d: dict) -> bool:
        if not isinstance(d, dict) or d.get("schema") != SCHEMA:
            return False
        coeffs = d.get("coeffs")
        if not isinstance(coeffs, dict):
            return False
        clean = dict(DEFAULT_COEFFS)
        for key, default in DEFAULT_COEFFS.items():
            c = coeffs.get(key, default)
            try:
                c = float(c)
            except (TypeError, ValueError):
                return False
            if not (default / MAX_SCALE <= c <= default * MAX_SCALE):
                return False
            clean[key] = c
        with self._lock:
            self.coeffs = clean
            self.provenance = str(d.get("provenance") or "default")
        return True

    def reset(self) -> None:
        with self._lock:
            self.coeffs = dict(DEFAULT_COEFFS)
            self.provenance = "default"

    def curves_view(self) -> dict:
        with self._lock:
            return {"coeffs": dict(self.coeffs), "engines": list(ENGINES)}


MODEL = FusionBatchModel()
