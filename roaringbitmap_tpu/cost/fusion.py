"""The fusion-batch pricing authority: batch-vs-solo cost curves for the
micro-batching executor (ISSUE 13).

The fusion executor (query/fusion.py) must decide, per drained window,
whether coalescing the queries' plan steps into merged per-tier
dispatches beats running the queries back-to-back. Like every other
pricing authority it predicts in microseconds from linear curves, records
its verdict at a decision site (``fusion.batch``) with the per-engine
estimates, and is scored by the decision–outcome ledger: the measured
batch wall joins against the prediction, mispricings show up as regret
and error-ratio rows, and :meth:`refit_from_outcomes` moves the
coefficients toward measured truth from live traffic — the same
measured-not-guessed discipline as ``columnar.costmodel``, behind the
same ``cost/`` facade protocol (curves / provenance / drift / refit /
state), so ``cost.refit_all()`` and the sentinel's drift actuation cover
it without special cases.

Model shape (two curves, engines ``fused`` | ``per-query``)::

    per-query: steps * solo_step_us          (every step pays a dispatch)
    fused:     tiers * tier_us + steps * merge_step_us
               (one dispatch per merged tier + per-step merge overhead;
                `steps` here is the post-dedup unique step count, so the
                shared-subexpression saving prices in by construction)

The defaults encode the structural prior (per-dispatch overhead is the
dominant per-step cost; merging N same-class steps pays one dispatch and
a small per-step concat) and deliberately predict ``fused`` ahead for
any window with more steps than tiers — first traffic then calibrates
the real slopes via refit, with provenance recorded.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, List, Optional

SCHEMA = "rb_tpu_fusion_cost/1"

ENGINES = ("fused", "per-query")

# structural-prior defaults (µs): a solo plan step costs about one
# columnar-engine call's fixed overhead; a merged tier costs one such
# dispatch plus a small per-step concat/slice tax
DEFAULT_COEFFS = {
    "solo_step_us": 120.0,
    "tier_us": 150.0,
    "merge_step_us": 25.0,
}
# refit clamps, the CARD_MODEL discipline: one window cannot invert the
# verdict ordering outright, and coefficients stay in a sane decade band
MAX_STEP = 8.0
MAX_SCALE = 64.0


class FusionBatchModel:
    """Thread-safe batch-vs-solo cost curves. Reads are lock-free dict
    gets (atomic under the GIL); refits swap under a leaf lock."""

    def __init__(self):
        self._lock = threading.Lock()
        self.coeffs: Dict[str, float] = dict(DEFAULT_COEFFS)
        self.provenance = "default"

    # -- pricing -------------------------------------------------------------

    def estimate(self, steps: int, tiers: int) -> Dict[str, float]:
        """Per-engine predicted wall (µs) for a window of ``steps`` unique
        plan steps merging into ``tiers`` dispatches — the ``est_us`` dict
        the decision site records and the outcome join prices against."""
        c = self.coeffs
        steps = max(1, int(steps))
        tiers = max(1, int(tiers))
        return {
            "per-query": round(steps * c["solo_step_us"], 3),
            "fused": round(
                tiers * c["tier_us"] + steps * c["merge_step_us"], 3
            ),
        }

    def choose(self, steps: int, tiers: int) -> str:
        est = self.estimate(steps, tiers)
        return "fused" if est["fused"] <= est["per-query"] else "per-query"

    # -- refit from the decision-outcome ledger ------------------------------

    def refit_from_outcomes(
        self, samples: Optional[List[dict]] = None, min_samples: int = 2
    ) -> dict:
        """Scale each engine's curve by the geometric mean of
        measured/predicted over its joined ``fusion.batch`` samples (the
        CARD_MODEL's multiplicative-correction discipline: the curve
        SHAPE is structural, the refit learns this host's constants).
        ``per-query`` scales ``solo_step_us``; ``fused`` scales
        ``tier_us`` and ``merge_step_us`` together (their ratio is the
        structural prior; the join cannot separate them)."""
        if samples is None:
            from ..observe import outcomes as _outcomes

            samples = _outcomes.tail()
        ratios: Dict[str, List[float]] = {}
        rejected = 0
        for s in samples:
            if s.get("site") != "fusion.batch":
                continue
            engine = s.get("engine")
            predicted = s.get("predicted_us")
            measured_s = s.get("measured_s")
            if engine not in ENGINES:
                continue
            try:
                predicted = float(predicted)
                measured_us = float(measured_s) * 1e6
            except (TypeError, ValueError):
                rejected += 1
                continue
            if not (
                predicted > 0 and measured_us > 0
                and math.isfinite(predicted) and math.isfinite(measured_us)
            ):
                rejected += 1
                continue
            r = measured_us / predicted
            if not (2.0 ** -20 <= r <= 2.0 ** 20):
                rejected += 1  # corrupt telemetry, not bias
                continue
            ratios.setdefault(engine, []).append(r)
        moved: Dict[str, dict] = {}
        scaled_keys = {
            "per-query": ("solo_step_us",),
            "fused": ("tier_us", "merge_step_us"),
        }
        with self._lock:
            coeffs = dict(self.coeffs)
            for engine, rs in ratios.items():
                if len(rs) < min_samples:
                    continue
                step = math.exp(sum(math.log(r) for r in rs) / len(rs))
                step = min(MAX_STEP, max(1.0 / MAX_STEP, step))
                for key in scaled_keys[engine]:
                    default = DEFAULT_COEFFS[key]
                    new = coeffs[key] * step
                    new = min(default * MAX_SCALE, max(default / MAX_SCALE, new))
                    if new != coeffs[key]:
                        moved[key] = {
                            "from": round(coeffs[key], 3),
                            "to": round(new, 3),
                            "samples": len(rs),
                        }
                        coeffs[key] = new
            if moved:
                self.coeffs = coeffs
                self.provenance = "refit-from-traffic"
            provenance = self.provenance
        return {"moved": moved, "rejected": rejected, "provenance": provenance}

    def drift(self) -> Dict[str, float]:
        """{engine: geomean(measured/predicted)} over the ledger's current
        ``fusion.batch`` joins — 1.0 means the curves still price live
        windows truthfully. Stateless: derived from the ledger tail, so a
        refit (which consumes the same joins) naturally re-bases it as
        new traffic arrives under the new coefficients."""
        from ..observe import outcomes as _outcomes

        sums: Dict[str, List[float]] = {}
        for s in _outcomes.tail():
            if s.get("site") != "fusion.batch":
                continue
            err = s.get("error_ratio")  # predicted / measured
            engine = s.get("engine")
            if engine in ENGINES and err and err > 0:
                sums.setdefault(engine, []).append(math.log(1.0 / err))
        return {
            engine: round(math.exp(sum(ls) / len(ls)), 4)
            for engine, ls in sorted(sums.items())
        }

    # -- one persistence lifecycle (cost facade protocol) --------------------

    def to_dict(self) -> dict:
        with self._lock:
            return {
                "schema": SCHEMA,
                "coeffs": dict(self.coeffs),
                "provenance": self.provenance,
            }

    def from_dict(self, d: dict) -> bool:
        if not isinstance(d, dict) or d.get("schema") != SCHEMA:
            return False
        coeffs = d.get("coeffs")
        if not isinstance(coeffs, dict):
            return False
        clean = dict(DEFAULT_COEFFS)
        for key, default in DEFAULT_COEFFS.items():
            c = coeffs.get(key, default)
            try:
                c = float(c)
            except (TypeError, ValueError):
                return False
            if not (default / MAX_SCALE <= c <= default * MAX_SCALE):
                return False
            clean[key] = c
        with self._lock:
            self.coeffs = clean
            self.provenance = str(d.get("provenance") or "default")
        return True

    def reset(self) -> None:
        with self._lock:
            self.coeffs = dict(DEFAULT_COEFFS)
            self.provenance = "default"

    def curves_view(self) -> dict:
        with self._lock:
            return {"coeffs": dict(self.coeffs), "engines": list(ENGINES)}


MODEL = FusionBatchModel()
