"""The unified cost facade: every pricing authority behind one
curves / drift / refit / state protocol (ISSUE 12 tentpole, leg 2 —
closing ROADMAP item 4).

The system grew eight pricing authorities, each calibrated differently:

========================= ===============================================
authority                 wraps
========================= ===============================================
``columnar-cutoff``       ``columnar.costmodel.MODEL`` — the measured
                          three-way per-pair engine curves (ISSUE 10)
``planner-cardinality``   ``query.plan.CARD_MODEL`` — per-op cardinality
                          corrections (ISSUE 11)
``device-breakeven``      ``cost.breakeven.MODEL`` — the agg dispatch
                          gate, the bench's ``cold_breakeven`` story as a
                          live refittable curve
``pack-residency``        ``cost.residency.MODEL`` — ship µs/row (shared
                          with the columnar calibration) + per-kind
                          measured re-pack cost
``fusion-batch``          ``cost.fusion.MODEL`` — the micro-batching
                          executor's batch-vs-solo window curves
                          (ISSUE 13)
``serve-admission``       ``cost.admission.MODEL`` — the serving tier's
                          admission curve: predicted queue wait /
                          admit cost vs measured (ISSUE 14)
``epoch-flip``            ``cost.epoch.MODEL`` — the epoch ledger's
                          flip-now vs accumulate-more curve: predicted
                          flip wall vs measured, staleness priced at the
                          declared exchange rate (ISSUE 15)
``compaction``            ``cost.compaction.MODEL`` — the maintenance
                          tier's compact-now vs let-it-ride curve:
                          predicted pass wall vs measured, structure
                          drift priced at the declared exchange rate
                          (ISSUE 16)
========================= ===============================================

Each adapter answers the same five questions — ``curves()`` (what do you
currently believe), ``provenance()`` (where did that belief come from:
static / calibrated / refit-from-traffic), ``drift()`` (how far is live
traffic from the belief), ``refit_from_outcomes()`` (update the belief
from the decision–outcome ledger), ``state()``/``load_state()`` (one
serialization lifecycle) — so the health sentinel can actuate a refit
without knowing which authority drifted, and a flight bundle captures
every authority's calibration in one ``calibration.json``.

**One persistence lifecycle**: ``save_state()``/``load_state()`` round-
trip ALL authorities through one JSON file (``RB_TPU_COST_STATE``); the
columnar model's own ``RB_TPU_COLUMNAR_CAL`` path keeps working (its
refit persists there too) — the unified file is a superset, not a
replacement.

Lock discipline: the facade holds no lock of its own — every adapter
delegates to its model's existing leaf lock; ``refit_all`` runs the
refits sequentially, each under its own model's lock only.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional

STATE_SCHEMA = "rb_tpu_cost_state/1"


class Authority:
    """Adapter protocol (duck-typed base). Subclasses delegate to the
    underlying model singletons; all methods return plain json-able
    data."""

    name: str = "?"

    def curves(self) -> dict:
        raise NotImplementedError

    def provenance(self) -> str:
        raise NotImplementedError

    def drift(self) -> Dict[str, float]:
        """{cell: measured/believed ratio} — {} when nothing to judge."""
        return {}

    def refit_from_outcomes(self, samples: Optional[List[dict]] = None) -> dict:
        raise NotImplementedError

    def state(self) -> dict:
        raise NotImplementedError

    def load_state(self, d: dict) -> bool:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError


class ColumnarCutoffAuthority(Authority):
    name = "columnar-cutoff"

    def _model(self):
        from ..columnar import costmodel as _costmodel

        return _costmodel.MODEL

    def curves(self) -> dict:
        m = self._model()
        return {
            "calibrated": m.calibrated,
            "backend": m.backend,
            "coeffs": m.coeffs,
            "ship_us_per_row": m.ship_us_per_row,
            "fold_rows_min": m.fold_rows_min,
        }

    def provenance(self) -> str:
        m = self._model()
        return m.provenance if m.calibrated else "default-gate"

    def drift(self) -> Dict[str, float]:
        from ..observe import outcomes as _outcomes

        # the per-coefficient-cell gauge IS this authority's drift view
        # (every cell is a columnar (group, engine, shape) coefficient)
        return _outcomes.drift()

    def refit_from_outcomes(self, samples: Optional[List[dict]] = None) -> dict:
        from ..columnar import costmodel as _costmodel
        from ..observe import outcomes as _outcomes

        report = _costmodel.refit_from_outcomes(samples=samples)
        moved = report.get("moved") or {}
        if moved:
            # the refit replaced these cells' coefficients: their drift
            # EWMAs measured the OLD curves and must re-base, or the
            # sentinel's drift rule would re-fire against beliefs that
            # already moved (ISSUE 12)
            _outcomes.rebase_drift(list(moved))
        return report

    def state(self) -> dict:
        return self._model().to_dict()

    def load_state(self, d: dict) -> bool:
        return self._model().from_dict(d)

    def reset(self) -> None:
        self._model().reset()


class PlannerCardinalityAuthority(Authority):
    name = "planner-cardinality"

    def _model(self):
        from ..query.plan import CARD_MODEL

        return CARD_MODEL

    def curves(self) -> dict:
        m = self._model()
        return {"corrections": dict(m.corrections)}

    def provenance(self) -> str:
        return self._model().provenance

    def refit_from_outcomes(self, samples: Optional[List[dict]] = None) -> dict:
        return self._model().refit_from_outcomes(samples=samples)

    def state(self) -> dict:
        return self._model().to_dict()

    def load_state(self, d: dict) -> bool:
        return self._model().from_dict(d)

    def reset(self) -> None:
        self._model().reset()


class DeviceBreakevenAuthority(Authority):
    name = "device-breakeven"

    def _model(self):
        from . import breakeven as _breakeven

        return _breakeven.MODEL

    def curves(self) -> dict:
        return self._model().curves_view()

    def provenance(self) -> str:
        return self._model().provenance

    def drift(self) -> Dict[str, float]:
        return self._model().drift()

    def refit_from_outcomes(self, samples: Optional[List[dict]] = None) -> dict:
        return self._model().refit_from_outcomes(samples=samples)

    def state(self) -> dict:
        return self._model().to_dict()

    def load_state(self, d: dict) -> bool:
        return self._model().from_dict(d)

    def reset(self) -> None:
        self._model().reset()


class PackResidencyAuthority(Authority):
    name = "pack-residency"

    def _model(self):
        from . import residency as _residency

        return _residency.MODEL

    def curves(self) -> dict:
        return self._model().curves_view()

    def provenance(self) -> str:
        return self._model().provenance

    def drift(self) -> Dict[str, float]:
        return self._model().drift()

    def refit_from_outcomes(self, samples: Optional[List[dict]] = None) -> dict:
        return self._model().refit_from_outcomes(samples=samples)

    def state(self) -> dict:
        return self._model().to_dict()

    def load_state(self, d: dict) -> bool:
        return self._model().from_dict(d)

    def reset(self) -> None:
        self._model().reset()


class FusionBatchAuthority(Authority):
    """The micro-batching executor's batch-vs-solo curves (ISSUE 13):
    ``fusion.batch`` verdicts price fused-window against per-query
    dispatch; the ledger joins score them and the refit learns this
    host's dispatch/merge constants from live windows."""

    name = "fusion-batch"

    def _model(self):
        from . import fusion as _fusion

        return _fusion.MODEL

    def curves(self) -> dict:
        return self._model().curves_view()

    def provenance(self) -> str:
        return self._model().provenance

    def drift(self) -> Dict[str, float]:
        return self._model().drift()

    def refit_from_outcomes(self, samples: Optional[List[dict]] = None) -> dict:
        return self._model().refit_from_outcomes(samples=samples)

    def state(self) -> dict:
        return self._model().to_dict()

    def load_state(self, d: dict) -> bool:
        return self._model().from_dict(d)

    def reset(self) -> None:
        self._model().reset()


class ServeAdmissionAuthority(Authority):
    """The serving tier's admission curve (ISSUE 14): ``serve.admit``
    verdicts predict the admission wall (admit bookkeeping / queued
    backpressure wait); ledger joins score predicted-vs-measured and the
    refit learns this host's service-rate constants from live traffic."""

    name = "serve-admission"

    def _model(self):
        from . import admission as _admission

        return _admission.MODEL

    def curves(self) -> dict:
        return self._model().curves_view()

    def provenance(self) -> str:
        return self._model().provenance

    def drift(self) -> Dict[str, float]:
        return self._model().drift()

    def refit_from_outcomes(self, samples: Optional[List[dict]] = None) -> dict:
        return self._model().refit_from_outcomes(samples=samples)

    def state(self) -> dict:
        return self._model().to_dict()

    def load_state(self, d: dict) -> bool:
        return self._model().from_dict(d)

    def reset(self) -> None:
        self._model().reset()


class EpochFlipAuthority(Authority):
    """The epoch ledger's flip curve (ISSUE 15): ``epoch.flip`` verdicts
    price flip-now (predicted flip wall) against accumulate-more
    (pending staleness at the declared exchange rate); ledger joins
    score taken flips and the refit learns this host's drain/repack
    constants from live traffic."""

    name = "epoch-flip"

    def _model(self):
        from . import epoch as _epoch

        return _epoch.MODEL

    def curves(self) -> dict:
        return self._model().curves_view()

    def provenance(self) -> str:
        return self._model().provenance

    def drift(self) -> Dict[str, float]:
        return self._model().drift()

    def refit_from_outcomes(self, samples: Optional[List[dict]] = None) -> dict:
        return self._model().refit_from_outcomes(samples=samples)

    def state(self) -> dict:
        return self._model().to_dict()

    def load_state(self, d: dict) -> bool:
        return self._model().from_dict(d)

    def reset(self) -> None:
        self._model().reset()


class CompactionAuthority(Authority):
    """The maintenance tier's compaction curve (ISSUE 16):
    ``serve.maintain`` verdicts price compact-now (predicted pass wall)
    against let-it-ride (bytes-over-optimal drift at the declared
    exchange rate); ledger joins score taken passes and the refit
    learns this host's rewrite/merge constants from live maintenance."""

    name = "compaction"

    def _model(self):
        from . import compaction as _compaction

        return _compaction.MODEL

    def curves(self) -> dict:
        return self._model().curves_view()

    def provenance(self) -> str:
        return self._model().provenance

    def drift(self) -> Dict[str, float]:
        return self._model().drift()

    def refit_from_outcomes(self, samples: Optional[List[dict]] = None) -> dict:
        return self._model().refit_from_outcomes(samples=samples)

    def state(self) -> dict:
        return self._model().to_dict()

    def load_state(self, d: dict) -> bool:
        return self._model().from_dict(d)

    def reset(self) -> None:
        self._model().reset()


AUTHORITIES: Dict[str, Authority] = {
    a.name: a
    for a in (
        ColumnarCutoffAuthority(),
        PlannerCardinalityAuthority(),
        DeviceBreakevenAuthority(),
        PackResidencyAuthority(),
        FusionBatchAuthority(),
        ServeAdmissionAuthority(),
        EpochFlipAuthority(),
        CompactionAuthority(),
    )
}


def names() -> List[str]:
    return sorted(AUTHORITIES)


def authority(name: str) -> Authority:
    try:
        return AUTHORITIES[name]
    except KeyError:
        raise KeyError(
            f"unknown pricing authority {name!r} (have {names()})"
        ) from None


def refit_all(samples: Optional[List[dict]] = None) -> Dict[str, dict]:
    """Refit every authority from the live decision–outcome ledger (or an
    explicit sample list, passed to each adapter — adapters filter by
    site). This is the sentinel's drift actuation: one call, every
    pricing authority self-tunes, each recording its own provenance."""
    return {
        name: AUTHORITIES[name].refit_from_outcomes(samples=samples)
        for name in names()
    }


def provenances() -> Dict[str, str]:
    return {name: AUTHORITIES[name].provenance() for name in names()}


def drift_summary() -> Dict[str, Dict[str, float]]:
    """{authority: {cell: ratio}} over every authority reporting drift."""
    out = {}
    for name in names():
        d = AUTHORITIES[name].drift()
        if d:
            out[name] = d
    return out


def calibration_state() -> dict:
    """Every authority's current belief + provenance + drift — the flight
    bundle's ``calibration.json`` and the rb_top cost panel's feed."""
    return {
        "schema": STATE_SCHEMA,
        "authorities": {
            name: {
                "curves": AUTHORITIES[name].curves(),
                "provenance": AUTHORITIES[name].provenance(),
                "drift": AUTHORITIES[name].drift(),
            }
            for name in names()
        },
    }


# ---------------------------------------------------------------------------
# one persistence lifecycle (RB_TPU_COST_STATE)
# ---------------------------------------------------------------------------


def save_state(path: Optional[str] = None) -> Optional[str]:
    """Persist all authorities' state to one JSON file (atomic write);
    ``path`` defaults to ``RB_TPU_COST_STATE`` — None (and no-op) when
    neither names a destination. Returns the path written."""
    path = path if path is not None else os.environ.get("RB_TPU_COST_STATE")
    if not path:
        return None
    from ..observe.export import _atomic_write

    doc = {
        "schema": STATE_SCHEMA,
        "generated_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "authorities": {name: AUTHORITIES[name].state() for name in names()},
    }
    _atomic_write(path, json.dumps(doc, indent=1, sort_keys=True) + "\n")
    return path


def load_state(path: Optional[str] = None) -> Dict[str, bool]:
    """Adopt a persisted unified state; per-authority verdicts (an
    authority whose sub-state fails validation — foreign backend, bad
    schema — is left untouched and reported False). Missing/corrupt file
    → all False."""
    path = path if path is not None else os.environ.get("RB_TPU_COST_STATE")
    verdicts = {name: False for name in names()}
    if not path:
        return verdicts
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return verdicts
    if not isinstance(doc, dict) or doc.get("schema") != STATE_SCHEMA:
        return verdicts
    states = doc.get("authorities") or {}
    for name in names():
        sub = states.get(name)
        if isinstance(sub, dict):
            verdicts[name] = bool(AUTHORITIES[name].load_state(sub))
    return verdicts


def reset_all() -> None:
    """Every authority back to its pre-calibration default (tests)."""
    for name in names():
        AUTHORITIES[name].reset()
