"""Device-breakeven authority: the measured "when does a device dispatch
pay" gate (ISSUE 12).

The aggregation dispatcher's device gate has been a hand-tuned constant
since the seed (``aggregation.config.min_device_containers = 64``); the
bench's ``cold_breakeven`` rows measure the amortization story offline
but never feed back. This model closes that loop from the decision–
outcome ledger: every ``agg.dispatch`` decision resolves with the tier
that absorbed the traffic and its measured wall over a known row count
(``inputs.rows``), which is exactly a per-tier ``overhead + rows·slope``
fit — the same curve family as the columnar cutoff model, one level up.

``refit_from_outcomes()`` fits the per-tier curves from joined samples
(outlier-rejected, ≥2 distinct row counts per tier) and, when BOTH a
device curve and a CPU-tier curve exist, moves the dispatch gate to the
measured crossover (clamped to ``[16, 8192]``), pushing it into
``aggregation.config.min_device_containers``. On CPU-only hosts the
device tier never runs, so no device samples ever arrive and the gate
provably never moves — the r13 behavior, by construction.

Registered behind the ``cost/`` facade protocol (curves / provenance /
drift / refit / state) like the other three pricing authorities.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, List, Optional, Tuple

SCHEMA = "rb_tpu_cost_breakeven/1"
# the dispatch gate may not leave this window no matter what one traffic
# sample says (the columnar model's clamp discipline)
GATE_MIN, GATE_MAX = 16, 8192
_OUTLIER_FACTOR = 20.0


class BreakevenModel:
    """Per-tier dispatch cost curves + the measured device gate."""

    def __init__(self):
        self._lock = threading.Lock()
        # {tier: [overhead_us, per_row_us]} from agg.dispatch joins
        self.curves: Dict[str, List[float]] = {}  # guarded-by: self._lock
        self.gate_rows: Optional[int] = None  # guarded-by: self._lock
        self.provenance = "static"  # guarded-by: self._lock
        self.backend: Optional[str] = None  # guarded-by: self._lock

    def curves_view(self) -> dict:
        from ..parallel import aggregation as _agg

        with self._lock:
            return {
                "tiers": {t: list(c) for t, c in sorted(self.curves.items())},
                "gate_rows": self.gate_rows,
                "config_min_device_containers": _agg.config.min_device_containers,
            }

    def drift(self) -> Dict[str, float]:
        """Measured/predicted geomean per tier over the CURRENT live
        samples, judged against the installed curves — {} until curves
        exist (the static gate predicts nothing to drift from)."""
        with self._lock:
            curves = {t: list(c) for t, c in self.curves.items()}
        if not curves:
            return {}
        out: Dict[str, float] = {}
        for tier, pts in _site_samples().items():
            c = curves.get(tier)
            if c is None or len(pts) < 2:
                continue
            logs = []
            for rows, us in pts:
                pred = c[0] + rows * c[1]
                if pred > 0 and us > 0:
                    logs.append(math.log(us / pred))
            if logs:
                out[tier] = round(math.exp(sum(logs) / len(logs)), 4)
        return out

    def refit_from_outcomes(
        self, samples: Optional[List[dict]] = None, min_samples: int = 6
    ) -> dict:
        """Fit per-tier curves from joined ``agg.dispatch`` samples and
        move the device gate to the measured crossover when both sides of
        it have curves. Returns the facade-shape report."""
        pts_by_tier = _site_samples(samples)
        moved: Dict[str, dict] = {}
        rejected = 0
        fitted: Dict[str, List[float]] = {}
        for tier, pts in sorted(pts_by_tier.items()):
            med = _median([us for _, us in pts])
            clean = [
                (rows, us) for rows, us in pts
                if med / _OUTLIER_FACTOR <= us <= med * _OUTLIER_FACTOR
            ]
            rejected += len(pts) - len(clean)
            if len(clean) < min_samples or len({r for r, _ in clean}) < 2:
                continue
            fitted[tier] = _fit(clean)
        with self._lock:
            for tier, new in fitted.items():
                old = self.curves.get(tier)
                if new != old:
                    self.curves[tier] = new
                    moved[tier] = {"from": old, "to": new,
                                   "samples": len(pts_by_tier[tier])}
            gate = _crossover(self.curves)
            if gate is not None and gate != self.gate_rows:
                moved["gate_rows"] = {"from": self.gate_rows, "to": gate}
                self.gate_rows = gate
            if moved:
                self.provenance = "refit-from-traffic"
                self.backend = _current_backend()
            gate_now = self.gate_rows
            prov = self.provenance
        if "gate_rows" in moved and gate_now is not None:
            from ..parallel import aggregation as _agg

            _agg.config.min_device_containers = int(gate_now)
        return {"moved": moved, "rejected": rejected, "provenance": prov,
                "samples": sum(len(p) for p in pts_by_tier.values())}

    def to_dict(self) -> dict:
        with self._lock:
            return {
                "schema": SCHEMA,
                "backend": self.backend,
                "curves": {t: list(c) for t, c in sorted(self.curves.items())},
                "gate_rows": self.gate_rows,
                "provenance": self.provenance,
            }

    def from_dict(self, d: dict) -> bool:
        if not isinstance(d, dict) or d.get("schema") != SCHEMA:
            return False
        # dispatch curves (and the gate they move) are per-host
        # measurements: a state fit on a different backend must not move
        # THIS host's device gate (the columnar model's per-backend
        # discipline)
        if d.get("backend") is not None and d["backend"] != _current_backend():
            return False
        curves = d.get("curves")
        if not isinstance(curves, dict):
            return False
        clean: Dict[str, List[float]] = {}
        for tier, c in curves.items():
            try:
                overhead, slope = float(c[0]), float(c[1])
            except (TypeError, ValueError, IndexError):
                return False
            if not (overhead >= 0 and slope >= 0
                    and math.isfinite(overhead) and math.isfinite(slope)):
                return False
            clean[str(tier)] = [overhead, slope]
        gate = d.get("gate_rows")
        if gate is not None:
            gate = int(gate)
            if not GATE_MIN <= gate <= GATE_MAX:
                return False
        with self._lock:
            self.curves = clean
            self.gate_rows = gate
            self.provenance = str(d.get("provenance") or "static")
            self.backend = d.get("backend")
        if gate is not None:
            from ..parallel import aggregation as _agg

            _agg.config.min_device_containers = int(gate)
        return True

    def reset(self) -> None:
        with self._lock:
            self.curves = {}
            self.gate_rows = None
            self.provenance = "static"
            self.backend = None


def _current_backend() -> Optional[str]:
    try:
        import jax

        return jax.default_backend()
    except (ImportError, RuntimeError):
        return None


def _site_samples(samples: Optional[List[dict]] = None) -> Dict[str, List[Tuple[int, float]]]:
    """``{tier: [(rows, measured_us), ...]}`` from joined agg.dispatch
    ledger entries (or an explicit sample list in the same shape)."""
    if samples is None:
        from ..observe import outcomes as _outcomes

        samples = [e for e in _outcomes.tail() if e.get("site") == "agg.dispatch"]
    out: Dict[str, List[Tuple[int, float]]] = {}
    for e in samples:
        tier = e.get("engine")
        rows = (e.get("inputs") or {}).get("rows")
        measured = e.get("measured_s")
        if tier is None or rows is None or measured is None:
            continue
        try:
            rows, us = int(rows), float(measured) * 1e6
        except (TypeError, ValueError):
            continue
        if rows < 1 or not math.isfinite(us) or us <= 0:
            continue
        out.setdefault(str(tier), []).append((rows, us))
    return out


def _median(xs: List[float]) -> float:
    s = sorted(xs)
    return s[len(s) // 2] if s else 0.0


def _fit(pts: List[Tuple[int, float]]) -> List[float]:
    """Least-squares overhead + rows·slope, clamped non-negative (the
    calibrate()/refit discipline from the columnar model)."""
    n = len(pts)
    sx = sum(r for r, _ in pts)
    sy = sum(u for _, u in pts)
    sxx = sum(r * r for r, _ in pts)
    sxy = sum(r * u for r, u in pts)
    denom = n * sxx - sx * sx
    if denom == 0:
        return [round(max(0.0, sy / n), 2), 0.0]
    slope = max(0.0, (n * sxy - sx * sy) / denom)
    overhead = max(0.0, (sy - slope * sx) / n)
    return [round(overhead, 2), round(slope, 4)]


def _crossover(curves: Dict[str, List[float]]) -> Optional[int]:
    """Smallest row count where the device curve undercuts every fitted
    CPU tier (None when the device column or all CPU columns are
    missing, or when device never wins inside the clamp window)."""
    dev = curves.get("device")
    cpu = [c for t, c in curves.items() if t != "device"]
    if dev is None or not cpu:
        return None
    for n in range(GATE_MIN, GATE_MAX + 1):
        dev_cost = dev[0] + n * dev[1]
        if all(dev_cost < c[0] + n * c[1] for c in cpu):
            return n
    return None


MODEL = BreakevenModel()
