"""Unified cost substrate (ISSUE 12; ROADMAP item 4's closing half).

One facade over the seven pricing authorities — the columnar cutoff
model, the planner's cardinality corrections, the device-breakeven
dispatch gate, pack/ship residency pricing, (ISSUE 13) the fusion
executor's batch-vs-solo window curves, (ISSUE 14) the serving tier's
admission curve, and (ISSUE 15) the epoch-flip curve (flip-now vs
accumulate-more over the streaming ingest log) — behind a shared
curves / provenance / drift / refit / state protocol, with ONE
persistence lifecycle (``RB_TPU_COST_STATE``). The health sentinel
(``observe.sentinel``) actuates ``refit_all()`` when a drift gauge
leaves its band, which is what makes the authorities self-tuning
instead of calibrated-once-per-host. See ``cost/facade.py``.
"""

from .facade import (
    AUTHORITIES,
    STATE_SCHEMA,
    Authority,
    authority,
    calibration_state,
    drift_summary,
    load_state,
    names,
    provenances,
    refit_all,
    reset_all,
    save_state,
)
from . import admission, breakeven, epoch, fusion, residency

__all__ = [
    "AUTHORITIES",
    "STATE_SCHEMA",
    "Authority",
    "admission",
    "authority",
    "breakeven",
    "calibration_state",
    "drift_summary",
    "epoch",
    "fusion",
    "load_state",
    "names",
    "provenances",
    "refit_all",
    "reset_all",
    "residency",
    "save_state",
]
