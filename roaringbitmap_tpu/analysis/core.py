"""AST-based static analysis framework (ISSUE 3 tentpole).

The reference repo gates every push on static analysis next to its test
matrix (.github/workflows/java-all-versions.yml); this package is the
project-native equivalent: a small checker registry walking ``ast`` with a
per-file context, emitting findings with ``file:line``, severity, and rule
ids. The rules (analysis/rules/) encode invariants that otherwise live in
reviewers' heads — container payloads stay unsigned, jitted paths never
sync to host, guarded state is written under its lock, broad excepts are
justified, metric names follow the ``rb_tpu_`` convention.

Suppression pragmas::

    some_code()  # rb-ok: <rule-id>[, <rule-id>] -- <justification>

on the offending line, or on a comment-only line directly above it.
File-level directives (``# rb-payload-path``) opt a file into path-scoped
rules. ``# guarded-by: <lock>`` on an assignment declares lock discipline
for that target (rules/locks.py).

Pure stdlib (ast/tokenize/hashlib) — running the analyzer never imports
jax or numpy.
"""

from __future__ import annotations

import ast
import hashlib
import io
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Set, Tuple

SEVERITIES = ("error", "warning")

# rb-ok: rule-a, rule-b -- reason   (reason separator: --, —, or :)
_PRAGMA_RE = re.compile(
    r"#\s*rb-ok:\s*(?P<rules>[a-z0-9-]+(?:\s*,\s*[a-z0-9-]+)*)"
    r"(?:\s*(?:--|—|:)\s*(?P<reason>.*\S))?"
)
_DIRECTIVE_RE = re.compile(r"#\s*rb-(?P<name>payload-path)\b")
_GUARDED_BY_RE = re.compile(r"#\s*guarded-by:\s*(?P<lock>[A-Za-z_][\w.]*)")


@dataclass(frozen=True)
class Finding:
    """One rule violation, pinned to file:line. ``end_line`` bounds the
    offending construct's physical span, so a pragma on any of its lines
    (a continuation line of a wrapped call, say) suppresses it."""

    rule: str
    path: str  # scan-root-relative when under the root, else as given
    line: int
    col: int
    severity: str
    message: str
    snippet: str = ""
    end_line: int = 0  # 0 -> same as line
    # pragma-proof findings ignore `# rb-ok:` suppression: rules use this
    # where a pragma would waive a contract the rule exists to enforce
    # (exception-hygiene's fault-site strictness, ISSUE 7)
    pragma_proof: bool = False

    def render(self) -> str:
        return (
            f"{self.path}:{self.line}:{self.col}: "
            f"{self.rule} [{self.severity}] {self.message}"
        )

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "severity": self.severity,
            "message": self.message,
            "snippet": self.snippet,
        }


def fingerprints(findings: Sequence[Finding]) -> List[str]:
    """Stable ids for baselining: hash of (rule, path, offending line text,
    occurrence index) — independent of line *numbers*, so unrelated edits
    above a baselined finding don't churn the baseline."""
    seen: Dict[Tuple[str, str, str], int] = {}
    out = []
    for f in findings:
        key = (f.rule, f.path, f.snippet.strip())
        k = seen.get(key, 0)
        seen[key] = k + 1
        raw = f"{f.rule}|{f.path}|{f.snippet.strip()}|{k}"
        out.append(hashlib.sha1(raw.encode("utf-8")).hexdigest()[:16])
    return out


class FileContext:
    """Parsed view of one source file shared by every checker: AST, raw
    lines, pragma map, file directives, and guarded-by annotations."""

    def __init__(self, path: str, source: str, relpath: Optional[str] = None):
        self.path = path
        self.relpath = relpath if relpath is not None else path
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        # line -> set of rule ids suppressed there
        self.pragmas: Dict[int, Set[str]] = {}
        self.directives: Set[str] = set()
        # line -> lock name (terminal segment) declared via # guarded-by:
        self.guards: Dict[int, str] = {}
        self._scan_comments()

    def _scan_comments(self) -> None:
        try:
            tokens = tokenize.generate_tokens(io.StringIO(self.source).readline)
            comments = [
                (tok.start[0], tok.start[1], tok.string)
                for tok in tokens
                if tok.type == tokenize.COMMENT
            ]
        except (tokenize.TokenError, IndentationError):
            comments = [
                (i + 1, line.index("#"), line[line.index("#") :])
                for i, line in enumerate(self.lines)
                if "#" in line
            ]
        for lineno, col, text in comments:
            m = _PRAGMA_RE.search(text)
            if m:
                rules = {r.strip() for r in m.group("rules").split(",")}
                self.pragmas.setdefault(lineno, set()).update(rules)
                # a comment-only pragma covers the next *code* line (a
                # justification may continue over several comment lines)
                if self.lines[lineno - 1].lstrip().startswith("#"):
                    nxt = lineno + 1
                    while nxt <= len(self.lines) and (
                        not self.lines[nxt - 1].strip()
                        or self.lines[nxt - 1].lstrip().startswith("#")
                    ):
                        self.pragmas.setdefault(nxt, set()).update(rules)
                        nxt += 1
                    self.pragmas.setdefault(nxt, set()).update(rules)
            d = _DIRECTIVE_RE.search(text)
            if d:
                self.directives.add(d.group("name"))
            g = _GUARDED_BY_RE.search(text)
            if g:
                # terminal segment: "self._lock" and "_lock" both key "_lock"
                self.guards[lineno] = g.group("lock").rsplit(".", 1)[-1]

    def has_directive(self, name: str) -> bool:
        return name in self.directives

    def suppressed(self, rule: str, line: int, end_line: int = 0) -> bool:
        """Pragma present on any physical line of [line, end_line]?"""
        for ln in range(line, max(end_line, line) + 1):
            if rule in self.pragmas.get(ln, ()):
                return True
        return False

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1]
        return ""


class Checker:
    """One rule. Subclasses set ``rule_id``/``description``/``severity``
    and implement ``check(ctx) -> iterable of (line, col, message)`` or
    yield full Findings via :meth:`finding`."""

    rule_id: str = "abstract"
    description: str = ""
    severity: str = "error"

    def finding(self, ctx: FileContext, node_or_line, message: str, col: int = 0,
                suppress_pragma: bool = False):
        if isinstance(node_or_line, int):
            line = end = node_or_line
        else:
            node = node_or_line
            line, col = node.lineno, node.col_offset
            # pragma span: the construct's own lines — but for block
            # statements only the header (test/iter/except clause), never
            # the body, else a pragma deep inside an `if` would suppress it
            if isinstance(node, (ast.If, ast.While)):
                end = getattr(node.test, "end_lineno", line)
            elif isinstance(node, ast.For):
                end = getattr(node.iter, "end_lineno", line)
            elif isinstance(node, ast.ExceptHandler):
                # the clause header only (a wrapped type tuple), not the body
                end = (
                    getattr(node.type, "end_lineno", line)
                    if node.type is not None
                    else line
                )
            elif isinstance(node, (ast.FunctionDef, ast.With)):
                end = line
            else:
                end = getattr(node, "end_lineno", line) or line
        return Finding(
            rule=self.rule_id,
            path=ctx.relpath,
            line=line,
            col=col,
            severity=self.severity,
            message=message,
            snippet=ctx.line_text(line).strip(),
            end_line=end,
            pragma_proof=suppress_pragma,
        )

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        raise NotImplementedError


class ProjectChecker:
    """One whole-program contract rule (ISSUE 18). Unlike :class:`Checker`,
    which sees one file, a ProjectChecker receives the
    :class:`~roaringbitmap_tpu.analysis.project.ProjectContext` — the full
    parsed tree plus the extracted implicit registries — and emits findings
    anchored to whatever file each contract leg lives in. Pragma
    suppression works exactly like the lexical tier: a finding anchored at
    ``path:line`` is waived by ``# rb-ok: <rule>`` on that line (the
    anchored file's FileContext carries the pragma map)."""

    rule_id: str = "abstract-contract"
    description: str = ""
    severity: str = "error"

    def finding(self, project, path: str, line: int, message: str,
                col: int = 0, end_line: int = 0,
                suppress_pragma: bool = False) -> Finding:
        ctx = project.files.get(path)
        snippet = ctx.line_text(line).strip() if ctx is not None else ""
        return Finding(
            rule=self.rule_id,
            path=path,
            line=line,
            col=col,
            severity=self.severity,
            message=message,
            snippet=snippet,
            end_line=end_line or line,
            pragma_proof=suppress_pragma,
        )

    def check_project(self, project) -> Iterable[Finding]:
        raise NotImplementedError


# rule-id -> checker class; rules/__init__.py populates this at import
CHECKERS: Dict[str, type] = {}
# rule-id -> ProjectChecker class (the contract tier, ISSUE 18)
CONTRACT_CHECKERS: Dict[str, type] = {}


def register(cls: type) -> type:
    """Class decorator adding a Checker to the global registry."""
    if cls.rule_id in CHECKERS and CHECKERS[cls.rule_id] is not cls:
        raise ValueError(f"duplicate rule id {cls.rule_id!r}")
    CHECKERS[cls.rule_id] = cls
    return cls


def register_contract(cls: type) -> type:
    """Class decorator adding a ProjectChecker to the contract registry.
    The two tiers share one rule-id namespace so ``--rules`` selection and
    the per-rule findings counter stay unambiguous."""
    if cls.rule_id in CONTRACT_CHECKERS and CONTRACT_CHECKERS[cls.rule_id] is not cls:
        raise ValueError(f"duplicate contract rule id {cls.rule_id!r}")
    if cls.rule_id in CHECKERS:
        raise ValueError(
            f"contract rule id {cls.rule_id!r} collides with a lexical rule"
        )
    CONTRACT_CHECKERS[cls.rule_id] = cls
    return cls


def all_rule_ids() -> List[str]:
    _load_rules()
    return sorted(CHECKERS)


def all_contract_rule_ids() -> List[str]:
    _load_rules()
    return sorted(CONTRACT_CHECKERS)


def _load_rules() -> None:
    # import side effect populates CHECKERS; lazy so core stays importable
    # standalone (scripts/analyze.py bootstraps through here)
    from . import rules as _rules  # noqa: F401


def iter_python_files(paths: Sequence[str]) -> List[str]:
    """Expand files/directories to a sorted list of .py files.

    A path that does not exist or names a non-.py file is a ValueError —
    silently scanning nothing would turn a typo'd CI invocation into a
    vacuously green gate."""
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [
                    d for d in dirnames if d not in ("__pycache__", ".git")
                ]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        out.append(os.path.join(dirpath, fn))
        elif os.path.isfile(p) and p.endswith(".py"):
            out.append(p)
        else:
            raise ValueError(f"not a directory or .py file: {p}")
    return sorted(dict.fromkeys(out))


@dataclass
class RunResult:
    findings: List[Finding] = field(default_factory=list)
    suppressed: int = 0  # pragma-suppressed count
    files: int = 0
    parse_errors: List[str] = field(default_factory=list)


def run_checks(
    paths: Sequence[str],
    rules: Optional[Sequence[str]] = None,
    root: Optional[str] = None,
) -> RunResult:
    """Run the selected rules (default: all registered) over ``paths``.

    ``root`` anchors reported paths (and therefore baseline fingerprints);
    defaults to the current directory.
    """
    _load_rules()
    wanted = list(rules) if rules else sorted(CHECKERS)
    unknown = [r for r in wanted if r not in CHECKERS]
    if unknown:
        raise ValueError(f"unknown rule(s) {unknown}; known: {sorted(CHECKERS)}")
    checkers = [CHECKERS[r]() for r in wanted]
    base = os.path.abspath(root or os.getcwd())
    result = RunResult()
    for path in iter_python_files(paths):
        ap = os.path.abspath(path)
        rel = os.path.relpath(ap, base) if ap.startswith(base + os.sep) else path
        try:
            with open(path, "r", encoding="utf-8") as f:
                source = f.read()
            ctx = FileContext(path, source, relpath=rel)
        except (OSError, SyntaxError, ValueError) as e:
            result.parse_errors.append(f"{rel}: {e}")
            continue
        result.files += 1
        for checker in checkers:
            for f in checker.check(ctx):
                if not f.pragma_proof and ctx.suppressed(f.rule, f.line, f.end_line):
                    result.suppressed += 1
                else:
                    result.findings.append(f)
    result.findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return result


def run_contract_checks(
    project,
    rules: Optional[Sequence[str]] = None,
) -> RunResult:
    """Run the contract tier (default: every registered ProjectChecker)
    over an already-built ProjectContext. Pragma suppression consults the
    FileContext of whatever file each finding is anchored in, so the two
    tiers share one waiver mechanism (and one baseline format)."""
    _load_rules()
    wanted = list(rules) if rules else sorted(CONTRACT_CHECKERS)
    unknown = [r for r in wanted if r not in CONTRACT_CHECKERS]
    if unknown:
        raise ValueError(
            f"unknown contract rule(s) {unknown}; known: {sorted(CONTRACT_CHECKERS)}"
        )
    result = RunResult(files=len(project.files))
    result.parse_errors.extend(project.parse_errors)
    for rid in wanted:
        checker = CONTRACT_CHECKERS[rid]()
        for f in checker.check_project(project):
            ctx = project.files.get(f.path)
            if (
                ctx is not None
                and not f.pragma_proof
                and ctx.suppressed(f.rule, f.line, f.end_line)
            ):
                result.suppressed += 1
            else:
                result.findings.append(f)
    result.findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return result


# ---------------------------------------------------------------------------
# shared AST helpers used by several rules
# ---------------------------------------------------------------------------


def dotted_name(node: ast.AST) -> Optional[str]:
    """'a.b.c' for nested Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def terminal_name(node: ast.AST) -> Optional[str]:
    """Last segment of a Name/Attribute chain ('cls._POOL_LOCK' -> '_POOL_LOCK')."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


class ParentedVisit:
    """Iterative walk yielding (node, with_lock_stack, func_stack) — the
    lexical context several rules need (enclosing ``with`` lock names and
    enclosing function defs)."""

    def __init__(self, tree: ast.AST):
        self.tree = tree

    def __iter__(self):
        # stack entries: (node, locks_tuple, funcs_tuple)
        stack = [(self.tree, (), ())]
        while stack:
            node, locks, funcs = stack.pop()
            yield node, locks, funcs
            child_locks, child_funcs = locks, funcs
            if isinstance(node, (ast.With, ast.AsyncWith)):
                names = tuple(
                    t for t in (terminal_name(i.context_expr) for i in node.items) if t
                )
                child_locks = locks + names
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                child_funcs = funcs + (node,)
            for child in ast.iter_child_nodes(node):
                stack.append((child, child_locks, child_funcs))
