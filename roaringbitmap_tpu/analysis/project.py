"""ProjectContext: the whole-program view behind the contract tier
(ISSUE 18 tentpole).

The lexical tier (rules/) sees one file at a time; the contracts it
cannot see are exactly the framework's *implicit registries* — cross-file
name sets that must stay in lockstep:

* ``robust/faults.SITES`` ↔ the ``fault_point()`` guards, ladder routes,
  and fuzz/ci-chaos exercise that make a declared site real;
* ``record_decision(..., outcome=True)`` sites ↔ the ``resolve()`` joins
  that keep the decision–outcome economy honest;
* the ``cost/`` facade's ``AUTHORITIES`` ↔ the state-lifecycle protocol,
  the facade's own doc table, and the docs surface;
* ``observe/registry.py``'s ``rb_tpu_*`` name constants ↔ their
  registrations and consumers;
* ``observe/health.py``'s ``DEFAULT_RULES`` ↔ its committed docstring
  threshold table;
* ``RB_TPU_*`` env knobs ↔ the KNOBS.md table;
* ``donate_argnums`` jits ↔ every caller's use of the consumed buffer.

This module parses the package tree ONCE (reusing FileContext, so
pragmas/guards ride along), extracts each registry with narrow AST
walks, and hands the result to every ProjectChecker. A module-level
mtime-keyed cache makes repeated builds (the CLI, tests, ci.sh --fast
--diff runs) free; the cache is thread-safe (tests hammer it).

Pure stdlib, like the rest of analysis/ — building a ProjectContext
never imports the framework.
"""

from __future__ import annotations

import ast
import os
import threading
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import FileContext, dotted_name, terminal_name

# package-relative anchor files for the registry extractors; a rename
# shows up as an extraction failure (empty registry), which the contract
# rules report loudly rather than passing vacuously
FAULTS_MODULE = os.path.join("robust", "faults.py")
FACADE_MODULE = os.path.join("cost", "facade.py")
REGISTRY_MODULE = os.path.join("observe", "registry.py")
HEALTH_MODULE = os.path.join("observe", "health.py")

# calls that read an env knob: os.environ.get / os.getenv / environ[...]
# plus the tree's typed wrappers (_env_int / _env_float / ...)
_ENV_CALL_TERMINALS = {"get", "getenv", "pop", "setdefault"}

# the Authority protocol (cost/facade.py): a facade-registered authority
# must define every method itself — the base raises, so an inherited slot
# means save_state()/load_state() (the RB_TPU_COST_STATE lifecycle) or a
# sentinel-actuated refit would blow up at runtime on that authority
AUTHORITY_PROTOCOL = (
    "curves", "provenance", "refit_from_outcomes", "state", "load_state",
    "reset",
)


class DonationSite:
    """One call to a donating jit: the argument expressions sitting in
    donated positions, resolved by the caller-side rule."""

    __slots__ = ("path", "line", "func", "donated_args")

    def __init__(self, path: str, line: int, func: str, donated_args):
        self.path = path
        self.line = line
        self.func = func
        self.donated_args = donated_args


class DecisionSite:
    """One ``record_decision(...)`` call: its site literal (None when
    dynamic), whether it asked for an outcome join, and the AST call."""

    __slots__ = ("path", "line", "site", "outcome", "call")

    def __init__(self, path: str, line: int, site: Optional[str],
                 outcome: Optional[bool], call: ast.Call):
        self.path = path
        self.line = line
        self.site = site
        self.outcome = outcome  # None == non-constant expression
        self.call = call


class AuthorityInfo:
    __slots__ = ("name", "class_name", "line", "methods", "registered")

    def __init__(self, name: str, class_name: str, line: int,
                 methods: Set[str], registered: bool):
        self.name = name
        self.class_name = class_name
        self.line = line
        self.methods = methods
        self.registered = registered


class ProjectContext:
    """Parsed whole-program view: every package file's FileContext plus
    the extracted implicit registries. Build once per tree state (see
    :func:`get_project`); all fields are read-only after construction."""

    def __init__(self, root: str, package: str = "roaringbitmap_tpu"):
        self.root = os.path.abspath(root)
        self.package = package
        self.files: Dict[str, FileContext] = {}
        self.parse_errors: List[str] = []
        self._text_cache: Dict[str, str] = {}
        self._text_lock = threading.Lock()
        self._parse_tree()

        # -- registries (each a narrow walk over the parsed files) --
        self.fault_sites: Dict[str, int] = {}
        self._extract_fault_sites()
        self.fault_guards: Dict[str, List[Tuple[str, int]]] = {}
        self.ladder_routes: Dict[str, List[Tuple[str, int]]] = {}
        self.decision_sites: List[DecisionSite] = []
        self.knobs: Dict[str, List[Tuple[str, int]]] = {}
        self.donating: Dict[str, Tuple[int, ...]] = {}
        self.metric_constants: Dict[str, Tuple[str, int]] = {}
        self.metric_registrations: List[Tuple[str, int, str, Optional[str],
                                              Optional[Tuple[str, ...]]]] = []
        self.metric_const_uses: Dict[str, Set[str]] = {}
        # the constant table must exist before the use-collecting walk —
        # uses are only recorded for known constant names
        registry_ctx = self.file("observe", "registry.py")
        if registry_ctx is not None:
            self._extract_metric_constants(registry_ctx)
        self._walk_files()
        self.authorities: List[AuthorityInfo] = []
        self._extract_authorities()
        self.sentinel_rules: Dict[str, int] = {}
        self.sentinel_doc_rules: Dict[str, int] = {}
        self._extract_sentinel_rules()

    # ------------------------------------------------------------------
    # parsing
    # ------------------------------------------------------------------

    def _parse_tree(self) -> None:
        pkg_dir = os.path.join(self.root, self.package)
        for dirpath, dirnames, filenames in os.walk(pkg_dir):
            dirnames[:] = [
                d for d in dirnames if d not in ("__pycache__", ".git")
            ]
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                rel = os.path.relpath(path, self.root)
                try:
                    with open(path, "r", encoding="utf-8") as f:
                        source = f.read()
                    self.files[rel] = FileContext(path, source, relpath=rel)
                except (OSError, SyntaxError, ValueError) as e:
                    self.parse_errors.append(f"{rel}: {e}")

    def pkg_path(self, *parts: str) -> str:
        """Root-relative path of a package file (the files-dict key)."""
        return os.path.join(self.package, *parts)

    def file(self, *parts: str) -> Optional[FileContext]:
        return self.files.get(self.pkg_path(*parts))

    def text(self, relpath: str) -> str:
        """Raw text of any repo file (docs, scripts, tests) — the
        extractors' non-Python drift surfaces. Missing file -> ''. Cached
        per ProjectContext build (thread-safe: rules may run parallel)."""
        with self._text_lock:
            if relpath in self._text_cache:
                return self._text_cache[relpath]
        try:
            with open(os.path.join(self.root, relpath), encoding="utf-8") as f:
                content = f.read()
        except OSError:
            content = ""
        with self._text_lock:
            self._text_cache[relpath] = content
        return content

    def exercise_text(self) -> str:
        """The fault-exercise surface: the fuzz harness + the tests tree +
        ci.sh (the ci-chaos gate arms every site via RB_TPU_FAULTS)."""
        parts = [
            self.text(self.pkg_path("fuzz.py")),
            self.text(os.path.join("scripts", "ci.sh")),
        ]
        tests_dir = os.path.join(self.root, "tests")
        if os.path.isdir(tests_dir):
            for fn in sorted(os.listdir(tests_dir)):
                if fn.endswith(".py"):
                    parts.append(self.text(os.path.join("tests", fn)))
        return "\n".join(parts)

    # ------------------------------------------------------------------
    # extractors
    # ------------------------------------------------------------------

    def _extract_fault_sites(self) -> None:
        """``SITES: Tuple[str, ...] = ("store.ship", ...)`` in
        robust/faults.py — each element's own line is the anchor every
        per-site contract finding (and waiver pragma) attaches to."""
        ctx = self.file("robust", "faults.py")
        if ctx is None:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            names = [t.id for t in targets if isinstance(t, ast.Name)]
            if "SITES" not in names or node.value is None:
                continue
            if isinstance(node.value, (ast.Tuple, ast.List)):
                for elt in node.value.elts:
                    if isinstance(elt, ast.Constant) and isinstance(
                        elt.value, str
                    ):
                        self.fault_sites[elt.value] = elt.lineno
            return

    def _walk_files(self) -> None:
        """One pass over every file's AST collecting the call-shaped
        registries: fault guards, ladder routes, decision sites, env-knob
        reads, donate-decorated jits, and metric constant uses."""
        for rel, ctx in self.files.items():
            in_registry = rel == self.pkg_path("observe", "registry.py")
            for node in ast.walk(ctx.tree):
                if isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    donated = _donate_argnums(node)
                    if donated is not None:
                        self.donating[node.name] = donated
                if isinstance(node, (ast.Attribute, ast.Name)):
                    t = terminal_name(node)
                    if t and t in self.metric_const_uses:
                        self.metric_const_uses[t].add(rel)
                if not isinstance(node, ast.Call):
                    continue
                func = node.func
                t = terminal_name(func)
                if t == "fault_point":
                    site = _str_arg(node, 0)
                    if site is not None:
                        self.fault_guards.setdefault(site, []).append(
                            (rel, node.lineno)
                        )
                elif t in ("run", "note_degrade", "retry"):
                    # LADDER.run(site, ...) / LADDER.note_degrade(site, ...)
                    # / ladder.retry(site, ...): the degradation routes
                    recv = _receiver_terminal(func)
                    if recv in ("LADDER", "ladder", "_ladder") or (
                        t == "retry" and recv in ("ladder", "_ladder", None)
                    ):
                        site = _str_arg(node, 0)
                        if site is not None:
                            self.ladder_routes.setdefault(site, []).append(
                                (rel, node.lineno)
                            )
                elif t == "record_decision":
                    site = _str_arg(node, 0)
                    outcome: Optional[bool] = False
                    for kw in node.keywords:
                        if kw.arg == "outcome":
                            if isinstance(kw.value, ast.Constant):
                                outcome = bool(kw.value.value)
                            else:
                                outcome = None  # dynamic
                    self.decision_sites.append(
                        DecisionSite(rel, node.lineno, site, outcome, node)
                    )
                # env knob reads: os.environ.get("RB_TPU_X"),
                # os.getenv("RB_TPU_X"), _env_int("RB_TPU_X", ...), and
                # os.environ["RB_TPU_X"] is handled via Subscript below
                dn = dotted_name(func) or ""
                is_env_call = (
                    ("environ" in dn and t in _ENV_CALL_TERMINALS)
                    or t == "getenv"
                    or (t or "").startswith("_env")
                )
                if is_env_call:
                    for arg in node.args:
                        knob = _rb_knob(arg)
                        if knob is not None:
                            self.knobs.setdefault(knob, []).append(
                                (rel, arg.lineno)
                            )
                # metric registrations: counter/gauge/histogram(name, ...)
                if t in ("counter", "gauge", "histogram") and node.args:
                    self._record_registration(rel, node, in_registry)
            for node in ast.walk(ctx.tree):
                if isinstance(node, ast.Subscript) and "environ" in (
                    dotted_name(node.value) or ""
                ):
                    knob = _rb_knob(node.slice)
                    if knob is not None:
                        self.knobs.setdefault(knob, []).append(
                            (rel, node.lineno)
                        )

    def _extract_metric_constants(self, ctx: FileContext) -> None:
        for node in ast.iter_child_nodes(ctx.tree):
            if not isinstance(node, ast.Assign):
                continue
            if not (
                isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)
                and node.value.value.startswith("rb_tpu_")
            ):
                continue
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id.isupper():
                    self.metric_constants[t.id] = (
                        node.value.value, node.lineno
                    )
                    self.metric_const_uses.setdefault(t.id, set())

    def _record_registration(
        self, rel: str, node: ast.Call, in_registry: bool
    ) -> None:
        """(path, line, kind, name, labels): kind is 'const' (first arg is
        a Name/Attribute — resolved against the constant table when it
        matches), 'literal' (an inline rb_tpu_ string), or 'dynamic'."""
        first = node.args[0]
        labels: Optional[Tuple[str, ...]] = None
        label_arg = node.args[2] if len(node.args) > 2 else None
        for kw in node.keywords:
            if kw.arg in ("labelnames", "labels"):
                label_arg = kw.value
        if isinstance(label_arg, (ast.Tuple, ast.List)):
            if all(
                isinstance(e, ast.Constant) and isinstance(e.value, str)
                for e in label_arg.elts
            ):
                labels = tuple(e.value for e in label_arg.elts)
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            if first.value.startswith("rb_tpu_") and not in_registry:
                self.metric_registrations.append(
                    (rel, node.lineno, "literal", first.value, labels)
                )
        elif isinstance(first, (ast.Name, ast.Attribute)):
            const = terminal_name(first)
            self.metric_registrations.append(
                (rel, node.lineno, "const", const, labels)
            )

    def _extract_authorities(self) -> None:
        """cost/facade.py: every ``class XAuthority(Authority)`` with its
        ``name`` class attr and defined protocol methods, plus whether it
        is instantiated inside the ``AUTHORITIES`` dict literal."""
        ctx = self.file("cost", "facade.py")
        if ctx is None:
            return
        registered_classes: Set[str] = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                if any(
                    isinstance(t, ast.Name) and t.id == "AUTHORITIES"
                    for t in targets
                ) and node.value is not None:
                    for call in ast.walk(node.value):
                        if isinstance(call, ast.Call) and isinstance(
                            call.func, ast.Name
                        ):
                            registered_classes.add(call.func.id)
        for node in ast.iter_child_nodes(ctx.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            bases = {terminal_name(b) for b in node.bases}
            if "Authority" not in bases:
                continue
            name = None
            name_line = node.lineno
            methods: Set[str] = set()
            for item in node.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    methods.add(item.name)
                elif isinstance(item, (ast.Assign, ast.AnnAssign)):
                    targets = (
                        item.targets
                        if isinstance(item, ast.Assign)
                        else [item.target]
                    )
                    if any(
                        isinstance(t, ast.Name) and t.id == "name"
                        for t in targets
                    ) and isinstance(item.value, ast.Constant):
                        name = item.value.value
                        name_line = item.lineno
            if name:
                self.authorities.append(
                    AuthorityInfo(
                        name, node.name, name_line, methods,
                        node.name in registered_classes,
                    )
                )

    def _extract_sentinel_rules(self) -> None:
        """observe/health.py: the ``DEFAULT_RULES`` tuple's ``Rule(...)``
        names, and the committed docstring threshold table's row names —
        the two must agree (sentinel-table-drift)."""
        ctx = self.file("observe", "health.py")
        if ctx is None:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            if not any(
                isinstance(t, ast.Name) and t.id == "DEFAULT_RULES"
                for t in targets
            ):
                continue
            if node.value is None:
                continue
            for call in ast.walk(node.value):
                if not (
                    isinstance(call, ast.Call)
                    and terminal_name(call.func) == "Rule"
                ):
                    continue
                name = _str_arg(call, 0)
                if name is None:
                    for kw in call.keywords:
                        if kw.arg == "name" and isinstance(
                            kw.value, ast.Constant
                        ):
                            name = kw.value.value
                if name:
                    self.sentinel_rules[name] = call.lineno
        doc = ast.get_docstring(ctx.tree, clean=False) or ""
        for off, line in enumerate(doc.splitlines()):
            stripped = line.strip()
            # a table row: a rule-shaped name followed by >=2 spaces of
            # description ("costmodel-drift       geomean ...")
            parts = stripped.split()
            if (
                len(parts) >= 2
                and "  " in stripped
                and _rule_shaped(parts[0])
            ):
                # +2: docstring body starts on the line after the opener
                self.sentinel_doc_rules.setdefault(parts[0], off + 2)


def _rule_shaped(word: str) -> bool:
    return (
        "-" in word
        and word.replace("-", "").isalnum()
        and word == word.lower()
        and not word.startswith("rb")
    )


def _str_arg(call: ast.Call, idx: int) -> Optional[str]:
    if len(call.args) > idx and isinstance(call.args[idx], ast.Constant):
        v = call.args[idx].value
        if isinstance(v, str):
            return v
    return None


def _receiver_terminal(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Attribute):
        return terminal_name(func.value)
    return None


def _rb_knob(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        v = node.value
        if v.startswith("RB_TPU_") and v.replace("_", "").isalnum():
            return v
    return None


def _donate_argnums(fn: ast.AST) -> Optional[Tuple[int, ...]]:
    """Donated positional indices from a ``@functools.partial(jax.jit,
    donate_argnums=(0,))`` / ``@jax.jit(..., donate_argnums=...)``
    decorator, else None."""
    for dec in getattr(fn, "decorator_list", ()):
        if not isinstance(dec, ast.Call):
            continue
        for kw in dec.keywords:
            if kw.arg != "donate_argnums":
                continue
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return (v.value,)
            if isinstance(v, (ast.Tuple, ast.List)):
                idxs = tuple(
                    e.value
                    for e in v.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, int)
                )
                if idxs:
                    return idxs
    return None


# ---------------------------------------------------------------------------
# build cache: (root, package) -> (stamp, ProjectContext)
# ---------------------------------------------------------------------------

_CACHE_LOCK = threading.Lock()
_CACHE: Dict[Tuple[str, str], Tuple[Tuple, ProjectContext]] = {}


def _tree_stamp(root: str, package: str) -> Tuple:
    """(path, mtime_ns, size) for every package .py file — cheap enough
    to recompute per call, and any edit (or add/remove) changes it."""
    out = []
    pkg_dir = os.path.join(root, package)
    for dirpath, dirnames, filenames in os.walk(pkg_dir):
        dirnames[:] = [d for d in dirnames if d not in ("__pycache__", ".git")]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                p = os.path.join(dirpath, fn)
                try:
                    st = os.stat(p)
                    out.append((p, st.st_mtime_ns, st.st_size))
                except OSError:
                    out.append((p, -1, -1))
    return tuple(out)


def get_project(root: str, package: str = "roaringbitmap_tpu") -> ProjectContext:
    """Cached ProjectContext for the tree rooted at ``root``: reused while
    no package file's (mtime, size) changes, rebuilt otherwise. Safe to
    call from concurrent threads — a stale double-build races benignly
    (last writer wins; both are equivalent)."""
    root = os.path.abspath(root)
    key = (root, package)
    stamp = _tree_stamp(root, package)
    with _CACHE_LOCK:
        hit = _CACHE.get(key)
        if hit is not None and hit[0] == stamp:
            return hit[1]
    project = ProjectContext(root, package=package)
    with _CACHE_LOCK:
        _CACHE[key] = (stamp, project)
    return project


def invalidate_cache() -> None:
    with _CACHE_LOCK:
        _CACHE.clear()
