"""Checked-in finding baseline: pre-existing findings don't block CI while
new ones fail it.

The baseline file (ANALYSIS_BASELINE.json at the repo root) records the
fingerprint of every accepted finding (see ``core.fingerprints``: hash of
rule + path + offending line text + occurrence index, so line-number churn
does not invalidate entries). ``scripts/analyze.py --check`` fails on any
finding whose fingerprint is not in the baseline;
``--update-baseline`` rewrites the file from the current tree.

Workflow::

    python scripts/analyze.py --check            # gate (CI)
    python scripts/analyze.py --update-baseline  # accept current findings
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Sequence, Set, Tuple

from .core import Finding, fingerprints

BASELINE_VERSION = 1
DEFAULT_BASELINE_NAME = "ANALYSIS_BASELINE.json"


def load(path: str) -> Set[str]:
    """Fingerprint set from a baseline file; empty when missing."""
    if not os.path.isfile(path):
        return set()
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    if not isinstance(data, dict) or data.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"{path}: not a v{BASELINE_VERSION} analysis baseline "
            f"(got {type(data).__name__})"
        )
    entries = data.get("findings", [])
    out = set()
    for e in entries:
        fp = e.get("fingerprint") if isinstance(e, dict) else None
        if not isinstance(fp, str) or not fp:
            # hand-edits / merge damage surface as the CLI's "bad baseline"
            # path (exit 2), not a KeyError traceback
            raise ValueError(f"{path}: baseline entry without fingerprint: {e!r}")
        out.add(fp)
    return out


def dump(path: str, findings: Sequence[Finding]) -> dict:
    """Write a baseline accepting every current finding; returns the doc."""
    fps = fingerprints(findings)
    doc = {
        "version": BASELINE_VERSION,
        "tool": "scripts/analyze.py",
        "findings": [
            {
                "fingerprint": fp,
                "rule": f.rule,
                "path": f.path,
                "message": f.message,
            }
            for fp, f in sorted(
                zip(fps, findings), key=lambda p: (p[1].path, p[1].line, p[1].rule)
            )
        ],
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=False)
        f.write("\n")
    return doc


def partition(
    findings: Sequence[Finding], baseline_fps: Set[str]
) -> Tuple[List[Finding], List[Finding]]:
    """(new, baselined) split of ``findings`` against the fingerprint set."""
    new: List[Finding] = []
    old: List[Finding] = []
    for f, fp in zip(findings, fingerprints(findings)):
        (old if fp in baseline_fps else new).append(f)
    return new, old
