"""Project-native static analysis (ISSUE 3, whole-program tier ISSUE 18):
machine-checked invariants next to the test matrix, the analogue of the
reference's per-push analysis workflow
(.github/workflows/java-all-versions.yml).

Two tiers:

**Lexical rules** (per file, ``core.CHECKERS``):

* ``dtype-discipline``  — container payloads stay uint16/uint64; signed
  sub-64-bit intermediates on payload paths need a justifying pragma.
* ``trace-safety``      — no Python control flow or host syncs on traced
  values inside jax.jit / Pallas entry points.
* ``lock-discipline``   — state annotated ``# guarded-by: <lock>`` is
  written only inside ``with <lock>:`` — upgraded (ISSUE 18) with
  may-hold-set propagation through intra-module helper calls.
* ``exception-hygiene`` — broad excepts re-raise or carry a pragma.
* ``metric-naming``     — observe/ registrations use ``rb_tpu_`` names
  with declared label sets.

**Contract rules** (whole-program over a :class:`project.ProjectContext`,
``core.CONTRACT_CHECKERS``, ISSUE 18): registry-drift checks —
``fault-site-contract``, ``decision-discipline``, ``authority-surface``,
``metric-discipline``, ``sentinel-table-drift``, ``knob-doc`` — plus
CFG dataflow rules ``use-after-donation`` and ``epoch-pin`` (cfg.py is
the light intra-function CFG + forward may-analysis they share).

CLI: ``python scripts/analyze.py [--check] [--contracts] [--diff REF]
[--json]``; baseline in ANALYSIS_BASELINE.json keeps pre-existing
findings from blocking while new ones fail CI (see baseline.py).
``lockwitness`` is the dynamic complement: a lock-acquisition-order
recorder the thread-hammer tests assert on.

The analysis modules themselves are pure stdlib (ast/tokenize/hashlib);
scripts/analyze.py additionally reports per-rule finding counts into the
observe registry (``rb_tpu_analysis_findings_total`` and
``rb_tpu_analysis_contract_findings_total``) when run in-process.
"""

from .core import (
    CHECKERS,
    CONTRACT_CHECKERS,
    Checker,
    FileContext,
    Finding,
    ProjectChecker,
    RunResult,
    all_contract_rule_ids,
    all_rule_ids,
    fingerprints,
    iter_python_files,
    register,
    register_contract,
    run_checks,
    run_contract_checks,
)
from . import baseline
from . import knobs
from .lockwitness import LockOrderError, LockWitness
from .project import ProjectContext, get_project

__all__ = [
    "CHECKERS",
    "CONTRACT_CHECKERS",
    "Checker",
    "FileContext",
    "Finding",
    "ProjectChecker",
    "ProjectContext",
    "RunResult",
    "all_contract_rule_ids",
    "all_rule_ids",
    "baseline",
    "fingerprints",
    "get_project",
    "iter_python_files",
    "knobs",
    "register",
    "register_contract",
    "run_checks",
    "run_contract_checks",
    "LockOrderError",
    "LockWitness",
]
