"""Project-native static analysis (ISSUE 3): machine-checked invariants
next to the test matrix, the analogue of the reference's per-push analysis
workflow (.github/workflows/java-all-versions.yml).

Five rules (analysis/rules/):

* ``dtype-discipline``  — container payloads stay uint16/uint64; signed
  sub-64-bit intermediates on payload paths need a justifying pragma.
* ``trace-safety``      — no Python control flow or host syncs on traced
  values inside jax.jit / Pallas entry points.
* ``lock-discipline``   — state annotated ``# guarded-by: <lock>`` is
  written only inside ``with <lock>:``.
* ``exception-hygiene`` — broad excepts re-raise or carry a pragma.
* ``metric-naming``     — observe/ registrations use ``rb_tpu_`` names
  with declared label sets.

CLI: ``python scripts/analyze.py [--check] [--json]``; baseline in
ANALYSIS_BASELINE.json keeps pre-existing findings from blocking while new
ones fail CI (see baseline.py). ``lockwitness`` is the dynamic complement:
a lock-acquisition-order recorder the thread-hammer tests assert on.

The analysis modules themselves are pure stdlib (ast/tokenize/hashlib);
scripts/analyze.py additionally reports per-rule finding counts into the
observe registry (``rb_tpu_analysis_findings_total``) when run in-process.
"""

from .core import (
    CHECKERS,
    Checker,
    FileContext,
    Finding,
    RunResult,
    all_rule_ids,
    fingerprints,
    iter_python_files,
    register,
    run_checks,
)
from . import baseline
from .lockwitness import LockOrderError, LockWitness

__all__ = [
    "CHECKERS",
    "Checker",
    "FileContext",
    "Finding",
    "RunResult",
    "all_rule_ids",
    "baseline",
    "fingerprints",
    "iter_python_files",
    "register",
    "run_checks",
    "LockOrderError",
    "LockWitness",
]
