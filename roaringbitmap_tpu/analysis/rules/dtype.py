"""dtype-discipline: container payloads are uint16/uint64 — signed-narrow
intermediates must be explicitly justified.

The roaring invariant (Lemire et al., arXiv:1709.07821) is that container
payloads are unsigned: uint16 values/runs, uint64 words, uint32 universe
points. numpy happily promotes through signed int32 (``astype``, ``dtype=``
kwargs, ``np.int32(...)`` casts), which is lossy for uint32-scale data
(values >= 2^31 wrap negative) and a silent-corruption hazard when a
payload round-trips through such an intermediate. ``int64`` is the blessed
widening type — it holds every uint16/uint32 payload exactly — so this rule
flags only signed types *narrower than 64 bits* (int8/int16/int32/intc/
short/byte) plus the platform-width builtins (``dtype=int`` / ``astype(int)``
/ ``np.int_``), on container payload paths.

Scope: files ending in ``utils/bits.py`` / ``models/container.py`` /
``models/bitset.py``, plus any file carrying a ``# rb-payload-path``
directive. Bounded intermediates (e.g. the ±(2^16+1) cumsum in
words_from_intervals) are annotated ``# rb-ok: dtype-discipline <bound>``.
"""

from __future__ import annotations

import ast
from typing import Iterable

from ..core import Checker, FileContext, Finding, dotted_name, register

PAYLOAD_PATH_SUFFIXES = (
    "utils/bits.py",
    "models/container.py",
    "models/bitset.py",
)

# signed dtypes that cannot hold the full uint32 payload range
_NARROW_SIGNED = {
    "int8", "int16", "int32", "intc", "short", "byte", "int_", "intp",
}
_PLATFORM_INT = {"int"}  # bare builtin: width is platform-defined


def _dtype_token(node: ast.AST):
    """The signed-dtype identifier named by an expression, or None.

    Matches ``np.int32`` / ``numpy.int32`` / bare ``int32`` / ``int`` /
    string literals ``"int32"`` / ``"i4"``.
    """
    name = dotted_name(node)
    if name is not None:
        tail = name.rsplit(".", 1)[-1]
        if tail in _NARROW_SIGNED or tail in _PLATFORM_INT:
            return tail
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        v = node.value.lower()
        if v in _NARROW_SIGNED or v in _PLATFORM_INT:
            return v
        if v in ("i1", "i2", "i4", "<i4", ">i4", "=i4"):
            return v
    return None


@register
class DtypeDiscipline(Checker):
    rule_id = "dtype-discipline"
    description = (
        "container payload paths must stay uint16/uint64 (int64 widening "
        "allowed); signed-narrow casts need a justifying pragma"
    )
    severity = "error"

    def _applies(self, ctx: FileContext) -> bool:
        rel = ctx.relpath.replace("\\", "/")
        return rel.endswith(PAYLOAD_PATH_SUFFIXES) or ctx.has_directive(
            "payload-path"
        )

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if not self._applies(ctx):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = dotted_name(node.func)
            # x.astype(np.int32) / x.astype("int32") / x.astype(dtype=int)
            if isinstance(node.func, ast.Attribute) and node.func.attr == "astype":
                dtype_args = list(node.args[:1]) + [
                    kw.value for kw in node.keywords if kw.arg == "dtype"
                ]
                for arg in dtype_args:
                    tok = _dtype_token(arg)
                    if tok:
                        yield self.finding(
                            ctx,
                            node,
                            f"astype({tok}) on a container payload path: "
                            f"signed-narrow intermediate can wrap uint payloads"
                            f" — widen to int64/uint or justify with "
                            f"`# rb-ok: {self.rule_id} <bound>`",
                        )
                continue
            # np.int32(x) direct casts — bare `int32(x)` (from-import) too
            if fname is not None:
                tail = fname.rsplit(".", 1)[-1]
                if tail in _NARROW_SIGNED:
                    yield self.finding(
                        ctx,
                        node,
                        f"{fname}(...) cast on a container payload path: "
                        f"use uint/int64 or justify with a pragma",
                    )
                    continue
            # dtype=np.int32 keyword on any call (np.cumsum, np.zeros, ...)
            for kw in node.keywords:
                if kw.arg == "dtype":
                    tok = _dtype_token(kw.value)
                    if tok:
                        yield self.finding(
                            ctx,
                            node,
                            f"dtype={tok} on a container payload path: "
                            f"signed-narrow accumulator can wrap uint payloads"
                            f" — widen to int64/uint or justify with "
                            f"`# rb-ok: {self.rule_id} <bound>`",
                        )
