"""The project-native rules, two tiers. Importing this package registers
every checker: lexical rules in ``core.CHECKERS`` (one file at a time),
contract/dataflow rules in ``core.CONTRACT_CHECKERS`` (whole-program,
over a ProjectContext — ISSUE 18). Add a module here (with ``@register``
or ``@register_contract``) to grow either set."""

# lexical tier (per-file)
from . import dtype  # noqa: F401
from . import exceptions  # noqa: F401
from . import locks  # noqa: F401
from . import metrics  # noqa: F401
from . import trace_safety  # noqa: F401

# contract tier (whole-program, ISSUE 18)
from . import decision_contract  # noqa: F401
from . import donation  # noqa: F401
from . import epochpin  # noqa: F401
from . import fault_contract  # noqa: F401
from . import registry_contracts  # noqa: F401
