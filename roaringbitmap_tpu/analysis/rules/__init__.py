"""The five project-native rules. Importing this package registers every
checker in ``core.CHECKERS``; add a module here (with ``@register``) to
grow the rule set."""

from . import dtype  # noqa: F401
from . import exceptions  # noqa: F401
from . import locks  # noqa: F401
from . import metrics  # noqa: F401
from . import trace_safety  # noqa: F401
