"""exception-hygiene: broad catches are justified or they are bugs.

A bare ``except:`` or ``except Exception`` that swallows is how kernel
dispatch bugs hide — the Pallas probe path *deliberately* catches
everything (a Mosaic lowering error must degrade to XLA, not crash the
aggregation), but that judgement belongs in the source, not a reviewer's
memory. The rule:

* ``except:`` / ``except Exception`` / ``except BaseException`` (alone or
  in a tuple) requires ``# rb-ok: exception-hygiene <why>`` on the line
  (or the comment line above);
* a handler with a top-level ``raise`` is exempt — re-wrapping into a
  domain error (fuzz.InvarianceFailure) or cleanup-then-reraise
  (observe/export._atomic_write) is not a swallow;
* narrow catches (``except (ImportError, RuntimeError)``) never need a
  pragma — prefer narrowing where the error taxonomy is stable.
"""

from __future__ import annotations

import ast
from typing import Iterable

from ..core import Checker, FileContext, Finding, dotted_name, register

_BROAD = {"Exception", "BaseException"}


def _is_broad(type_node) -> bool:
    if type_node is None:
        return True  # bare except
    if isinstance(type_node, ast.Tuple):
        return any(_is_broad(el) for el in type_node.elts)
    name = dotted_name(type_node)
    return name is not None and name.rsplit(".", 1)[-1] in _BROAD


def _reraises(handler: ast.ExceptHandler) -> bool:
    # a top-level raise anywhere in the handler: both immediate re-wraps
    # (`raise Domain(...) from e`) and cleanup-then-`raise` are not swallows
    return any(isinstance(stmt, ast.Raise) for stmt in handler.body)


@register
class ExceptionHygiene(Checker):
    rule_id = "exception-hygiene"
    description = (
        "bare/broad `except Exception` must re-raise or carry a "
        "justifying `# rb-ok: exception-hygiene` pragma"
    )
    severity = "error"

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad(node.type):
                continue
            if _reraises(node):
                continue
            what = (
                "bare except"
                if node.type is None
                else f"except {ast.unparse(node.type)}"
            )
            yield self.finding(
                ctx,
                node,
                f"{what} swallows unexpected failures: narrow the type, "
                f"re-raise, or justify with "
                f"`# rb-ok: {self.rule_id} <why degrading is safe>`",
            )
