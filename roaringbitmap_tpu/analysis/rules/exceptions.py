"""exception-hygiene: broad catches are justified or they are bugs.

A bare ``except:`` or ``except Exception`` that swallows is how kernel
dispatch bugs hide — the Pallas probe path *deliberately* catches
everything (a Mosaic lowering error must degrade to XLA, not crash the
aggregation), but that judgement belongs in the source, not a reviewer's
memory. The rule:

* ``except:`` / ``except Exception`` / ``except BaseException`` (alone or
  in a tuple) requires ``# rb-ok: exception-hygiene <why>`` on the line
  (or the comment line above);
* a handler with a top-level ``raise`` is exempt — re-wrapping into a
  domain error (fuzz.InvarianceFailure) or cleanup-then-reraise
  (observe/export._atomic_write) is not a swallow;
* a **classify-then-route** handler is exempt: one that calls the fault
  taxonomy's ``classify(...)`` (robust/errors.py) AND contains a
  ``raise`` anywhere (the ``if classify(e) == FATAL: raise`` idiom) —
  this is the ladder's declared degradation contract, ISSUE 7;
* narrow catches (``except (ImportError, RuntimeError)``) never need a
  pragma — prefer narrowing where the error taxonomy is stable.

**Fault-site strictness** (ISSUE 7 satellite): inside a function that
contains a registered fault site (a ``fault_point(...)`` call), a raw
``except Exception`` must be the classify-then-route idiom or a top-level
re-raise — a pragma is NOT accepted there. Fault sites are exactly where
injected (and real) failures surface; a swallowing handler on such a path
would make the chaos gate's "no exception escapes the ladder" guarantee
vacuous by eating the evidence.
"""

from __future__ import annotations

import ast
from typing import Iterable

from ..core import Checker, FileContext, Finding, dotted_name, register

_BROAD = {"Exception", "BaseException"}


def _is_broad(type_node) -> bool:
    if type_node is None:
        return True  # bare except
    if isinstance(type_node, ast.Tuple):
        return any(_is_broad(el) for el in type_node.elts)
    name = dotted_name(type_node)
    return name is not None and name.rsplit(".", 1)[-1] in _BROAD


def _reraises(handler: ast.ExceptHandler) -> bool:
    # a top-level raise anywhere in the handler: both immediate re-wraps
    # (`raise Domain(...) from e`) and cleanup-then-`raise` are not swallows
    return any(isinstance(stmt, ast.Raise) for stmt in handler.body)


def _calls_named(node: ast.AST, tail: str) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            name = dotted_name(sub.func)
            if name is not None and name.rsplit(".", 1)[-1] == tail:
                return True
    return False


def _classify_routes(handler: ast.ExceptHandler) -> bool:
    """The ladder's classify-then-route idiom: the handler consults the
    fault taxonomy (``classify(...)``) and keeps a re-raise path for fatal
    classifications (a ``raise`` anywhere, including nested under the
    ``if classify(e) == FATAL`` test)."""
    if not _calls_named(handler, "classify"):
        return False
    return any(isinstance(sub, ast.Raise) for sub in ast.walk(handler))


def _fault_site_functions(tree: ast.AST):
    """Line spans of every function whose body contains a registered
    fault site (a ``fault_point(...)`` call)."""
    spans = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if _calls_named(node, "fault_point"):
                spans.append((node.lineno, node.end_lineno or node.lineno))
    return spans


@register
class ExceptionHygiene(Checker):
    rule_id = "exception-hygiene"
    description = (
        "bare/broad `except Exception` must re-raise, classify-then-route, "
        "or carry a justifying `# rb-ok: exception-hygiene` pragma "
        "(pragmas are not accepted on fault-site paths)"
    )
    severity = "error"

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        fault_spans = _fault_site_functions(ctx.tree)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad(node.type):
                continue
            if _reraises(node) or _classify_routes(node):
                continue
            what = (
                "bare except"
                if node.type is None
                else f"except {ast.unparse(node.type)}"
            )
            on_fault_site = any(
                lo <= node.lineno <= hi for lo, hi in fault_spans
            )
            if on_fault_site:
                # pragma-proof: yield with suppress_pragma so core's rb-ok
                # handling cannot waive it (see Checker.finding)
                yield self.finding(
                    ctx,
                    node,
                    f"{what} swallows failures inside a fault-site function "
                    f"(a fault_point() call is in scope): route through the "
                    f"taxonomy — `if classify(e) == FATAL: raise` — or "
                    f"re-raise; pragmas are not accepted on fault-site paths",
                    suppress_pragma=True,
                )
                continue
            yield self.finding(
                ctx,
                node,
                f"{what} swallows unexpected failures: narrow the type, "
                f"re-raise, classify-then-route, or justify with "
                f"`# rb-ok: {self.rule_id} <why degrading is safe>`",
            )
