"""trace-safety: no host sync or Python control flow on traced values
inside jit/Pallas entry points.

Inside a function staged by ``jax.jit`` (or handed to ``pl.pallas_call``),
Python ``if``/``while``/``int()``/``float()``/``bool()`` on a traced value
raises a ConcretizationTypeError at best and silently forces a device→host
sync at worst; ``.item()``/``.tolist()``/``np.asarray``/``jax.device_get``
are unconditional syncs. The reference library has no analogue (the JVM has
no tracing); for this port the invariant is load-bearing — every hot
aggregation routes through jitted entry points in ops/ and parallel/.

Detection, per module:

* traced entry points: ``def`` decorated with ``jax.jit`` / ``jit`` /
  ``[functools.]partial(jax.jit, ...)``, functions wrapped as
  ``jax.jit(f)``, and kernels passed to ``[pl.]pallas_call(f, ...)``.
* static arguments (``static_argnames=`` / ``static_argnums=`` literals)
  are exempt — Python control flow on them is resolved at trace time.
* one-level closure: module-local functions *called from* a traced body
  are checked for the unconditional syncs only (``.item``/``.tolist``/
  ``jax.device_get``/``block_until_ready``) — their parameters' tracedness
  is unknown, so value-flow checks stay at the entry point.
* ``np.array``/``np.asarray`` are flagged only when fed a traced value —
  building a trace-time constant table inside a jitted function is fine.

Shape access is static under trace: expressions reaching a traced name
only through ``.shape``/``.ndim``/``.size``/``.dtype``/``len()`` are fine.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..core import Checker, FileContext, Finding, dotted_name, register

_SYNC_ATTRS = {"item", "tolist", "block_until_ready"}
# unconditional host syncs: flagged wherever they appear in traced code
# (dotted or bare from-import spelling — the names are distinctive)
_SYNC_CALLS = {
    "jax.device_get",
    "jax.block_until_ready",
    "device_get",
    "block_until_ready",
}
# host materializers: legitimate on trace-time constants (np.array lookup
# tables), a sync only when fed a traced value — gated on taint
_MATERIALIZERS = {
    "np.asarray",
    "np.array",
    "np.ascontiguousarray",
    "numpy.asarray",
    "numpy.array",
    "numpy.ascontiguousarray",
}
_CONCRETIZERS = {"int", "float", "bool"}
_STATIC_ATTRS = {"shape", "ndim", "size", "dtype"}


def _is_jit_expr(node: ast.AST) -> bool:
    """True for ``jax.jit`` / ``jit`` references."""
    name = dotted_name(node)
    return name in ("jax.jit", "jit")


def _static_names_from_call(call: ast.Call, params: List[str]) -> Set[str]:
    """Literal static_argnames/static_argnums from a jit(...) call."""
    statics: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                statics.add(v.value)
            elif isinstance(v, (ast.Tuple, ast.List)):
                for el in v.elts:
                    if isinstance(el, ast.Constant) and isinstance(el.value, str):
                        statics.add(el.value)
        elif kw.arg == "static_argnums":
            nums: List[int] = []
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                nums = [v.value]
            elif isinstance(v, (ast.Tuple, ast.List)):
                nums = [
                    el.value
                    for el in v.elts
                    if isinstance(el, ast.Constant) and isinstance(el.value, int)
                ]
            for n in nums:
                if 0 <= n < len(params):
                    statics.add(params[n])
    return statics


def _jit_decoration(fn: ast.FunctionDef, params: List[str]) -> Optional[Set[str]]:
    """Static-param set if ``fn`` is jit-decorated, else None."""
    for dec in fn.decorator_list:
        if _is_jit_expr(dec):
            return set()
        if isinstance(dec, ast.Call):
            dn = dotted_name(dec.func)
            if dn in ("functools.partial", "partial") and dec.args:
                if _is_jit_expr(dec.args[0]):
                    return _static_names_from_call(dec, params)
            elif _is_jit_expr(dec.func):
                return _static_names_from_call(dec, params)
    return None


def _param_names(fn: ast.FunctionDef) -> List[str]:
    """Positional params in order, then keyword-only (jit traces kwonly
    arguments too; only the positional prefix matters for static_argnums)."""
    a = fn.args
    return [x.arg for x in a.posonlyargs + a.args + a.kwonlyargs]


def _taints(node: ast.AST, traced: Set[str]) -> bool:
    """True when the expression can reach a traced name as a *value* —
    access through .shape/.ndim/.size/.dtype or len() is static."""
    if isinstance(node, ast.Attribute) and node.attr in _STATIC_ATTRS:
        return False
    if isinstance(node, ast.Compare) and all(
        isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops
    ):
        # `x is None` is a pytree-structure check, resolved at trace time
        return False
    if isinstance(node, ast.Call):
        fname = dotted_name(node.func)
        if fname == "len":
            return False
        if isinstance(node.func, ast.Attribute):
            # x.get_cardinality() etc: recurse into the receiver + args
            return any(_taints(c, traced) for c in [node.func.value, *node.args])
    if isinstance(node, ast.Name):
        return node.id in traced
    return any(_taints(c, traced) for c in ast.iter_child_nodes(node))


@register
class TraceSafety(Checker):
    rule_id = "trace-safety"
    description = (
        "no Python control flow / host syncs on traced values inside "
        "jax.jit or Pallas entry points"
    )
    severity = "error"

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        # --- collect traced functions -----------------------------------
        # name -> (FunctionDef, static param names); funcs wrapped by call
        # sites (jax.jit(f), pallas_call(f)) have no static info -> set()
        defs: Dict[int, Tuple[ast.FunctionDef, Set[str]]] = {}
        by_name: Dict[str, ast.FunctionDef] = {}
        wrapped: Dict[str, ast.Call] = {}  # fn name -> wrapping jit/pallas call
        factories: Set[str] = set()  # kernel factories / transformed fns

        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.FunctionDef):
                by_name.setdefault(node.name, node)
                statics = _jit_decoration(node, _param_names(node))
                if statics is not None:
                    defs[id(node)] = (node, statics)
            elif isinstance(node, ast.Call):
                fname = dotted_name(node.func)
                is_wrapper = fname in ("jax.jit", "jit") or (
                    fname is not None
                    and fname.rsplit(".", 1)[-1] == "pallas_call"
                )
                if is_wrapper and node.args:
                    tgt = node.args[0]
                    if isinstance(tgt, ast.Name):
                        wrapped.setdefault(tgt.id, node)
                    else:
                        # pallas_call(_make_kernel(fn, ...)) / jit(vmap(f)):
                        # the staged callable comes out of a factory or
                        # transform — every module-local name reachable in
                        # that expression (the factory, whose body holds the
                        # kernel closure, and any function arguments) gets
                        # the definite-sync closure checks
                        for sub in ast.walk(tgt):
                            if isinstance(sub, ast.Name):
                                factories.add(sub.id)
        for name, call in wrapped.items():
            fn = by_name.get(name)
            if fn is not None and id(fn) not in defs:
                # jax.jit(f, static_argnames=...) carries statics at the
                # call site, same as the decorator form
                defs[id(fn)] = (fn, _static_names_from_call(call, _param_names(fn)))

        # --- check each traced body -------------------------------------
        called: Set[str] = set()
        for fn, statics in defs.values():
            params = [p for p in _param_names(fn) if p not in ("self", "cls")]
            traced = {p for p in params if p not in statics}
            yield from self._check_body(ctx, fn, traced, entry=True)
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
                    called.add(node.func.id)

        # --- one-level closure: definite syncs only ---------------------
        for name in called | factories:
            fn = by_name.get(name)
            if fn is not None and id(fn) not in defs:
                yield from self._check_body(ctx, fn, set(), entry=False)

    def _check_body(
        self, ctx: FileContext, fn: ast.FunctionDef, traced: Set[str], entry: bool
    ) -> Iterable[Finding]:
        where = "jit/Pallas entry point" if entry else "function called from a traced body"
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                fname = dotted_name(node.func)
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in _SYNC_ATTRS
                ):
                    yield self.finding(
                        ctx,
                        node,
                        f".{node.func.attr}() inside a {where}: "
                        f"device→host sync under trace",
                    )
                elif fname in _SYNC_CALLS:
                    yield self.finding(
                        ctx,
                        node,
                        f"{fname}(...) inside a {where}: device→host sync",
                    )
                elif (
                    entry
                    and fname in _MATERIALIZERS
                    and any(_taints(a, traced) for a in node.args)
                ):
                    yield self.finding(
                        ctx,
                        node,
                        f"{fname}(...) on a traced value inside a jit entry "
                        f"point: materializes the tracer on host",
                    )
                elif (
                    entry
                    and fname in _CONCRETIZERS
                    and node.args
                    and _taints(node.args[0], traced)
                ):
                    yield self.finding(
                        ctx,
                        node,
                        f"{fname}() on traced value inside a jit entry point: "
                        f"concretizes the tracer (host sync / trace error)",
                    )
            elif entry and isinstance(node, (ast.If, ast.While)):
                if _taints(node.test, traced):
                    kind = "if" if isinstance(node, ast.If) else "while"
                    yield self.finding(
                        ctx,
                        node,
                        f"Python `{kind}` on traced value inside a jit entry "
                        f"point: use lax.cond/select or mark the argument "
                        f"static",
                    )
            elif entry and isinstance(node, ast.For):
                if _taints(node.iter, traced):
                    yield self.finding(
                        ctx,
                        node,
                        "Python `for` over a traced value inside a jit entry "
                        "point: use lax.fori_loop/scan",
                    )
