"""fault-site-contract: every declared fault site is real, and every
fault-site string in the pipeline is declared (ISSUE 18).

``robust/faults.SITES`` is the fault-injection framework's registry; a
site that exists only in the tuple is theater, and a ``fault_point()``
call on an undeclared site raises at runtime — on the first hit, which a
green test run may never produce. The contract, per declared site:

1. **guard**   — at least one ``fault_point("<site>")`` call in package
   source (outside robust/faults.py itself);
2. **route**   — a ladder/degradation path mentioning the site:
   ``LADDER.run(site, ...)``, ``LADDER.note_degrade(site, ...)``, or
   ``ladder.retry(site, ...)``. Sites whose failures deliberately ride a
   *different* site's route carry a justified ``# rb-ok:
   fault-site-contract`` pragma on their SITES entry line;
3. **exercise** — the site string appears in the exercise surface (the
   fuzz harness, tests/, or scripts/ci.sh — the ci-chaos schedule
   ``RB_TPU_FAULTS=ci-chaos-seed`` arms every site it lists).

And the reverse direction: every ``fault_point("<literal>")`` in package
source must name a declared site. Findings for legs 1–3 anchor on the
site's own line in the SITES tuple; reverse findings anchor on the
offending call.
"""

from __future__ import annotations

from typing import Iterable

from ..core import Finding, ProjectChecker, register_contract
from ..project import FAULTS_MODULE, ProjectContext


@register_contract
class FaultSiteContract(ProjectChecker):
    rule_id = "fault-site-contract"
    description = (
        "every robust/faults.SITES entry needs a fault_point guard, a "
        "ladder route, and a fuzz/ci exercise; every fault_point literal "
        "must be declared"
    )
    severity = "error"

    def check_project(self, project: ProjectContext) -> Iterable[Finding]:
        faults_rel = project.pkg_path("robust", "faults.py")
        if not project.fault_sites:
            ctx = project.file("robust", "faults.py")
            if ctx is not None:
                yield self.finding(
                    project, faults_rel, 1,
                    "could not extract the SITES tuple — the fault-site "
                    "contract has no registry to check",
                )
            return
        route_sites = set(project.ladder_routes)
        exercise = project.exercise_text()
        for site, line in sorted(project.fault_sites.items()):
            guards = [
                (p, ln)
                for p, ln in project.fault_guards.get(site, ())
                if p != faults_rel
            ]
            if not guards:
                yield self.finding(
                    project, faults_rel, line,
                    f"declared fault site {site!r} has no "
                    f"fault_point({site!r}) guard anywhere in the package "
                    "— the site can never fire",
                )
            if site not in route_sites:
                yield self.finding(
                    project, faults_rel, line,
                    f"declared fault site {site!r} has no ladder route "
                    "(LADDER.run / note_degrade / retry with this site) — "
                    "an injected fault here has no degradation story; if "
                    "it deliberately rides another site's route, waive "
                    "with a justified pragma",
                )
            if f'"{site}"' not in exercise and f"'{site}'" not in exercise:
                yield self.finding(
                    project, faults_rel, line,
                    f"declared fault site {site!r} is never exercised "
                    "(no mention in fuzz.py, tests/, or scripts/ci.sh)",
                )
        for site, uses in sorted(project.fault_guards.items()):
            if site in project.fault_sites:
                continue
            for path, line in uses:
                yield self.finding(
                    project, path, line,
                    f"fault_point({site!r}) names an undeclared site "
                    "(not in robust/faults.SITES) — it will raise "
                    "ValueError on its first armed hit",
                )
