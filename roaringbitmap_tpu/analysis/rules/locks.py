"""lock-discipline: state annotated ``# guarded-by: <lock>`` is written
only inside a ``with <lock>:`` block (a GuardedBy-style lexical pass).

Declaration::

    _TIMINGS: Dict[str, list] = defaultdict(...)  # guarded-by: _TIMINGS_LOCK
    self._entries = OrderedDict()                 # guarded-by: self._lock
    _POOL: Optional[...] = None                   # guarded-by: _POOL_LOCK

The annotation attaches to the assignment target(s) on that line. The rule
then requires every *write* to the guarded name — assignment, augmented
assignment, subscript store, ``del``, or a call to a known mutator method
(``append``/``pop``/``clear``/``update``/``move_to_end``/...) — to sit
lexically inside a ``with`` statement whose context expression's terminal
segment matches the declared lock name (``with self._lock:``,
``with cls._POOL_LOCK:``, ...).

Exemptions (single-threaded by construction):

* the declaring line itself and module-level / class-body assignments
  (import time);
* any write inside ``__init__``/``__new__`` for instance attributes
  (construction happens-before publication);
* a bare-name assignment to a guarded module global in a function with no
  ``global`` declaration (it creates a shadowing local, not a write —
  subscript stores and mutator calls count regardless, since they mutate
  the shared object through the name).

Reads are deliberately out of scope: the codebase uses double-checked
locking (native/__init__.py) and lock-free snapshots-by-copy, which a read
check would flag wholesale. Aliasing (``st = self._series[k]; st[...] = v``)
is also out of scope — keep mutations syntactically on the guarded name.

**May-hold propagation (ISSUE 18 upgrade).** A helper that writes guarded
state is legal when *every* intra-module call site holds the lock — the
classic locked-region-helper pattern that previously needed a pragma.
The rule now computes, per function, the greatest-fixpoint intersection
of the lock sets held at its call sites (``entry ⊇ ∩ site-locks ∪
caller-entry``), and a write passes when the lock is held lexically OR
at every entry. The propagation is sound in the removing direction only:
a function whose reference escapes as a value (callback, decorator,
multiple same-named defs) gets the empty entry set, so it behaves
exactly like the lexical rule.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..core import (
    Checker,
    FileContext,
    Finding,
    ParentedVisit,
    register,
    terminal_name,
)

_MUTATORS = {
    "append", "extend", "insert", "add", "discard", "remove", "pop",
    "popitem", "clear", "update", "setdefault", "move_to_end", "appendleft",
    "popleft", "sort", "reverse",
}
_INIT_METHODS = ("__init__", "__new__")


def _guarded_targets_on_line(
    tree: ast.AST, line: int
) -> List[Tuple[str, str]]:
    """[(kind, name)] declared by the statement at ``line``; kind is
    'global' (module-level name), 'classattr' (class-body name, written
    later as Cls.X/cls.X/self.X), or 'attr' (self./cls. attribute)."""
    out: List[Tuple[str, str]] = []

    def scan(node: ast.AST, in_class: bool) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                if child.lineno == line:
                    targets = (
                        child.targets
                        if isinstance(child, ast.Assign)
                        else [child.target]
                    )
                    for t in targets:
                        if isinstance(t, ast.Name):
                            out.append(
                                ("classattr" if in_class else "global", t.id)
                            )
                        elif isinstance(t, ast.Attribute) and isinstance(
                            t.value, ast.Name
                        ):
                            if t.value.id in ("self", "cls"):
                                out.append(("attr", t.attr))
                            else:
                                out.append(("classattr", t.attr))
            scan(child, in_class or isinstance(child, ast.ClassDef))

    scan(tree, False)
    return out


def _write_targets(node: ast.AST) -> List[ast.AST]:
    if isinstance(node, ast.Assign):
        return list(node.targets)
    if isinstance(node, ast.AugAssign):
        return [node.target]
    if isinstance(node, ast.AnnAssign):
        return [node.target] if node.value is not None else []
    if isinstance(node, ast.Delete):
        return list(node.targets)
    return []


def _match_write(
    target: ast.AST, kind: str, name: str, has_global_decl: bool
) -> bool:
    """Does this assignment target write the guarded entity?

    A bare-Name assignment to a guarded module global only counts when the
    enclosing function declares ``global <name>`` — otherwise it creates a
    shadowing local, not a write to the shared state. Subscript stores
    (``G[k] = v``) mutate the shared object regardless of scoping.
    """
    was_subscript = isinstance(target, ast.Subscript)
    while isinstance(target, ast.Subscript):
        target = target.value
    if kind == "global":
        if isinstance(target, ast.Name) and target.id == name:
            return was_subscript or has_global_decl
        return False
    if kind == "classattr":
        # written as Cls.X / cls.X / self.X (a bare name inside a function
        # is a local; the class body itself is import-time and exempt)
        return (
            isinstance(target, ast.Attribute)
            and target.attr == name
            and isinstance(target.value, ast.Name)
        )
    # kind == "attr": self.X / cls.X
    return (
        isinstance(target, ast.Attribute)
        and target.attr == name
        and isinstance(target.value, ast.Name)
        and target.value.id in ("self", "cls")
    )


def _match_mutator_call(node: ast.Call, kind: str, name: str) -> bool:
    """G.append(...) / self.X.update(...) style mutation of the guarded name."""
    if not (
        isinstance(node.func, ast.Attribute) and node.func.attr in _MUTATORS
    ):
        return False
    recv = node.func.value
    while isinstance(recv, ast.Subscript):
        recv = recv.value
    if isinstance(recv, ast.Name):
        # mutation through a bare name reaches the module global whether or
        # not `global` is declared (no rebind involved)
        return kind == "global" and recv.id == name
    if isinstance(recv, ast.Attribute):
        if recv.attr != name:
            return False
        if kind == "attr":
            return isinstance(recv.value, ast.Name) and recv.value.id in (
                "self",
                "cls",
            )
        # classattr: Cls.X.mutator(...) / cls.X.mutator(...) / self.X...
        return kind == "classattr" and isinstance(recv.value, ast.Name)
    return False


def _own_scope_nodes(fn: ast.AST):
    """Nodes in ``fn``'s own scope (nested function/class bodies excluded)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)
        ):
            continue  # new scope boundary
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _scope_info(fn: ast.AST) -> Tuple[Set[str], Set[str]]:
    """(global_decls, local_rebinds) for ``fn``'s own scope: the ``global``
    names it declares, and the bare names it assigns (which — absent a
    ``global`` declaration — are shadowing locals, so subscript stores and
    mutator calls through them never touch the module state)."""
    global_decls: Set[str] = set()
    rebinds: Set[str] = set()
    for node in _own_scope_nodes(fn):
        if isinstance(node, ast.Global):
            global_decls.update(node.names)
        elif isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            for t in targets:
                if isinstance(t, ast.Name):
                    rebinds.add(t.id)
    return global_decls, rebinds - global_decls


def _may_hold_entries(tree: ast.AST, universe: Set[str]) -> Dict[int, Set[str]]:
    """id(function node) -> locks held at EVERY intra-module call site
    (greatest-fixpoint intersection). Functions that escape as values
    (callbacks, decorators), share a name with another def, or have no
    visible call site get ∅ — the propagation only ever removes findings
    relative to the lexical rule.

    A call site counts when it is a bare ``helper(...)`` or a
    ``self._helper(...)`` / ``cls._helper(...)`` method call — the
    intra-module shapes. Anything else (``module.fn(...)``) may target a
    different module's name and is ignored."""
    defs: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, []).append(node)
    # unique, undecorated defs are propagation candidates
    candidates = {
        name: nodes[0]
        for name, nodes in defs.items()
        if len(nodes) == 1 and not nodes[0].decorator_list
    }
    escaped: Set[str] = set()
    # (callee name) -> [(caller node or None, lexical locks at site)]
    sites: Dict[str, List[Tuple[Optional[ast.AST], Tuple[str, ...]]]] = {}
    call_funcs = set()
    for node, locks, funcs in ParentedVisit(tree):
        if isinstance(node, ast.Call):
            call_funcs.add(id(node.func))
            name = None
            if isinstance(node.func, ast.Name):
                name = node.func.id
            elif isinstance(node.func, ast.Attribute) and isinstance(
                node.func.value, ast.Name
            ) and node.func.value.id in ("self", "cls"):
                name = node.func.attr
            if name in candidates:
                sites.setdefault(name, []).append(
                    (funcs[-1] if funcs else None, locks)
                )
    for node, _locks, _funcs in ParentedVisit(tree):
        # a bare reference to a candidate outside call position = escape
        if (
            isinstance(node, ast.Name)
            and isinstance(node.ctx, ast.Load)
            and node.id in candidates
            and id(node) not in call_funcs
        ):
            escaped.add(node.id)
        elif (
            isinstance(node, ast.Attribute)
            and isinstance(node.ctx, ast.Load)
            and node.attr in candidates
            and id(node) not in call_funcs
            and isinstance(node.value, ast.Name)
            and node.value.id in ("self", "cls")
        ):
            escaped.add(node.attr)
    entry: Dict[int, Set[str]] = {}
    for name, fn in candidates.items():
        if name in escaped or name not in sites:
            entry[id(fn)] = set()
        else:
            entry[id(fn)] = set(universe)
    changed = True
    while changed:
        changed = False
        for name, fn in candidates.items():
            if name in escaped or name not in sites:
                continue
            acc: Optional[Set[str]] = None
            for caller, locks in sites[name]:
                held = set(locks)
                if caller is not None:
                    held |= entry.get(id(caller), set())
                acc = held if acc is None else (acc & held)
            acc = acc or set()
            if acc != entry[id(fn)]:
                entry[id(fn)] = acc
                changed = True
    return entry


@register
class LockDiscipline(Checker):
    rule_id = "lock-discipline"
    description = (
        "writes to `# guarded-by: <lock>` state must sit inside a "
        "`with <lock>:` block"
    )
    severity = "error"

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if not ctx.guards:
            return
        # (kind, name) -> (lock terminal name, declaring line)
        guarded: Dict[Tuple[str, str], Tuple[str, int]] = {}
        for line, lock in ctx.guards.items():
            for kind, name in _guarded_targets_on_line(ctx.tree, line):
                guarded[(kind, name)] = (lock, line)
        if not guarded:
            return

        universe = {lock for lock, _decl in guarded.values()}
        entry_holds = _may_hold_entries(ctx.tree, universe)
        decl_cache: Dict[int, Tuple[Set[str], Set[str]]] = {}
        for node, locks, funcs in ParentedVisit(ctx.tree):
            if not funcs:
                continue  # module/class level runs at import time
            in_init = any(f.name in _INIT_METHODS for f in funcs)
            fid = id(funcs[-1])
            info = decl_cache.get(fid)
            if info is None:
                info = decl_cache[fid] = _scope_info(funcs[-1])
            global_decls, local_rebinds = info
            writes: List[Tuple[str, str, str, int]] = []
            for t in _write_targets(node):
                for (kind, name), (lock, decl) in guarded.items():
                    if decl == node.lineno:
                        continue  # the declaration itself
                    if kind == "global" and name in local_rebinds:
                        continue  # operates on the shadowing local
                    if _match_write(t, kind, name, name in global_decls):
                        writes.append((kind, name, lock, decl))
            if isinstance(node, ast.Call):
                for (kind, name), (lock, decl) in guarded.items():
                    if kind == "global" and name in local_rebinds:
                        continue
                    if _match_mutator_call(node, kind, name):
                        writes.append((kind, name, lock, decl))
            for kind, name, lock, decl in writes:
                if kind == "attr" and in_init:
                    continue  # construction happens-before publication
                if lock in locks:
                    continue
                # may-hold propagation: every intra-module call site of
                # the enclosing helper holds the lock
                if lock in entry_holds.get(id(funcs[-1]), ()):
                    continue
                yield self.finding(
                    ctx,
                    node,
                    f"write to `{name}` (guarded-by {lock}, declared "
                    f"line {decl}) outside `with {lock}:`",
                )
