"""use-after-donation: a buffer passed to a ``donate_argnums`` jit is
dead after the call — reading the variable again without re-binding is
the PR 8 aliasing bug the runtime only catches with a deleted-buffer
error, and only when the race actually lands (ISSUE 18 dataflow tier).

The ProjectContext maps every ``@functools.partial(jax.jit,
donate_argnums=(...))``-decorated function to its donated positions
(today: ``scatter_rows_donated`` donates arg 0). This rule then runs a
CFG-based forward may-analysis per function, in every file:

* a call to a donating function puts the ``ast.Name`` argument sitting
  in a donated position into the *may-donated* state after that
  statement;
* any re-binding of the name (assignment, for-target, with-as) kills the
  state — ``d = scatter_rows_donated(d, ...)`` is the blessed idiom;
* a Name load while may-donated → finding. The loop back edge is what
  catches the subtle case: a donation late in a loop body reaches the
  body's top on the next iteration unless the loop re-binds first.

Scope is the function's own statements; a nested closure is analyzed as
its own function (free-variable flows across closures are out of scope —
the ``_gather_guard`` epoch machinery handles that dynamic race).
Intentional metadata reads of a consumed buffer (``d.is_deleted()``)
carry ``# rb-ok: use-after-donation`` with the reason.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Set, Tuple

from .. import cfg as _cfg
from ..core import Finding, ProjectChecker, register_contract
from ..project import ProjectContext


def _donated_names(
    stmt: ast.stmt, donating: Dict[str, Tuple[int, ...]]
) -> Set[str]:
    """Names donated by calls evaluated at this CFG node."""
    out: Set[str] = set()
    for root in _cfg.header_expr_nodes(stmt):
        for node in ast.walk(root):
            if not isinstance(node, ast.Call):
                continue
            t = _terminal(node.func)
            positions = donating.get(t or "")
            if not positions:
                continue
            for pos in positions:
                if pos < len(node.args) and isinstance(
                    node.args[pos], ast.Name
                ):
                    out.add(node.args[pos].id)
    return out


def _terminal(node: ast.AST):
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


@register_contract
class UseAfterDonation(ProjectChecker):
    rule_id = "use-after-donation"
    description = (
        "a variable passed in a donate_argnums position is dead after "
        "the call until re-bound"
    )
    severity = "error"

    def check_project(self, project: ProjectContext) -> Iterable[Finding]:
        donating = project.donating
        if not donating:
            return
        for rel, ctx in sorted(project.files.items()):
            # cheap pre-filter: no donating callee name in the source text
            if not any(name in ctx.source for name in donating):
                continue
            for fn in _cfg.functions(ctx.tree):
                yield from self._check_function(project, rel, fn, donating)

    def _check_function(
        self,
        project: ProjectContext,
        rel: str,
        fn: ast.AST,
        donating: Dict[str, Tuple[int, ...]],
    ) -> Iterable[Finding]:
        graph = _cfg.CFG(fn)
        if not any(_donated_names(s, donating) for s in graph.stmts):
            return
        # transfer: OUT = (IN - KILL) ∪ (GEN - KILL). Subtracting the
        # kill from the gen makes the blessed idiom
        # `d = scatter_rows_donated(d, ...)` leave d NOT donated (the
        # name is re-bound to the fresh result in the same statement),
        # while `x = scatter_rows_donated(d, ...)` leaves d donated.
        ins = _cfg.may_reach(
            graph,
            gen=lambda s: _donated_names(s, donating) - _cfg.bound_names(s),
            kill=_cfg.bound_names,
        )
        flagged: Set[Tuple[str, int]] = set()
        for i, stmt in enumerate(graph.stmts):
            state = ins[i]
            if not state:
                continue
            # IN is the state *before* the statement evaluates, and reads
            # evaluate before any re-binding — so every load of a
            # may-donated name is a use-after, re-binding or not
            for load in _cfg.name_loads(stmt):
                name = load.id
                if name in state:
                    key = (name, load.lineno)
                    if key in flagged:
                        continue
                    flagged.add(key)
                    yield self.finding(
                        project, rel, load.lineno,
                        f"`{name}` was donated to a donate_argnums jit on "
                        "a path reaching this read and never re-bound — "
                        "the buffer is consumed; reading it raises a "
                        "deleted-buffer error at runtime",
                        col=load.col_offset,
                        end_line=load.lineno,
                    )
