"""epoch-pin: query execution on serve/ request paths happens inside an
``EpochStore.reader()`` pin (ISSUE 18 dataflow tier).

The epoch ledger's snapshot-isolation guarantee (PR 15) holds only when
a request's device-word reads are bracketed by a reader ticket — the pin
fixes the epoch for the whole execution, so a concurrent flip can't
hand one request bits from two lineage records. The harness does this
with::

    pin = (self.epoch_store.reader() if ... else contextlib.nullcontext())
    with pin as tk:
        out = executor.submit(req.expr).result()   # or _exec.execute(...)

This rule finds every execution-shaped call in ``serve/`` files — a call
whose terminal name is ``execute``, or ``submit`` on an executor — and
requires it to sit lexically inside a ``with`` statement whose context
expression *is* (or traces, through its reaching assignment in the same
function, to) a ``.reader(...)`` call. The ``nullcontext`` branch of the
conditional-pin idiom passes because the reaching assignment's RHS
contains the reader call on one branch — exactly the dynamic contract
(no store → nothing to pin).

Deliberately unpinned paths — the serial oracles that replay a schedule
against a quiesced corpus — carry ``# rb-ok: epoch-pin`` with the
justification.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, List, Optional, Set

from ..core import Finding, ProjectChecker, register_contract, terminal_name
from ..project import ProjectContext

# executor-shaped receivers for .submit(): the serve tier's execution
# pools — NOT the ingest log's submit (epoch_store.submit is the write
# path; writes go through the flip, not a reader pin)
_SUBMIT_RECEIVERS = {"executor", "_executor", "pool", "_pool"}


def _contains_reader_call(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Call) and terminal_name(n.func) == "reader":
            return True
    return False


class _FunctionScan:
    """Lexical with-stack walk of one function, resolving Name context
    expressions through their latest preceding assignment."""

    def __init__(self, fn: ast.AST):
        self.fn = fn
        # name -> lines of assignments whose RHS contains .reader(...)
        self.reader_assigns: Dict[str, List[int]] = {}
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and _contains_reader_call(
                node.value
            ):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.reader_assigns.setdefault(t.id, []).append(
                            node.lineno
                        )

    def pin_satisfied(self, item: ast.withitem, at_line: int) -> bool:
        expr = item.context_expr
        if _contains_reader_call(expr):
            return True
        if isinstance(expr, ast.Name):
            return any(
                line < at_line
                for line in self.reader_assigns.get(expr.id, ())
            )
        return False


@register_contract
class EpochPin(ProjectChecker):
    rule_id = "epoch-pin"
    description = (
        "serve/ execution calls sit inside an EpochStore.reader() pin "
        "(or a justified annotation)"
    )
    severity = "error"

    def check_project(self, project: ProjectContext) -> Iterable[Finding]:
        prefix = project.pkg_path("serve") + os.sep
        for rel, ctx in sorted(project.files.items()):
            if not rel.startswith(prefix):
                continue
            yield from self._check_file(project, rel, ctx.tree)

    def _check_file(
        self, project: ProjectContext, rel: str, tree: ast.AST
    ) -> Iterable[Finding]:
        # walk with an explicit (node, with-items-stack, fn) stack so the
        # enclosing with *statements* (not just lock names) are visible
        for fn in [
            n
            for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]:
            scan: Optional[_FunctionScan] = None
            stack = [(child, ()) for child in ast.iter_child_nodes(fn)]
            while stack:
                node, withs = stack.pop()
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue  # nested defs walk as their own fn
                child_withs = withs
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    child_withs = withs + tuple(node.items)
                for child in ast.iter_child_nodes(node):
                    stack.append((child, child_withs))
                if not isinstance(node, ast.Call):
                    continue
                if not self._is_execution_call(node):
                    continue
                if scan is None:
                    scan = _FunctionScan(fn)
                if any(
                    scan.pin_satisfied(item, node.lineno) for item in withs
                ):
                    continue
                yield self.finding(
                    project, rel, node.lineno,
                    "execution call on a serve/ path outside an "
                    "EpochStore.reader() pin — a concurrent epoch flip "
                    "can tear this read across lineage records; pin it "
                    "or annotate the oracle with a justified pragma",
                    col=node.col_offset,
                    end_line=node.end_lineno or node.lineno,
                )

    @staticmethod
    def _is_execution_call(node: ast.Call) -> bool:
        t = terminal_name(node.func)
        if t == "execute":
            return True
        if t == "submit" and isinstance(node.func, ast.Attribute):
            recv = terminal_name(node.func.value)
            return recv in _SUBMIT_RECEIVERS
        return False
