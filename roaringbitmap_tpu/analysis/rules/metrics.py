"""metric-naming: every metric registered through observe/ uses the
``rb_tpu_`` prefix with a declared (literal) label set.

The registry's convention (observe/registry.py) is ``rb_tpu_<layer>_<name>``
so a Prometheus scrape of a fleet is groupable by layer; a stray prefix or
a computed label tuple silently forks the namespace. Checked per
registration call (``observe.counter(...)`` / ``_observe.gauge(...)`` /
``_registry.histogram(...)`` / ``observe.latency_histogram(...)`` /
``REGISTRY.counter(...)``):

* a literal name must start with ``rb_tpu_``;
* an ALL_CAPS constant reference is accepted when it is either defined in
  another scanned module (the canonical-name block in registry.py, which
  this rule validates directly via the constant check below) or resolves
  in-file to a compliant literal;
* a computed name (f-string, concatenation, lowercase variable) is flagged
  — metric names are declared, not built;
* ``labelnames`` (3rd positional or keyword) must be a literal tuple/list
  of string literals (or absent);
* any module-level ``ALL_CAPS = "rb..."`` string constant must start with
  ``rb_tpu_`` (this is what validates registry.py's canonical names);
* **latency histograms** (``latency_histogram(...)``, ISSUE 6) measure
  seconds and must carry the ``_seconds`` unit suffix — a literal or
  in-file constant is validated directly, a cross-module constant must be
  ``*_SECONDS``-shaped so the defining module's check covers it;
* **enum gauges** (ISSUE 12): ``_state``/``_status`` join the recognised
  unit suffixes — an integer level from a declared enum (the health
  sentinel's ``rb_tpu_health_status`` 0/1/2 = green/yellow/red and
  ``rb_tpu_health_rule_state{rule}`` 0/1/2 = ok/warn/critical), so their
  cross-module constants validate like the other shaped names.

**Label-value cardinality** (ISSUE 9): metric *mutations* on module-level
metric constants (``_FOO_TOTAL.inc(1, (value,))`` / ``.observe`` /
``.set`` / ``.dec``) must not pass unbounded-cardinality label values —
a trace id, fingerprint, or raw container key as a label value mints a
new time series per query and melts any scrape backend. Each element of
a literal label tuple must be:

* a string literal, or
* a subscript of an in-file ALL_CAPS constant collection
  (``CLASS_NAMES[ci]`` — a member of a frozen declared set), or
* a name/attribute whose terminal identifier does NOT read as an
  unbounded value (``trace``/``fingerprint``/``uid``/``hash``/``key``/
  ... — see ``_UNBOUNDED``); benign enumerator names (``kind``, ``op``,
  ``site``, ``tier``) pass, pinned by false-positive fixtures.

f-strings, string concatenation, and call results (``bm.fingerprint()``)
are computed values and always flagged (``str(name)`` of a benign name is
the one exemption — it stringifies, it does not fabricate). Unbounded
values belong on flight-recorder events and decision-log entries, which
are bounded rings. A labels argument that is itself a variable is out of
lexical scope, like aliasing in lock-discipline.

**Tenant label values** (ISSUE 14): a tenant name is user-controlled
input — the serving tier's ``{tenant, phase}`` label sets stay bounded
only because every tenant label value resolves through the capacity-
bounded DECLARED tenant registry (``serve/slo.py`` ``TENANTS``), spelt
as the ``TENANTS[tenant]`` subscript (the declared-collection escape
above). A bare ``tenant``-shaped name in a label tuple is flagged with
its own message pointing at the registry; fixtures pin both directions.

**Epoch label values** (ISSUE 15): epoch ids advance forever — one
series per flip is the same cardinality melt as a series per trace. An
``epoch``-shaped name in a label tuple is flagged with its own message:
the current epoch is a gauge VALUE (``rb_tpu_serve_epoch_count``) and
lineage lives in the epoch ledger / trace / decision attrs. Flip STAGE
labels (``drain``/``repack``/``publish``/``reclaim``) are a declared
frozen set and pass; fixtures pin both directions.

**Container-format label values** (ISSUE 16): the structure census
gauge (``rb_tpu_structure_containers{format}``) labels by container
format — a set closed by construction (array | bitmap | run), but only
while every label value resolves through the DECLARED frozen format set
(``observe/structure.py`` ``FORMATS``), spelt as the ``FORMATS[fmt]``
subscript. A bare ``format``-shaped name in a label tuple is flagged
with its own message pointing at the declared set; the ``_containers``
census suffix joins the recognised unit suffixes so the cross-module
``STRUCTURE_CONTAINERS`` constant validates like the other shaped
names. Fixtures pin both directions.

Forwarding wrappers (a call whose name argument is the enclosing
function's own ``name`` parameter, e.g. the module-level ``counter()``
helpers in registry.py) are exempt — the real declaration is at their
call sites.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, Optional, Set

from ..core import Checker, FileContext, Finding, dotted_name, register

PREFIX = "rb_tpu_"
_REG_METHODS = {"counter", "gauge", "histogram", "latency_histogram"}
# registration methods whose metrics measure seconds (unit suffix required)
_SECONDS_METHODS = {"latency_histogram"}
# metric mutation methods whose label values are cardinality-checked
_MUT_METHODS = {"inc", "dec", "set", "observe"}
# receivers checked for mutations: module-level metric constants
# (optionally underscore-private), the registration convention throughout
_METRIC_CONST = re.compile(r"^_?[A-Z][A-Z0-9_]*$")
# identifier fragments that read as unbounded-cardinality values: one per
# query / operand / container, never a closed enumeration. Word-bounded so
# benign enumerators (kind, op, site, tier, stage, route, state) pass.
_UNBOUNDED = re.compile(
    r"(^|_)(trace|traceid|span_id|fingerprint|fingerprints|fp|fps|uid|"
    r"uuid|digest|hash|hashes|token|key|keys|qid|query_id|request_id|"
    r"id)(_|$)"
)
# tenant-valued identifiers (ISSUE 14): a tenant name is user-controlled
# input, so a bare `tenant` variable in a label tuple is the same
# unbounded-cardinality bug as a trace id — tenant label values must come
# from the bounded DECLARED tenant registry (serve/slo.py TENANTS), spelt
# as the `TENANTS[tenant]` subscript the declared-collection escape below
# already accepts (false-positive fixtures in tests/test_analysis.py)
_TENANT_VALUE = re.compile(r"(^|_)(tenant|tenants|tenant_name)(_|$)")
# epoch-valued identifiers (ISSUE 15): epoch ids advance forever — one
# series per flip melts the scrape backend exactly like a trace id. The
# current epoch is exported as a gauge VALUE (rb_tpu_serve_epoch_count);
# lineage lives in the epoch ledger and trace/decision attrs, never in
# label sets (false-positive fixtures pin flip-STAGE labels, which are a
# declared frozen set and fine)
_EPOCH_VALUE = re.compile(r"(^|_)(epoch|epochs|epoch_id|epoch_gen)(_|$)")
# container-format identifiers (ISSUE 16): the structure census gauge
# (rb_tpu_structure_containers{format}) labels by container format. The
# format set is closed by construction (Chambi et al.: array | bitmap |
# run) but only as long as every label value resolves through the
# DECLARED frozen format set (observe/structure.py FORMATS) — a bare
# `fmt` variable carrying Container.TYPE would silently mint a series
# for any future/typo'd format string, so it is flagged like a bare
# tenant name: spell it FORMATS[fmt] (false-positive fixtures pin
# literal "run"/"array" labels, which are declared and fine)
_FORMAT_VALUE = re.compile(r"(^|_)(format|formats|fmt|container_format)(_|$)")
_ALL_CAPS = re.compile(r"^[A-Z][A-Z0-9_]*$")
# constant names that read as canonical metric names (unit-suffixed; RATIO
# is the dimensionless gauge unit — e.g. rb_tpu_store_overlap_ratio;
# STATE/STATUS are the enum-gauge suffixes, ISSUE 12 — an integer level
# from a declared enum, e.g. rb_tpu_health_status 0/1/2 = green/yellow/red
# and rb_tpu_health_rule_state{rule} 0/1/2 = ok/warn/critical; QPS is the
# serving tier's requests-per-second gauge unit, ISSUE 14 —
# rb_tpu_serve_qps{tenant}; CONTAINERS is the structure observatory's
# census-gauge unit, ISSUE 16 — rb_tpu_structure_containers{format}, a
# live-object count by declared format)
_SHAPED_CONST = re.compile(
    r"^[A-Z][A-Z0-9_]*_(TOTAL|SECONDS|BYTES|COUNT|RATIO|STATE|STATUS|QPS|"
    r"CONTAINERS)$"
)


def _literal_label_tuple(node: ast.AST) -> bool:
    if isinstance(node, (ast.Tuple, ast.List)):
        return all(
            isinstance(el, ast.Constant) and isinstance(el.value, str)
            for el in node.elts
        )
    # () default shows up as an empty tuple; a lone string is a caller bug
    # the registry itself rejects, not a naming issue
    return False


def _function_spans(tree: ast.AST):
    """[(lineno, end_lineno, param-name set)] for every def, computed once
    per file (the per-call lookup below is then a linear scan of defs, not
    a full-tree walk)."""
    spans = []
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            a = node.args
            names = {x.arg for x in a.posonlyargs + a.args + a.kwonlyargs}
            for star in (a.vararg, a.kwarg):
                if star is not None:
                    names.add(star.arg)
            spans.append((node.lineno, node.end_lineno or node.lineno, names))
    return spans


def _enclosing_function_params(spans, call: ast.Call) -> Set[str]:
    best = None
    for lineno, end, names in spans:
        if lineno <= call.lineno <= end and (best is None or lineno >= best[0]):
            best = (lineno, names)
    return best[1] if best else set()


@register
class MetricNaming(Checker):
    rule_id = "metric-naming"
    description = (
        "metrics registered via observe/ use the rb_tpu_ prefix with "
        "declared literal label sets"
    )
    severity = "error"

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        # module-level ALL_CAPS string constants (the canonical-name block)
        constants: Dict[str, str] = {}
        for node in ctx.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                t = node.targets[0]
                if (
                    isinstance(t, ast.Name)
                    and _ALL_CAPS.match(t.id)
                    and isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, str)
                ):
                    constants[t.id] = node.value.value
                    v = node.value.value
                    # a constant is metric-name-shaped when its VALUE
                    # carries the rb prefix / a Prometheus unit suffix, or
                    # its NAME does (SPAN_SECONDS etc.) — the name-shape
                    # half pairs with the use-site rule below: cross-module
                    # references are only accepted for shaped names, and
                    # shaped names are validated here where they're defined
                    looks_like_metric = (
                        v.startswith("rb")
                        or re.search(
                            r"_(total|seconds|bytes|count|ratio|state|"
                            r"status|qps|containers)$",
                            v,
                        )
                        or _SHAPED_CONST.match(t.id)
                    )
                    if looks_like_metric and not v.startswith(PREFIX):
                        yield self.finding(
                            ctx,
                            node,
                            f"metric-name constant {t.id} = {v!r} does not "
                            f"use the {PREFIX!r} prefix",
                        )

        spans = _function_spans(ctx.tree)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = dotted_name(node.func)
            if fname is None:
                continue
            tail = fname.rsplit(".", 1)[-1]
            if tail in _MUT_METHODS:
                yield from self._check_label_values(ctx, node, tail)
            if tail not in _REG_METHODS:
                continue
            # registration needs at least the name argument
            if not node.args and not any(k.arg == "name" for k in node.keywords):
                continue
            name_arg = node.args[0] if node.args else next(
                k.value for k in node.keywords if k.arg == "name"
            )
            # forwarding wrapper: counter(name, ...) inside def counter(name,
            # ...) — including the star form, counter(*args, **kw)
            fwd = name_arg.value if isinstance(name_arg, ast.Starred) else name_arg
            if (
                isinstance(fwd, ast.Name)
                and fwd.id in _enclosing_function_params(spans, node)
            ):
                continue
            yield from self._check_name(
                ctx, node, name_arg, constants,
                needs_seconds=tail in _SECONDS_METHODS,
            )
            yield from self._check_labels(ctx, node)

    def _check_name(
        self, ctx, call, name_arg, constants, needs_seconds=False
    ) -> Iterable[Finding]:
        if isinstance(name_arg, ast.Constant) and isinstance(name_arg.value, str):
            if not name_arg.value.startswith(PREFIX):
                yield self.finding(
                    ctx,
                    call,
                    f"metric name {name_arg.value!r} must start with "
                    f"{PREFIX!r} (rb_tpu_<layer>_<name> convention)",
                )
            if needs_seconds and not name_arg.value.endswith("_seconds"):
                yield self.finding(
                    ctx,
                    call,
                    f"latency histogram {name_arg.value!r} must end in "
                    "'_seconds' (latency histograms measure seconds)",
                )
            return
        term = dotted_name(name_arg)
        term = term.rsplit(".", 1)[-1] if term else None
        if term is not None and _ALL_CAPS.match(term):
            val = constants.get(term)
            if val is not None:
                # in-file constant: resolve and validate the value here
                if not val.startswith(PREFIX):
                    yield self.finding(
                        ctx,
                        call,
                        f"metric registered under constant {term} = {val!r} "
                        f"which lacks the {PREFIX!r} prefix",
                    )
                if needs_seconds and not val.endswith("_seconds"):
                    yield self.finding(
                        ctx,
                        call,
                        f"latency histogram registered under constant {term} "
                        f"= {val!r} which lacks the '_seconds' unit suffix",
                    )
            elif needs_seconds and not term.endswith("_SECONDS"):
                # cross-module latency constants must be _SECONDS-shaped so
                # the defining module's value check enforces the suffix
                yield self.finding(
                    ctx,
                    call,
                    f"latency histogram name constant {term} is not "
                    "_SECONDS-shaped: the '_seconds' suffix cannot be "
                    "verified",
                )
            elif not _SHAPED_CONST.match(term):
                # cross-module constants are accepted only when the NAME is
                # metric-shaped — that shape is exactly what the
                # definition-site check validates in the defining module, so
                # an unshaped name here would escape both checks
                yield self.finding(
                    ctx,
                    call,
                    f"metric name constant {term} is neither defined in this "
                    f"module nor unit-suffixed (_TOTAL/_SECONDS/_BYTES/"
                    f"_COUNT/_RATIO/_STATE/_STATUS): the prefix cannot be "
                    f"verified",
                )
            return
        yield self.finding(
            ctx,
            call,
            "metric name must be a string literal or ALL_CAPS constant "
            "(computed names fork the metric namespace)",
        )

    def _check_label_values(self, ctx, call, method) -> Iterable[Finding]:
        """Unbounded-cardinality guard on metric *mutations* (ISSUE 9):
        ``_FOO_TOTAL.inc(1, (trace_id,))`` mints a series per query."""
        # receiver must be a module-level metric constant (_FOO_TOTAL.inc /
        # mod._FOO_SECONDS.observe); instance attrs and locals are other
        # objects wearing the same method names
        if not isinstance(call.func, ast.Attribute):
            return
        recv = call.func.value
        term = recv.attr if isinstance(recv, ast.Attribute) else (
            recv.id if isinstance(recv, ast.Name) else None
        )
        if term is None or not _METRIC_CONST.match(term):
            return
        label_arg = call.args[1] if len(call.args) >= 2 else None
        for kw in call.keywords:
            if kw.arg == "labels":
                label_arg = kw.value
        # a non-tuple labels argument (a variable) is out of lexical scope,
        # like aliasing in lock-discipline
        if not isinstance(label_arg, (ast.Tuple, ast.List)):
            return
        for el in label_arg.elts:
            yield from self._check_label_value(ctx, call, el)

    def _check_label_value(self, ctx, call, el) -> Iterable[Finding]:
        if isinstance(el, ast.Constant):
            return  # literal: declared, bounded
        if isinstance(el, ast.Subscript) and isinstance(el.value, ast.Name) \
                and _METRIC_CONST.match(el.value.id):
            return  # member of an in-file ALL_CAPS constant collection
        if isinstance(el, (ast.JoinedStr, ast.BinOp)):
            yield self.finding(
                ctx, call,
                "computed metric label value (f-string/concatenation): "
                "unbounded values belong on recorder events or the "
                "decision log, not in label sets",
            )
            return
        if isinstance(el, ast.Call):
            # str(<benign name>) merely stringifies: check the inner name
            if (
                isinstance(el.func, ast.Name) and el.func.id == "str"
                and len(el.args) == 1 and isinstance(el.args[0], ast.Name)
            ):
                yield from self._check_label_value(ctx, call, el.args[0])
                return
            yield self.finding(
                ctx, call,
                "metric label value computed by a call: unbounded values "
                "(fingerprints, ids) belong on recorder events or the "
                "decision log, not in label sets",
            )
            return
        term = dotted_name(el)
        term = term.rsplit(".", 1)[-1] if term else None
        if term is None:
            return
        if _TENANT_VALUE.search(term.lower()):
            yield self.finding(
                ctx, call,
                f"metric label value `{term}` is a tenant name: tenant "
                "label values must come from the bounded declared tenant "
                "registry (spell it TENANTS[" + term + "] — the "
                "declared-collection subscript — so an undeclared tenant "
                "can never mint a series)",
            )
            return
        if _EPOCH_VALUE.search(term.lower()):
            yield self.finding(
                ctx, call,
                f"metric label value `{term}` is an epoch id: epoch ids "
                "are unbounded (one per flip, forever) and must never be "
                "metric label values — export the current epoch as a "
                "gauge VALUE and put lineage in the epoch ledger / "
                "trace / decision attrs",
            )
            return
        if _FORMAT_VALUE.search(term.lower()):
            yield self.finding(
                ctx, call,
                f"metric label value `{term}` is a container format: "
                "format label values must come from the declared frozen "
                "format set (spell it FORMATS[" + term + "] — the "
                "declared-collection subscript — so a future or typo'd "
                "format string can never mint a series)",
            )
            return
        if _UNBOUNDED.search(term.lower()):
            yield self.finding(
                ctx, call,
                f"metric label value `{term}` reads as unbounded "
                "cardinality (per-query/per-operand): use a literal or a "
                "member of a declared frozen set, and put the raw value "
                "on a recorder event or decision-log entry instead",
            )

    def _check_labels(self, ctx, call) -> Iterable[Finding]:
        label_arg = None
        if len(call.args) >= 3:
            label_arg = call.args[2]
        for kw in call.keywords:
            if kw.arg == "labelnames":
                label_arg = kw.value
        if label_arg is None:
            return
        if not _literal_label_tuple(label_arg):
            yield self.finding(
                ctx,
                call,
                "labelnames must be a literal tuple of string literals "
                "(declared label sets, not computed)",
            )
