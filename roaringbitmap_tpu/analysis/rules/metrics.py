"""metric-naming: every metric registered through observe/ uses the
``rb_tpu_`` prefix with a declared (literal) label set.

The registry's convention (observe/registry.py) is ``rb_tpu_<layer>_<name>``
so a Prometheus scrape of a fleet is groupable by layer; a stray prefix or
a computed label tuple silently forks the namespace. Checked per
registration call (``observe.counter(...)`` / ``_observe.gauge(...)`` /
``_registry.histogram(...)`` / ``observe.latency_histogram(...)`` /
``REGISTRY.counter(...)``):

* a literal name must start with ``rb_tpu_``;
* an ALL_CAPS constant reference is accepted when it is either defined in
  another scanned module (the canonical-name block in registry.py, which
  this rule validates directly via the constant check below) or resolves
  in-file to a compliant literal;
* a computed name (f-string, concatenation, lowercase variable) is flagged
  — metric names are declared, not built;
* ``labelnames`` (3rd positional or keyword) must be a literal tuple/list
  of string literals (or absent);
* any module-level ``ALL_CAPS = "rb..."`` string constant must start with
  ``rb_tpu_`` (this is what validates registry.py's canonical names);
* **latency histograms** (``latency_histogram(...)``, ISSUE 6) measure
  seconds and must carry the ``_seconds`` unit suffix — a literal or
  in-file constant is validated directly, a cross-module constant must be
  ``*_SECONDS``-shaped so the defining module's check covers it.

Forwarding wrappers (a call whose name argument is the enclosing
function's own ``name`` parameter, e.g. the module-level ``counter()``
helpers in registry.py) are exempt — the real declaration is at their
call sites.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, Optional, Set

from ..core import Checker, FileContext, Finding, dotted_name, register

PREFIX = "rb_tpu_"
_REG_METHODS = {"counter", "gauge", "histogram", "latency_histogram"}
# registration methods whose metrics measure seconds (unit suffix required)
_SECONDS_METHODS = {"latency_histogram"}
_ALL_CAPS = re.compile(r"^[A-Z][A-Z0-9_]*$")
# constant names that read as canonical metric names (unit-suffixed; RATIO
# is the dimensionless gauge unit — e.g. rb_tpu_store_overlap_ratio)
_SHAPED_CONST = re.compile(r"^[A-Z][A-Z0-9_]*_(TOTAL|SECONDS|BYTES|COUNT|RATIO)$")


def _literal_label_tuple(node: ast.AST) -> bool:
    if isinstance(node, (ast.Tuple, ast.List)):
        return all(
            isinstance(el, ast.Constant) and isinstance(el.value, str)
            for el in node.elts
        )
    # () default shows up as an empty tuple; a lone string is a caller bug
    # the registry itself rejects, not a naming issue
    return False


def _function_spans(tree: ast.AST):
    """[(lineno, end_lineno, param-name set)] for every def, computed once
    per file (the per-call lookup below is then a linear scan of defs, not
    a full-tree walk)."""
    spans = []
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            a = node.args
            names = {x.arg for x in a.posonlyargs + a.args + a.kwonlyargs}
            for star in (a.vararg, a.kwarg):
                if star is not None:
                    names.add(star.arg)
            spans.append((node.lineno, node.end_lineno or node.lineno, names))
    return spans


def _enclosing_function_params(spans, call: ast.Call) -> Set[str]:
    best = None
    for lineno, end, names in spans:
        if lineno <= call.lineno <= end and (best is None or lineno >= best[0]):
            best = (lineno, names)
    return best[1] if best else set()


@register
class MetricNaming(Checker):
    rule_id = "metric-naming"
    description = (
        "metrics registered via observe/ use the rb_tpu_ prefix with "
        "declared literal label sets"
    )
    severity = "error"

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        # module-level ALL_CAPS string constants (the canonical-name block)
        constants: Dict[str, str] = {}
        for node in ctx.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                t = node.targets[0]
                if (
                    isinstance(t, ast.Name)
                    and _ALL_CAPS.match(t.id)
                    and isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, str)
                ):
                    constants[t.id] = node.value.value
                    v = node.value.value
                    # a constant is metric-name-shaped when its VALUE
                    # carries the rb prefix / a Prometheus unit suffix, or
                    # its NAME does (SPAN_SECONDS etc.) — the name-shape
                    # half pairs with the use-site rule below: cross-module
                    # references are only accepted for shaped names, and
                    # shaped names are validated here where they're defined
                    looks_like_metric = (
                        v.startswith("rb")
                        or re.search(r"_(total|seconds|bytes|count|ratio)$", v)
                        or _SHAPED_CONST.match(t.id)
                    )
                    if looks_like_metric and not v.startswith(PREFIX):
                        yield self.finding(
                            ctx,
                            node,
                            f"metric-name constant {t.id} = {v!r} does not "
                            f"use the {PREFIX!r} prefix",
                        )

        spans = _function_spans(ctx.tree)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = dotted_name(node.func)
            if fname is None:
                continue
            tail = fname.rsplit(".", 1)[-1]
            if tail not in _REG_METHODS:
                continue
            # registration needs at least the name argument
            if not node.args and not any(k.arg == "name" for k in node.keywords):
                continue
            name_arg = node.args[0] if node.args else next(
                k.value for k in node.keywords if k.arg == "name"
            )
            # forwarding wrapper: counter(name, ...) inside def counter(name,
            # ...) — including the star form, counter(*args, **kw)
            fwd = name_arg.value if isinstance(name_arg, ast.Starred) else name_arg
            if (
                isinstance(fwd, ast.Name)
                and fwd.id in _enclosing_function_params(spans, node)
            ):
                continue
            yield from self._check_name(
                ctx, node, name_arg, constants,
                needs_seconds=tail in _SECONDS_METHODS,
            )
            yield from self._check_labels(ctx, node)

    def _check_name(
        self, ctx, call, name_arg, constants, needs_seconds=False
    ) -> Iterable[Finding]:
        if isinstance(name_arg, ast.Constant) and isinstance(name_arg.value, str):
            if not name_arg.value.startswith(PREFIX):
                yield self.finding(
                    ctx,
                    call,
                    f"metric name {name_arg.value!r} must start with "
                    f"{PREFIX!r} (rb_tpu_<layer>_<name> convention)",
                )
            if needs_seconds and not name_arg.value.endswith("_seconds"):
                yield self.finding(
                    ctx,
                    call,
                    f"latency histogram {name_arg.value!r} must end in "
                    "'_seconds' (latency histograms measure seconds)",
                )
            return
        term = dotted_name(name_arg)
        term = term.rsplit(".", 1)[-1] if term else None
        if term is not None and _ALL_CAPS.match(term):
            val = constants.get(term)
            if val is not None:
                # in-file constant: resolve and validate the value here
                if not val.startswith(PREFIX):
                    yield self.finding(
                        ctx,
                        call,
                        f"metric registered under constant {term} = {val!r} "
                        f"which lacks the {PREFIX!r} prefix",
                    )
                if needs_seconds and not val.endswith("_seconds"):
                    yield self.finding(
                        ctx,
                        call,
                        f"latency histogram registered under constant {term} "
                        f"= {val!r} which lacks the '_seconds' unit suffix",
                    )
            elif needs_seconds and not term.endswith("_SECONDS"):
                # cross-module latency constants must be _SECONDS-shaped so
                # the defining module's value check enforces the suffix
                yield self.finding(
                    ctx,
                    call,
                    f"latency histogram name constant {term} is not "
                    "_SECONDS-shaped: the '_seconds' suffix cannot be "
                    "verified",
                )
            elif not _SHAPED_CONST.match(term):
                # cross-module constants are accepted only when the NAME is
                # metric-shaped — that shape is exactly what the
                # definition-site check validates in the defining module, so
                # an unshaped name here would escape both checks
                yield self.finding(
                    ctx,
                    call,
                    f"metric name constant {term} is neither defined in this "
                    f"module nor unit-suffixed (_TOTAL/_SECONDS/_BYTES/"
                    f"_COUNT/_RATIO): the prefix cannot be verified",
                )
            return
        yield self.finding(
            ctx,
            call,
            "metric name must be a string literal or ALL_CAPS constant "
            "(computed names fork the metric namespace)",
        )

    def _check_labels(self, ctx, call) -> Iterable[Finding]:
        label_arg = None
        if len(call.args) >= 3:
            label_arg = call.args[2]
        for kw in call.keywords:
            if kw.arg == "labelnames":
                label_arg = kw.value
        if label_arg is None:
            return
        if not _literal_label_tuple(label_arg):
            yield self.finding(
                ctx,
                call,
                "labelnames must be a literal tuple of string literals "
                "(declared label sets, not computed)",
            )
