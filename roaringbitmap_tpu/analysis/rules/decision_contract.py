"""decision-discipline: an ``outcome=True`` decision's seq must be able
to reach a resolve/measure join (ISSUE 18).

``record_decision(site, verdict, outcome=True, ...)`` parks the decision
in the outcome ledger's pending ring and returns the join key (``seq``).
The economy only closes when something later calls
``outcomes.resolve(seq, ...)`` (or threads the seq through
``LADDER.run(..., outcome_seq=seq)``). A site that asks for an outcome
and then *drops the seq on the floor* can never be joined: every such
decision ages out of the pending ring as an orphan, silently starving
the refit loop the cost authorities depend on.

Function-scope dataflow, deliberately conservative (escape == fine):

* the call's value is discarded (an expression statement, or bound to
  ``_``) → finding;
* the seq is bound to a name that is never read anywhere else in the
  function's own scope → finding;
* any read counts as an escape — passed to a call (``resolve(seq, …)``,
  ``outcome_seq=seq``), returned, yielded, stored into an attribute or
  container. Reachability past the escape is runtime behavior.

Sites with ``outcome=False`` (or dynamic ``outcome=flag``) are exempt;
deliberate fire-and-forget outcome sites carry ``# rb-ok:
decision-discipline`` with a reason.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Tuple

from ..core import Finding, ProjectChecker, register_contract
from ..project import ProjectContext


def _enclosing_function(
    tree: ast.AST, call: ast.Call
) -> Optional[ast.AST]:
    """Innermost function def whose span contains the call."""
    best: Optional[ast.AST] = None
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if (
                node.lineno <= call.lineno
                and (node.end_lineno or node.lineno) >= (call.end_lineno or call.lineno)
            ):
                if best is None or node.lineno >= best.lineno:
                    best = node
    return best


def _own_scope_name_loads(fn: ast.AST, name: str, skip: ast.AST) -> int:
    """Load-count of ``name`` in ``fn``'s own scope AND nested scopes
    (a closure reading the seq is a legitimate escape), excluding the
    binding statement ``skip`` itself."""
    count = 0
    for node in ast.walk(fn):
        if node is skip:
            continue
        if isinstance(node, ast.Name) and node.id == name and isinstance(
            node.ctx, ast.Load
        ):
            # reads inside the binding statement itself (the call's own
            # args) don't count as a later use
            if not (
                skip.lineno <= node.lineno
                and node.lineno <= (skip.end_lineno or skip.lineno)
            ):
                count += 1
    return count


@register_contract
class DecisionDiscipline(ProjectChecker):
    rule_id = "decision-discipline"
    description = (
        "record_decision(..., outcome=True) must bind its seq and the seq "
        "must escape toward a resolve/measure join"
    )
    severity = "error"

    def check_project(self, project: ProjectContext) -> Iterable[Finding]:
        decisions_rel = project.pkg_path("observe", "decisions.py")
        for site in project.decision_sites:
            if site.outcome is not True:
                continue
            if site.path == decisions_rel:
                continue  # the recorder's own docs/plumbing
            ctx = project.files.get(site.path)
            if ctx is None:
                continue
            fn = _enclosing_function(ctx.tree, site.call)
            stmt = self._binding_statement(ctx.tree, fn, site.call)
            if stmt is None:
                continue  # call spans something exotic; don't guess
            kind, name = stmt
            if kind == "discarded":
                yield self.finding(
                    project, site.path, site.call.lineno,
                    f"outcome=True decision at site {site.site!r} discards "
                    "its seq — the pending entry can never be resolved "
                    "and will age out as an orphan",
                    end_line=site.call.end_lineno or site.call.lineno,
                )
            elif kind == "bound" and fn is not None:
                binding = self._binding_node(fn, site.call)
                if binding is not None and not _own_scope_name_loads(
                    fn, name, binding
                ):
                    yield self.finding(
                        project, site.path, site.call.lineno,
                        f"outcome=True decision at site {site.site!r} "
                        f"binds its seq to `{name}` but never reads it — "
                        "no resolve/measure path can join this decision",
                        end_line=site.call.end_lineno or site.call.lineno,
                    )

    @staticmethod
    def _binding_statement(
        tree: ast.AST, fn: Optional[ast.AST], call: ast.Call
    ) -> Optional[Tuple[str, str]]:
        """('discarded'|'bound'|'escaped', bound-name). The call escapes
        when it is nested inside any larger expression (an argument, a
        return value, a comparison) — those uses ARE the seq's use."""
        scope = fn if fn is not None else tree
        for node in ast.walk(scope):
            if isinstance(node, ast.Expr) and node.value is call:
                return ("discarded", "")
            if isinstance(node, ast.Assign) and node.value is call:
                if len(node.targets) == 1 and isinstance(
                    node.targets[0], ast.Name
                ):
                    tname = node.targets[0].id
                    if tname == "_":
                        return ("discarded", "")
                    return ("bound", tname)
                return ("escaped", "")
            if isinstance(node, ast.AnnAssign) and node.value is call:
                if isinstance(node.target, ast.Name):
                    if node.target.id == "_":
                        return ("discarded", "")
                    return ("bound", node.target.id)
                return ("escaped", "")
        return ("escaped", "")

    @staticmethod
    def _binding_node(fn: ast.AST, call: ast.Call) -> Optional[ast.stmt]:
        for node in ast.walk(fn):
            if isinstance(node, (ast.Assign, ast.AnnAssign)) and node.value is call:
                return node
        return None
