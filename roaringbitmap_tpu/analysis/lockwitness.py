"""Dynamic lock-order witness — the runtime complement of the static
lock-discipline rule.

The static pass (rules/locks.py) proves guarded state is written under its
lock; it cannot prove the *order* locks nest in is consistent. A deadlock
needs a cycle: thread 1 holds A wanting B while thread 2 holds B wanting A.
This witness wraps the framework's locks during a test, records every
"acquired Y while holding X" edge into a directed graph, and fails on any
cycle — an inconsistent ordering that could deadlock under the right
interleaving even if the test run itself got lucky.

Usage (tests/test_observe.py, tests/test_query_cache.py)::

    w = LockWitness()
    cache._lock = w.wrap("cache", cache._lock)
    registry_lock = w.wrap("registry", observe.REGISTRY._lock)
    ...patch every reference to the wrapped object...
    <run the thread hammer>
    w.assert_consistent()          # raises LockOrderError on a cycle
    assert ("cache", "registry") in w.edges   # the nesting was exercised

Wrapped locks proxy ``acquire``/``release``/context-manager onto the inner
lock (plain Lock or RLock); the edge graph and per-thread held stacks live
behind the witness's own private lock, which is a leaf — it is never held
while acquiring an instrumented lock, so the witness cannot introduce an
ordering of its own.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Set, Tuple


class LockOrderError(AssertionError):
    """Inconsistent lock-acquisition ordering (potential deadlock cycle)."""


class WitnessedLock:
    """Proxy over a Lock/RLock that reports acquisitions to the witness."""

    def __init__(self, name: str, inner, witness: "LockWitness"):
        self.name = name
        self._inner = inner
        self._witness = witness

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._witness._note_acquire(self.name)
        return got

    def release(self) -> None:
        self._witness._note_release(self.name)
        self._inner.release()

    def __enter__(self) -> "WitnessedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<WitnessedLock {self.name} over {self._inner!r}>"


class LockWitness:
    """Records the acquisition-order graph across a set of named locks."""

    def __init__(self) -> None:
        self._mu = threading.Lock()  # leaf lock: guards edges only
        self._held = threading.local()  # per-thread stack of held names
        self.edges: Set[Tuple[str, str]] = set()  # guarded-by: _mu
        self.acquisitions: Dict[str, int] = {}  # guarded-by: _mu

    def wrap(self, name: str, lock) -> WitnessedLock:
        return WitnessedLock(name, lock, self)

    def _stack(self) -> List[str]:
        st = getattr(self._held, "stack", None)
        if st is None:
            st = self._held.stack = []
        return st

    def _note_acquire(self, name: str) -> None:
        st = self._stack()
        new_edges = [(h, name) for h in st if h != name]
        st.append(name)
        with self._mu:
            self.acquisitions[name] = self.acquisitions.get(name, 0) + 1
            self.edges.update(new_edges)

    def _note_release(self, name: str) -> None:
        st = self._stack()
        # remove the innermost matching hold (reentrant locks release LIFO)
        for i in range(len(st) - 1, -1, -1):
            if st[i] == name:
                del st[i]
                break

    # -- analysis ---------------------------------------------------------

    def find_cycle(self) -> Optional[List[str]]:
        """A lock-name cycle in the order graph, or None."""
        with self._mu:
            graph: Dict[str, Set[str]] = {}
            for a, b in self.edges:
                graph.setdefault(a, set()).add(b)
        WHITE, GRAY, BLACK = 0, 1, 2
        color = {n: WHITE for n in graph}
        path: List[str] = []

        def dfs(n: str) -> Optional[List[str]]:
            color[n] = GRAY
            path.append(n)
            for m in sorted(graph.get(n, ())):
                c = color.get(m, WHITE)
                if c == GRAY:
                    return path[path.index(m) :] + [m]
                if c == WHITE:
                    color.setdefault(m, WHITE)
                    cyc = dfs(m)
                    if cyc:
                        return cyc
            color[n] = BLACK
            path.pop()
            return None

        for n in sorted(graph):
            if color.get(n, WHITE) == WHITE:
                cyc = dfs(n)
                if cyc:
                    return cyc
        return None

    def assert_consistent(self) -> None:
        """Raise LockOrderError if any inconsistent ordering was observed."""
        cyc = self.find_cycle()
        if cyc:
            with self._mu:
                witnessed = sorted(self.edges)
            raise LockOrderError(
                f"inconsistent lock acquisition order (potential deadlock): "
                f"cycle {' -> '.join(cyc)}; witnessed edges {witnessed}"
            )
