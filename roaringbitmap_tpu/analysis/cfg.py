"""A light intra-function CFG + forward may-analysis (ISSUE 18, the
dataflow tier's spine).

Nodes are *statements* of one function's own scope (nested defs/lambdas
are separate functions — they get their own CFG). Edges model the
control flow the dataflow rules care about:

* sequence within a block;
* ``if``/``elif``/``else`` branch + join;
* ``for``/``while`` loop body with a back edge to the header and an exit
  edge past the loop (so state flows *around* an iteration: a variable
  donated late in a loop body reaches the body's top on the next trip
  unless re-bound first);
* ``try`` — every body statement may also jump to each handler (any
  statement can raise), handlers and ``finally`` rejoin;
* ``break``/``continue``/``return``/``raise`` cut the fall-through edge.

The analysis is a classic may-forward fixpoint over small sets of
variable names: :func:`may_reach` takes per-statement GEN (names entering
the tracked state) and KILL (re-bindings leaving it) and returns each
statement's IN set. Functions in this tree are small, so the worklist
converges in a handful of passes; no basic-block construction is needed
at this scale.
"""

from __future__ import annotations

import ast
from typing import Callable, Dict, Iterable, List, Set, Tuple

_SCOPE_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)


class CFG:
    """Statement-level control-flow graph of one function body."""

    def __init__(self, fn: ast.AST):
        self.fn = fn
        self.stmts: List[ast.stmt] = []
        self._index: Dict[int, int] = {}  # id(stmt) -> node index
        self.succs: Dict[int, Set[int]] = {}
        self.entry: List[int] = []
        self._build(list(getattr(fn, "body", ())))

    # -- construction ---------------------------------------------------

    def _add(self, stmt: ast.stmt) -> int:
        idx = len(self.stmts)
        self.stmts.append(stmt)
        self._index[id(stmt)] = idx
        self.succs[idx] = set()
        return idx

    def _edge(self, frm: Iterable[int], to: int) -> None:
        for f in frm:
            self.succs[f].add(to)

    def _build(self, body: List[ast.stmt]) -> None:
        exits, _breaks, _continues = self._block(body, [], loop=None)
        self.exits = exits

    def _block(
        self,
        body: List[ast.stmt],
        preds: List[int],
        loop,
    ) -> Tuple[List[int], List[int], List[int]]:
        """Wire ``body`` after ``preds``; returns (fall-through exits,
        break sources, continue sources). ``loop`` is the enclosing loop
        header's index (for back edges), or None."""
        breaks: List[int] = []
        continues: List[int] = []
        cur = list(preds)
        first = True
        for stmt in body:
            idx = self._add(stmt)
            if first and not preds:
                self.entry.append(idx)
            first = False
            self._edge(cur, idx)
            cur = [idx]
            if isinstance(stmt, ast.If):
                then_exits, b1, c1 = self._block(stmt.body, [idx], loop)
                # no orelse: building the empty block returns [idx] — the
                # fall-past-the-test path
                else_exits, b2, c2 = self._block(stmt.orelse, [idx], loop)
                breaks += b1 + b2
                continues += c1 + c2
                cur = then_exits + else_exits
            elif isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                body_exits, b, c = self._block(stmt.body, [idx], idx)
                # back edge: end of body (and every continue) re-enters
                # the header, so state flows around an iteration
                self._edge(body_exits + c, idx)
                else_exits, b2, c2 = self._block(stmt.orelse, [idx], loop)
                breaks += b2
                continues += c2
                cur = [idx] + b + (else_exits if stmt.orelse else [])
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                body_exits, b, c = self._block(stmt.body, [idx], loop)
                breaks += b
                continues += c
                cur = body_exits
            elif isinstance(stmt, ast.Try):
                body_start = len(self.stmts)
                body_exits, b, c = self._block(stmt.body, [idx], loop)
                breaks += b
                continues += c
                # any statement of the try body may raise into a handler
                handler_entries_from = [idx] + list(
                    range(body_start, len(self.stmts))
                )
                joined = list(body_exits)
                for h in stmt.handlers:
                    h_exits, b, c = self._block(
                        h.body, handler_entries_from, loop
                    )
                    breaks += b
                    continues += c
                    joined += h_exits
                else_exits, b, c = self._block(stmt.orelse, body_exits, loop)
                breaks += b
                continues += c
                if stmt.orelse:
                    joined = [e for e in joined if e not in body_exits]
                    joined += else_exits
                fin_exits, b, c = self._block(stmt.finalbody, joined, loop)
                breaks += b
                continues += c
                cur = fin_exits if stmt.finalbody else joined
            elif isinstance(stmt, (ast.Return, ast.Raise)):
                cur = []
            elif isinstance(stmt, ast.Break):
                breaks.append(idx)
                cur = []
            elif isinstance(stmt, ast.Continue):
                continues.append(idx)
                cur = []
        return cur, breaks, continues

def own_statements(fn: ast.AST) -> List[ast.stmt]:
    """Every statement in ``fn``'s own scope, nested scopes excluded."""
    out: List[ast.stmt] = []
    work = list(getattr(fn, "body", ()))
    while work:
        s = work.pop(0)
        out.append(s)
        if isinstance(s, _SCOPE_NODES):
            continue
        for field in ("body", "orelse", "finalbody"):
            work.extend(
                c for c in getattr(s, field, ())
                if not isinstance(c, _SCOPE_NODES)
            )
        for h in getattr(s, "handlers", ()):
            work.extend(h.body)
    return out


def bound_names(stmt: ast.stmt) -> Set[str]:
    """Names (re-)bound by this statement — the KILL set for per-variable
    state: assignment / aug-assign / with-as / for-target / walrus."""
    out: Set[str] = set()

    def target_names(t: ast.AST) -> None:
        for n in ast.walk(t):
            if isinstance(n, ast.Name):
                out.add(n.id)

    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            if isinstance(t, (ast.Name, ast.Tuple, ast.List, ast.Starred)):
                target_names(t)
    elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
        if isinstance(stmt.target, ast.Name):
            out.add(stmt.target.id)
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        target_names(stmt.target)
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        for item in stmt.items:
            if item.optional_vars is not None:
                target_names(item.optional_vars)
    # walrus in the expressions evaluated AT this node (compound-statement
    # bodies are their own CFG nodes; nested scopes excluded)
    work: List[ast.AST] = list(header_expr_nodes(stmt))
    while work:
        n = work.pop()
        if isinstance(n, _SCOPE_NODES):
            continue
        if isinstance(n, ast.NamedExpr) and isinstance(n.target, ast.Name):
            out.add(n.target.id)
        work.extend(ast.iter_child_nodes(n))
    return out


def header_expr_nodes(stmt: ast.stmt) -> Iterable[ast.AST]:
    """The expression nodes evaluated *at* a statement node itself (for
    compound statements: the header only — the body is separate CFG
    nodes). Name loads inside these are 'reads at this node'."""
    if isinstance(stmt, ast.If) or isinstance(stmt, ast.While):
        yield stmt.test
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        yield stmt.iter
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        for item in stmt.items:
            yield item.context_expr
    elif isinstance(stmt, ast.Try):
        return
    else:
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                yield child


def name_loads(stmt: ast.stmt) -> List[ast.Name]:
    """Name loads evaluated at this CFG node (headers only for compound
    statements; nested scopes excluded — a closure capturing the name is
    analyzed as its own function)."""
    out: List[ast.Name] = []
    for root in header_expr_nodes(stmt):
        work = [root]
        while work:
            n = work.pop()
            if isinstance(n, _SCOPE_NODES):
                continue
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
                out.append(n)
            work.extend(ast.iter_child_nodes(n))
    return out


def may_reach(
    cfg: CFG,
    gen: Callable[[ast.stmt], Set[str]],
    kill: Callable[[ast.stmt], Set[str]],
) -> Dict[int, Set[str]]:
    """Forward may-analysis: IN[s] = ∪ OUT[p] over predecessors;
    OUT[s] = (IN[s] - KILL[s]) ∪ GEN[s]. Returns IN per statement index —
    the state *before* the statement executes (reads happen then)."""
    n = len(cfg.stmts)
    gens = [gen(s) for s in cfg.stmts]
    kills = [kill(s) for s in cfg.stmts]
    ins: Dict[int, Set[str]] = {i: set() for i in range(n)}
    outs: Dict[int, Set[str]] = {i: set() for i in range(n)}
    work = list(range(n))
    while work:
        i = work.pop(0)
        new_out = (ins[i] - kills[i]) | gens[i]
        if new_out != outs[i]:
            outs[i] = new_out
            for s in cfg.succs[i]:
                if not new_out <= ins[s]:
                    ins[s] |= new_out
                    if s not in work:
                        work.append(s)
    return ins


def functions(tree: ast.AST) -> List[ast.AST]:
    """Every function/method def in the module, nested ones included —
    each is analyzed as its own scope."""
    return [
        n
        for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]
