"""L0' device kernels: batched word ops on ``[N, W]`` container blocks.

This is the TPU re-expression of the reference's hot loops — the 1024-long
word loop + popcount pass that underlies every wide aggregation
(FastAggregation.java:602 naive lazy fold, BitmapContainer.ilazyor
BitmapContainer.java:657-678, repairAfterLazy Container.java:873). Instead of
folding bitmap-by-bitmap on one core, thousands of containers are packed into
a single device array and reduced in one fused XLA computation; the
"lazy cardinality" protocol (defer popcounts, repair once) is free here
because popcount fuses into the tail of the reduction.

Device layout: ``uint32 [N, 2048]`` — each row is one container
(65536 bits); uint32 lanes suit the 8x128 VPU. Host words are ``uint64
[1024]``; the views are interchangeable little-endian
(u64 word k == u32[2k] | u32[2k+1] << 32).
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..observe import compilewatch as _compilewatch

DEVICE_WORDS = 2048  # uint32 words per container row
HOST_WORDS = 1024  # uint64 words per container


def pow2(k: int) -> int:
    """Pow2 bucket length (min 8) for variable-length jit operands — the
    retrace-bounding discipline shared by the marshal kernels (payload
    expansion, donated delta scatter)."""
    return max(8, 1 << (max(1, int(k)) - 1).bit_length())


def pad_pow2(arr: np.ndarray, fill) -> np.ndarray:
    """Pad ``arr`` to its pow2 bucket with ``fill`` (an out-of-range id
    for index streams — scatter ``mode="drop"`` discards the padding)."""
    kp = pow2(len(arr))
    out = np.full(kp, fill, dtype=arr.dtype)
    out[: len(arr)] = arr
    return out

_INIT = {
    "or": np.uint32(0),
    "xor": np.uint32(0),
    "and": np.uint32(0xFFFFFFFF),
}
_OPS = {
    "or": lax.bitwise_or,
    "xor": lax.bitwise_xor,
    "and": lax.bitwise_and,
}


def to_device_words(host_words: np.ndarray) -> np.ndarray:
    """uint64 [..., 1024] host words -> uint32 [..., 2048] device layout."""
    w = np.ascontiguousarray(host_words, dtype=np.uint64)
    return w.view(np.uint32).reshape(*w.shape[:-1], DEVICE_WORDS)


def from_device_words(dev_words) -> np.ndarray:
    """uint32 [..., 2048] -> uint64 [..., 1024] host words."""
    w = np.ascontiguousarray(np.asarray(dev_words), dtype=np.uint32)
    return w.view(np.uint64).reshape(*w.shape[:-1], HOST_WORDS)


# ---------------------------------------------------------------------------
# elementwise pairwise ops (batched): [N, W] op [N, W]
# ---------------------------------------------------------------------------


@jax.jit
@_compilewatch.tracked("batched_or")
def batched_or(a, b):
    return a | b


@jax.jit
@_compilewatch.tracked("batched_and")
def batched_and(a, b):
    return a & b


@jax.jit
@_compilewatch.tracked("batched_xor")
def batched_xor(a, b):
    return a ^ b


@jax.jit
@_compilewatch.tracked("batched_andnot")
def batched_andnot(a, b):
    return a & ~b


@jax.jit
@_compilewatch.tracked("popcount_rows")
def popcount_rows(words):
    """Per-row cardinality: fused population_count + row sum."""
    return jnp.sum(lax.population_count(words).astype(jnp.int32), axis=-1)


# ---------------------------------------------------------------------------
# wide reductions over the container axis
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("op",))
@_compilewatch.tracked("wide_reduce")
def wide_reduce(words, op: str = "or"):
    """Reduce [N, W] -> [W] with a bitwise op (the wide-OR/AND/XOR kernel)."""
    return lax.reduce(words, _INIT[op], _OPS[op], dimensions=(0,))


@functools.partial(jax.jit, static_argnames=("op",))
@_compilewatch.tracked("wide_reduce_with_cardinality")
def wide_reduce_with_cardinality(words, op: str = "or"):
    """Fused reduce + popcount: returns (result [W], cardinality scalar).

    The reference does this as a lazy fold + repairAfterLazy
    (FastAggregation.java:541-602); XLA fuses the popcount into the
    reduction epilogue so "lazy mode" needs no protocol here.
    """
    red = lax.reduce(words, _INIT[op], _OPS[op], dimensions=(0,))
    card = jnp.sum(lax.population_count(red).astype(jnp.int32))
    return red, card


@functools.partial(jax.jit, static_argnames=("op", "stage_groups"))
@_compilewatch.tracked("wide_reduce_two_stage")
def wide_reduce_two_stage(words, op: str = "or", stage_groups: int = 128):
    """Two-stage wide reduce: view [N, W] as [G, N/G, W], grouped-reduce the
    inner axis, then fold the G partial rows.

    Rationale (measured, BENCH_NOTES.md per-tile table): XLA's grouped
    reduce over a large inner axis sustains ~4x the bandwidth of the flat
    [N, W] -> [W] reduction on v5e (423 vs 59 GB/s) — the flat single-row
    output starves the reduction schedule. N is padded to a stage_groups
    multiple with the op identity."""
    n, w = words.shape
    g = min(stage_groups, max(1, n))
    pad = (-n) % g
    if pad:
        words = jnp.pad(words, ((0, pad), (0, 0)), constant_values=_INIT[op])
    partial_rows = lax.reduce(
        words.reshape(g, (n + pad) // g, w), _INIT[op], _OPS[op], dimensions=(1,)
    )
    red = lax.reduce(partial_rows, _INIT[op], _OPS[op], dimensions=(0,))
    card = jnp.sum(lax.population_count(red).astype(jnp.int32))
    return red, card


@functools.partial(jax.jit, static_argnames=("op",))
@_compilewatch.tracked("grouped_reduce")
def grouped_reduce(words3, op: str = "or"):
    """Reduce padded groups: [G, M, W] -> [G, W].

    Pad rows with the op identity (0 for or/xor, all-ones for and). This is
    the device analogue of ParallelAggregation.groupByKey + per-key reduce
    (ParallelAggregation.java:136-175): key-groups become the G axis.
    """
    return lax.reduce(words3, _INIT[op], _OPS[op], dimensions=(1,))


@functools.partial(jax.jit, static_argnames=("op",))
@_compilewatch.tracked("grouped_reduce_with_cardinality")
def grouped_reduce_with_cardinality(words3, op: str = "or"):
    red = lax.reduce(words3, _INIT[op], _OPS[op], dimensions=(1,))
    card = jnp.sum(lax.population_count(red).astype(jnp.int32), axis=-1)
    return red, card


@functools.partial(jax.jit, static_argnames=("op",))
@_compilewatch.tracked("segmented_reduce")
def segmented_reduce(words, seg_start, op: str = "or"):
    """Segmented reduce over sorted segments without padding.

    ``words``: [N, W]; ``seg_start``: bool [N], True at the first row of each
    segment. Returns [N, W] where the row at each segment's END holds the
    segment reduction (gather those rows host-side). Implemented as a
    flagged ``lax.associative_scan`` — O(N log N) word-ops, fully parallel,
    for key-group distributions too skewed to pad densely
    (the reference splits skewed slices across the pool instead,
    ParallelAggregation.java:222-228).
    """
    fn = _OPS[op]

    def combine(a, b):
        flag_a, val_a = a
        flag_b, val_b = b
        val = jnp.where(flag_b[:, None], val_b, fn(val_a, val_b))
        return flag_a | flag_b, val

    _, vals = lax.associative_scan(combine, (seg_start, words), axis=0)
    return vals


# ---------------------------------------------------------------------------
# columnar device tier support (ISSUE 10)
# ---------------------------------------------------------------------------


@jax.jit
@_compilewatch.tracked("word_test_rows")
def word_test_rows(rows, row_ids, word_idx, bit_idx):
    """Batched membership word-test against resident flat rows: is bit
    ``bit_idx[i]`` set in word ``word_idx[i]`` of row ``row_ids[i]``?
    (the array x bitmap columnar class's whole-bucket probe — only the
    bool mask leaves the device). OOB pad ids clamp to a real row; the
    host wrapper slices the pads off."""
    w = rows[row_ids, word_idx]
    return ((w >> bit_idx) & jnp.uint32(1)).astype(jnp.bool_)


def word_test_rows_host(rows, row_ids: np.ndarray, vals: np.ndarray) -> np.ndarray:
    """Host wrapper for :func:`word_test_rows`: uint16 probe values split
    into (word, bit) coordinates, streams padded to pow2 (retrace-bounded),
    bool mask back as numpy sliced to the live probe count."""
    n = int(vals.size)
    v = vals.astype(np.int64)
    row_p = pad_pow2(np.asarray(row_ids, dtype=np.int32), 0)
    word_p = pad_pow2((v >> 5).astype(np.int32), 0)
    bit_p = pad_pow2((v & 31).astype(np.uint32), 0)
    mask = word_test_rows(
        rows, jnp.asarray(row_p), jnp.asarray(word_p), jnp.asarray(bit_p)
    )
    return np.asarray(mask)[:n]


# ---------------------------------------------------------------------------
# batched rank / select support
# ---------------------------------------------------------------------------


@jax.jit
@_compilewatch.tracked("rank_rows")
def rank_rows(words, positions):
    """Per-row rank: number of set bits at index <= position (int32 [N]).

    Batched analogue of BitmapContainer.rank: mask words beyond the position,
    popcount-sum each row.
    """
    n_words = words.shape[-1]
    word_idx = positions // 32
    bit_idx = positions % 32
    iota = jnp.arange(n_words, dtype=jnp.int32)[None, :]
    full = (iota < word_idx[:, None]).astype(jnp.uint32) * jnp.uint32(0xFFFFFFFF)
    partial_mask = jnp.where(
        iota == word_idx[:, None],
        (jnp.uint32(0xFFFFFFFF) >> (jnp.uint32(31) - bit_idx[:, None].astype(jnp.uint32))),
        jnp.uint32(0),
    )
    masked = words & (full | partial_mask)
    return jnp.sum(lax.population_count(masked).astype(jnp.int32), axis=-1)
